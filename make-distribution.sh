#!/usr/bin/env bash
# Build a relocatable distribution tarball (role of the reference's
# make-distribution.sh): package + bin + conf + docs, versioned from
# pyproject.toml. Result: dist/predictionio_tpu-<ver>.tar.gz
set -euo pipefail
cd "$(dirname "$0")"
VER=$(python3 -c "
import tomllib
print(tomllib.load(open('pyproject.toml','rb'))['project']['version'])")
NAME="predictionio_tpu-${VER}"
STAGE="dist/${NAME}"
rm -rf "$STAGE" && mkdir -p "$STAGE"
cp -r predictionio_tpu bin conf docs pyproject.toml README.md "$STAGE/"
find "$STAGE" -name '__pycache__' -type d -exec rm -rf {} + 2>/dev/null || true
find "$STAGE" -name '*.so' -delete   # natives rebuild on first use
tar -C dist -czf "dist/${NAME}.tar.gz" "$NAME"
rm -rf "$STAGE"
echo "dist/${NAME}.tar.gz"
tar -tzf "dist/${NAME}.tar.gz" | head -5
