#!/usr/bin/env bash
# Editable install of the framework (role of bin/install.sh).
set -e
cd "$(dirname "$0")/.."
"${PIO_PYTHON:-python3}" -m pip install -e .
echo "Installed. Try: pio status"
