#!/usr/bin/env bash
# Source conf/pio-env.sh (or $PIO_CONF_DIR/pio-env.sh) into the calling
# shell. Role of the reference's bin/load-pio-env.sh: one place where the
# PIO_STORAGE_* / server env vars come from.
if [ -z "$PIO_HOME" ]; then
  export PIO_HOME="$(cd "$(dirname "${BASH_SOURCE[0]}")/.."; pwd)"
fi
PIO_CONF_DIR="${PIO_CONF_DIR:-$PIO_HOME/conf}"
if [ -f "$PIO_CONF_DIR/pio-env.sh" ]; then
  . "$PIO_CONF_DIR/pio-env.sh"
fi
