"""Benchmark entry: prints ONE JSON line with the north-star metrics.

Primary contract (driver): {"metric", "value", "unit", "vs_baseline"}.
The line also carries the rest of the BASELINE.md north star so every
round is comparable on all axes (VERDICT r1 items 1, 2, 7, 10):

- ``value``/``stdev_pct``/``iter_ms`` — ALS train throughput at
  MovieLens-20M shape (138,493 x 26,744, 20M ratings, power-law skew),
  rank 32, full alternating iterations, min-of-N over ``REPS`` timed
  repeats with the relative spread reported (this host's load varies).
- ``mfu_pct``/``useful_tflops``/``padding_x`` — useful-FLOP model
  utilisation and the layout-padding overhead (ops/als.half_step_flops);
  "useful" counts only real rating entries, so padding work earns no
  credit. MFU is quoted against the chip's headline dense bf16 peak
  even though the normal equations run f32-HIGHEST (which cannot reach
  bf16 peak on the MXU) — conservative by construction.
- ``p50_ms``/``p99_ms`` — end-to-end serving latency of the trained
  model behind the real engine server: POST /queries.json driven
  ``SERVE_QUERIES`` times over HTTP loopback (reference counter:
  CreateServer.scala:583-590). Includes JSON, HTTP, and host<->device
  transfer; on a remote-attached device (axon tunnel) the link
  dominates — see README serving notes.
- ``map10_tpu``/``map10_ref``/``rmse_tpu``/``rmse_ref`` — quality
  parity on an ML-100k-statistics dataset: the device-path ALS vs an
  independent NumPy ALS-WR (the MLlib estimator) under the reference's
  Evaluation.scala protocol (e2/quality.py). The north star is
  throughput *at matching MAP@10*; these keys prove the "matching".
- ``seqrec_tokens_per_sec``/``seqrec_mfu_pct`` — the beyond-reference
  sessionrec transformer's training rate (50k vocab, d256, L4, S256,
  bf16) so its perf claims are measured round-over-round.
- ``ingest_events_per_sec`` — batched REST ingest through the real
  event server into file-backed sqlite (the serving plane's front
  door; host-bound, no device).

Baseline (``vs_baseline``): Spark/MLlib cannot run here (no JVM), so
the Spark-on-CPU comparable is a measured proxy: a single-process NumPy
ALS-WR iteration (segment reductions — pure useful work) on a
subsample (size-normalised rate), scaled by this host's core count as
if Spark local[N] scaled perfectly with zero overhead — strictly
generous to Spark, so ``vs_baseline`` is a lower bound on the real
ratio. The BASELINE.md gate is >=10x.

``--sweep`` re-measures the chunk-layout grid and prints one JSON line
per config (throughput, padding overhead, MFU) — the data behind the
README layout table.
"""

from __future__ import annotations

import argparse
import json
import math
import os
import statistics
import time

import numpy as np

USERS = 138_493
ITEMS = 26_744
NNZ = 20_000_000
RANK = 32
LAM = 0.08
REPS = 5
SUB_NNZ = 500_000   # numpy-baseline subsample (rate is size-normalised)
SERVE_QUERIES = 500
SERVE_WARMUP = 20

# Chosen by `bench.py --sweep` on TPU v5e (see README layout table):
# fixed-size chunks, MXU-width contraction, zero dropped ratings.
CHUNK_SIZES = (512, 128)

# MEASUREMENT PROTOCOL (critical on remote-attached devices): on the
# axon tunnel, jax.block_until_ready can return before the computation
# actually executes — chained f32 matmuls "measured" 20 PFLOP/s that
# way. Every timing below therefore forces real execution by fetching a
# scalar reduction of the full result (float(jnp.sum(...))), and
# per-iteration time comes from the difference of a long and a short
# chain, which cancels the fetch's round-trip latency.
N_SHORT, N_LONG = 2, 10

# headline dense bf16 peak per chip (MFU denominator)
_PEAK_BF16 = {
    "TPU v4": 275e12,
    "TPU v5 lite": 197e12,
    "TPU v5e": 197e12,
    "TPU v5": 459e12,
    "TPU v5p": 459e12,
    "TPU v6 lite": 918e12,
    "TPU v6e": 918e12,
}


def make_ratings(nnz: int, seed: int = 0):
    """Power-law-skewed synthetic (user, item, rating) triples."""
    rng = np.random.default_rng(seed)
    users = (USERS * rng.random(nnz) ** 1.8).astype(np.int32)
    items = (ITEMS * rng.random(nnz) ** 1.8).astype(np.int32)
    vals = rng.integers(1, 11, size=nnz).astype(np.float32) / 2.0
    return users, items, vals


def _device_peak():
    import jax

    kind = jax.devices()[0].device_kind
    return kind, _PEAK_BF16.get(kind)


# ---------------------------------------------------------------------------
# ALS train throughput + MFU/padding accounting
# ---------------------------------------------------------------------------


def bench_als(users, items, vals, chunk_sizes=CHUNK_SIZES, reps=REPS):
    import jax
    import jax.numpy as jnp

    from predictionio_tpu.ops.als import (
        RatingsCOO,
        chunk_rows,
        half_step_flops,
        solve_half,
        stage_chunks,
    )

    coo = RatingsCOO(users, items, vals, USERS, ITEMS)
    by_user = chunk_rows(coo, chunk_sizes)
    by_item = chunk_rows(coo.transpose(), chunk_sizes)

    fl_u = half_step_flops(by_user, RANK)
    fl_i = half_step_flops(by_item, RANK)
    useful = fl_u["useful_flops"] + fl_i["useful_flops"]
    executed = fl_u["executed_flops"] + fl_i["executed_flops"]

    rng = np.random.default_rng(1)
    item_f0 = (rng.standard_normal((ITEMS, RANK)) / np.sqrt(RANK)).astype(
        np.float32
    )
    item_f = jax.device_put(jnp.asarray(item_f0))
    dev_user = stage_chunks(by_user, RANK)
    dev_item = stage_chunks(by_item, RANK)

    def run(n):
        """n chained full iterations ending in a forcing scalar fetch."""
        cur = item_f
        for _ in range(n):
            user_f = solve_half(cur, dev_user, RANK, LAM)
            cur = solve_half(user_f, dev_item, RANK, LAM)
        return float(jnp.sum(jnp.abs(cur))), user_f, cur

    run(1)  # compile warm-up
    iter_times = []
    for _ in range(reps):
        t0 = time.perf_counter()
        run(N_SHORT)
        t_short = time.perf_counter() - t0
        t0 = time.perf_counter()
        _, user_f, cur = run(N_LONG)
        t_long = time.perf_counter() - t0
        iter_times.append((t_long - t_short) / (N_LONG - N_SHORT))
    best = min(iter_times)
    mean = statistics.fmean(iter_times)
    stdev_pct = (
        100.0 * statistics.stdev(iter_times) / mean if reps > 1 else 0.0
    )

    kind, peak = _device_peak()
    result = {
        "rate": NNZ / best,
        "iter_ms": round(best * 1e3, 3),
        "stdev_pct": round(stdev_pct, 1),
        "reps": reps,
        "useful_tflops": round(useful / best / 1e12, 2),
        "padding_x": round(executed / useful, 2),
        "device": kind,
    }
    if peak:
        result["mfu_pct"] = round(100.0 * useful / best / peak, 2)
    # final factors reused by the serving benchmark
    return result, np.asarray(user_f), np.asarray(cur)


# ---------------------------------------------------------------------------
# NumPy single-process baseline -> Spark-on-CPU proxy
# ---------------------------------------------------------------------------


def bench_numpy_baseline(users, items, vals):
    """Single-core NumPy ALS-WR iteration (segment reductions, zero
    padding — the useful work a CPU executor actually does), scaled by
    core count as a Spark local[N] perfect-scaling proxy."""
    from predictionio_tpu.e2.quality import _segment_half_solve

    s_users, s_items, s_vals = (users[:SUB_NNZ], items[:SUB_NNZ],
                                vals[:SUB_NNZ])
    rng = np.random.default_rng(1)
    V0 = (rng.standard_normal((ITEMS, RANK)) / np.sqrt(RANK)).astype(np.float32)
    t0 = time.perf_counter()
    uf = _segment_half_solve(V0, s_users, s_items, s_vals, USERS, LAM)
    _segment_half_solve(uf, s_items, s_users, s_vals, ITEMS, LAM)
    one_core_rate = SUB_NNZ / (time.perf_counter() - t0)
    cores = os.cpu_count() or 1
    return {
        "numpy_1core_rate": round(one_core_rate, 1),
        "baseline_rate": round(one_core_rate * cores, 1),
        "baseline_cores": cores,
        "baseline": (
            f"single-process NumPy ALS-WR (segment reductions) x {cores} "
            "core(s) (Spark local[N] perfect-scaling proxy; generous to "
            "Spark)"
        ),
    }


# ---------------------------------------------------------------------------
# Serving latency: the trained model behind the real engine server
# ---------------------------------------------------------------------------


def bench_serving(user_f, item_f, users, items, n_queries=SERVE_QUERIES):
    import datetime
    import urllib.request

    import jax
    import jax.numpy as jnp

    from predictionio_tpu.api.engine_server import EngineServer
    from predictionio_tpu.controller.base import FirstServing
    from predictionio_tpu.models.als import ALSModel
    from predictionio_tpu.storage.base import EngineInstance
    from predictionio_tpu.templates import recommendation as rec
    from predictionio_tpu.utils.bimap import BiMap, EntityIdIxMap
    from predictionio_tpu.workflow.deploy import DeployedEngine, ServerConfig

    # id maps over the full catalog (string ids, as in production)
    user_ids = EntityIdIxMap(BiMap({f"u{i}": i for i in range(USERS)}))
    item_ids = EntityIdIxMap(BiMap({f"i{i}": i for i in range(ITEMS)}))

    # seen-item lists only for the users we will query
    order = np.argsort(users, kind="stable")
    su, si = users[order], items[order]
    rng = np.random.default_rng(7)
    query_uix = rng.choice(np.unique(su), size=n_queries + SERVE_WARMUP,
                           replace=True)
    seen_by_user = {}
    for u in np.unique(query_uix):
        lo, hi = np.searchsorted(su, u), np.searchsorted(su, u, side="right")
        seen_by_user[int(u)] = np.unique(si[lo:hi]).astype(np.int32)

    model = ALSModel(
        rank=RANK,
        # device-resident factors: np arrays would re-upload per query
        user_factors=jax.device_put(jnp.asarray(user_f)),
        item_factors=jax.device_put(jnp.asarray(item_f)),
        user_ids=user_ids,
        item_ids=item_ids,
        seen_by_user=seen_by_user,
    )
    algo = rec.ALSAlgorithm(rec.ALSAlgorithmParams(rank=RANK, use_mesh=False))
    now = datetime.datetime.now(datetime.timezone.utc)
    instance = EngineInstance(
        id="bench", status="COMPLETED", start_time=now, completion_time=now,
        engine_id="bench", engine_version="1", engine_variant="bench",
        engine_factory="bench",
    )
    deployed = DeployedEngine(None, instance, [algo], FirstServing(), [model])
    server = EngineServer(deployed, ServerConfig(ip="127.0.0.1", port=0))
    server.start()
    try:
        url = f"http://127.0.0.1:{server.port}/queries.json"

        def query(uix: int) -> float:
            body = json.dumps({"user": f"u{int(uix)}", "num": 10}).encode()
            req = urllib.request.Request(
                url, data=body, headers={"Content-Type": "application/json"},
                method="POST",
            )
            t0 = time.perf_counter()
            with urllib.request.urlopen(req, timeout=60) as r:
                r.read()
            return time.perf_counter() - t0

        for uix in query_uix[:SERVE_WARMUP]:       # compile + warm caches
            query(uix)
        lat = np.asarray([query(u) for u in query_uix[SERVE_WARMUP:]])
    finally:
        server.stop()
    return {
        "p50_ms": round(float(np.percentile(lat, 50)) * 1e3, 2),
        "p99_ms": round(float(np.percentile(lat, 99)) * 1e3, 2),
        "serve_queries": int(len(lat)),
    }


# ---------------------------------------------------------------------------
# Event-server ingest throughput (the serving plane's front door)
# ---------------------------------------------------------------------------


def bench_ingest(n_events: int = 2000, batch: int = 50):
    """Batched REST ingest rate over HTTP loopback into a file-backed
    sqlite event store (reference front door: POST /batch/events.json,
    EventServer.scala:376-460; <=50 events/request). CPU + storage
    bound — no device involvement."""
    import json as _json
    import tempfile
    import urllib.request

    from predictionio_tpu.api.event_server import (
        EventServer,
        EventServerConfig,
    )
    from predictionio_tpu.storage.base import AccessKey, App
    from predictionio_tpu.storage.registry import Storage

    with tempfile.TemporaryDirectory() as tmp:
        storage = Storage({
            "PIO_STORAGE_SOURCES_S_TYPE": "sqlite",
            "PIO_STORAGE_SOURCES_S_PATH": f"{tmp}/pio.db",
            "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "S",
            "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "S",
            "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "S",
        })
        app_id = storage.get_meta_data_apps().insert(App(0, "BenchApp"))
        storage.get_meta_data_access_keys().insert(
            AccessKey("bench-key", app_id, []))
        storage.get_events().init(app_id)
        server = EventServer(
            storage, EventServerConfig(ip="127.0.0.1", port=0))
        server.start()
        try:
            url = (f"http://127.0.0.1:{server.port}/batch/events.json"
                   f"?accessKey=bench-key")
            payload = [
                {"event": "rate", "entityType": "user",
                 "entityId": f"u{j % 97}", "targetEntityType": "item",
                 "targetEntityId": f"i{j % 53}",
                 "properties": {"rating": float(j % 5 + 1)}}
                for j in range(batch)
            ]
            body = _json.dumps(payload).encode()

            def post():
                req = urllib.request.Request(
                    url, data=body,
                    headers={"Content-Type": "application/json"})
                with urllib.request.urlopen(req, timeout=30) as r:
                    r.read()

            for _ in range(4):  # warm connections/WAL
                post()
            posted = (n_events // batch) * batch
            t0 = time.perf_counter()
            for _ in range(n_events // batch):
                post()
            dt = time.perf_counter() - t0
        finally:
            server.stop()
    return {"ingest_events_per_sec": round(posted / dt, 1)}


# ---------------------------------------------------------------------------
# Quality parity (the "at matching MAP@10" half of the north star)
# ---------------------------------------------------------------------------


def bench_quality():
    from predictionio_tpu.data.movielens import synthesize_ml100k
    from predictionio_tpu.e2 import quality

    q = quality.compare_quality(
        synthesize_ml100k(), rank=10, iterations=10, lam=0.05, k_fold=5
    )
    return {
        "map10_tpu": q["map10_tpu"],
        "map10_ref": q["map10_ref"],
        "map10_popularity": q["map10_popularity"],
        "rmse_tpu": q["rmse_tpu"],
        "rmse_ref": q["rmse_ref"],
    }


# ---------------------------------------------------------------------------
# sessionrec transformer train step (beyond-reference model family)
# ---------------------------------------------------------------------------


def bench_seqrec(steps: int = 20, batch: int = 64):
    import jax
    import jax.numpy as jnp

    from predictionio_tpu.models.seqrec import (
        SeqRecConfig,
        init_params,
        make_train_step,
    )

    cfg = SeqRecConfig(vocab=50_000, max_len=256, d_model=256, n_heads=4,
                       n_layers=4)
    s, d, v, layers = cfg.max_len, cfg.d_model, cfg.vocab, cfg.n_layers
    rng = np.random.default_rng(5)
    seqs = rng.integers(1, v, size=(batch, s), dtype=np.int64).astype(np.int32)
    targets = rng.integers(1, v, size=(batch, s), dtype=np.int64).astype(np.int32)

    params0 = init_params(jax.random.PRNGKey(0), cfg)
    opt_m0 = jax.tree.map(jnp.zeros_like, params0)
    opt_v0 = jax.tree.map(jnp.zeros_like, params0)
    step_fn = make_train_step(cfg)

    def run(n):
        """n chained steps; the final loss fetch forces the whole chain
        (see the measurement-protocol note at the top)."""
        params, opt_m, opt_v = params0, opt_m0, opt_v0
        for i in range(n):
            params, opt_m, opt_v, loss = step_fn(
                params, opt_m, opt_v, i + 1, seqs, targets, 1e-3)
        return float(loss)

    run(1)  # compile
    t0 = time.perf_counter()
    run(2)
    t_short = time.perf_counter() - t0
    t0 = time.perf_counter()
    loss = run(2 + steps)
    dt = (time.perf_counter() - t0) - t_short

    tokens = batch * s * steps
    # fwd FLOPs/token: per layer qkv 6d^2 + wo 2d^2 + mlp 16d^2 (mult 4)
    # + attention 4Sd; tied-logits 2dV. Training ~= 3x fwd.
    per_token = 3.0 * (layers * (24.0 * d * d + 4.0 * s * d) + 2.0 * d * v)
    _, peak = _device_peak()
    out = {
        "seqrec_tokens_per_sec": round(tokens / dt, 1),
        "seqrec_loss": round(float(loss), 3),
    }
    if peak:
        out["seqrec_mfu_pct"] = round(
            100.0 * tokens * per_token / dt / peak, 2)
    return out


# ---------------------------------------------------------------------------
# Chunk-layout sweep (README table; VERDICT r1 item 3)
# ---------------------------------------------------------------------------


def sweep():
    users, items, vals = make_ratings(NNZ)
    for sizes in [(1024, 128), (2048, 256), (512, 128), (1024, 256),
                  (4096, 512, 128)]:
        res, _, _ = bench_als(users, items, vals, chunk_sizes=sizes, reps=3)
        print(json.dumps({"chunk_sizes": sizes, **res}), flush=True)


# ---------------------------------------------------------------------------


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--sweep", action="store_true",
                        help="bucket-layout grid instead of the bench line")
    args = parser.parse_args()
    if args.sweep:
        sweep()
        return

    users, items, vals = make_ratings(NNZ)
    als, user_f, item_f = bench_als(users, items, vals)
    line = {
        "metric": "als_train_throughput_ml20m_rank32",
        "value": round(als.pop("rate"), 1),
        "unit": "ratings/sec",
        **als,
    }

    base = bench_numpy_baseline(users, items, vals)
    line["vs_baseline"] = round(line["value"] / base["baseline_rate"], 2)
    line.update(base)

    for section, fn in (
        ("serving", lambda: bench_serving(user_f, item_f, users, items)),
        ("quality", bench_quality),
        ("seqrec", bench_seqrec),
        ("ingest", bench_ingest),
    ):
        try:
            line.update(fn())
        except Exception as e:  # keep the primary metric on partial failure
            line[f"error_{section}"] = f"{type(e).__name__}: {e}"

    print(json.dumps(line))


if __name__ == "__main__":
    main()
