"""Benchmark entry: prints ONE JSON line with the north-star metrics.

Primary contract (driver): {"metric", "value", "unit", "vs_baseline"}.
The line also carries the rest of the BASELINE.md north star so every
round is comparable on all axes:

- ``value``/``stdev_pct``/``iter_ms`` — ALS train throughput at
  MovieLens-20M shape (138,493 x 26,744, 20M ratings, power-law skew),
  rank 32, full alternating iterations on the library-default path
  (fused MXU-width ladder, bf16 normal equations with f32 accumulation,
  one device program for the whole run — ops/als layout="fused").
  Min-of-N over ``REPS`` timed repeats, relative spread reported.
- ``phase_*_ms`` — per-phase decomposition of one iteration (VERDICT
  r2 weak #1): gather-only and gather+einsum chain variants isolate
  the factor row-gather (row-count-bound: measured invariant to row
  width 32->128 lanes, dtype, and index locality — ~2.8ns/row) and
  the normal-equation einsums; solve+write-back is the remainder.
- ``als_f32_rate`` — the f32-HIGHEST opt-in path
  (matmul_dtype="float32"), tracked so the precision trade stays
  visible round-over-round.
- ``rank200_*`` — the BASELINE.md rank-200 configuration on the same
  ML-20M shape (fused layout; CG step cap active). Its quality
  validation lives in ``rank200_rmse_tpu``/``rank200_rmse_ref``:
  device rank-200 ALS vs an exact per-row NumPy solver on the
  ML-100k-statistics dataset.
- ``mfu_pct``/``useful_tflops``/``padding_x`` — useful-FLOP model
  utilisation (ops/als.half_step_flops): "useful" counts real rating
  entries and algorithmic-minimum (Cholesky-priced) solves; executed
  prices the solve at the CG steps actually run, so padding_x carries
  both layout padding and solver overhead. MFU is quoted against the
  chip's headline dense bf16 peak — conservative by construction.
- ``p50_ms``/``p99_ms``/``serve_inproc_p50_ms`` — end-to-end serving
  latency over HTTP loopback (reference counter:
  CreateServer.scala:583-590) AND the in-process serve path (same
  query flow minus HTTP + tunnel), so the link share is measured, not
  asserted. ``serve_rtt_floor_ms`` — the tunnel's minimal
  dispatch+fetch p50, so cross-session p50 drift is attributable to
  the link. ``serve_batched_qps_32c`` — 32-concurrent-client HTTP
  throughput through the query micro-batcher
  (ServerConfig.batching; r5). ``batch_predict_qps_2m`` — batched
  top-k scoring rate against a 2M-item catalog (the eval hot path).
  ``calibration_matmul_ms`` — fixed bf16 matmul anchor; quote
  ``rank200_iter_per_calib`` for regime-adjusted comparison.
  ``serving_qps_*``/``serving_speedup_x``/``serving_cached_qps`` —
  the serving-path section (bench_serving.py): adaptive micro-batcher
  vs strict per-query dispatch under concurrent clients, and the
  result-cache regime (full harness artifacts: BENCH_serving_rNN.json).
  ``sections_failed`` — ALWAYS present; [] means complete.
- ``flash_s4096_ms``/``xla_s4096_ms`` — pallas flash (force=True) vs
  XLA attention forward at S=4096. Tracking this pair is what caught
  the round-2 envelope claim being wrong (XLA wins at every measured
  serving shape; auto-dispatch retired — ops/pallas_attention).
- ``map10_*``/``rmse_*`` — quality on the ML-100k-statistics dataset.
  map10_tpu/map10_ref vs an independent NumPy ALS-WR are PARITY keys;
  map10_implicit vs map10_popularity is the ranking-WINS key (explicit
  ALS models rating values and sits below the popularity baseline on
  top-N — MLlib's does too; the implicit path must beat it).
- ``seqrec_*`` — sessionrec transformer training at S=256 (dense
  attention), S=4096 (blockwise long-context path), and serving p50 at
  S=2048.
- ``ingest_events_per_sec`` — batched REST ingest through the real
  event server into file-backed sqlite.

Baseline: Spark/MLlib cannot run here (no JVM), so the comparable is a
measured proxy — a single-core NumPy ALS-WR iteration (segment
reductions, pure useful work), scaled two ways: ``vs_baseline``
against this host's core count as a Spark local[N] perfect-scaling
bound, and ``vs_baseline_64core`` against a 64-core cluster width
(a realistic production Spark allocation) — both generous to Spark by
construction. The BASELINE.md gate is >=10x and is evaluated against
the 64-core figure in README.

MEASUREMENT PROTOCOL (critical on remote-attached devices): on the
axon tunnel, jax.block_until_ready can return before the computation
actually executes — chained f32 matmuls "measured" 20 PFLOP/s that
way. Every timing below therefore forces real execution by fetching a
scalar reduction of the full result (float(jnp.sum(...))), and
per-iteration time comes from the difference of a long and a short
chain, which cancels the fetch's round-trip latency. Chain inputs vary
per step (factors feed back), since repeated identical dispatches
measure inconsistently on this backend.
"""

from __future__ import annotations

import argparse
import json
import math
import os
import statistics
import time

import numpy as np

USERS = 138_493
ITEMS = 26_744
NNZ = 20_000_000
RANK = 32
LAM = 0.08
REPS = 5
SUB_NNZ = 500_000   # numpy-baseline subsample (rate is size-normalised)
SERVE_QUERIES = 500
SERVE_WARMUP = 20

N_SHORT, N_LONG = 2, 10

# headline dense bf16 peak per chip (MFU denominator)
_PEAK_BF16 = {
    "TPU v4": 275e12,
    "TPU v5 lite": 197e12,
    "TPU v5e": 197e12,
    "TPU v5": 459e12,
    "TPU v5p": 459e12,
    "TPU v6 lite": 918e12,
    "TPU v6e": 918e12,
}


def make_ratings(nnz: int, seed: int = 0):
    """Power-law-skewed synthetic (user, item, rating) triples."""
    rng = np.random.default_rng(seed)
    users = (USERS * rng.random(nnz) ** 1.8).astype(np.int32)
    items = (ITEMS * rng.random(nnz) ** 1.8).astype(np.int32)
    vals = rng.integers(1, 11, size=nnz).astype(np.float32) / 2.0
    return users, items, vals


def _device_peak():
    import jax

    kind = jax.devices()[0].device_kind
    return kind, _PEAK_BF16.get(kind)


def _chain_time_many(runs: dict, n_short=None, n_long=None, reps=REPS):
    """Differential chains for one or more run variants, INTERLEAVED.

    Each rep times every variant's short chain, then every variant's
    long chain, so variants whose numbers will be SUBTRACTED sample the
    same load conditions (back-to-back variant measurement lets a
    host-load shift between them turn the difference negative). The
    per-variant estimate differences the MIN short and MIN long
    endpoint across reps — immune to the tunnel's asymmetric
    multi-second stalls. Returns {name: (robust, per_rep)}."""
    n_short = N_SHORT if n_short is None else n_short
    n_long = N_LONG if n_long is None else n_long
    times = {name: {"s": [], "l": []} for name in runs}
    for _ in range(reps):
        for n_calls, key in ((n_short, "s"), (n_long, "l")):
            for name, run in runs.items():
                t0 = time.perf_counter()
                run(n_calls)
                times[name][key].append(time.perf_counter() - t0)
    dn = n_long - n_short
    out = {}
    for name, t in times.items():
        robust = (min(t["l"]) - min(t["s"])) / dn
        per_rep = [(tl - ts) / dn for ts, tl in zip(t["s"], t["l"])]
        out[name] = (robust, per_rep)
    return out


def _chain_time(run, n_short=None, n_long=None, reps=REPS):
    """Single-variant differential chain (see :func:`_chain_time_many`)."""
    return _chain_time_many({"_": run}, n_short, n_long, reps)["_"]


def bench_calibration(n: int = 2048, rounds: int = 16):
    """Fixed reference-matmul timing: one bf16 ``n x n x n`` matmul's
    per-call ms, measured with the same differential-chain protocol as
    everything else. The chip/session regime drifts session to session
    (rank-200 iter spans 330-497 ms across sessions — VERDICT r4 weak
    #6); this constant-workload anchor makes a future drift in any
    other number attributable: if calibration moved too, it is the
    session, not the code."""
    import jax
    import jax.numpy as jnp

    rng = np.random.default_rng(11)
    a0 = jax.device_put(jnp.asarray(
        rng.standard_normal((n, n)).astype(np.float32))).astype(jnp.bfloat16)
    b = jax.device_put(jnp.asarray(
        rng.standard_normal((n, n)).astype(np.float32))).astype(jnp.bfloat16)

    @jax.jit
    def step(a):
        c = jnp.dot(a, b, preferred_element_type=jnp.float32)
        # feed back so chained dispatches differ (protocol)
        return (c * (1.0 / float(n))).astype(jnp.bfloat16)

    def run(k):
        a = a0
        for _ in range(k):
            a = step(a)
        return float(jnp.sum(a.astype(jnp.float32)))

    run(1)
    per_call, _ = _chain_time(run, n_short=1, n_long=1 + rounds, reps=3)
    return {"calibration_matmul_ms": round(per_call * 1e3, 3)}


# ---------------------------------------------------------------------------
# ALS train throughput (fused ladder, the library default) + f32 + rank 200
# ---------------------------------------------------------------------------


_LADDER_CACHE: dict = {}


def _staged_ladder(users, items, vals, rank):
    """One ladder layout + HBM staging per rank, memoized — bench_als,
    bench_phases, and bench_rank200 share it (the 20M-entry packing and
    both orientations' device upload are seconds each)."""
    # fingerprint the FULL index arrays (CRC over the raw bytes): a
    # prefix-sum key can alias two datasets that agree on their first
    # entries and silently hand back stale staged buffers (ADVICE r3)
    import zlib

    key = (rank, len(users),
           zlib.crc32(np.ascontiguousarray(users)),
           zlib.crc32(np.ascontiguousarray(items)),
           zlib.crc32(np.ascontiguousarray(vals)))
    if key in _LADDER_CACHE:
        return _LADDER_CACHE[key]
    from predictionio_tpu.ops import als as A

    coo = A.RatingsCOO(users, items, vals, USERS, ITEMS)
    by_u = A.ladder_rows(coo)
    by_i = A.ladder_rows(coo.transpose())
    dev_u = A.stage_buckets(by_u, rank)
    dev_i = A.stage_buckets(by_i, rank)
    out = (by_u, by_i, A._fused_bucket_args(dev_u),
           A._fused_bucket_args(dev_i))
    _LADDER_CACHE[key] = out
    return out


def _fused_run_fn(bu, bi, rank, bf16, item0_np):
    import jax
    import jax.numpy as jnp

    from predictionio_tpu.ops import als as A

    def run(n):
        # item0 uploads fresh per call (the program donates arg 0)
        u, it = A._als_iterate_fused(
            jax.device_put(item0_np), bu, bi, n, LAM, 40.0, False,
            USERS, ITEMS, bf16=bf16, cg_steps=None)
        return float(jnp.sum(jnp.abs(u))) + float(jnp.sum(jnp.abs(it)))

    return run


def bench_als(users, items, vals, reps=REPS):
    from predictionio_tpu.ops.als import half_step_flops

    by_u, by_i, bu, bi = _staged_ladder(users, items, vals, RANK)
    fl_u = half_step_flops(by_u, RANK)
    fl_i = half_step_flops(by_i, RANK)
    useful = fl_u["useful_flops"] + fl_i["useful_flops"]
    executed = fl_u["executed_flops"] + fl_i["executed_flops"]

    rng = np.random.default_rng(1)
    item0 = (rng.standard_normal((ITEMS, RANK)) / np.sqrt(RANK)).astype(
        np.float32)

    run = _fused_run_fn(bu, bi, RANK, True, item0)
    run(N_SHORT)  # compile warm-up — BOTH chain lengths, so no rep
    run(N_LONG)   # ever times a compile
    best, iter_times = _chain_time(run, reps=reps)
    mean = statistics.fmean(iter_times)
    stdev_pct = (
        100.0 * statistics.stdev(iter_times) / mean if reps > 1 else 0.0
    )

    kind, peak = _device_peak()
    result = {
        "rate": NNZ / best,
        "iter_ms": round(best * 1e3, 3),
        "stdev_pct": round(stdev_pct, 1),
        "reps": reps,
        "useful_tflops": round(useful / best / 1e12, 2),
        "padding_x": round(executed / useful, 2),
        "device": kind,
    }
    if peak:
        result["mfu_pct"] = round(100.0 * useful / best / peak, 2)

    # f32-HIGHEST opt-in rate (the precision trade, tracked)
    run32 = _fused_run_fn(bu, bi, RANK, False, item0)
    run32(N_SHORT)
    run32(N_LONG)
    result["als_f32_rate"] = round(
        NNZ / _chain_time(run32, reps=max(2, reps - 3))[0], 1)

    # final factors for the serving benchmark (one more full train)
    import jax
    import numpy as _np

    from predictionio_tpu.ops import als as A

    u, it = A._als_iterate_fused(
        jax.device_put(item0), bu, bi, 10, LAM, 40.0, False,
        USERS, ITEMS, bf16=True, cg_steps=None)
    return result, _np.asarray(u), _np.asarray(it)


def bench_phases(users, items, vals):
    """Per-phase decomposition via chain variants on the ladder layout:
    G = gather + fused reduce (the lightest full consumer), E = gather
    + mask + normal-equation einsums; the full iteration comes from the
    headline. Feedback keeps chain inputs varying (protocol)."""
    import jax
    import jax.numpy as jnp
    from functools import partial

    _, _, bu, bi = _staged_ladder(users, items, vals, RANK)
    _HI = jax.lax.Precision.HIGHEST

    @partial(jax.jit, static_argnames=("einsum",))
    def half_variant(V, buckets, base, einsum: bool):
        # gather from the bf16 table, like the default fused path since
        # r4 (the cast commutes with the row-gather; phase accounting
        # must walk the same bytes the real kernel walks)
        Vb = V.astype(jnp.bfloat16)
        tot = jnp.float32(0.0)
        for row_ids, cols, vals_, deg in buckets:
            L = cols.shape[-1]

            def body(carry, xs):
                c, v, d = xs
                F = Vb[c]
                if einsum:
                    m = (jnp.arange(L, dtype=jnp.int32)[None, :]
                         < d[:, None]).astype(jnp.float32)
                    Fm = F * m[..., None].astype(jnp.bfloat16)
                    Ap = jnp.einsum("blk,blm->bkm", Fm, F,
                                    preferred_element_type=jnp.float32)
                    bp = jnp.einsum("bl,blk->bk", (v * m).astype(jnp.bfloat16),
                                    F, preferred_element_type=jnp.float32)
                    s = jnp.sum(Ap) + jnp.sum(bp)
                else:
                    # lightest full consumer: a fused reduce with f32
                    # accumulation. (An earlier f32-cast-then-mask
                    # consumer materialized an f32 copy of F that the
                    # einsum variant never pays, making "gather-only"
                    # measure SLOWER than gather+einsum.)
                    s = jnp.sum(F, dtype=jnp.float32) + jnp.sum(v)
                return carry + s, None

            tot, _ = jax.lax.scan(body, tot, (cols, vals_, deg))
        return base * (1.0 + 1e-12 * jnp.tanh(tot))

    rng = np.random.default_rng(1)
    item0 = jax.device_put(jnp.asarray(
        (rng.standard_normal((ITEMS, RANK)) / np.sqrt(RANK)).astype(np.float32)))
    base_u = jax.device_put(jnp.asarray(
        (rng.standard_normal((USERS, RANK)) / np.sqrt(RANK)).astype(np.float32)))
    base_i = jax.device_put(jnp.asarray(
        (rng.standard_normal((ITEMS, RANK)) / np.sqrt(RANK)).astype(np.float32)))

    def make_run(einsum):
        def run(n):
            cur = item0
            for _ in range(n):
                uf = half_variant(cur, bu, base_u, einsum)
                cur = half_variant(uf, bi, base_i, einsum)
            return float(jnp.sum(jnp.abs(cur)))

        return run

    runs = {name: make_run(einsum)
            for name, einsum in (("gather", False), ("einsum", True))}
    # interleaved: the einsum number is a DIFFERENCE of the two
    # variants, so they must sample the same load conditions (observed
    # otherwise under a concurrently loaded host: gather 194.7, einsum
    # delta -53.7 — see _chain_time_many)
    for run in runs.values():
        run(N_SHORT)
        run(N_LONG)
    timed = _chain_time_many(runs, reps=3)
    gather_s = timed["gather"][0]
    delta_s = timed["einsum"][0] - gather_s
    result = {
        "phase_gather_ms": round(gather_s * 1e3, 1),
        "phase_einsum_ms": round(delta_s * 1e3, 1),
    }
    if delta_s < 0:
        # still possible under violent load shifts; flag rather than
        # silently report an impossible negative phase (guard on the
        # RAW difference — round() can hide small negatives as -0.0)
        result["phase_warning"] = "negative einsum delta (noisy session)"
    return result


RANK200 = 200


def bench_rank200(users, items, vals):
    """BASELINE.md's rank-200 ML-20M configuration, in the bench
    contract (VERDICT r2 missing #2). Heavy: the normal-equation build
    is 2K^2 FLOPs/entry = ~4.3 PFLOP/iteration at rank 200, so short
    chains."""
    import jax
    import jax.numpy as jnp

    from predictionio_tpu.ops import als as A
    from predictionio_tpu.ops.als import half_step_flops

    by_u, by_i, bu, bi = _staged_ladder(users, items, vals, RANK200)
    fl_u = half_step_flops(by_u, RANK200)
    fl_i = half_step_flops(by_i, RANK200)
    useful = fl_u["useful_flops"] + fl_i["useful_flops"]

    rng = np.random.default_rng(1)
    item0 = (rng.standard_normal((ITEMS, RANK200)) /
             np.sqrt(RANK200)).astype(np.float32)

    def run(n):
        # cg_bf16 matches als_train's "auto" policy at rank >= 64
        # (bf16 A-matvec, f32 accumulation — 1.51x measured r4)
        u, it = A._als_iterate_fused(
            jax.device_put(item0), bu, bi, n, LAM, 40.0, False,
            USERS, ITEMS, bf16=True, cg_steps=None, cg_bf16=True)
        return float(jnp.sum(jnp.abs(u))) + float(jnp.sum(jnp.abs(it)))

    run(1)
    run(5)    # warm both chain lengths before timing
    best, _ = _chain_time(run, n_short=1, n_long=5, reps=3)
    _, peak = _device_peak()
    out = {
        "rank200_rate": round(NNZ / best, 1),
        "rank200_iter_ms": round(best * 1e3, 1),
    }
    if peak:
        out["rank200_mfu_pct"] = round(100.0 * useful / best / peak, 2)
    return out


# ---------------------------------------------------------------------------
# NumPy single-process baseline -> Spark-on-CPU proxy
# ---------------------------------------------------------------------------


def bench_numpy_baseline(users, items, vals, reps: int = 2):
    """MEASURED CPU baseline (VERDICT r4 next #3): the reference
    template's estimator (ALSAlgorithm.scala:79-93's ALS.train math) as
    a NumPy ALS-WR iteration, actually executed (a) single-threaded and
    (b) multi-threaded at this host's core count — per-row solves are
    independent, so threads take contiguous row-id stripes and NumPy
    releases the GIL inside the einsum/solve kernels. Spark itself
    cannot run here (no JVM — see BASELINE.md "measured baseline" for
    the attempt transcript); `baseline_64core_rate` remains a LABELED
    linear extrapolation of the measured rate to a 64-core cluster
    width, generous to Spark."""
    from concurrent.futures import ThreadPoolExecutor

    from predictionio_tpu.e2.quality import _segment_half_solve

    s_users, s_items, s_vals = (users[:SUB_NNZ], items[:SUB_NNZ],
                                vals[:SUB_NNZ])
    rng = np.random.default_rng(1)
    V0 = (rng.standard_normal((ITEMS, RANK)) / np.sqrt(RANK)).astype(np.float32)

    def half(V, rows, cols, num_rows, threads):
        if threads == 1:
            return _segment_half_solve(V, rows, cols, s_vals, num_rows, LAM)
        out = np.zeros((num_rows, RANK), dtype=V.dtype)
        bounds = np.linspace(0, num_rows, threads + 1).astype(np.int64)

        def work(t):
            lo, hi = int(bounds[t]), int(bounds[t + 1])
            m = (rows >= lo) & (rows < hi)
            if m.any():
                out[lo:hi] = _segment_half_solve(
                    V, rows[m] - lo, cols[m], s_vals[m], hi - lo, LAM)

        with ThreadPoolExecutor(threads) as ex:
            list(ex.map(work, range(threads)))
        return out

    def one_pass(threads):
        t0 = time.perf_counter()
        uf = half(V0, s_users, s_items, USERS, threads)
        half(uf, s_items, s_users, ITEMS, threads)
        return SUB_NNZ / (time.perf_counter() - t0)

    cores = os.cpu_count() or 1
    one_core_rate = max(one_pass(1) for _ in range(reps))
    measured_rate = (one_core_rate if cores == 1
                     else max(one_pass(cores) for _ in range(reps)))
    return {
        "numpy_1core_rate": round(one_core_rate, 1),
        "baseline_rate": round(measured_rate, 1),
        "baseline_cores": cores,
        "baseline_64core_rate": round(measured_rate * 64 / cores, 1),
        "baseline": (
            f"MEASURED multi-threaded NumPy ALS-WR (segment reductions, "
            f"row-stripe threads) at {cores} core(s), best of {reps}; "
            "Spark/JVM unavailable here (BASELINE.md); "
            "vs_baseline_64core linearly extrapolates the measured rate "
            "to a 64-core cluster width (generous to Spark)"
        ),
    }


# ---------------------------------------------------------------------------
# Serving latency: HTTP + in-process + batched top-k at 2M items
# ---------------------------------------------------------------------------


def bench_serving(user_f, item_f, users, items, n_queries=SERVE_QUERIES):
    import datetime
    import urllib.request

    import jax
    import jax.numpy as jnp

    from predictionio_tpu.api.engine_server import EngineServer
    from predictionio_tpu.controller.base import FirstServing
    from predictionio_tpu.models.als import ALSModel
    from predictionio_tpu.storage.base import EngineInstance
    from predictionio_tpu.templates import recommendation as rec
    from predictionio_tpu.utils.bimap import BiMap, EntityIdIxMap
    from predictionio_tpu.workflow.deploy import DeployedEngine, ServerConfig

    # id maps over the full catalog (string ids, as in production)
    user_ids = EntityIdIxMap(BiMap({f"u{i}": i for i in range(USERS)}))
    item_ids = EntityIdIxMap(BiMap({f"i{i}": i for i in range(ITEMS)}))

    # seen-item lists only for the users we will query
    order = np.argsort(users, kind="stable")
    su, si = users[order], items[order]
    rng = np.random.default_rng(7)
    query_uix = rng.choice(np.unique(su), size=n_queries + SERVE_WARMUP,
                           replace=True)
    seen_by_user = {}
    for u in np.unique(query_uix):
        lo, hi = np.searchsorted(su, u), np.searchsorted(su, u, side="right")
        seen_by_user[int(u)] = np.unique(si[lo:hi]).astype(np.int32)

    model = ALSModel(
        rank=RANK,
        # device-resident factors: np arrays would re-upload per query
        user_factors=jax.device_put(jnp.asarray(user_f)),
        item_factors=jax.device_put(jnp.asarray(item_f)),
        user_ids=user_ids,
        item_ids=item_ids,
        seen_by_user=seen_by_user,
    )
    algo = rec.ALSAlgorithm(rec.ALSAlgorithmParams(rank=RANK, use_mesh=False))
    now = datetime.datetime.now(datetime.timezone.utc)
    instance = EngineInstance(
        id="bench", status="COMPLETED", start_time=now, completion_time=now,
        engine_id="bench", engine_version="1", engine_variant="bench",
        engine_factory="bench",
    )
    serving = FirstServing()

    # Compile the predict program IN-PROCESS before any HTTP request is
    # in flight: the first query at ML-20M scale pays a full jit compile
    # of the top-k program, and r4 lost the whole serving section to a
    # 60s socket timeout on exactly that query (VERDICT r4 weak #1). A
    # forced scalar fetch guarantees execution, not just dispatch.
    q0 = rec.Query(user=f"u{int(query_uix[0])}", num=10)
    pre = serving.serve(q0, [algo.predict(model, q0)])
    assert pre is not None

    deployed = DeployedEngine(None, instance, [algo], serving, [model])
    server = EngineServer(deployed, ServerConfig(ip="127.0.0.1", port=0))
    server.start()
    try:
        url = f"http://127.0.0.1:{server.port}/queries.json"

        def query(uix: int, timeout: float = 60.0) -> float:
            body = json.dumps({"user": f"u{int(uix)}", "num": 10}).encode()
            req = urllib.request.Request(
                url, data=body, headers={"Content-Type": "application/json"},
                method="POST",
            )
            t0 = time.perf_counter()
            with urllib.request.urlopen(req, timeout=timeout) as r:
                r.read()
            return time.perf_counter() - t0

        # warmup: generous timeout (residual compiles, cold caches) and
        # one retry — a single slow warmup query must never void the
        # section again
        for uix in query_uix[:SERVE_WARMUP]:
            try:
                query(uix, timeout=300.0)
            except OSError:
                query(uix, timeout=300.0)
        lat = np.asarray([query(u) for u in query_uix[SERVE_WARMUP:]])
    finally:
        server.stop()

    # concurrent-clients HTTP throughput with the micro-batcher
    # (ServerConfig.batching, r5): N clients' queries coalesce into one
    # device dispatch, amortizing the tunnel RTT that dominates p50
    batched = _bench_batched_serving(deployed, query_uix)

    # in-process p50: the identical serve flow minus HTTP + loopback,
    # so the link's share of p50 is measured rather than asserted
    # (VERDICT r2 weak #5)
    def inproc(uix: int) -> float:
        q = rec.Query(user=f"u{int(uix)}", num=10)
        t0 = time.perf_counter()
        serving.serve(q, [algo.predict(model, q)])
        return time.perf_counter() - t0

    for uix in query_uix[:SERVE_WARMUP]:
        inproc(uix)
    inlat = np.asarray([inproc(u) for u in query_uix[SERVE_WARMUP:]])

    # MEASURED single-process CPU serving baseline (VERDICT r4 next
    # #3): the identical serve computation — score, mask seen, top-10 —
    # in plain NumPy, the stand-in for the reference's local-model JVM
    # predict (CreateServer.scala:583-590's avgServingSec observable).
    # In-process on both sides, so the comparison excludes HTTP.
    def np_serve(uix: int) -> float:
        t0 = time.perf_counter()
        scores = item_f @ user_f[int(uix)]
        seen = seen_by_user.get(int(uix))
        if seen is not None and len(seen):
            scores = scores.copy()
            scores[seen] = -np.inf
        top = np.argpartition(scores, -10)[-10:]
        top = top[np.argsort(scores[top])[::-1]]   # cost matters, not order
        return time.perf_counter() - t0

    for uix in query_uix[:SERVE_WARMUP]:
        np_serve(uix)
    nplat = np.asarray([np_serve(u) for u in query_uix[SERVE_WARMUP:]])

    # the tunnel's dispatch+fetch floor: a minimal varying device op
    # with a forced scalar fetch. p50 minus this is the framework's own
    # serving cost — so a cross-session p50 drift is attributable to
    # the link, like calibration_matmul_ms for kernel time
    one = jax.device_put(jnp.ones((8, 8), jnp.float32))
    float(jnp.sum(one))                       # compile
    rtts = []
    for j in range(30):
        t0 = time.perf_counter()
        float(jnp.sum(one * (1.0 + j)))
        rtts.append(time.perf_counter() - t0)
    rtt_floor = round(float(np.percentile(rtts, 50)) * 1e3, 2)

    return {
        "serve_rtt_floor_ms": rtt_floor,
        "p50_ms": round(float(np.percentile(lat, 50)) * 1e3, 2),
        "p99_ms": round(float(np.percentile(lat, 99)) * 1e3, 2),
        **batched,
        "serve_inproc_p50_ms": round(float(np.percentile(inlat, 50)) * 1e3, 2),
        "baseline_serve_inproc_p50_ms": round(
            float(np.percentile(nplat, 50)) * 1e3, 3),
        "serve_queries": int(len(lat)),
        **bench_batch_predict(),
    }


def _bench_batched_serving(deployed, query_uix, clients: int = 32,
                           per_client: int = 8):
    """HTTP throughput with ``clients`` concurrent connections against
    a batching engine server (one device dispatch per coalesced batch).
    Sequential HTTP tops out at ~1000/p50 qps on the tunnel; this is
    the number that shows the dispatch RTT amortizing."""
    import json as _json
    import threading
    import urllib.request

    from predictionio_tpu.api.engine_server import EngineServer
    from predictionio_tpu.workflow.deploy import ServerConfig

    from predictionio_tpu.templates import recommendation as rec

    uixs = np.asarray(query_uix)
    # pre-compile EVERY padded batch signature the coalescer can
    # produce (batch dims pad to powers of two): a partial batch whose
    # signature first appears inside the timed loop would bill a
    # multi-second remote compile as serving time (observed: 24 vs
    # ~113 qps)
    for b in (1, 2, 4, 8, 16, 32):
        if b <= clients:
            deployed.query_batch([
                rec.Query(user=f"u{int(uixs[j % len(uixs)])}", num=10)
                for j in range(b)
            ])

    server = EngineServer(deployed, ServerConfig(
        ip="127.0.0.1", port=0, batching=True,
        # 25ms wait: on this 1-core host 32 client threads need more
        # than the 5ms default to get their requests enqueued past
        # the GIL
        batch_max=clients, batch_wait_ms=25.0))
    server.start()
    try:
        url = f"http://127.0.0.1:{server.port}/queries.json"

        def client(cid, count):
            for j in range(count):
                body = _json.dumps({
                    "user": f"u{int(uixs[(cid * per_client + j) % len(uixs)])}",
                    "num": 10}).encode()
                req = urllib.request.Request(
                    url, data=body,
                    headers={"Content-Type": "application/json"})
                with urllib.request.urlopen(req, timeout=120) as r:
                    r.read()

        def run(count):
            threads = [threading.Thread(target=client, args=(c, count))
                       for c in range(clients)]
            t0 = time.perf_counter()
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            return time.perf_counter() - t0

        run(2)                                  # warm the batched path
        dt = run(per_client)
        # key carries the client count so the metric always describes
        # its own measurement
        return {f"serve_batched_qps_{clients}c":
                round(clients * per_client / dt, 1)}
    finally:
        server.stop()


def bench_serving_path():
    """Adaptive micro-batcher vs strict per-query dispatch over HTTP
    loopback, plus the cached regime — the PR 3 serving-path
    trajectory. Standalone harness: bench_serving.py (committed
    artifacts: BENCH_serving_rNN.json); this section runs it at
    reduced volume so every round's line carries the serving numbers."""
    import bench_serving

    return bench_serving.bench_section()


def bench_ann_retrieval(shrunk: bool = False):
    """Brute vs ANN (IVF-flat MIPS + exact rescore) catalog-size sweep
    — the PR 8 sublinear-retrieval trajectory. Standalone harness:
    bench_serving.py --ann-only (committed artifacts:
    BENCH_ann_rNN.json); under --skip-heavy it runs one small-but-
    indexable catalog so the harness contract stays exercised."""
    import bench_serving

    return bench_serving.bench_ann_section(shrunk=shrunk)


def bench_workers_scaling(shrunk: bool = False):
    """Prefork serving-pool core scaling (1 vs 2 SO_REUSEPORT workers)
    — the `pio deploy --workers N` trajectory. Standalone harness:
    bench_serving.py --workers-only (committed artifacts:
    BENCH_workers_rNN.json, which also carry the 1M ANN-under-workers
    re-run — skipped in this section at BOTH sizes: the index build
    runs minutes). Under --skip-heavy the catalog and round count
    shrink so the harness contract stays exercised cheaply. The
    scaling ratio only clears 1 on a multi-core host — the section
    records host_cores alongside."""
    import bench_serving

    return bench_serving.bench_workers_section(shrunk=shrunk)


def bench_shm_cache(shrunk: bool = False):
    """Shared-memory serving plane (private per-worker LRU vs ONE
    seqlock shm segment at 1 and 2 SO_REUSEPORT workers) — the PR 18
    trajectory: paired qps/p99, the pool-wide hit ratio from the
    merged /metrics scrape, and the post-invalidation rewarm probe
    (a shared segment pays each cold key ONCE pool-wide; replicated
    LRUs pay it once per worker the replays land on). Standalone
    harness: bench_serving.py --shm-only (committed artifacts:
    BENCH_shm_rNN.json); under --skip-heavy it runs shrunk (small
    catalog, fewer rounds, smaller probe — same contract)."""
    import bench_serving

    return bench_serving.bench_shm_section(shrunk=shrunk)


def bench_gateway_phase(shrunk: bool = False):
    """Multi-tenant gateway: 1 vs 2 engines behind one router + the
    quota-isolation pin (a tenant driven past its qps quota is 429'd
    while the sibling's p99 holds) — the PR 15 trajectory. Standalone
    harness: bench_serving.py --gateway-only (committed artifacts:
    BENCH_gateway_rNN.json); under --skip-heavy it runs shrunk (fewer
    clients/rounds, same contract)."""
    import bench_serving

    return bench_serving.bench_gateway_section(shrunk=shrunk)


def bench_data_plane():
    """Columnar scan vs row iterator + transactional batch ingest — the
    PR 4 data-plane trajectory. Standalone harness: bench_ingest.py
    (committed artifacts: BENCH_ingest_rNN.json); this section runs it
    at reduced volume so every round's line carries the data-plane
    numbers."""
    import bench_ingest

    return bench_ingest.bench_section()


def bench_elasticity_section(shrunk: bool = False):
    """Per-tenant elasticity plane (bench_elasticity.py; committed
    artifacts: BENCH_elasticity_rNN.json): compliant-tenant p99 ratio
    while an abusive sibling is throttled, burst-credit admission vs a
    credit-less control, and the deterministic ManualClock
    scale-decision timeline under a shared replica budget. Router
    threads + stdlib echo backends, no device — runs (shrunk) under
    --skip-heavy."""
    import bench_elasticity

    return bench_elasticity.bench_section(shrunk=shrunk)


def bench_experiment_section(shrunk: bool = False):
    """Experimentation plane (bench_experiment.py; committed
    artifacts: BENCH_experiment_rNN.json): parallel-grid throughput
    1-vs-N (report-not-pin on a 1-core host — the ratio carries
    host_core_ratio_caveat) plus assign()/record() round-trips per
    second on the routed-query path. Fork children + one controller
    loop, no device — runs (shrunk) under --skip-heavy."""
    import bench_experiment

    return bench_experiment.bench_section(shrunk=shrunk)


def bench_freshness_section(shrunk: bool = False):
    """Real-time freshness plane (bench_freshness.py; committed
    artifacts: BENCH_freshness_rNN.json): event→recommendation lag
    distribution under live HTTP ingest+query load, fold-in throughput
    in events/s, and the `--workers 2` spool-propagation variant. CPU +
    storage bound — runs (shrunk) under --skip-heavy."""
    import bench_freshness

    return bench_freshness.bench_section(shrunk=shrunk)


def bench_train_profile():
    """Tiny `pio train --profile` on the recommendation template — the
    device/compiler observability trajectory (PR 12,
    docs/observability.md "Device and compiler observability"): the
    artifact carries MFU (null where no peak-FLOPs entry exists —
    honest-or-nothing), cumulative XLA compile seconds, and the compile
    count, so a drift in the compile story (a new shape sneaking into
    the menu, a program that stopped caching) shows round-over-round.
    Cheap enough to run under --skip-heavy."""
    import os
    import tempfile

    from predictionio_tpu.core.datamap import DataMap
    from predictionio_tpu.core.event import Event
    from predictionio_tpu.obs.compile import recorder
    from predictionio_tpu.obs.device import TrainProfiler
    from predictionio_tpu.storage.base import App
    from predictionio_tpu.utils.testing import memory_storage
    from predictionio_tpu.workflow.train import run_train

    storage = memory_storage()
    app_id = storage.get_meta_data_apps().insert(App(0, "BenchProfApp"))
    events = storage.get_events()
    events.init(app_id)
    rng = np.random.default_rng(5)
    for u in range(32):
        for i in range(24):
            if rng.random() < 0.4:
                events.insert(
                    Event(event="rate", entity_type="user",
                          entity_id=f"u{u}", target_entity_type="item",
                          target_entity_id=f"i{i}",
                          properties=DataMap(
                              {"rating": float(rng.integers(1, 6))})),
                    app_id)
    variant = {
        "id": "bench-profile",
        "engineFactory":
            "predictionio_tpu.templates.recommendation.engine_factory",
        "datasource": {"params": {"app_name": "BenchProfApp"}},
        "algorithms": [{"name": "als",
                        "params": {"rank": 8, "num_iterations": 3,
                                   "lambda_": 0.05, "seed": 4}}],
    }
    recorder().reset()
    with tempfile.TemporaryDirectory() as model_dir:
        old = os.environ.get("PIO_MODEL_DIR")
        os.environ["PIO_MODEL_DIR"] = model_dir
        try:
            outcome = run_train(variant=variant, storage=storage,
                                profiler=TrainProfiler())
        finally:
            if old is None:
                os.environ.pop("PIO_MODEL_DIR", None)
            else:
                os.environ["PIO_MODEL_DIR"] = old
    report = outcome.report
    recorder().reset()
    mfu = report["mfu"]
    return {
        "train_profile_mfu": (round(mfu, 6) if isinstance(mfu, float)
                              else None),
        "train_profile_compile_seconds": round(
            report["compile"]["totalSeconds"], 3),
        "train_profile_compiles": report["compile"]["totalCompiles"],
        "train_profile_wall_seconds": round(report["wallSeconds"], 3),
    }


def bench_train_sharding(shrunk: bool = False):
    """DP×MP factor-table sharding on the fused ALS flagship path —
    the ROADMAP item 1 trajectory (standalone harness:
    bench_sharding.py; committed artifacts: BENCH_sharding_rNN.json).
    Runs in a forced-8-device subprocess child (this process owns a
    1-device jax runtime): replicated-vs-sharded MFU/HBM at matched
    shapes from TRAIN_REPORT.json (honest-or-null on CPU) plus
    computed per-device table bytes, the factor-parity max |Δ|, and
    the rank-512 sharded-only point against the stated per-device
    budget. Under --skip-heavy it runs shrunk (tiny shapes, same
    contract)."""
    import bench_sharding

    return bench_sharding.bench_sharding_section(shrunk=shrunk)


def bench_batch_predict(n_items: int = 2_000_000, batch: int = 256,
                        rounds: int = 8):
    """Batched top-k scoring against a 2M-item catalog — the eval hot
    path (recommend_topk_chunked's envelope; VERDICT r2 weak #5)."""
    import jax
    import jax.numpy as jnp

    from predictionio_tpu.ops.topk import recommend_topk_fused

    rng = np.random.default_rng(3)
    item_f = jax.device_put(jnp.asarray(
        rng.standard_normal((n_items, RANK)).astype(np.float32)))
    uv = jax.device_put(jnp.asarray(
        rng.standard_normal((batch, RANK)).astype(np.float32)))
    seen = np.zeros((batch, 32), dtype=np.int32)
    mask = np.zeros((batch, 32), dtype=np.float32)
    allow = jnp.ones((n_items,), dtype=jnp.float32)

    def run(n):
        cur = uv
        for _ in range(n):
            v, i = recommend_topk_fused(cur, item_f, seen, mask, allow, 10)
            # feed the scores back so chained inputs differ (protocol)
            cur = cur * (1.0 + 1e-9 * jnp.tanh(jnp.sum(v)))
        return float(jnp.sum(jnp.asarray(i)))

    run(1)
    per_call, _ = _chain_time(run, n_short=1, n_long=1 + rounds, reps=3)
    return {"batch_predict_qps_2m": round(batch / per_call, 1)}


# ---------------------------------------------------------------------------
# Attention: pallas flash vs XLA at the envelope midpoint
# ---------------------------------------------------------------------------


def bench_attention(S: int = 4096, B: int = 1, H: int = 4, D: int = 64,
                    rounds: int = 64):
    """Forward serving attention at S=4096: the pallas flash kernel vs
    the XLA formulation (VERDICT r2 weak #4 — the 35x/OOM envelope
    lived only in a docstring)."""
    import jax
    import jax.numpy as jnp
    from functools import partial

    from predictionio_tpu.ops.attention import full_attention
    from predictionio_tpu.ops.pallas_attention import flash_attention

    rng = np.random.default_rng(2)

    def mk():
        return jax.device_put(jnp.asarray(
            rng.standard_normal((B, H, S, D)).astype(np.float32) * 0.05))

    q, k, v = mk(), mk(), mk()

    @partial(jax.jit, static_argnames=("flash",))
    def step(q, k, v, flash: bool):
        fn = (lambda *a, **kw: flash_attention(*a, force=True, **kw)) \
            if flash else full_attention
        o = fn(q, k, v, causal=True)
        # feed back: next q depends on this output (protocol)
        return q * (1.0 + 1e-9 * jnp.tanh(jnp.sum(o))), o

    out = {}
    for name, flash in (("flash", True), ("xla", False)):
        def run(n):
            cur = q
            o = None
            for _ in range(n):
                cur, o = step(cur, k, v, flash)
            return float(jnp.sum(jnp.abs(o)))

        run(1)
        out[f"{name}_s{S}_ms"] = round(
            _chain_time(run, n_short=1, n_long=1 + rounds, reps=3)[0] * 1e3,
            2)
    return out


# ---------------------------------------------------------------------------
# Event-server ingest throughput (the serving plane's front door)
# ---------------------------------------------------------------------------


def bench_ingest(n_events: int = 2000, batch: int = 50):
    """Batched REST ingest rate over HTTP loopback into TWO event
    stores (reference front door: POST /batch/events.json,
    EventServer.scala:376-460; <=50 events/request): file-backed sqlite
    (the jdbc role) AND the binevents C++ append log (the hbase role,
    native/eventlog.cc — its ingest number is tracked so the backend
    earns its keep in the contract, VERDICT r3 weak #7). CPU + storage
    bound — no device involvement."""
    out = {}
    # per-backend isolation: one backend's failure must not discard the
    # other's already-measured number
    for key, backend in (("ingest_events_per_sec", "sqlite"),
                         ("ingest_binevents_per_sec", "binevents")):
        try:
            rate, stdev_pct, reps = _ingest_one(backend, n_events, batch)
            out[key] = rate
            # regression vs host noise must be decidable from the
            # artifact alone (VERDICT r4 weak #3)
            out[f"{key}_stdev_pct"] = stdev_pct
            out[f"{key}_reps"] = reps
        except Exception as e:
            out[f"error_ingest_{backend}"] = f"{type(e).__name__}: {e}"
    return out


def _ingest_one(backend: str, n_events: int, batch: int):
    import json as _json
    import tempfile
    import urllib.request

    from predictionio_tpu.api.event_server import (
        EventServer,
        EventServerConfig,
    )
    from predictionio_tpu.storage.base import AccessKey, App
    from predictionio_tpu.storage.registry import Storage

    with tempfile.TemporaryDirectory() as tmp:
        if backend == "sqlite":
            src = {"PIO_STORAGE_SOURCES_S_TYPE": "sqlite",
                   "PIO_STORAGE_SOURCES_S_PATH": f"{tmp}/pio.db"}
        else:
            # metadata stays sqlite (binevents is an event store);
            # events go to the native log
            src = {"PIO_STORAGE_SOURCES_S_TYPE": "sqlite",
                   "PIO_STORAGE_SOURCES_S_PATH": f"{tmp}/pio.db",
                   "PIO_STORAGE_SOURCES_B_TYPE": "binevents",
                   "PIO_STORAGE_SOURCES_B_PATH": f"{tmp}/binevents"}
        storage = Storage({
            **src,
            "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "S",
            "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE":
                "B" if backend == "binevents" else "S",
            "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "S",
        })
        app_id = storage.get_meta_data_apps().insert(App(0, "BenchApp"))
        storage.get_meta_data_access_keys().insert(
            AccessKey("bench-key", app_id, []))
        storage.get_events().init(app_id)
        server = EventServer(
            storage, EventServerConfig(ip="127.0.0.1", port=0))
        server.start()
        try:
            url = (f"http://127.0.0.1:{server.port}/batch/events.json"
                   f"?accessKey=bench-key")
            payload = [
                {"event": "rate", "entityType": "user",
                 "entityId": f"u{j % 97}", "targetEntityType": "item",
                 "targetEntityId": f"i{j % 53}",
                 "properties": {"rating": float(j % 5 + 1)}}
                for j in range(batch)
            ]
            body = _json.dumps(payload).encode()

            def post():
                req = urllib.request.Request(
                    url, data=body,
                    headers={"Content-Type": "application/json"})
                with urllib.request.urlopen(req, timeout=30) as r:
                    r.read()

            for _ in range(4):  # warm connections/WAL
                post()
            posted = (n_events // batch) * batch
            reps = 3
            rates = []
            for _ in range(reps):
                t0 = time.perf_counter()
                for _ in range(n_events // batch):
                    post()
                rates.append(posted / (time.perf_counter() - t0))
        finally:
            server.stop()
    mean = statistics.fmean(rates)
    stdev_pct = 100.0 * statistics.stdev(rates) / mean
    return round(max(rates), 1), round(stdev_pct, 1), reps


# ---------------------------------------------------------------------------
# Quality (parity + ranking-wins) and the rank-200 quality validation
# ---------------------------------------------------------------------------


def bench_quality():
    from predictionio_tpu.data.movielens import synthesize_ml100k
    from predictionio_tpu.e2 import quality

    ds = synthesize_ml100k()
    q = quality.compare_quality(ds, rank=10, iterations=10, lam=0.05,
                                k_fold=5)
    out = {
        "map10_tpu": q["map10_tpu"],
        "map10_ref": q["map10_ref"],
        "map10_popularity": q["map10_popularity"],
        # ranking-WINS key (vs the parity keys above): the implicit
        # path must beat the popularity baseline; explicit ALS does not
        # (MLlib's doesn't either — it models rating values, not
        # interaction propensity)
        "map10_implicit": q["map10_implicit"],
        "rmse_tpu": q["rmse_tpu"],
        "rmse_ref": q["rmse_ref"],
    }
    out.update(_real_data_ranking())
    out.update(_rank200_quality(ds))
    return out


def _real_data_ranking():
    """Implicit-vs-popularity on the vendored REAL Spark sample dataset
    (examples/data/sample_movielens.txt — public data, not generated by
    us), mean over all 5 folds (VERDICT r3 weak #1: the ranking gate
    must not rest solely on the synthetic generator). 30x100, ~1.5k
    ratings: error bars are wide by construction and the keys are
    REPORTING, not a gate — the gate's domain of validity is stated in
    README."""
    import os

    from predictionio_tpu.data.movielens import load_ratings_file
    from predictionio_tpu.e2 import quality

    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "examples", "data", "sample_movielens.txt")
    r = quality.implicit_vs_popularity_kfold(load_ratings_file(path))
    return {
        "map10_implicit_real": round(r["map10_implicit"], 4),
        "map10_popularity_real": round(r["map10_popularity"], 4),
    }


def _rank200_quality(ds, iterations: int = 5, lam: float = 0.1):
    """Rank-200 RMSE parity: device ALS at the BASELINE rank vs an
    exact per-row NumPy solver on the same fold — validates the CG step
    cap at the rank where it matters (VERDICT r2 missing #2 /
    ADVICE r2 medium)."""
    from predictionio_tpu.e2 import quality
    from predictionio_tpu.ops.als import RatingsCOO, als_train

    train, test_by_user = quality.kfold_split(ds, k_fold=5)
    f = als_train(
        RatingsCOO(train.users, train.items, train.ratings,
                   train.num_users, train.num_items),
        rank=RANK200, iterations=iterations, lam=lam, seed=3)
    rmse_tpu = quality.test_rmse(f.user, f.item, test_by_user)
    U, V = quality.numpy_als_wr_rowloop(
        train, rank=RANK200, iterations=iterations, lam=lam, seed=4)
    rmse_ref = quality.test_rmse(U, V, test_by_user)
    return {
        "rank200_rmse_tpu": round(rmse_tpu, 4),
        "rank200_rmse_ref": round(rmse_ref, 4),
    }


# ---------------------------------------------------------------------------
# sessionrec transformer: dense, long-context training, flash serving
# ---------------------------------------------------------------------------


def bench_seqrec(steps: int = 20, batch: int = 64):
    import jax
    import jax.numpy as jnp

    from predictionio_tpu.models.seqrec import (
        SeqRecConfig,
        init_params,
        make_train_step,
    )

    cfg = SeqRecConfig(vocab=50_000, max_len=256, d_model=256, n_heads=4,
                       n_layers=4)
    s, d, v, layers = cfg.max_len, cfg.d_model, cfg.vocab, cfg.n_layers
    rng = np.random.default_rng(5)
    seqs = rng.integers(1, v, size=(batch, s), dtype=np.int64).astype(np.int32)
    targets = rng.integers(1, v, size=(batch, s), dtype=np.int64).astype(np.int32)

    params0 = init_params(jax.random.PRNGKey(0), cfg)
    opt_m0 = jax.tree.map(jnp.zeros_like, params0)
    opt_v0 = jax.tree.map(jnp.zeros_like, params0)
    step_fn = make_train_step(cfg)

    def run(n):
        params, opt_m, opt_v = params0, opt_m0, opt_v0
        for i in range(n):
            params, opt_m, opt_v, loss = step_fn(
                params, opt_m, opt_v, i + 1, seqs, targets, 1e-3)
        return float(loss)

    run(1)  # compile
    t0 = time.perf_counter()
    run(2)
    t_short = time.perf_counter() - t0
    t0 = time.perf_counter()
    loss = run(2 + steps)
    dt = (time.perf_counter() - t0) - t_short

    tokens = batch * s * steps
    # fwd FLOPs/token: per layer qkv 6d^2 + wo 2d^2 + mlp 16d^2 (mult 4)
    # + attention 4Sd; tied-logits 2dV. Training ~= 3x fwd.
    per_token = 3.0 * (layers * (24.0 * d * d + 4.0 * s * d) + 2.0 * d * v)
    _, peak = _device_peak()
    out = {
        "seqrec_tokens_per_sec": round(tokens / dt, 1),
        "seqrec_loss": round(float(loss), 3),
    }
    if peak:
        out["seqrec_mfu_pct"] = round(
            100.0 * tokens * per_token / dt / peak, 2)
    out.update(bench_seqrec_longcontext())
    return out


def bench_seqrec_longcontext(steps: int = 4):
    """The long-context ladder's tracked numbers (VERDICT r2 weak #7):
    training step rate at S=4096 (blockwise attention path) and serving
    p50 at S=2048 (predict_topk end to end)."""
    import jax
    import jax.numpy as jnp

    from predictionio_tpu.models.seqrec import (
        PAD,
        SeqRecConfig,
        init_params,
        make_train_step,
        predict_topk,
    )

    out = {}
    rng = np.random.default_rng(6)

    # --- S=4096 training (forward routes through blockwise_attention)
    cfg = SeqRecConfig(vocab=50_000, max_len=4096, d_model=256, n_heads=4,
                       n_layers=4)
    batch = 4
    seqs = rng.integers(1, cfg.vocab, size=(batch, cfg.max_len),
                        dtype=np.int64).astype(np.int32)
    tgts = rng.integers(1, cfg.vocab, size=(batch, cfg.max_len),
                        dtype=np.int64).astype(np.int32)
    params0 = init_params(jax.random.PRNGKey(0), cfg)
    m0 = jax.tree.map(jnp.zeros_like, params0)
    v0 = jax.tree.map(jnp.zeros_like, params0)
    step_fn = make_train_step(cfg)

    def run(n):
        params, m, v = params0, m0, v0
        for i in range(n):
            params, m, v, loss = step_fn(params, m, v, i + 1, seqs, tgts,
                                         1e-3)
        return float(loss)

    run(1)
    per_step, _ = _chain_time(run, n_short=1, n_long=1 + steps, reps=2)
    out["seqrec_s4096_tokens_per_sec"] = round(
        batch * cfg.max_len / per_step, 1)

    # --- S=2048 serving p50 (predict_topk end to end)
    scfg = SeqRecConfig(vocab=50_000, max_len=2048, d_model=256, n_heads=4,
                        n_layers=4)
    sparams = init_params(jax.random.PRNGKey(1), scfg)
    hist = rng.integers(1, scfg.vocab, size=(1, scfg.max_len),
                        dtype=np.int64).astype(np.int32)
    vocab_mask = jnp.zeros((scfg.vocab,), dtype=jnp.float32)

    lats = []
    predict_topk(sparams, jnp.asarray(hist), 10, scfg, vocab_mask)  # compile
    for j in range(40):
        h = jnp.asarray(
            np.where(hist == 0, 0, (hist + j) % (scfg.vocab - 1) + 1)
            .astype(np.int32))
        t0 = time.perf_counter()
        v_, i_ = predict_topk(sparams, h, 10, scfg, vocab_mask)
        float(jnp.sum(v_)) + float(jnp.sum(i_))   # forcing fetch
        lats.append(time.perf_counter() - t0)
    out["seqrec_serve_s2048_p50_ms"] = round(
        float(np.percentile(lats, 50)) * 1e3, 2)
    return out


# ---------------------------------------------------------------------------


def _retry_once(fn, label: str):
    """One retry for the pre-section headline path: it runs BEFORE the
    per-section failure isolation, so a transient tunnel error there
    (observed: 'remote_compile: response body closed before all bytes
    were read') would otherwise cost the driver the ENTIRE artifact."""
    import sys

    try:
        return fn()
    except Exception as e:
        print(f"# {label} failed ({type(e).__name__}: {e}); retrying once",
              file=sys.stderr)
        return fn()


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--skip-heavy", action="store_true",
                        help="headline + quality + ingest only")
    args = parser.parse_args()

    users, items, vals = make_ratings(NNZ)
    calib = _retry_once(bench_calibration, "calibration")
    als, user_f, item_f = _retry_once(
        lambda: bench_als(users, items, vals), "als_headline")
    line = {
        "metric": "als_train_throughput_ml20m_rank32",
        "value": round(als.pop("rate"), 1),
        "unit": "ratings/sec",
        **als,
    }

    line.update(calib)

    base = bench_numpy_baseline(users, items, vals)
    line["vs_baseline"] = round(line["value"] / base["baseline_rate"], 2)
    line["vs_baseline_64core"] = round(
        line["value"] / base["baseline_64core_rate"], 2)
    line.update(base)

    sections = [
        ("phases", lambda: bench_phases(users, items, vals)),
        ("rank200", lambda: bench_rank200(users, items, vals)),
        ("serving", lambda: bench_serving(user_f, item_f, users, items)),
        ("serving_path", bench_serving_path),
        ("attention", bench_attention),
        ("quality", bench_quality),
        ("seqrec", bench_seqrec),
        ("ingest", bench_ingest),
        ("data_plane", bench_data_plane),
        ("ann_retrieval",
         lambda: bench_ann_retrieval(shrunk=args.skip_heavy)),
        ("workers_scaling",
         lambda: bench_workers_scaling(shrunk=args.skip_heavy)),
        ("shm_cache",
         lambda: bench_shm_cache(shrunk=args.skip_heavy)),
        ("gateway",
         lambda: bench_gateway_phase(shrunk=args.skip_heavy)),
        ("freshness",
         lambda: bench_freshness_section(shrunk=args.skip_heavy)),
        ("elasticity",
         lambda: bench_elasticity_section(shrunk=args.skip_heavy)),
        ("experiment",
         lambda: bench_experiment_section(shrunk=args.skip_heavy)),
        ("train_profile", bench_train_profile),
        ("train_sharding",
         lambda: bench_train_sharding(shrunk=args.skip_heavy)),
    ]
    failed = []
    if args.skip_heavy:
        # skipped sections' keys are absent, which IS an incomplete
        # artifact — the completeness marker must say so. data_plane
        # stays: it is CPU+storage bound like ingest, no device needed;
        # ann_retrieval runs SHRUNK (one small indexable catalog), and
        # workers_scaling SHRUNK (small catalog, no 1M ANN re-run);
        # train_profile is a seconds-scale tiny train either way
        # freshness rides along shrunk: CPU + storage bound like
        # data_plane, no device involvement
        # gateway rides along shrunk: CPU + loopback HTTP bound, no
        # device involvement
        # elasticity rides along shrunk: router threads + stdlib echo
        # backends + a ManualClock timeline, no device involvement
        # experiment rides along shrunk: fork eval children + a
        # single-threaded controller loop, no device involvement
        # shm_cache rides along shrunk: subprocess serving pools +
        # loopback HTTP + one POSIX shm segment, no device involvement
        # train_sharding rides along shrunk: a seconds-scale forced-8-
        # device subprocess child (tiny matched-shape parity + a small
        # sharded point — same contract as the full artifact)
        keep = ("quality", "ingest", "data_plane", "ann_retrieval",
                "workers_scaling", "freshness", "train_profile",
                "gateway", "elasticity", "experiment", "shm_cache",
                "train_sharding")
        failed.extend(s[0] for s in sections if s[0] not in keep)
        sections = [s for s in sections if s[0] in keep]
    for section, fn in sections:
        try:
            line.update(fn())
        except Exception as e:  # keep the primary metric on partial failure
            line[f"error_{section}"] = f"{type(e).__name__}: {e}"
            failed.append(section)
    # ingest reports per-backend errors without raising (isolation)
    failed.extend(k.removeprefix("error_") for k in line
                  if k.startswith("error_ingest_"))
    # an incomplete artifact must be impossible to mistake for a
    # complete one (VERDICT r4 weak #7) — always present, [] = complete
    line["sections_failed"] = failed

    if {"iter_ms", "phase_gather_ms", "phase_einsum_ms"} <= line.keys():
        # the CG-solve + factor-write-back remainder of the headline
        # iteration (VERDICT r3 weak #5: without it a solver regression
        # is invisible round-over-round)
        line["phase_solve_ms"] = round(
            line["iter_ms"] - line["phase_gather_ms"]
            - line["phase_einsum_ms"], 1)
    if {"rank200_iter_ms", "calibration_matmul_ms"} <= line.keys():
        # session-normalized rank-200 quote (VERDICT r4 weak #6):
        # identical programs measured 330-497 ms/iter across sessions;
        # dividing by the constant-workload anchor makes a
        # round-over-round comparison regime-adjusted
        line["rank200_iter_per_calib"] = round(
            line["rank200_iter_ms"] / line["calibration_matmul_ms"], 1)

    print(json.dumps(line))


if __name__ == "__main__":
    main()
