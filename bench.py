"""Benchmark entry: prints ONE JSON line
{"metric": ..., "value": N, "unit": ..., "vs_baseline": N}.

North star (BASELINE.md): MovieLens ALS ratings/sec vs Spark-on-CPU; until
the sharded ALS engine lands this measures the NaiveBayes training
throughput (samples/sec) on the available accelerator.

vs_baseline: ratio vs the Spark-CPU-equivalent figure. The reference
publishes no numbers (BASELINE.md); the comparison base used here is a
numpy single-core implementation of the same computation measured in
the same run — honest, reproducible on this machine.
"""

from __future__ import annotations

import json
import time

import numpy as np


def _numpy_nb(features, labels, num_classes, smoothing=1.0):
    one_hot = np.zeros((len(labels), num_classes), dtype=np.float32)
    one_hot[np.arange(len(labels)), labels] = 1.0
    class_counts = one_hot.sum(axis=0)
    feature_sums = one_hot.T @ features
    log_prior = np.log(class_counts) - np.log(class_counts.sum())
    log_theta = np.log(feature_sums + smoothing) - np.log(
        feature_sums.sum(axis=1, keepdims=True) + smoothing * features.shape[1]
    )
    return log_prior, log_theta


def main() -> None:
    import jax

    from predictionio_tpu.models.naive_bayes import train_multinomial

    n, f, c = 2_000_000, 64, 16
    rng = np.random.default_rng(0)
    features = rng.poisson(3.0, size=(n, f)).astype(np.float32)
    labels = rng.integers(0, c, size=n).astype(np.int32)

    # numpy single-core baseline
    t0 = time.perf_counter()
    _numpy_nb(features, labels, c)
    numpy_s = time.perf_counter() - t0

    # stage data on device once (the data path keeps training batches
    # resident; transfer overlaps ingest in the real pipeline)
    import jax.numpy as jnp

    f_dev = jax.device_put(jnp.asarray(features))
    l_dev = jax.device_put(jnp.asarray(labels))
    jax.block_until_ready(f_dev)

    # warm up (compile)
    jax.block_until_ready(train_multinomial(f_dev, l_dev, c).log_theta)
    t0 = time.perf_counter()
    reps = 5
    for _ in range(reps):
        model = train_multinomial(f_dev, l_dev, c)
    jax.block_until_ready(model.log_theta)
    jax_s = (time.perf_counter() - t0) / reps

    samples_per_sec = n / jax_s
    print(
        json.dumps(
            {
                "metric": "naive_bayes_train_throughput",
                "value": round(samples_per_sec, 1),
                "unit": "samples/sec",
                "vs_baseline": round((n / numpy_s) and samples_per_sec / (n / numpy_s), 2),
            }
        )
    )


if __name__ == "__main__":
    main()
