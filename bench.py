"""Benchmark entry: prints ONE JSON line
{"metric": ..., "value": N, "unit": ..., "vs_baseline": N}.

North star (BASELINE.md): MovieLens-20M-scale ALS training throughput in
ratings/sec on the available accelerator, vs a Spark-on-CPU-class
baseline. The reference publishes no numbers (BASELINE.md `published: {}`),
so the comparison base is measured in the same run: a NumPy
single-process implementation of the identical bucketed normal-equation
solves (the per-core work a Spark executor would do), on a subsample —
ratings/sec is size-normalized, so the rates compare directly.

Dataset: synthetic ratings with MovieLens-20M's shape (138,493 users ×
26,744 items × 20M ratings, power-law degree skew), rank 32. Timing
excludes compilation (one warm-up iteration covers every bucket shape)
and measures full alternating iterations (user half + item half).
"""

from __future__ import annotations

import json
import time

import numpy as np

USERS = 138_493
ITEMS = 26_744
NNZ = 20_000_000
RANK = 32
LAM = 0.08
ITERS = 3
SUB_NNZ = 2_000_000  # numpy-baseline subsample


def make_ratings(nnz: int, seed: int = 0):
    """Power-law-skewed synthetic (user, item, rating) triples."""
    rng = np.random.default_rng(seed)
    users = (USERS * rng.random(nnz) ** 1.8).astype(np.int32)
    items = (ITEMS * rng.random(nnz) ** 1.8).astype(np.int32)
    vals = rng.integers(1, 11, size=nnz).astype(np.float32) / 2.0
    return users, items, vals


def numpy_half_solve(V, bucketed, rank, lam):
    """The same bucketed ALS-WR half-step in single-process NumPy."""
    out = np.zeros((bucketed.num_rows, rank), dtype=np.float32)
    eye = np.eye(rank, dtype=np.float32)
    for b in bucketed.buckets:
        F = V[b.cols]                        # (n, L, K)
        Fm = F * b.mask[..., None]
        A = np.einsum("blk,blm->bkm", Fm, F)
        n_u = b.mask.sum(axis=1)
        A = A + (lam * n_u)[:, None, None] * eye
        rhs = np.einsum("bl,blk->bk", b.vals * b.mask, F)
        A[n_u == 0] = eye
        x = np.linalg.solve(A, rhs[..., None])[..., 0]
        x[n_u == 0] = 0.0
        out[b.row_ids] = x
    return out


def main() -> None:
    import jax

    from predictionio_tpu.ops.als import (
        RatingsCOO,
        bucket_rows,
        solve_half,
        stage_buckets,
    )

    bucket_kw = dict(min_len=128, growth=8, max_len=1024)

    users, items, vals = make_ratings(NNZ)
    coo = RatingsCOO(users, items, vals, USERS, ITEMS)
    by_user = bucket_rows(coo, **bucket_kw)
    by_item = bucket_rows(coo.transpose(), **bucket_kw)

    rng = np.random.default_rng(1)
    item_f0 = (rng.standard_normal((ITEMS, RANK)) / np.sqrt(RANK)).astype(np.float32)

    # ---- TPU path ----------------------------------------------------------
    import jax.numpy as jnp

    item_f = jax.device_put(jnp.asarray(item_f0))
    # slabs staged in HBM once; iterations measure pure device compute
    dev_user = stage_buckets(by_user, RANK)
    dev_item = stage_buckets(by_item, RANK)

    def iteration(item_f):
        user_f = solve_half(item_f, dev_user, RANK, LAM)
        item_f = solve_half(user_f, dev_item, RANK, LAM)
        return user_f, item_f

    # warm-up compiles every bucket-shape kernel
    user_f, item_w = iteration(item_f)
    jax.block_until_ready(item_w)

    t0 = time.perf_counter()
    for _ in range(ITERS):
        user_f, item_f = iteration(item_f)
    jax.block_until_ready(item_f)
    tpu_iter_s = (time.perf_counter() - t0) / ITERS
    tpu_rate = NNZ / tpu_iter_s

    # ---- NumPy single-process baseline (subsample; rate is normalized) -----
    s_users, s_items, s_vals = users[:SUB_NNZ], items[:SUB_NNZ], vals[:SUB_NNZ]
    sub = RatingsCOO(s_users, s_items, s_vals, USERS, ITEMS)
    sub_user = bucket_rows(sub, **bucket_kw)
    sub_item = bucket_rows(sub.transpose(), **bucket_kw)
    t0 = time.perf_counter()
    uf = numpy_half_solve(item_f0, sub_user, RANK, LAM)
    numpy_half_solve(uf, sub_item, RANK, LAM)
    numpy_iter_s = time.perf_counter() - t0
    numpy_rate = SUB_NNZ / numpy_iter_s

    print(
        json.dumps(
            {
                "metric": "als_train_throughput_ml20m_rank32",
                "value": round(tpu_rate, 1),
                "unit": "ratings/sec",
                "vs_baseline": round(tpu_rate / numpy_rate, 2),
            }
        )
    )


if __name__ == "__main__":
    main()
