"""Similarproduct template, add-rateevent variant.

Mirror of the reference's add-rateevent variant (reference:
examples/scala-parallel-similarproduct/add-rateevent/): the DataSource
reads "rate" events carrying a ``rating`` property instead of binary
views (DataSource.scala:80-111), a user re-rating the same item keeps
only the LATEST rating (ALSAlgorithm.scala:105-113 reduceByKey on
event time), and training switches from ``ALS.trainImplicit`` to
EXPLICIT ``ALS.train`` on the rating values (ALSAlgorithm.scala:128).
Queries and cosine-similarity serving are unchanged.

TPU design note: the keep-latest dedup is one vectorized host pass
(lexsort by (user, item, time), keep each group's last) before the
COO build — no shuffle, no reduceByKey. Explicit training reuses
ops/als.als_train(implicit=False), the same ALS-WR kernel the
recommendation template runs.
"""

from __future__ import annotations

import numpy as np

from predictionio_tpu.controller import Engine, FirstServing
from predictionio_tpu.templates.similarproduct import (
    DataSourceParams,
    SimilarALSAlgorithm,
    SimilarProductDataSource,
    SimilarProductPreparator,
    SimilarTrainingData,
)


class RateEventDataSource(SimilarProductDataSource):
    """Reads user-rate-item events; keeps the latest rating per
    (user, item) pair."""

    params_class = DataSourceParams

    def read_training(self, ctx) -> SimilarTrainingData:
        p = self.params
        users, items, ratings, times = [], [], [], []
        for ev in ctx.event_store().find(
            p.app_name,
            entity_type=p.entity_type,
            event_names=["rate"],
            target_entity_type=p.target_entity_type,
        ):
            if ev.target_entity_id is None:
                continue
            rating = ev.properties.get_opt("rating")
            if rating is None:
                continue
            users.append(ev.entity_id)
            items.append(ev.target_entity_id)
            ratings.append(float(rating))
            times.append(ev.event_time.timestamp() if ev.event_time
                         else 0.0)
        # keep-latest per (user, item): stable sort by time, then one
        # pass keeping each pair's last occurrence (the reference's
        # reduceByKey-on-t, ALSAlgorithm.scala:105-113, as a host pass)
        latest: dict[tuple[str, str], int] = {}
        order = np.argsort(np.asarray(times), kind="stable")
        for j in order:
            latest[(users[j], items[j])] = int(j)
        keep = sorted(latest.values())
        categories: dict[str, tuple] = {}
        props = ctx.event_store().aggregate_properties(
            p.app_name, p.item_entity_type)
        for item_id, pm in props.items():
            cats = pm.get_opt("categories")
            if cats:
                categories[item_id] = tuple(cats)
        return SimilarTrainingData(
            users=np.asarray([users[j] for j in keep], dtype=object),
            items=np.asarray([items[j] for j in keep], dtype=object),
            ratings=np.asarray([ratings[j] for j in keep],
                               dtype=np.float32),
            categories=categories,
        )


class RateEventALSAlgorithm(SimilarALSAlgorithm):
    """Explicit ALS-WR on the rating values (the reference variant's
    ALS.train swap, ALSAlgorithm.scala:128); serving unchanged."""

    implicit_prefs = False


def engine_factory() -> Engine:
    return Engine(
        data_source_class_map=RateEventDataSource,
        preparator_class_map=SimilarProductPreparator,
        algorithm_class_map={"als": RateEventALSAlgorithm},
        serving_class_map=FirstServing,
    )
