"""Seed RateEventApp: two taste communities rating 16 items 1-5, with
some re-rates (only the latest counts). Run after
`pio app new RateEventApp`."""

import sys

import numpy as np

from predictionio_tpu.core.datamap import DataMap
from predictionio_tpu.core.event import Event
from predictionio_tpu.storage.registry import Storage

storage = Storage.default()
app = storage.get_meta_data_apps().get_by_name("RateEventApp")
if app is None:
    sys.exit("app 'RateEventApp' not found — run "
             "`pio app new RateEventApp` first")

events = storage.get_events()
rng = np.random.default_rng(17)
n = 0
for u in range(20):
    for i in range(16):
        if rng.random() < 0.7:
            liked = i % 2 == u % 2
            rating = float(rng.integers(4, 6) if liked
                           else rng.integers(1, 3))
            events.insert(
                Event(event="rate", entity_type="user", entity_id=f"u{u}",
                      target_entity_type="item", target_entity_id=f"i{i}",
                      properties=DataMap({"rating": rating})),
                app.id,
            )
            n += 1
print(f"seeded {n} rate events into RateEventApp (app id {app.id})")
