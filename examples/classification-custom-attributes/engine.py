"""Classification template, custom-attributes variant.

Mirror of the reference's custom-attributes variant (reference:
examples/scala-parallel-classification/custom-attributes/): users carry
CATEGORICAL string attributes — ``gender`` ("Male"/"Female") and
``education`` ("No School"/"High School"/"College") — plus numeric
``age``, labeled by ``plan``. The DataSource maps the categorical
values to numerics with fixed maps carried through training
(DataSource.scala:46-75), queries arrive as
``{"gender": "Female", "age": 30, "education": "College"}``
(Engine.scala:23-28), and the algorithm is a random forest
(RandomForestAlgorithm.scala:43-56 — MLlib
RandomForest.trainClassifier; here models/random_forest: host CART
growth + jitted flattened-tree batched inference).

Only users with ALL FOUR properties train (the reference's
``required = Some(List("plan","gender","age","education"))``,
DataSource.scala:52 — incomplete users are silently skipped, not
errors). An unknown categorical value in a QUERY is a client error and
returns a clear ValueError, where the reference would throw a
NoSuchElementException from the raw map lookup.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from predictionio_tpu.controller import (
    DataSource,
    Engine,
    FirstServing,
    HostModelAlgorithm,
    IdentityPreparator,
    Params,
    SanityCheck,
)
from predictionio_tpu.models.random_forest import (
    ForestModel,
    predict_forest,
    train_forest,
)
from predictionio_tpu.utils.bimap import BiMap

GENDERS = {"Male": 0.0, "Female": 1.0}
EDUCATIONS = {"No School": 0.0, "High School": 1.0, "College": 2.0}


@dataclasses.dataclass(frozen=True)
class Query:
    """Parity: custom-attributes Engine.scala:23-28."""

    gender: str
    age: float
    education: str


@dataclasses.dataclass(frozen=True)
class PredictedResult:
    label: str
    scores: dict


@dataclasses.dataclass(frozen=True)
class CustomAttrTrainingData(SanityCheck):
    features: np.ndarray          # (N, 3) [gender, age, education]
    labels: np.ndarray            # (N,) int
    label_map: BiMap

    def sanity_check(self) -> None:
        if len(self.features) == 0:
            raise ValueError(
                "no users with plan/gender/age/education properties; "
                "ingest $set events first")


@dataclasses.dataclass(frozen=True)
class CustomAttrDataSourceParams(Params):
    app_name: str = ""
    entity_type: str = "user"


class CustomAttrDataSource(DataSource):
    """Featurizes gender/age/education with the fixed categorical maps
    (DataSource.scala:46-75); only complete users train."""

    params_class = CustomAttrDataSourceParams

    def read_training(self, ctx) -> CustomAttrTrainingData:
        p = self.params
        props = ctx.event_store().aggregate_properties(
            p.app_name, p.entity_type)
        feats, labels = [], []
        for entity_id, pm in props.items():
            plan = pm.get_opt("plan")
            gender = pm.get_opt("gender")
            age = pm.get_opt("age")
            education = pm.get_opt("education")
            if None in (plan, gender, age, education):
                continue          # required-properties filter
            if gender not in GENDERS or education not in EDUCATIONS:
                continue          # unmapped categorical: skip like missing
            feats.append([GENDERS[gender], float(age),
                          EDUCATIONS[education]])
            labels.append(str(plan))
        label_map = BiMap.string_int(labels)
        return CustomAttrTrainingData(
            features=np.asarray(feats, dtype=np.float32).reshape(-1, 3),
            labels=np.asarray([label_map[l] for l in labels],
                              dtype=np.int64),
            label_map=label_map,
        )


@dataclasses.dataclass(frozen=True)
class RandomForestParams(Params):
    """Parity: RandomForestAlgorithm.scala:33-41 (numTrees/maxDepth;
    featureSubsetStrategy; impurity fixed to gini)."""

    num_trees: int = 10
    max_depth: int = 5
    feature_subset: str = "all"   # 3 features: use them all per split
    seed: int = 0


@dataclasses.dataclass
class RFModel:
    forest: ForestModel
    label_map: BiMap


class RandomForestAlgorithm(HostModelAlgorithm):
    """models/random_forest in the DASE slot MLlib RandomForest held."""

    params_class = RandomForestParams
    query_class = Query

    def train(self, ctx, pd: CustomAttrTrainingData) -> RFModel:
        p = self.params
        forest = train_forest(
            pd.features, pd.labels, num_classes=len(pd.label_map),
            num_trees=p.num_trees, max_depth=p.max_depth,
            feature_subset=p.feature_subset, seed=p.seed)
        return RFModel(forest=forest, label_map=pd.label_map)

    def _featurize(self, query: Query) -> np.ndarray:
        if query.gender not in GENDERS:
            raise ValueError(
                f"unknown gender {query.gender!r}; expected one of "
                f"{sorted(GENDERS)}")
        if query.education not in EDUCATIONS:
            raise ValueError(
                f"unknown education {query.education!r}; expected one of "
                f"{sorted(EDUCATIONS)}")
        return np.asarray(
            [[GENDERS[query.gender], float(query.age),
              EDUCATIONS[query.education]]], dtype=np.float32)

    def predict(self, model: RFModel, query: Query) -> PredictedResult:
        votes = predict_forest(model.forest, self._featurize(query))[0]
        inv = model.label_map.inverse
        scores = {inv[i]: float(v) / model.forest.num_trees
                  for i, v in enumerate(votes)}
        return PredictedResult(
            label=inv[int(votes.argmax())], scores=scores)


def engine_factory() -> Engine:
    return Engine(
        data_source_class_map=CustomAttrDataSource,
        preparator_class_map=IdentityPreparator,
        algorithm_class_map={"randomforest": RandomForestAlgorithm},
        serving_class_map=FirstServing,
    )
