"""Seed CustomAttrApp: users whose plan correlates with age/education.
Run after `pio app new CustomAttrApp`."""

import sys

import numpy as np

from predictionio_tpu.core.datamap import DataMap
from predictionio_tpu.core.event import Event
from predictionio_tpu.storage.registry import Storage

storage = Storage.default()
app = storage.get_meta_data_apps().get_by_name("CustomAttrApp")
if app is None:
    sys.exit("app 'CustomAttrApp' not found — run "
             "`pio app new CustomAttrApp` first")

events = storage.get_events()
rng = np.random.default_rng(23)
genders = ["Male", "Female"]
educations = ["No School", "High School", "College"]
n = 0
for u in range(120):
    gender = genders[int(rng.integers(0, 2))]
    education = educations[int(rng.integers(0, 3))]
    age = float(rng.integers(18, 70))
    # plan: college grads and the young skew premium
    premium = (education == "College") or (age < 30 and rng.random() < 0.7)
    events.insert(
        Event(event="$set", entity_type="user", entity_id=f"u{u}",
              properties=DataMap({
                  "plan": "premium" if premium else "basic",
                  "gender": gender, "age": age, "education": education,
              })),
        app.id,
    )
    n += 1
print(f"seeded {n} users into CustomAttrApp (app id {app.id})")
