"""A from-scratch DASE engine: entity similarity over word sets.

Demonstrates the controller API without any template: typed params,
reading aggregated properties from the event store, a jitted compute
kernel, and a custom Query/PredictedResult pair. See docs/dase.md.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from predictionio_tpu.controller import (
    DataSource,
    Engine,
    FirstServing,
    HostModelAlgorithm,
    IdentityPreparator,
    Params,
)


@dataclasses.dataclass(frozen=True)
class Query:
    entity: str = ""
    num: int = 3


@dataclasses.dataclass(frozen=True)
class Neighbor:
    entity: str
    score: float


@dataclasses.dataclass(frozen=True)
class PredictedResult:
    neighbors: tuple = ()


@dataclasses.dataclass(frozen=True)
class DSParams(Params):
    app_name: str = ""
    entity_type: str = "doc"


class WordsDataSource(DataSource):
    params_class = DSParams

    def read_training(self, ctx):
        props = ctx.event_store().aggregate_properties(
            self.params.app_name, self.params.entity_type, required=["words"]
        )
        return {
            entity_id: tuple(pm.get("words", list))
            for entity_id, pm in sorted(props.items())
        }


@dataclasses.dataclass(frozen=True)
class AlgoParams(Params):
    pass


@dataclasses.dataclass
class SimilarityModel:
    entities: list
    vectors: np.ndarray  # (n, vocab) L2-normalised
    # device-resident copy, populated on first predict and dropped from
    # pickles (the framework's device-weight-cache practice)
    _device_vectors: object = dataclasses.field(default=None, repr=False,
                                                compare=False)

    def __getstate__(self):
        state = self.__dict__.copy()
        state["_device_vectors"] = None
        return state


class CosineAlgorithm(HostModelAlgorithm):
    params_class = AlgoParams
    query_class = Query

    def train(self, ctx, td: dict) -> SimilarityModel:
        vocab = sorted({w for words in td.values() for w in words})
        w_ix = {w: i for i, w in enumerate(vocab)}
        entities = list(td)
        mat = np.zeros((len(entities), max(len(vocab), 1)), np.float32)
        for r, e in enumerate(entities):
            for w in td[e]:
                mat[r, w_ix[w]] = 1.0
        norm = np.linalg.norm(mat, axis=1, keepdims=True)
        mat = mat / np.maximum(norm, 1e-9)
        return SimilarityModel(entities=entities, vectors=np.asarray(mat))

    def predict(self, model: SimilarityModel, query: Query) -> PredictedResult:
        import jax
        import jax.numpy as jnp

        if query.entity not in model.entities:
            return PredictedResult()
        if model._device_vectors is None:
            model._device_vectors = jax.device_put(model.vectors)
        row = model.entities.index(query.entity)
        vecs = model._device_vectors              # HBM-resident between queries
        sims = vecs @ vecs[row]
        sims = sims.at[row].set(-1.0)             # exclude self
        k = max(0, min(query.num, len(model.entities) - 1))
        if k == 0:
            return PredictedResult()
        vals, idxs = jax.lax.top_k(sims, k)
        return PredictedResult(neighbors=tuple(
            Neighbor(entity=model.entities[int(i)], score=float(v))
            for v, i in zip(vals, idxs) if v > -1.0
        ))


def engine_factory() -> Engine:
    return Engine(
        data_source_class_map=WordsDataSource,
        preparator_class_map=IdentityPreparator,
        algorithm_class_map={"cosine": CosineAlgorithm},
        serving_class_map=FirstServing,
    )
