"""Seed $set events for the hello-similarity example."""

from predictionio_tpu.core.datamap import DataMap
from predictionio_tpu.core.event import Event
from predictionio_tpu.storage.registry import Storage

DOCS = {
    "doc1": ["jax", "tpu", "mesh", "sharding"],
    "doc2": ["jax", "tpu", "pallas", "kernel"],
    "doc3": ["http", "rest", "server", "events"],
    "doc4": ["mesh", "sharding", "collective", "tpu"],
}

storage = Storage.default()
app = storage.get_meta_data_apps().get_by_name("HelloApp")
if app is None:
    raise SystemExit("app 'HelloApp' not found — run: pio app new HelloApp")
events = storage.get_events()
for doc, words in DOCS.items():
    events.insert(
        Event(event="$set", entity_type="doc", entity_id=doc,
              properties=DataMap({"words": words})),
        app.id,
    )
print(f"seeded {len(DOCS)} docs into app {app.id}")
