"""Similarproduct template, no-set-user variant.

Mirror of the reference's no-set-user variant (reference:
examples/scala-parallel-similarproduct/no-set-user/): the engine must
work when the app NEVER sends ``$set`` user events — users exist only
as the subjects of view events. The reference had to modify its
DataSource (drop the usersRDD properties read) and its ALSAlgorithm
(build the user index from ``data.viewEvents.map(_.user)`` instead of
the user entity set, ALSAlgorithm.scala:75).

In this framework that behavior is the TEMPLATE DEFAULT:
``SimilarProductDataSource.read_training`` already derives users from
the view events themselves (templates/similarproduct.py), so the
variant is configuration-only — this module re-exports the stock
factory, and the scenario test (tests/test_no_set_user_example.py)
pins the property by training and serving against storage seeded with
ZERO ``$set`` user events. The divergence (a simpler default, not a
missing feature) is documented here and in the README.
"""

from __future__ import annotations

from predictionio_tpu.templates.similarproduct import engine_factory

__all__ = ["engine_factory"]
