"""Seed NoSetUserApp with ONLY view events — no $set of any kind.
Run after `pio app new NoSetUserApp`."""

import sys

import numpy as np

from predictionio_tpu.core.datamap import DataMap
from predictionio_tpu.core.event import Event
from predictionio_tpu.storage.registry import Storage

storage = Storage.default()
app = storage.get_meta_data_apps().get_by_name("NoSetUserApp")
if app is None:
    sys.exit("app 'NoSetUserApp' not found — run "
             "`pio app new NoSetUserApp` first")

events = storage.get_events()
rng = np.random.default_rng(19)
n = 0
for u in range(20):
    for i in range(16):
        if i % 2 == u % 2 and rng.random() < 0.8:
            events.insert(
                Event(event="view", entity_type="user", entity_id=f"u{u}",
                      target_entity_type="item", target_entity_id=f"i{i}",
                      properties=DataMap({})),
                app.id,
            )
            n += 1
print(f"seeded {n} view events into NoSetUserApp (app id {app.id})")
