"""Recommendation template + custom Serving: serve-time item blacklist.

Mirror of the reference's custom-serving variant (reference:
examples/scala-parallel-recommendation/custom-serving/src/main/scala/
Serving.scala): a Serving component with its own Params pointing at a
disabled-products file, re-read on EVERY query so operators can disable
items live — no retrain, no redeploy, just edit the file. Everything
else (DataSource, Preparator, ALS algorithm) is reused straight from
the built-in template; only the Serving class is custom.
"""

from __future__ import annotations

import dataclasses
import os

from predictionio_tpu.controller import Engine, Params, Serving
from predictionio_tpu.templates.recommendation import (
    ALSAlgorithm,
    ALSPreparator,
    PredictedResult,
    Query,
    RecommendationDataSource,
)


@dataclasses.dataclass(frozen=True)
class ServingParams(Params):
    """filepath: one disabled item id per line (ServingParams in the
    reference's custom-serving Serving.scala)."""

    filepath: str = "disabled.txt"


class DisabledItemsServing(Serving):
    """Drops disabled items from the head prediction at serve time."""

    params_class = ServingParams

    def _disabled(self) -> set[str]:
        # re-read per query, like the reference's Source.fromFile in
        # serve(): the file is the live control surface
        if not os.path.exists(self.params.filepath):
            return set()
        with open(self.params.filepath) as f:
            return {line.strip() for line in f if line.strip()}

    def serve(self, query: Query, predictions) -> PredictedResult:
        disabled = self._disabled()
        head = predictions[0]
        return PredictedResult(
            item_scores=tuple(
                s for s in head.item_scores if s.item not in disabled
            )
        )


def engine_factory() -> Engine:
    return Engine(
        data_source_class_map=RecommendationDataSource,
        preparator_class_map=ALSPreparator,
        algorithm_class_map={"als": ALSAlgorithm},
        serving_class_map=DisabledItemsServing,
    )
