"""Markov-chain next-page prediction — an experimental-pattern engine.

Role parity: the reference's experimental engines built on
``e2.MarkovChain`` (reference: e2/src/main/scala/.../engine/
MarkovChain.scala:33-84 — row-normalized top-N transition model used by
pattern engines under examples/experimental/). This example turns each
user's time-ordered ``view`` stream into (page -> next page)
transitions, trains the e2 Markov chain (dense transition build +
``lax.top_k`` on device), and serves "what page comes next".

Demonstrates: a HostModelAlgorithm over an e2 library model, session
ordering from event time, and BiMap id indexing.
"""

from __future__ import annotations

import dataclasses
from collections import defaultdict

from predictionio_tpu.controller import (
    DataSource,
    Engine,
    FirstServing,
    HostModelAlgorithm,
    IdentityPreparator,
    Params,
)
from predictionio_tpu.e2.engine import MarkovChain, MarkovChainModel
from predictionio_tpu.utils.bimap import BiMap


@dataclasses.dataclass(frozen=True)
class Query:
    page: str = ""
    num: int = 3


@dataclasses.dataclass(frozen=True)
class PageScore:
    page: str
    prob: float


@dataclasses.dataclass(frozen=True)
class PredictedResult:
    pages: tuple = ()


@dataclasses.dataclass(frozen=True)
class DSParams(Params):
    app_name: str = ""
    event_name: str = "view"
    entity_type: str = "user"
    target_entity_type: str = "page"


@dataclasses.dataclass(frozen=True)
class TrainingData:
    #: (from_page, to_page) consecutive-view pairs per user stream
    transitions: tuple


class PageViewDataSource(DataSource):
    params_class = DSParams

    def read_training(self, ctx):
        p = self.params
        store = ctx.event_store()
        events = [
            e for e in store.find(
                p.app_name,
                event_names=[p.event_name],
                entity_type=p.entity_type,
            )
            if e.target_entity_id
        ]
        by_user = defaultdict(list)
        for e in events:
            by_user[e.entity_id].append((e.event_time, e.target_entity_id))
        transitions = []
        for _, stream in sorted(by_user.items()):
            stream.sort()
            for (_, a), (_, b) in zip(stream, stream[1:]):
                transitions.append((a, b))
        if not transitions:
            raise ValueError(
                f"no {p.event_name} transitions for app {p.app_name!r}; "
                "need >=2 time-ordered views per user")
        return TrainingData(transitions=tuple(transitions))


@dataclasses.dataclass(frozen=True)
class MCParams(Params):
    top_n: int = 10


@dataclasses.dataclass(frozen=True)
class NextPageModel:
    pages: BiMap
    chain: MarkovChainModel


class MarkovChainAlgorithm(HostModelAlgorithm):
    params_class = MCParams
    query_class = Query

    def train(self, ctx, td: TrainingData) -> NextPageModel:
        pages = BiMap.string_int(
            pid for pair in td.transitions for pid in pair)
        counts = defaultdict(float)
        for a, b in td.transitions:
            counts[(pages[a], pages[b])] += 1.0
        chain = MarkovChain.train(
            n_states=len(pages),
            transitions=[(i, j, c) for (i, j), c in sorted(counts.items())],
            top_n=self.params.top_n,
        )
        return NextPageModel(pages=pages, chain=chain)

    def predict(self, model: NextPageModel, query: Query) -> PredictedResult:
        try:
            state = model.pages[query.page]
        except KeyError:
            return PredictedResult(pages=())
        inv = model.pages.inverse
        return PredictedResult(pages=tuple(
            PageScore(page=inv[j], prob=p)
            for j, p in model.chain.predict(state)[: query.num]
        ))


def engine_factory() -> Engine:
    return Engine(
        data_source_class_map=PageViewDataSource,
        preparator_class_map=IdentityPreparator,
        algorithm_class_map={"markov": MarkovChainAlgorithm},
        serving_class_map=FirstServing,
    )
