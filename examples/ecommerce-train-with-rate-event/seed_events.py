"""Seed RateEcommApp: two taste clusters of rate events (ratings 1-5)
plus one re-rate to exercise latest-wins. Run after
`pio app new RateEcommApp`."""

import sys
from datetime import datetime, timedelta, timezone

import numpy as np

from predictionio_tpu.core.datamap import DataMap
from predictionio_tpu.core.event import Event
from predictionio_tpu.storage.registry import Storage

storage = Storage.default()
app = storage.get_meta_data_apps().get_by_name("RateEcommApp")
if app is None:
    sys.exit("app 'RateEcommApp' not found — run "
             "`pio app new RateEcommApp` first")

events = storage.get_events()
rng = np.random.default_rng(17)
t0 = datetime.now(timezone.utc)
n = 0
for u in range(20):
    for i in range(16):
        if rng.random() < 0.5:
            same = (i % 2) == (u % 2)
            rating = float(rng.integers(4, 6) if same else rng.integers(1, 3))
            events.insert(
                Event(event="rate", entity_type="user", entity_id=f"u{u}",
                      target_entity_type="item", target_entity_id=f"i{i}",
                      properties=DataMap({"rating": rating}),
                      event_time=t0),
                app.id,
            )
            n += 1
# u0 re-rates i1 later: the 5.0 supersedes whatever came first
events.insert(
    Event(event="rate", entity_type="user", entity_id="u0",
          target_entity_type="item", target_entity_id="i1",
          properties=DataMap({"rating": 5.0}),
          event_time=t0 + timedelta(minutes=5)),
    app.id,
)
print(f"seeded {n + 1} rate events into RateEcommApp (app id {app.id})")
