"""E-commerce template, train-with-rate-event variant.

Mirror of the reference's train-with-rate-event variant (reference:
examples/scala-parallel-ecommercerecommendation/train-with-rate-event/
src/main/scala/{DataSource,ALSAlgorithm}.scala): instead of the base
template's unit-confidence view/buy events, training reads explicit
``rate`` events carrying a ``rating`` property (DataSource.scala:80-105)
— the LATEST rating per (user, item) wins when a user re-rates
(ALSAlgorithm.scala:115-116 reduceByKey on event time) — and the
rating VALUE becomes the per-interaction implicit-confidence weight
fed to ``ALS.trainImplicit`` (ALSAlgorithm.scala:97-111).

Only the DataSource changes; the base ECommAlgorithm already trains
implicit ALS from the prepared (user, item, weight) triples, and all
the template's serving machinery (business rules, unavailable items,
unknown-user fallback) carries over untouched.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from predictionio_tpu.controller import Engine, FirstServing
from predictionio_tpu.templates.ecommerce import (
    DataSourceParams,
    ECommAlgorithm,
    ECommDataSource,
    ECommPreparator,
    ECommTrainingData,
)


@dataclasses.dataclass(frozen=True)
class RateDataSourceParams(DataSourceParams):
    rate_events: tuple = ("rate",)
    rating_property: str = "rating"


class RateEventDataSource(ECommDataSource):
    """Reads rate events; latest rating per (user, item) wins; the
    rating value is the interaction's confidence weight."""

    params_class = RateDataSourceParams

    def read_training(self, ctx) -> ECommTrainingData:
        p = self.params
        store = ctx.event_store()
        latest: dict[tuple[str, str], tuple] = {}
        for ev in store.find(
            p.app_name,
            entity_type=p.entity_type,
            event_names=list(p.rate_events),
            target_entity_type=p.target_entity_type,
        ):
            if ev.target_entity_id is None:
                continue
            rating = ev.properties.get_opt(p.rating_property)
            if rating is None:
                continue
            key = (ev.entity_id, ev.target_entity_id)
            prev = latest.get(key)
            if prev is None or ev.event_time > prev[0]:
                latest[key] = (ev.event_time, float(rating))
        categories: dict[str, tuple] = {}
        for item_id, pm in store.aggregate_properties(
            p.app_name, p.item_entity_type
        ).items():
            cats = pm.get_opt("categories")
            if cats:
                categories[item_id] = tuple(cats)
        return ECommTrainingData(
            users=np.asarray([u for u, _ in latest], dtype=object),
            items=np.asarray([i for _, i in latest], dtype=object),
            weights=np.asarray([r for _, r in latest.values()],
                               dtype=np.float32),
            categories=categories,
        )


def engine_factory() -> Engine:
    return Engine(
        data_source_class_map=RateEventDataSource,
        preparator_class_map=ECommPreparator,
        algorithm_class_map={"ecomm": ECommAlgorithm},
        serving_class_map=FirstServing,
    )
