"""Seed WeightedEcommApp: two view-taste clusters plus an initial
weightedItems constraint. Run after `pio app new WeightedEcommApp`."""

import sys

import numpy as np

from predictionio_tpu.core.datamap import DataMap
from predictionio_tpu.core.event import Event
from predictionio_tpu.storage.registry import Storage

storage = Storage.default()
app = storage.get_meta_data_apps().get_by_name("WeightedEcommApp")
if app is None:
    sys.exit("app 'WeightedEcommApp' not found — run "
             "`pio app new WeightedEcommApp` first")

events = storage.get_events()
rng = np.random.default_rng(7)
n = 0
for u in range(20):
    for i in range(16):
        if i % 2 == u % 2 and rng.random() < 0.85:
            events.insert(
                Event(event="view", entity_type="user", entity_id=f"u{u}",
                      target_entity_type="item", target_entity_id=f"i{i}",
                      properties=DataMap({})),
                app.id,
            )
            n += 1

events.insert(
    Event(event="$set", entity_type="constraint", entity_id="weightedItems",
          properties=DataMap({"weights": [
              {"items": ["i3"], "weight": 2.0},
          ]})),
    app.id,
)
print(f"seeded {n} view events + 1 weights constraint into "
      f"WeightedEcommApp (app id {app.id})")
