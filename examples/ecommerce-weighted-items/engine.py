"""E-commerce template, weighted-items variant.

Mirror of the reference's weighted-items variant (reference:
examples/scala-parallel-ecommercerecommendation/weighted-items/
src/main/scala/ALSAlgorithm.scala:70-74, 234-295): operators publish
weight groups as a ``$set`` event on the constraint entity
``weightedItems`` —

    {"weights": [{"items": ["i1", "i2"], "weight": 2.0},
                 {"items": ["i9"],       "weight": 0.5}]}

— and every query re-reads the LATEST groups and multiplies each item's
score by its weight (default 1.0). Promoted items (> 1.0) surface more
often, demoted ones (< 1.0) less, all live: no retrain, no redeploy.

TPU design note: for known users the reference multiplies scores
item-by-item inside its ranking loop; here the weights fold into the
item-factor table (``score = u . (w * v) = w * (u . v)`` for w >= 0),
so the existing jitted matmul+top-k kernel runs unchanged — the
weighting costs one (I, K) elementwise multiply, cached per
(weights version, model). The unknown-user fallback ranks by cosine
similarity — which normalizes a table scaling away — so that path
re-weights the similarity scores over an expanded candidate pool
instead (both paths weighted, like the reference's
predictKnownUser/predictSimilar).
"""

from __future__ import annotations

import dataclasses
import logging
import math

import numpy as np

logger = logging.getLogger(__name__)

from predictionio_tpu.controller import Engine, FirstServing
from predictionio_tpu.templates.ecommerce import (
    ECommAlgorithm,
    ECommAlgorithmParams,
    ECommDataSource,
    ECommModel,
    ECommPreparator,
    ItemScore,
    PredictedResult,
    Query,
)


@dataclasses.dataclass(frozen=True)
class WeightedParams(ECommAlgorithmParams):
    weight_constraint_id: str = "weightedItems"


class WeightedECommAlgorithm(ECommAlgorithm):
    """ECommAlgorithm + live per-item score weights."""

    params_class = WeightedParams

    def __init__(self, params=None):
        super().__init__(params)
        # (weights-event version, base ALS model, weighted model)
        self._weight_cache: tuple[str | None, object, object] | None = None

    def _weight_groups(self):
        """Latest $set on (constraint, weightedItems) -> list of
        {items, weight} groups; [] when unset (ALSAlgorithm.scala:234-251
        in the variant, same live-read pattern as unavailableItems)."""
        p = self.params
        if self._ctx is None or not p.app_name:
            return None, []
        try:
            events = list(
                self._ctx.event_store().find_by_entity(
                    p.app_name, p.unavailable_constraint_entity,
                    p.weight_constraint_id, event_names=["$set"],
                    limit=1, latest=True,
                )
            )
        except Exception:
            return None, []
        if not events:
            return None, []
        ev = events[0]
        groups = ev.properties.get_opt("weights") or []
        return ev.event_id, groups

    def _weights_vector(self, model: ECommModel):
        version, groups = self._weight_groups()
        if not groups:
            return version, None
        w = np.ones(len(model.als.item_ids), dtype=np.float32)
        for group in groups:
            try:
                weight = float(group.get("weight", 1.0))
            except (TypeError, ValueError, AttributeError):
                # non-dict entries land here too (AttributeError on .get)
                logger.warning("skipping malformed weight group: %r", group)
                continue
            if not (math.isfinite(weight) and weight >= 0.0):
                # one malformed operator event must not poison the
                # serving path — the reference variant applies weights
                # unvalidated; we skip the bad group (negative, NaN or
                # inf weights would corrupt every score) and keep serving
                logger.warning(
                    "skipping invalid item weight group: %r", group)
                continue
            for item_id in group.get("items", []):
                ix = model.als.item_ids.get(item_id)
                if ix is not None:
                    w[ix] = weight
        return version, w

    def _weighted_model(self, model: ECommModel) -> ECommModel:
        """Item factors scaled by the current weights, cached per
        (weights-event version, base model) — the base model changes
        across eval folds and /reload hot-swaps, so the version alone
        is not a sound key."""
        version, w = self._weights_vector(model)
        if w is None:
            return model
        # hold the base ALS model itself in the cache entry and compare
        # by identity to that held object — a raw id() key can alias a
        # new model allocated at a freed model's address after /reload
        if (self._weight_cache is not None
                and self._weight_cache[0] == version
                and self._weight_cache[1] is model.als):
            return self._weight_cache[2]
        weighted = ECommModel(
            als=dataclasses.replace(
                model.als,
                item_factors=model.als.item_factors * w[:, None],
            ),
            categories=model.categories,
        )
        self._weight_cache = (version, model.als, weighted)
        return weighted

    def predict(self, model: ECommModel, query: Query) -> PredictedResult:
        if query.user in model.als.user_ids:
            # known user: dot-product ranking, where the weights fold
            # exactly into the factor table (u . (w v) = w (u . v))
            return super().predict(self._weighted_model(model), query)
        # unknown user: the fallback ranks by COSINE similarity, which
        # normalizes a factor-table scaling away — apply the weights to
        # the similarity scores instead (the reference variant
        # multiplies final scores on both paths, ALSAlgorithm.scala:
        # 294-295, 400-401), over an expanded candidate pool so
        # promoted items outside the unweighted top-num can surface
        version, w = self._weights_vector(model)
        recent = self._recent_items(query.user)
        if not recent or w is None:
            return super().predict(model, query)
        allow = self._allow_vector(model, query)
        pool = model.als.similar(recent, min(
            query.num * 8, model.als.item_factors.shape[0]), allow=allow)
        rescored = sorted(
            ((item, score * float(w[model.als.item_ids[item]]))
             for item, score in pool),
            key=lambda kv: -kv[1],
        )[: query.num]
        return PredictedResult(
            item_scores=tuple(ItemScore(item=i, score=s)
                              for i, s in rescored)
        )


def engine_factory() -> Engine:
    return Engine(
        data_source_class_map=ECommDataSource,
        preparator_class_map=ECommPreparator,
        algorithm_class_map={"ecomm": WeightedECommAlgorithm},
        serving_class_map=FirstServing,
    )
