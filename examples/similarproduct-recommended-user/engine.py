"""Similarproduct template, recommended-user variant.

Mirror of the reference's recommended-user variant (reference:
examples/scala-parallel-similarproduct/recommended-user/): the
similar-product machinery retargeted at a SOCIAL graph — "user follows
user" events train implicit ALS over (follower, followedUser) pairs
(DataSource.scala:55-84, ALSAlgorithm.scala:112-122 `ALS.trainImplicit`),
and queries ask for users most cosine-similar to a set of users
(ALSAlgorithm.scala:157 cosine ranking, query {users, num, whiteList,
blackList}).

The instructive point (and why the reference ships it): the template's
entity types are CONFIGURATION, not structure. Here the base
similarproduct DataSource/Preparator/Algorithm run UNCHANGED — the
"items" axis simply becomes followed users
(``event_names=("follow",)``, ``target_entity_type="user"``) — and only
a thin Query adapter renames ``items`` to ``users`` for wire parity
with the reference's query JSON.
"""

from __future__ import annotations

import dataclasses

from predictionio_tpu.controller import Engine, FirstServing
from predictionio_tpu.templates.similarproduct import (
    ALSAlgorithmParams,
    DataSourceParams,
    PredictedResult,
    Query,
    SimilarALSAlgorithm,
    SimilarModel,
    SimilarProductDataSource,
    SimilarProductPreparator,
)


@dataclasses.dataclass(frozen=True)
class RecommendedUserQuery:
    """Parity: recommended-user Query.scala — users, num, whiteList,
    blackList (no categories on a social graph)."""

    users: tuple = ()
    num: int = 10
    white_list: tuple | None = None
    black_list: tuple | None = None


class RecommendedUserAlgorithm(SimilarALSAlgorithm):
    """Cosine top-k over FOLLOWED-user factors; the query's own users
    are excluded from results (the reference filters them the same
    way)."""

    query_class = RecommendedUserQuery

    def predict(self, model: SimilarModel,
                query: RecommendedUserQuery) -> PredictedResult:
        return super().predict(
            model,
            Query(items=tuple(query.users), num=query.num,
                  white_list=query.white_list, black_list=query.black_list),
        )


def engine_factory() -> Engine:
    return Engine(
        data_source_class_map=SimilarProductDataSource,
        preparator_class_map=SimilarProductPreparator,
        algorithm_class_map={"als": RecommendedUserAlgorithm},
        serving_class_map=FirstServing,
    )
