"""Seed RecommendedUserApp: two follow communities with sparse
cross-links. Run after `pio app new RecommendedUserApp`."""

import sys

import numpy as np

from predictionio_tpu.core.datamap import DataMap
from predictionio_tpu.core.event import Event
from predictionio_tpu.storage.registry import Storage

storage = Storage.default()
app = storage.get_meta_data_apps().get_by_name("RecommendedUserApp")
if app is None:
    sys.exit("app 'RecommendedUserApp' not found — run "
             "`pio app new RecommendedUserApp` first")

events = storage.get_events()
rng = np.random.default_rng(13)
n = 0
for u in range(24):
    for v in range(24):
        if u == v:
            continue
        same = (u % 2) == (v % 2)
        if rng.random() < (0.7 if same else 0.02):
            events.insert(
                Event(event="follow", entity_type="user", entity_id=f"u{u}",
                      target_entity_type="user", target_entity_id=f"u{v}",
                      properties=DataMap({})),
                app.id,
            )
            n += 1
print(f"seeded {n} follow events into RecommendedUserApp (app id {app.id})")
