"""Recommendation template + category filtering: rating-based ALS whose
queries carry a ``categories`` field, with results restricted to items
in ANY of the requested categories.

Mirror of the reference's filter-by-category variant (reference:
examples/scala-parallel-recommendation/filter-by-category/src/main/scala/
{DataSource,ALSAlgorithm}.scala): items gain categories from their
``$set`` events, the Query grows a ``categories`` array, and the
eligibility filter applies BEFORE top-k, so the caller always gets
``num`` in-category results when enough exist (vs post-filtering, which
can under-fill). Composes entirely from framework pieces: the
recommendation template's DataSource/Preparator/ALS plus the shared
``build_allow_vector`` business-rule helper.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from predictionio_tpu.controller import Engine, FirstServing
from predictionio_tpu.models.als import ALSModel, build_allow_vector
from predictionio_tpu.templates.recommendation import (
    ALSAlgorithm,
    ALSPreparator,
    DataSourceParams,
    ItemScore,
    PredictedResult,
    RecommendationDataSource,
    TrainingData,
)


@dataclasses.dataclass(frozen=True)
class Query:
    """user + num + categories (Engine.scala:26 of the variant)."""

    user: str
    num: int = 10
    categories: tuple | None = None


@dataclasses.dataclass(frozen=True)
class CategoryTrainingData(TrainingData):
    categories: dict = dataclasses.field(default_factory=dict)


class CategoryDataSource(RecommendationDataSource):
    """Rate events + item ``$set`` ``categories`` properties
    (DataSource.scala:51 of the variant)."""

    params_class = DataSourceParams

    def read_eval(self, ctx):
        # like the reference variant (only readTraining is implemented):
        # the base read_eval would yield category-less folds and base
        # Query objects, which this engine's components can't consume
        raise NotImplementedError(
            "the filter-by-category example does not implement read_eval; "
            "evaluate the base recommendation template instead"
        )

    def read_training(self, ctx) -> CategoryTrainingData:
        td = super().read_training(ctx)
        p = self.params
        categories: dict[str, tuple] = {}
        for item_id, pm in ctx.event_store().aggregate_properties(
            p.app_name, p.target_entity_type
        ).items():
            cats = pm.get_opt("categories")
            if cats:
                categories[item_id] = tuple(cats)
        return CategoryTrainingData(
            users=td.users, items=td.items, ratings=td.ratings,
            categories=categories,
        )


@dataclasses.dataclass(frozen=True)
class CategoryPreparedData:
    coo: object
    user_ids: object
    item_ids: object
    seen_by_user: dict
    categories: dict


class CategoryPreparator(ALSPreparator):
    def prepare(self, ctx, td: CategoryTrainingData) -> CategoryPreparedData:
        pd = super().prepare(ctx, td)
        return CategoryPreparedData(
            coo=pd.coo, user_ids=pd.user_ids, item_ids=pd.item_ids,
            seen_by_user=pd.seen_by_user, categories=td.categories,
        )


@dataclasses.dataclass
class CategoryModel:
    """ALSModel + the item->categories map for query-time filtering."""

    als: ALSModel
    categories: dict


class CategoryALSAlgorithm(ALSAlgorithm):
    query_class = Query

    def train(self, ctx, pd: CategoryPreparedData) -> CategoryModel:
        return CategoryModel(als=super().train(ctx, pd),
                             categories=pd.categories)

    def predict(self, model: CategoryModel, query: Query) -> PredictedResult:
        allow = build_allow_vector(
            model.als.item_ids,
            categories=query.categories,
            category_map=model.categories,
        )
        recs = model.als.recommend(
            query.user, query.num,
            allow=None if allow is None else np.asarray(allow),
            exclude_seen=self.params.exclude_seen,
        )
        return PredictedResult(
            item_scores=tuple(ItemScore(item=i, score=s) for i, s in recs)
        )

    def batch_predict(self, model: CategoryModel, queries):
        # per-query category filters need per-query allow vectors — the
        # single-query path handles each (fine at example scale)
        return [(qi, self.predict(model, q)) for qi, q in queries]

    def make_persistent_model(self, ctx, model: CategoryModel):
        return model  # pickle blob (example scale)


def engine_factory() -> Engine:
    return Engine(
        data_source_class_map=CategoryDataSource,
        preparator_class_map=CategoryPreparator,
        algorithm_class_map={"als": CategoryALSAlgorithm},
        serving_class_map=FirstServing,
    )
