"""Recommendation template + custom Preparator: train-time item exclusion.

Mirror of the reference's custom-preparator variant (reference:
examples/scala-parallel-recommendation/custom-prepartor/src/main/scala/
Preparator.scala): a Preparator with its own Params pointing at a
no-train-items file; listed items are dropped from the ratings BEFORE
training, so the model never learns factors for them (vs the
custom-serving variant, which hides items at serve time but still
trains on them). Everything else (DataSource, ALS algorithm, Serving)
is reused straight from the built-in template; only the Preparator is
custom.
"""

from __future__ import annotations

import dataclasses
import os

from predictionio_tpu.controller import Engine, FirstServing, Params
from predictionio_tpu.templates.recommendation import (
    ALSAlgorithm,
    ALSPreparator,
    RecommendationDataSource,
    TrainingData,
)


@dataclasses.dataclass(frozen=True)
class CustomPreparatorParams(Params):
    """filepath: one item id per line to exclude from training
    (CustomPreparatorParams in the reference's Preparator.scala)."""

    filepath: str = "no_train_items.txt"


class ExcludeItemsPreparator(ALSPreparator):
    """Filters no-train items out of the raw triples, then applies the
    standard id-indexing preparation."""

    params_class = CustomPreparatorParams

    def prepare(self, ctx, td: TrainingData):
        no_train: set[str] = set()
        if os.path.exists(self.params.filepath):
            with open(self.params.filepath) as f:
                no_train = {line.strip() for line in f if line.strip()}
        if no_train:
            keep = [i for i, item in enumerate(td.items)
                    if item not in no_train]
            td = TrainingData(
                users=td.users[keep],
                items=td.items[keep],
                ratings=td.ratings[keep],
            )
        return super().prepare(ctx, td)


def engine_factory() -> Engine:
    return Engine(
        data_source_class_map=RecommendationDataSource,
        preparator_class_map=ExcludeItemsPreparator,
        algorithm_class_map={"als": ALSAlgorithm},
        serving_class_map=FirstServing,
    )
