"""Seed CustomPreparatorApp with rate events (two taste clusters) through
the storage API. Run after `pio app new CustomPreparatorApp`."""

import sys

import numpy as np

from predictionio_tpu.core.datamap import DataMap
from predictionio_tpu.core.event import Event
from predictionio_tpu.storage.registry import Storage

storage = Storage.default()
app = storage.get_meta_data_apps().get_by_name("CustomPreparatorApp")
if app is None:
    sys.exit("app 'CustomPreparatorApp' not found — run `pio app new CustomPreparatorApp` first")

events = storage.get_events()
rng = np.random.default_rng(5)
n = 0
for u in range(16):
    for i in range(12):
        if i % 2 == u % 2 and rng.random() < 0.9:
            events.insert(
                Event(
                    event="rate",
                    entity_type="user",
                    entity_id=f"u{u}",
                    target_entity_type="item",
                    target_entity_id=f"i{i}",
                    properties=DataMap({"rating": 5.0}),
                ),
                app.id,
            )
            n += 1
print(f"seeded {n} rate events into CustomPreparatorApp (app id {app.id})")
