"""Similarproduct template, add-and-return-item-properties variant.

Mirror of the reference's add-and-return-item-properties variant
(reference: examples/scala-parallel-similarproduct/
add-and-return-item-properties/): items carry required ``title``,
``date`` and ``imdbUrl`` properties read at TRAIN time
(DataSource.scala:68-75 — a $set item missing one fails training, same
here), and every returned ItemScore is ENRICHED with them
(Engine.scala:35-41, ALSAlgorithm.scala:188-194) so the caller gets a
render-ready result instead of bare item ids.

TPU design note: the properties ride the model as a host-side dict —
they never touch the device. The jitted cosine top-k runs unchanged;
enrichment is a dict lookup over the k winners. Items viewed but never
``$set`` have no properties to return and are ineligible at query time
(the reference drops their views at train time instead; we keep the
training signal — same divergence as the filterbyyear variant,
documented in README).
"""

from __future__ import annotations

import dataclasses
import json
import os

import numpy as np

from predictionio_tpu.controller import Engine, FirstServing
from predictionio_tpu.controller.base import PersistentModelManifest
from predictionio_tpu.templates.similarproduct import (
    Query,
    SimilarALSAlgorithm,
    SimilarModel,
    SimilarPreparedData,
    SimilarProductDataSource,
    SimilarProductPreparator,
    SimilarTrainingData,
)

REQUIRED_PROPS = ("title", "date", "imdbUrl")


@dataclasses.dataclass(frozen=True)
class RichItemScore:
    """Parity: the variant's ItemScore — item, title, date, imdbUrl,
    score (Engine.scala:35-41)."""

    item: str
    title: str
    date: str
    imdb_url: str
    score: float


@dataclasses.dataclass(frozen=True)
class RichPredictedResult:
    item_scores: tuple[RichItemScore, ...] = ()


@dataclasses.dataclass(frozen=True)
class RichTrainingData(SimilarTrainingData):
    item_props: dict = dataclasses.field(default_factory=dict)


@dataclasses.dataclass(frozen=True)
class RichPreparedData(SimilarPreparedData):
    item_props: dict = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class RichModel(SimilarModel):
    item_props: dict = dataclasses.field(default_factory=dict)
    #: index-aligned 0/1 "has display properties" vector, built once —
    #: predict multiplies it into the allow mask instead of looping the
    #: catalog per query
    has_props_vec: np.ndarray | None = None

    def __post_init__(self):
        if self.has_props_vec is None:
            vec = np.zeros(len(self.als.item_ids), dtype=np.float32)
            for item_id in self.item_props:
                ix = self.als.item_ids.get(item_id)
                if ix is not None:
                    vec[ix] = 1.0
            self.has_props_vec = vec


class RichItemDataSource(SimilarProductDataSource):
    """Base view/category read + the required item display properties."""

    def read_training(self, ctx) -> RichTrainingData:
        td = super().read_training(ctx)
        item_props: dict[str, dict] = {}
        props = ctx.event_store().aggregate_properties(
            self.params.app_name, self.params.item_entity_type)
        for item_id, pm in props.items():
            entry = {}
            for name in REQUIRED_PROPS:
                value = pm.get_opt(name)
                if value is None:
                    # reference parity: DataSource.scala:68-75 throws on
                    # a $set item missing a required property
                    raise ValueError(
                        f"item {item_id!r} has no {name!r} property; "
                        "this variant requires title/date/imdbUrl on "
                        "every item")
                entry[name] = str(value)
            item_props[item_id] = entry
        return RichTrainingData(
            users=td.users, items=td.items, ratings=td.ratings,
            categories=td.categories, item_props=item_props)


class RichItemPreparator(SimilarProductPreparator):
    def prepare(self, ctx, td: RichTrainingData) -> RichPreparedData:
        base = super().prepare(ctx, td)
        return RichPreparedData(
            coo=base.coo, user_ids=base.user_ids, item_ids=base.item_ids,
            seen_by_user=base.seen_by_user, categories=base.categories,
            item_props=td.item_props)


class RichItemAlgorithm(SimilarALSAlgorithm):
    query_class = Query

    def train(self, ctx, pd: RichPreparedData) -> RichModel:
        base = super().train(ctx, pd)
        return RichModel(als=base.als, categories=base.categories,
                         item_props=pd.item_props)

    def predict(self, model: RichModel, query: Query) -> RichPredictedResult:
        allow = self._allow_vector(model, query)
        if allow is None:
            allow = np.ones(len(model.als.item_ids), dtype=np.float32)
        # only items with known properties can be returned enriched
        sims = model.als.similar(list(query.items), query.num,
                                 allow=allow * model.has_props_vec)
        scores = []
        for item, score in sims:
            props = model.item_props[item]
            scores.append(RichItemScore(
                item=item, title=props["title"], date=props["date"],
                imdb_url=props["imdbUrl"], score=score))
        return RichPredictedResult(item_scores=tuple(scores))

    def make_persistent_model(self, ctx, model: RichModel):
        # base manifest already names type(self) dynamically
        manifest = super().make_persistent_model(ctx, model)
        with open(os.path.join(manifest.location, "item_props.json"),
                  "w") as f:
            json.dump(model.item_props, f)
        return manifest

    def load_model(self, ctx, manifest: PersistentModelManifest) -> RichModel:
        base = super().load_model(ctx, manifest)
        with open(os.path.join(manifest.location, "item_props.json")) as f:
            item_props = json.load(f)
        return RichModel(als=base.als, categories=base.categories,
                         item_props=item_props)


def engine_factory() -> Engine:
    return Engine(
        data_source_class_map=RichItemDataSource,
        preparator_class_map=RichItemPreparator,
        algorithm_class_map={"als": RichItemAlgorithm},
        serving_class_map=FirstServing,
    )
