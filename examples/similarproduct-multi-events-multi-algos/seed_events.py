"""Seed MultiSimilarApp: two view-taste clusters plus like/dislike
signals (with one like->dislike flip to exercise latest-wins dedup).
Run after `pio app new MultiSimilarApp`."""

import sys
from datetime import datetime, timedelta, timezone

import numpy as np

from predictionio_tpu.core.datamap import DataMap
from predictionio_tpu.core.event import Event
from predictionio_tpu.storage.registry import Storage

storage = Storage.default()
app = storage.get_meta_data_apps().get_by_name("MultiSimilarApp")
if app is None:
    sys.exit("app 'MultiSimilarApp' not found — run "
             "`pio app new MultiSimilarApp` first")

events = storage.get_events()
rng = np.random.default_rng(11)
t0 = datetime.now(timezone.utc)
n = 0


def emit(event, u, i, minutes=0):
    global n
    events.insert(
        Event(event=event, entity_type="user", entity_id=f"u{u}",
              target_entity_type="item", target_entity_id=f"i{i}",
              properties=DataMap({}),
              event_time=t0 + timedelta(minutes=minutes)),
        app.id,
    )
    n += 1


for u in range(20):
    for i in range(16):
        if i % 2 == u % 2 and rng.random() < 0.85:
            emit("view", u, i)
        if i % 2 == u % 2 and rng.random() < 0.5:
            emit("like", u, i)
# everyone dislikes item 0 (despite viewing it)
for u in range(0, 20, 2):
    emit("dislike", u, 0, minutes=5)
# u2 liked i0 late, then flipped to dislike even later: dislike wins
emit("like", 2, 0, minutes=6)
emit("dislike", 2, 0, minutes=7)

print(f"seeded {n} events into MultiSimilarApp (app id {app.id})")
