"""Similarproduct template, multi-events-multi-algos variant.

Mirror of the reference's most instructive similarproduct variant
(reference: examples/scala-parallel-similarproduct/multi/ — "Multiple
Events and Multiple Algorithms"):

- the DataSource reads **two event streams**: "view" events AND
  like/dislike events (DataSource.scala in the variant);
- **two algorithms** train side by side: the standard implicit ALS on
  views, plus a ``LikeAlgorithm`` that trains on like/dislike signals
  where the LATEST event per (user, item) wins and a dislike is a
  high-confidence negative (LikeAlgorithm.scala: like -> 1,
  dislike -> -1 into ``ALS.trainImplicit``; ops/als implements the same
  c = 1 + α|r|, p = [r > 0] semantics);
- a custom Serving **z-score-standardizes** each algorithm's scores and
  sums them per item before the final top-num cut (Serving.scala's
  meanAndVariance standardization), so neither algorithm's score scale
  dominates the blend.
"""

from __future__ import annotations

import dataclasses
import statistics

import numpy as np

from predictionio_tpu.controller import Engine, SanityCheck, Serving
from predictionio_tpu.models.als import ALSModel
from predictionio_tpu.ops.als import als_train
from predictionio_tpu.templates.similarproduct import (
    ALSAlgorithmParams,
    DataSourceParams,
    ItemScore,
    PredictedResult,
    Query,
    SimilarALSAlgorithm,
    SimilarModel,
    SimilarPreparedData,
    SimilarProductDataSource,
    SimilarProductPreparator,
    SimilarTrainingData,
)
from predictionio_tpu.templates.recommendation import ALSPreparator, TrainingData


@dataclasses.dataclass(frozen=True)
class MultiTrainingData(SanityCheck):
    """View triples + (deduped, latest-wins) like/dislike triples."""

    views: SimilarTrainingData
    like_users: np.ndarray   # object ids
    like_items: np.ndarray   # object ids
    like_signs: np.ndarray   # float32 +1 (like) / -1 (dislike)

    def sanity_check(self) -> None:
        self.views.sanity_check()
        if len(self.like_users) == 0:
            raise ValueError(
                "no like/dislike events; the LikeAlgorithm needs them")


@dataclasses.dataclass(frozen=True)
class MultiDataSourceParams(DataSourceParams):
    like_event: str = "like"
    dislike_event: str = "dislike"


class MultiDataSource(SimilarProductDataSource):
    """View events via the base template + like/dislike with
    latest-event-wins dedup (the variant's reduceByKey on event time,
    LikeAlgorithm.scala: "An user may like an item and change to
    dislike it later")."""

    params_class = MultiDataSourceParams

    def read_training(self, ctx) -> MultiTrainingData:
        views = super().read_training(ctx)
        p = self.params
        latest: dict[tuple[str, str], tuple] = {}
        for ev in ctx.event_store().find(
            p.app_name,
            entity_type=p.entity_type,
            event_names=[p.like_event, p.dislike_event],
            target_entity_type=p.target_entity_type,
        ):
            if ev.target_entity_id is None:
                continue
            key = (ev.entity_id, ev.target_entity_id)
            prev = latest.get(key)
            if prev is None or ev.event_time > prev[0]:
                latest[key] = (ev.event_time, ev.event == p.like_event)
        users = np.asarray([u for u, _ in latest], dtype=object)
        items = np.asarray([i for _, i in latest], dtype=object)
        signs = np.asarray(
            [1.0 if like else -1.0 for _, like in latest.values()],
            dtype=np.float32,
        )
        return MultiTrainingData(
            views=views, like_users=users, like_items=items, like_signs=signs
        )


@dataclasses.dataclass(frozen=True)
class MultiPreparedData:
    views: SimilarPreparedData
    likes: SimilarPreparedData   # coo.vals carry ±1 signs


class MultiPreparator(SimilarProductPreparator):
    """Prepares both event streams; the like stream gets its own id maps
    (its user/item vocabulary need not match the view stream's)."""

    def prepare(self, ctx, td: MultiTrainingData) -> MultiPreparedData:
        views = super().prepare(ctx, td.views)
        like_base = ALSPreparator.prepare(
            self,
            ctx,
            TrainingData(users=td.like_users, items=td.like_items,
                         ratings=td.like_signs),
        )
        likes = SimilarPreparedData(
            coo=like_base.coo,
            user_ids=like_base.user_ids,
            item_ids=like_base.item_ids,
            seen_by_user=like_base.seen_by_user,
            categories=td.views.categories,
        )
        return MultiPreparedData(views=views, likes=likes)


class ViewAlgorithm(SimilarALSAlgorithm):
    """The standard implicit-ALS-on-views algorithm, routed at the view
    half of the multi prepared data."""

    def train(self, ctx, pd: MultiPreparedData) -> SimilarModel:
        return super().train(ctx, pd.views)


class LikeAlgorithm(SimilarALSAlgorithm):
    """Implicit ALS on ±1 like/dislike signals (LikeAlgorithm.scala):
    a dislike trains as confidence 1 + α against preference 0."""

    def train(self, ctx, pd: MultiPreparedData) -> SimilarModel:
        p = self.params
        likes = pd.likes
        mesh = ctx.mesh_if_parallel if p.use_mesh else None
        factors = als_train(
            likes.coo, rank=p.rank, iterations=p.num_iterations,
            lam=p.lambda_, implicit=True, alpha=p.alpha, seed=p.seed,
            mesh=mesh,
        )
        als = ALSModel(
            rank=p.rank,
            user_factors=factors.user,
            item_factors=factors.item,
            user_ids=likes.user_ids,
            item_ids=likes.item_ids,
            seen_by_user=likes.seen_by_user,
        )
        return SimilarModel(als=als, categories=likes.categories)


class StandardizeServing(Serving):
    """z-score each algorithm's scores, then sum per item (Serving.scala
    in the multi variant: meanAndVariance standardization so the two
    score scales blend fairly; num == 1 queries skip standardization)."""

    def serve(self, query: Query, predictions) -> PredictedResult:
        preds = [p for p in predictions if p.item_scores]
        if not preds:
            return PredictedResult(item_scores=())
        if query.num == 1 or len(preds) == 1:
            standard = [list(p.item_scores) for p in preds]
        else:
            standard = []
            for p in preds:
                scores = [s.score for s in p.item_scores]
                mean = statistics.fmean(scores)
                std = statistics.pstdev(scores) if len(scores) > 1 else 0.0
                standard.append([
                    ItemScore(s.item,
                              0.0 if std == 0 else (s.score - mean) / std)
                    for s in p.item_scores
                ])
        combined: dict[str, float] = {}
        for scores in standard:
            for s in scores:
                combined[s.item] = combined.get(s.item, 0.0) + s.score
        top = sorted(combined.items(), key=lambda kv: -kv[1])[: query.num]
        return PredictedResult(
            item_scores=tuple(ItemScore(item=i, score=v) for i, v in top)
        )


def engine_factory() -> Engine:
    return Engine(
        data_source_class_map=MultiDataSource,
        preparator_class_map=MultiPreparator,
        algorithm_class_map={
            "als": ViewAlgorithm,
            "likealgo": LikeAlgorithm,
        },
        serving_class_map=StandardizeServing,
    )
