"""Similarproduct template, filter-by-year variant.

Mirror of the reference's filterbyyear variant (reference:
examples/scala-parallel-similarproduct/filterbyyear/): items carry a
required integer ``year`` property read at TRAIN time into the model
(DataSource.scala:88-96 ``properties.get[Int]("year")`` — a missing
year on a $set item fails training, same here), queries add
``recommendFromYear``, candidates must satisfy
``year > recommendFromYear`` (default 1, ALSAlgorithm.scala:247
``getOrElse(1)``), and each returned ItemScore carries the item's
``year`` (ALSAlgorithm.scala:188-193).

TPU design note: the reference applies the year test per item inside
its ranking loop (isCandidateItem); here the predicate folds into the
dense 0/1 eligibility vector once per query, so the jitted
matmul+top-k kernel runs unchanged — year filtering costs one host-side
vector build, not a per-item branch. Items that were viewed but never
``$set`` (so their year is unknown) are ineligible at query time — the
reference drops their view events entirely at train time instead; we
keep the training signal and document the divergence.
"""

from __future__ import annotations

import dataclasses
import json
import os

import numpy as np

from predictionio_tpu.controller import Engine, FirstServing
from predictionio_tpu.controller.base import PersistentModelManifest
from predictionio_tpu.templates.similarproduct import (
    Query,
    SimilarALSAlgorithm,
    SimilarModel,
    SimilarPreparedData,
    SimilarProductDataSource,
    SimilarProductPreparator,
    SimilarTrainingData,
)


@dataclasses.dataclass(frozen=True)
class YearQuery(Query):
    """Parity: filterbyyear Query.scala — base query +
    recommendFromYear."""

    recommend_from_year: int | None = None


@dataclasses.dataclass(frozen=True)
class YearItemScore:
    item: str
    score: float
    year: int


@dataclasses.dataclass(frozen=True)
class YearPredictedResult:
    item_scores: tuple[YearItemScore, ...] = ()


@dataclasses.dataclass(frozen=True)
class YearTrainingData(SimilarTrainingData):
    years: dict = dataclasses.field(default_factory=dict)


@dataclasses.dataclass(frozen=True)
class YearPreparedData(SimilarPreparedData):
    years: dict = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class YearModel(SimilarModel):
    years: dict = dataclasses.field(default_factory=dict)
    #: index-aligned year per item (unknown-year items carry a sentinel
    #: below any query year -> never eligible); built once so predict
    #: filters with one vectorized compare, not a per-item dict loop
    year_by_ix: np.ndarray | None = None

    def __post_init__(self):
        if self.year_by_ix is None:
            arr = np.full(len(self.als.item_ids), np.iinfo(np.int32).min,
                          dtype=np.int64)
            for item_id, year in self.years.items():
                ix = self.als.item_ids.get(item_id)
                if ix is not None:
                    arr[ix] = int(year)
            self.year_by_ix = arr


class FilterByYearDataSource(SimilarProductDataSource):
    """Base view/category read + the required per-item ``year``."""

    def read_training(self, ctx) -> YearTrainingData:
        td = super().read_training(ctx)
        years: dict[str, int] = {}
        props = ctx.event_store().aggregate_properties(
            self.params.app_name, self.params.item_entity_type)
        for item_id, pm in props.items():
            year = pm.get_opt("year")
            if year is None:
                # reference parity: a $set item without a year fails
                # training loudly (DataSource.scala:88-96 throws)
                raise ValueError(
                    f"item {item_id!r} has no 'year' property; "
                    "filterbyyear requires year on every item")
            years[item_id] = int(year)
        return YearTrainingData(
            users=td.users, items=td.items, ratings=td.ratings,
            categories=td.categories, years=years)


class FilterByYearPreparator(SimilarProductPreparator):
    def prepare(self, ctx, td: YearTrainingData) -> YearPreparedData:
        base = super().prepare(ctx, td)
        return YearPreparedData(
            coo=base.coo, user_ids=base.user_ids, item_ids=base.item_ids,
            seen_by_user=base.seen_by_user, categories=base.categories,
            years=td.years)


class FilterByYearAlgorithm(SimilarALSAlgorithm):
    query_class = YearQuery

    def train(self, ctx, pd: YearPreparedData) -> YearModel:
        base = super().train(ctx, pd)
        return YearModel(als=base.als, categories=base.categories,
                         years=pd.years)

    def predict(self, model: YearModel,
                query: YearQuery) -> YearPredictedResult:
        allow = self._allow_vector(model, query)
        if allow is None:
            allow = np.ones(len(model.als.item_ids), dtype=np.float32)
        # year > recommendFromYear, default 1 (reference
        # ALSAlgorithm.scala:247); unknown-year items carry the
        # sentinel in year_by_ix and are never eligible
        from_year = (1 if query.recommend_from_year is None
                     else int(query.recommend_from_year))
        year_ok = (model.year_by_ix > from_year).astype(np.float32)
        sims = model.als.similar(list(query.items), query.num,
                                 allow=allow * year_ok)
        return YearPredictedResult(
            item_scores=tuple(
                YearItemScore(item=i, score=s, year=model.years[i])
                for i, s in sims)
        )

    def make_persistent_model(self, ctx, model: YearModel):
        # base manifest already names type(self) dynamically
        manifest = super().make_persistent_model(ctx, model)
        with open(os.path.join(manifest.location, "years.json"), "w") as f:
            json.dump(model.years, f)
        return manifest

    def load_model(self, ctx, manifest: PersistentModelManifest) -> YearModel:
        base = super().load_model(ctx, manifest)
        with open(os.path.join(manifest.location, "years.json")) as f:
            years = {k: int(v) for k, v in json.load(f).items()}
        return YearModel(als=base.als, categories=base.categories,
                         years=years)


def engine_factory() -> Engine:
    return Engine(
        data_source_class_map=FilterByYearDataSource,
        preparator_class_map=FilterByYearPreparator,
        algorithm_class_map={"als": FilterByYearAlgorithm},
        serving_class_map=FirstServing,
    )
