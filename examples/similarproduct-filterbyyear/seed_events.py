"""Seed FilterByYearApp: two view communities over 16 items, each item
$set with a release year. Run after `pio app new FilterByYearApp`."""

import sys

import numpy as np

from predictionio_tpu.core.datamap import DataMap
from predictionio_tpu.core.event import Event
from predictionio_tpu.storage.registry import Storage

storage = Storage.default()
app = storage.get_meta_data_apps().get_by_name("FilterByYearApp")
if app is None:
    sys.exit("app 'FilterByYearApp' not found — run "
             "`pio app new FilterByYearApp` first")

events = storage.get_events()
rng = np.random.default_rng(11)
n = 0
for i in range(16):
    events.insert(
        Event(event="$set", entity_type="item", entity_id=f"i{i}",
              properties=DataMap({"year": 1990 + i})),
        app.id,
    )
    n += 1
for u in range(20):
    for i in range(16):
        if i % 2 == u % 2 and rng.random() < 0.8:
            events.insert(
                Event(event="view", entity_type="user", entity_id=f"u{u}",
                      target_entity_type="item", target_entity_id=f"i{i}",
                      properties=DataMap({})),
                app.id,
            )
            n += 1
print(f"seeded {n} events into FilterByYearApp (app id {app.id})")
