"""Local ridge regression — the pure-LocalAlgorithm pattern engine.

Role parity: the reference's ``examples/experimental/
scala-local-regression`` (a local ordinary-least-squares engine, the
canonical LAlgorithm demonstration — model trained and served entirely
on the driver, reference LAlgorithm.scala:45-133). Here the same
pattern on the TPU build's taxonomy: a ``LocalAlgorithm`` whose
closed-form ridge solve runs in host NumPy and never touches the mesh
— the right placement for models this small, where a device dispatch
would cost more than the solve.

DataSource reads each entity's ``$set`` properties: numeric features
(``x0..``) plus a numeric target (``y``).
"""

from __future__ import annotations

import dataclasses

import numpy as np

from predictionio_tpu.controller import (
    DataSource,
    Engine,
    FirstServing,
    IdentityPreparator,
    LocalAlgorithm,
    Params,
)


@dataclasses.dataclass(frozen=True)
class Query:
    features: tuple = ()


@dataclasses.dataclass(frozen=True)
class PredictedResult:
    prediction: float = 0.0


@dataclasses.dataclass(frozen=True)
class DSParams(Params):
    app_name: str = ""
    entity_type: str = "point"
    features: tuple = ("x0", "x1")
    target: str = "y"


@dataclasses.dataclass(frozen=True)
class TrainingData:
    X: np.ndarray  # [N, F]
    y: np.ndarray  # [N]


class PointDataSource(DataSource):
    params_class = DSParams

    def read_training(self, ctx) -> TrainingData:
        p = self.params
        props = ctx.event_store().aggregate_properties(
            p.app_name, p.entity_type,
            required=list(p.features) + [p.target],
        )
        rows, targets = [], []
        for _, pm in sorted(props.items()):
            rows.append([pm.get(f, float) for f in p.features])
            targets.append(pm.get(p.target, float))
        if not rows:
            raise ValueError(
                f"no {p.entity_type!r} entities with "
                f"{list(p.features) + [p.target]} for app {p.app_name!r}")
        return TrainingData(
            X=np.asarray(rows, dtype=np.float64),
            y=np.asarray(targets, dtype=np.float64),
        )


@dataclasses.dataclass(frozen=True)
class RidgeParams(Params):
    lambda_: float = 1e-6


@dataclasses.dataclass(frozen=True)
class RidgeModel:
    weights: np.ndarray    # [F]
    intercept: float


class RidgeRegressionAlgorithm(LocalAlgorithm):
    params_class = RidgeParams
    query_class = Query

    def train(self, ctx, td: TrainingData) -> RidgeModel:
        X = np.concatenate([td.X, np.ones((len(td.X), 1))], axis=1)
        A = X.T @ X + self.params.lambda_ * np.eye(X.shape[1])
        w = np.linalg.solve(A, X.T @ td.y)
        return RidgeModel(weights=w[:-1], intercept=float(w[-1]))

    def predict(self, model: RidgeModel, query: Query) -> PredictedResult:
        x = np.asarray(query.features, dtype=np.float64)
        if x.shape != model.weights.shape:
            raise ValueError(
                f"query has {x.size} features; model expects "
                f"{model.weights.size}")
        return PredictedResult(
            prediction=float(x @ model.weights + model.intercept))


def engine_factory() -> Engine:
    return Engine(
        data_source_class_map=PointDataSource,
        preparator_class_map=IdentityPreparator,
        algorithm_class_map={"ridge": RidgeRegressionAlgorithm},
        serving_class_map=FirstServing,
    )
