"""bench_sharding — DP×MP tensor-parallel factor tables on the fused
ALS flagship path (ROADMAP item 1 / ISSUE 19).

The measurement runs in a CHILD process pinned to
``--xla_force_host_platform_device_count=8`` (the bench parent owns a
1-device jax runtime that cannot re-topologize), prints one JSON line,
and the parent folds it into the round artifact. Two phases:

- **matched shapes** — the same synthetic training problem through
  `pio train --profile`'s run_train twice: replicated baseline vs
  ``PIO_TRAIN_SHARD_FACTORS=1`` on the EngineContext's own auto mesh
  (the artifact records the persisted model axis). The
  artifact carries each run's MFU and HBM high-water exactly as
  TRAIN_REPORT.json states them (honest-or-null: the CPU backend has
  no ``memory_stats()``, so measured HBM is null here and real on
  TPU — the COMPUTED factor-table bytes per device are recorded
  alongside and are exact either way), plus the max |Δ| between the
  two runs' saved factor tables — the numerics pin, restated as a
  bench number.
- **rank-512 point** — the table size the sharding exists for, run
  sharded-only at a catalog whose REPLICATED tables exceed the stated
  per-device budget while the 8-way shards fit. On this CPU host the
  budget is a scale model (``R512_DEVICE_BUDGET_BYTES``, stated in the
  artifact): virtual devices share host RAM, so "does not fit" is an
  arithmetic claim over the recorded byte sizes, not an OOM — the
  byte sizes themselves are exact and transfer 1:1 to a real HBM
  budget. The point records per-device table bytes, wall seconds, and
  MFU of the sharded run that completed.

Standalone: ``python bench_sharding.py`` writes
BENCH_sharding_rNN.json; ``bench.py`` runs the same child shrunk under
``--skip-heavy``.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

#: the rank-512 point's stated per-device budget (scale model of a
#: real device HBM budget — see module docstring; the artifact records
#: it so the "cannot fit replicated" claim is checkable arithmetic)
R512_DEVICE_BUDGET_BYTES = 64 << 20

_DEVICES = 8


def _table_bytes(users: int, items: int, rank: int) -> int:
    return (users + items) * rank * 4       # two f32 factor tables


# ---------------------------------------------------------------------------
# child (runs under forced 8 devices)
# ---------------------------------------------------------------------------


def _child(shrunk: bool) -> dict:
    from predictionio_tpu.utils.testing import force_cpu_devices

    force_cpu_devices(_DEVICES)

    import numpy as np
    import jax
    from jax.sharding import Mesh

    assert jax.device_count() == _DEVICES

    import tempfile

    from predictionio_tpu.core.datamap import DataMap
    from predictionio_tpu.core.event import Event
    from predictionio_tpu.models.als import ALSModel
    from predictionio_tpu.obs.compile import recorder
    from predictionio_tpu.obs.device import TrainProfiler
    from predictionio_tpu.ops.als import RatingsCOO, als_train
    from predictionio_tpu.storage.base import App
    from predictionio_tpu.utils.testing import memory_storage
    from predictionio_tpu.workflow.train import run_train

    out: dict = {"train_sharding_devices": _DEVICES}

    # -- phase 1: matched shapes through run_train --profile ------------
    users, items, rank = (96, 64, 8) if shrunk else (384, 256, 32)
    storage = memory_storage()
    app_id = storage.get_meta_data_apps().insert(App(0, "BenchShardApp"))
    events = storage.get_events()
    events.init(app_id)
    rng = np.random.default_rng(17)
    density = 0.3 if shrunk else 0.08
    for u in range(users):
        for i in rng.choice(items, size=max(1, int(items * density)),
                            replace=False):
            events.insert(
                Event(event="rate", entity_type="user",
                      entity_id=f"u{u}", target_entity_type="item",
                      target_entity_id=f"i{int(i)}",
                      properties=DataMap(
                          {"rating": float(rng.integers(1, 6))})),
                app_id)
    variant = {
        "id": "bench-sharding",
        "engineFactory":
            "predictionio_tpu.templates.recommendation.engine_factory",
        "datasource": {"params": {"app_name": "BenchShardApp"}},
        "algorithms": [{"name": "als",
                        "params": {"rank": rank, "num_iterations": 2,
                                   "lambda_": 0.05, "seed": 11}}],
    }

    factors = {}
    model_ax = None
    for label, env_val in (("replicated", "0"), ("sharded", "1")):
        os.environ["PIO_TRAIN_SHARD_FACTORS"] = env_val
        recorder().reset()
        with tempfile.TemporaryDirectory() as model_dir:
            os.environ["PIO_MODEL_DIR"] = model_dir
            outcome = run_train(variant=variant, storage=storage,
                                profiler=TrainProfiler())
            # reload replicated either way: the parity claim compares
            # host values, not layouts
            os.environ["PIO_SERVING_SHARD_FACTORS"] = "0"
            located = _find_model_dir(model_dir)
            with open(os.path.join(located, "model.json")) as f:
                sharded_meta = json.load(f).get("sharded")
            # the parity number is vacuous if the "sharded" run
            # silently trained replicated — pin the persisted fact
            assert (sharded_meta is not None) == (label == "sharded"), label
            if sharded_meta is not None:
                model_ax = int(sharded_meta["ways"])
            model = ALSModel.load(located)
            factors[label] = (np.asarray(model.user_factors),
                              np.asarray(model.item_factors))
        report = outcome.report
        mfu = report.get("mfu")
        hbm = (report.get("hbm") or {}).get("peakBytes")
        out[f"train_sharding_{label}_mfu"] = (
            round(mfu, 6) if isinstance(mfu, float) else None)
        out[f"train_sharding_{label}_hbm_peak_bytes"] = hbm
        out[f"train_sharding_{label}_wall_seconds"] = round(
            report["wallSeconds"], 3)
    n_users = factors["replicated"][0].shape[0]
    n_items = factors["replicated"][1].shape[0]
    out["train_sharding_model_axis"] = model_ax
    out["train_sharding_rank"] = rank
    out["train_sharding_users"] = n_users
    out["train_sharding_items"] = n_items
    out["train_sharding_replicated_table_bytes_per_device"] = _table_bytes(
        n_users, n_items, rank)
    # row-sharded tables put 1/model_ax of each table on a device
    out["train_sharding_sharded_table_bytes_per_device"] = (
        _table_bytes(n_users, n_items, rank) // model_ax)
    out["train_sharding_parity_max_abs_diff"] = float(max(
        np.max(np.abs(factors["replicated"][0] - factors["sharded"][0])),
        np.max(np.abs(factors["replicated"][1] - factors["sharded"][1]))))

    # -- phase 2: the rank-512 sharded-only point ------------------------
    r_users, r_items, r_rank, r_nnz = (
        (1024, 768, 64, 20_000) if shrunk
        else (24_576, 16_384, 512, 250_000))
    rep_bytes = _table_bytes(r_users, r_items, r_rank)
    shard_bytes = rep_bytes // _DEVICES     # 1×8 all-model bench mesh
    rng = np.random.default_rng(23)
    coo = RatingsCOO(
        (r_users * rng.random(r_nnz) ** 1.4).astype(np.int32),
        (r_items * rng.random(r_nnz) ** 1.4).astype(np.int32),
        (rng.random(r_nnz) * 5).astype(np.float32), r_users, r_items,
    )
    mesh = Mesh(np.asarray(jax.devices()).reshape(1, _DEVICES),
                ("data", "model"))
    os.environ["PIO_TRAIN_SHARD_FACTORS"] = "1"
    import time

    t0 = time.perf_counter()
    f512 = als_train(coo, rank=r_rank, iterations=1, lam=0.05, seed=29,
                     mesh=mesh, layout="fused", shard_factors=True,
                     cg_steps=4)
    f512.item.block_until_ready()
    wall = time.perf_counter() - t0
    assert f512.item.sharding.spec[0] == "model"
    out.update({
        "train_sharding_r512_rank": r_rank,
        "train_sharding_r512_users": r_users,
        "train_sharding_r512_items": r_items,
        "train_sharding_r512_device_budget_bytes": R512_DEVICE_BUDGET_BYTES,
        "train_sharding_r512_replicated_table_bytes": rep_bytes,
        "train_sharding_r512_sharded_table_bytes_per_device": shard_bytes,
        "train_sharding_r512_fits_replicated":
            rep_bytes <= R512_DEVICE_BUDGET_BYTES,
        "train_sharding_r512_fits_sharded":
            shard_bytes <= R512_DEVICE_BUDGET_BYTES,
        "train_sharding_r512_wall_seconds": round(wall, 3),
        "train_sharding_r512_completed": True,
    })
    return out


def _find_model_dir(model_dir: str) -> str:
    """run_train writes the model under an instance-id subdirectory;
    locate the one holding model.json."""
    for name in sorted(os.listdir(model_dir)):
        cand = os.path.join(model_dir, name)
        if os.path.isfile(os.path.join(cand, "model.json")):
            return cand
    raise FileNotFoundError(f"no trained model under {model_dir}")


# ---------------------------------------------------------------------------
# parent-side section
# ---------------------------------------------------------------------------


def bench_sharding_section(shrunk: bool = False) -> dict:
    """The bench.py ``train_sharding`` section: spawn the forced-8-device
    child, return its JSON line. Raises on a failed child so bench.py's
    section isolation records it in ``sections_failed``."""
    env = {k: v for k, v in os.environ.items()
           if not k.startswith(("PIO_", "XLA_", "JAX_"))}
    env["PYTHONPATH"] = os.path.dirname(os.path.abspath(__file__))
    env["XLA_FLAGS"] = (
        f"--xla_force_host_platform_device_count={_DEVICES}")
    env["JAX_PLATFORMS"] = "cpu"
    argv = [sys.executable, os.path.abspath(__file__), "--child"]
    if shrunk:
        argv.append("--shrunk")
    p = subprocess.run(argv, env=env, capture_output=True, text=True,
                       timeout=1800)
    if p.returncode != 0:
        raise RuntimeError(
            f"sharding child failed (rc={p.returncode}): "
            f"{p.stderr.strip().splitlines()[-3:]}")
    return json.loads(p.stdout.strip().splitlines()[-1])


if __name__ == "__main__":
    if "--child" in sys.argv:
        print(json.dumps(_child(shrunk="--shrunk" in sys.argv)))
    else:
        result = bench_sharding_section(shrunk="--shrunk" in sys.argv)
        print(json.dumps(result, indent=2))
        with open("BENCH_sharding_r01.json", "w") as f:
            json.dump(result, f, indent=2)
