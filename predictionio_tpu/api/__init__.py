"""Event Server REST API, stats, webhooks, plugins.

Reference: data/src/main/scala/.../data/api/ (EventServer.scala, Stats.scala,
Webhooks.scala, EventServerPlugin.scala).
"""

from predictionio_tpu.api.event_server import EventServer, EventServerConfig, EventService

__all__ = ["EventServer", "EventServerConfig", "EventService"]
