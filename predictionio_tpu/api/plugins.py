"""Event-server plugin framework.

Parity: data/src/main/scala/.../data/api/{EventServerPlugin.scala:20-36,
EventServerPluginContext.scala,PluginsActor.scala} — plugins are either
input *blockers* (run synchronously before insert; may raise to reject the
event) or input *sniffers* (observe asynchronously after insert). The
reference discovers plugins via java.util.ServiceLoader; here they are
passed in explicitly or registered via ``register_plugin`` (the
entry-point-registry equivalent, per SURVEY.md §7's translation table).
"""

from __future__ import annotations

import abc
import dataclasses
import logging
import queue
import threading

from predictionio_tpu.core.event import Event

logger = logging.getLogger(__name__)

INPUT_BLOCKER = "inputblocker"
INPUT_SNIFFER = "inputsniffer"


@dataclasses.dataclass(frozen=True)
class EventInfo:
    """Parity: EventInfo (EventServerPlugin.scala:34-36)."""
    app_id: int
    channel_id: int | None
    event: Event


class EventServerPlugin(abc.ABC):
    """Parity: EventServerPlugin (EventServerPlugin.scala:20-32)."""

    plugin_name: str = "plugin"
    plugin_description: str = ""
    plugin_type: str = INPUT_SNIFFER

    @abc.abstractmethod
    def process(self, event_info: EventInfo, context: "EventServerPluginContext") -> None:
        """Blockers: raise to reject the event. Sniffers: observe only."""


class EventServerPluginContext:
    """Plugin bookkeeping + async dispatch to sniffers.

    Parity: EventServerPluginContext.scala (plugin maps) + PluginsActor
    (async sniffer fan-out). The actor becomes a daemon worker thread
    draining a queue.
    """

    def __init__(self, plugins: list[EventServerPlugin] | None = None):
        plugins = list(plugins or []) + list(_REGISTERED_PLUGINS)
        self.input_blockers = {
            p.plugin_name: p for p in plugins if p.plugin_type == INPUT_BLOCKER
        }
        self.input_sniffers = {
            p.plugin_name: p for p in plugins if p.plugin_type == INPUT_SNIFFER
        }
        self._queue: "queue.Queue[EventInfo | None]" = queue.Queue()
        self._worker: threading.Thread | None = None
        if self.input_sniffers:
            self._worker = threading.Thread(
                target=self._drain, name="pio-plugin-sniffers", daemon=True
            )
            self._worker.start()

    def _drain(self) -> None:
        while True:
            info = self._queue.get()
            if info is None:
                return
            for sniffer in self.input_sniffers.values():
                try:
                    sniffer.process(info, self)
                except Exception:
                    logger.exception("sniffer %s failed", sniffer.plugin_name)

    def run_blockers(self, info: EventInfo) -> None:
        """Synchronous; exceptions propagate and reject the event
        (EventServer.scala:276-280)."""
        for blocker in self.input_blockers.values():
            blocker.process(info, self)

    def notify_sniffers(self, info: EventInfo) -> None:
        """Async; fire-and-forget (EventServer.scala:282-285)."""
        if self._worker is not None:
            self._queue.put(info)

    def describe(self) -> dict:
        """The /plugins.json payload (EventServer.scala:157-177)."""
        def block(plugins: dict[str, EventServerPlugin]) -> dict:
            return {
                name: {
                    "name": p.plugin_name,
                    "description": p.plugin_description,
                    "class": type(p).__qualname__,
                }
                for name, p in plugins.items()
            }

        return {
            "plugins": {
                "inputblockers": block(self.input_blockers),
                "inputsniffers": block(self.input_sniffers),
            }
        }

    def close(self) -> None:
        if self._worker is not None:
            self._queue.put(None)
            self._worker.join(timeout=5)
            self._worker = None


_REGISTERED_PLUGINS: list[EventServerPlugin] = []


def register_plugin(plugin: EventServerPlugin) -> None:
    """Process-wide plugin registration (ServiceLoader equivalent)."""
    _REGISTERED_PLUGINS.append(plugin)
