"""The Fleet Router server: ``pio router`` on :8100 (docs/fleet.md).

A thin HTTP process fronting N engine-server replicas. Routes:

- ``POST /queries.json``   forwarded to a healthy replica of the
                           DEFAULT engine (retry on a different one,
                           optional hedging, canary split) — body bytes
                           pass through untouched in BOTH directions:
                           the router never pays a JSON parse on the
                           hot path. ``X-PIO-Engine: <name>`` selects a
                           named engine instead
- ``POST /engines/<name>/queries.json``
                           the same, path-addressed per engine — each
                           engine is an independent backend group with
                           its own membership/breakers/canary/quota
                           (fleet/gateway.py, docs/fleet.md
                           "Multi-engine routing")
- ``GET|POST /fleet/engines`` the EngineTable: status JSON, and
                           key-authed register/retire/quota/weight
                           mutations propagated across --workers
                           siblings via the admin spool
- ``GET /``, ``GET /fleet`` fleet status document: per-backend state,
                           breaker, in-flight, canary, router counters
- ``GET /fleet/metrics``   every replica's /metrics scraped (bounded),
                           re-exported with replica/group labels +
                           pio_fleet_scrape_ok + the fleet-wide
                           pio_fleet_pressure gauge (docs/fleet.md)
- ``GET /traces.json``     the router's own trace ring; with
                           ``?trace_id=`` the CROSS-PROCESS stitched
                           tree (fan-out to replicas and --workers
                           siblings; obs/stitch.py, `pio trace`)
- ``GET|POST /fleet/canary`` canary admin: read the rollout state;
                           POST ``{"weight": 25}`` to start/resize,
                           ``{"action": "abort"}`` to kill it
                           (key-authenticated when ``--router-key``)
- ``GET|POST /fleet/experiments`` the online A/B plane
                           (experiment/controller.py): define an
                           experiment over registered variant engines,
                           fold attributed conversions in, read the
                           lifecycle + per-variant online scores;
                           mutations propagate over the admin spool
- ``GET /healthz``         router process liveness
- ``GET /readyz``          503 until at least one replica is routable
- ``GET /stats.json``      router counters + upstream latency
- ``GET /metrics``         Prometheus exposition (backend state gauge,
                           retries/hedges/sheds, canary weight, the
                           per-replica breaker families)
- ``POST /stop``           shutdown (key-authenticated)

Correlation: an inbound ``X-PIO-Request-Id`` is propagated to the
chosen replica and echoed on the response; the replica's
``X-PIO-Trace-Id`` (when it traced the query) passes back to the
client. The HTTP handler goes one step beyond the engine server's
hot-path discipline (keep-alive, TCP_NODELAY, chunked-body rejection):
the router sits on EVERY fleet query and does no model work to hide
parse costs behind, so its connection loop is a minimal single-buffer
parser with ONE write per response instead of the stdlib
``BaseHTTPRequestHandler`` machinery (``_read_request`` docstring).
"""

from __future__ import annotations

import json
import logging
import socketserver
import threading
import time
from typing import Mapping
from urllib.parse import parse_qs

from predictionio_tpu.api.http_base import (
    REQUEST_ID_HEADER,
    PlainTextPayload,
    RestServer,
    access_log_enabled,
    emit_access_log,
    ensure_access_log_handler,
    resolve_request_id,
    retry_after_header,
)
from predictionio_tpu.experiment.controller import (
    EXPERIMENT_FIELD,
    EXPERIMENT_HEADER,
    VARIANT_FIELD,
    VARIANT_HEADER,
    ExperimentConfig,
    ExperimentController,
    VariantSpec,
)
from predictionio_tpu.experiment.grid import eval_points_collector
from predictionio_tpu.fleet.canary import GuardrailConfig
from predictionio_tpu.fleet.gateway import (
    QUERIES_PATH,
    EngineGateway,
)
from predictionio_tpu.fleet.router import (
    FleetRouter,
    RouterConfig,
    RouterResponse,
)
from predictionio_tpu.fleet.transport import fan_out
from predictionio_tpu.fleet.workers import WorkerHub
from predictionio_tpu.obs.aggregate import (
    ExpositionParseError,
    merge_snapshots,
    merge_sources,
    parse_exposition,
    relabel,
    source_count_metric,
)
from predictionio_tpu.obs.exporter import CONTENT_TYPE as PROMETHEUS_CONTENT_TYPE
from predictionio_tpu.obs.exporter import render_metrics, render_prometheus
from predictionio_tpu.obs.registry import (
    HistogramFamily,
    Metric,
    MetricRegistry,
    resilience_collector,
    server_info_collector,
)
from predictionio_tpu.obs.slo import SLOEngine, pressure_metric
from predictionio_tpu.obs.stitch import stitch
from predictionio_tpu.obs.trace import (
    TRACE_ID_HEADER,
    TraceLog,
    parse_trace_context,
    start_trace,
    tracing_default,
    use_trace,
)

logger = logging.getLogger(__name__)


class _Reject(Exception):
    def __init__(self, status: int, message: str,
                 headers: dict[str, str] | None = None):
        self.status = status
        self.message = message
        self.headers = headers


class RouterService:
    """Transport-free request logic over an :class:`EngineGateway` —
    one router process, N independent engine groups (fleet/gateway.py).
    ``self.router`` stays the DEFAULT engine's FleetRouter, so every
    single-engine consumer (tests, the supervisor/controller wiring,
    operator muscle memory) is untouched."""

    def __init__(self, gateway: EngineGateway):
        self.gateway = gateway
        self.config = gateway.config
        self.on_stop = lambda: None
        self.access_log = access_log_enabled(self.config.access_log)
        if self.access_log:
            ensure_access_log_handler()
        #: fleet tracing (docs/observability.md): the router opens the
        #: ROOT segment of every traced query and forwards context so
        #: replica segments stitch under its attempt spans
        self.tracing = (self.config.tracing
                        if self.config.tracing is not None
                        else tracing_default())
        self.trace_log = TraceLog()
        #: SLO engine (obs/slo.py): every routed query's outcome feeds
        #: the burn-rate gauges — at the ROUTER the availability SLO
        #: measures what CLIENTS see (sheds and all-replicas-down count
        #: against the budget even though no replica mis-served)
        self.slo = SLOEngine()
        self.request_latency = HistogramFamily(
            "pio_http_request_seconds",
            "HTTP request walltime by route (handler-measured)",
            "route", ("queries", "fleet", "metrics", "status", "traces"))
        self.registry = MetricRegistry()
        self.registry.register(self.request_latency.collect)
        #: per-engine router families (single implicit engine renders
        #: exactly the pre-gateway exposition; multi-engine adds the
        #: engine label + quota/burn families — fleet/gateway.py)
        self.registry.register(gateway.collector())
        self.registry.register(resilience_collector())
        self.registry.register(server_info_collector("router"))
        self.registry.register(self.slo.collector())
        #: `--workers N` peering (fleet/workers.py): a /metrics scrape
        #: landing on THIS worker merges every sibling's registry
        self.worker_hub: WorkerHub | None = (
            WorkerHub(self.config.worker_spool_dir,
                      metrics_text=lambda: render_prometheus(self.registry),
                      traces_snapshot=self.trace_log.snapshot,
                      timeout_s=self.config.scrape_timeout_s)
            if self.config.worker_spool_dir else None)
        #: shared admin state (fleet/workers.py): canary mutations and
        #: guardrail abort verdicts published by ANY worker are applied
        #: by every sibling's sync loop, and a respawned worker adopts
        #: the latest document at startup instead of the launch-time
        #: weight — admin no longer addresses ONE worker
        self._admin_lock = threading.Lock()
        self._admin_seq = 0
        self._admin_stop = threading.Event()
        self._admin_thread: threading.Thread | None = None
        #: optional self-healing attachments (`pio router --supervise`):
        #: the process supervisor and the scale controller register
        #: their collectors and appear in the /fleet document
        self.supervisor = None
        self.controller = None
        self.scale_set = None
        #: online A/B (experiment/controller.py): splits bare-path
        #: query traffic across variant engines, auto-promotes through
        #: the guardrail discipline; every verdict publishes to the
        #: admin spool (the `experiment` key of the cumulative doc).
        #: Ticks ride the admin sync loop's Event.wait below plus the
        #: outcome feed — the controller itself never sleeps.
        self.experiment = ExperimentController(
            gateway=self.gateway,
            on_change=lambda: self._publish_admin(
                {"action": "experiment"}))
        self.registry.register(self.experiment.collector)
        self.registry.register(eval_points_collector)
        if self.worker_hub is not None:
            self._wire_abort_hooks()
            self._sync_admin_once()     # respawn adoption
            self._admin_thread = threading.Thread(
                target=self._admin_sync_loop,
                name="pio-router-admin-sync", daemon=True)
            self._admin_thread.start()

    @property
    def router(self) -> FleetRouter:
        """The CURRENT default engine's FleetRouter — resolved per
        access, not captured at construction: a runtime
        ``{"action": "default"}`` table mutation must repoint
        /stats.json, the /fleet doc and the probe reporting too, or an
        operator would watch a retired engine's frozen counters while
        believing they see the default tenant."""
        return self.gateway.default_group.router

    def _wire_abort_hooks(self) -> None:
        """Every engine group's guardrail verdict publishes to the
        admin spool — idempotent, re-run after table mutations so
        runtime-registered engines latch their siblings too."""
        for group in self.gateway.groups():
            if group.router.on_canary_abort is None:
                group.router.on_canary_abort = self._publish_canary_abort

    def attach_supervisor(self, supervisor) -> None:
        from predictionio_tpu.fleet.supervisor import supervisor_collector

        self.supervisor = supervisor
        self.registry.register(supervisor_collector(supervisor))

    def attach_controller(self, controller) -> None:
        from predictionio_tpu.fleet.controller import controller_collector

        self.controller = controller
        self.registry.register(controller_collector(controller))

    def attach_scale_set(self, scale_set) -> None:
        """Per-tenant elasticity (`pio router --engine ... --supervise`
        with scaling armed): one ScaleController per engine behind a
        CapacityArbiter. Mutually exclusive with attach_controller —
        the scale-set collector owns the pio_fleet_desired_replicas /
        decisions families (labeled per engine when the gateway is)."""
        from predictionio_tpu.fleet.controller import scale_set_collector

        self.scale_set = scale_set
        self.registry.register(scale_set_collector(scale_set))

    def close(self) -> None:
        self._admin_stop.set()
        if self._admin_thread is not None:
            self._admin_thread.join(timeout=5)
            self._admin_thread = None
        if self.worker_hub is not None:
            self.worker_hub.close()

    # -- shared admin state (fleet/workers.py) -------------------------------
    def _admin_sync_loop(self) -> None:
        # Event.wait doubles as interval sleep and prompt stop — the
        # membership-loop idiom, never a bare time.sleep
        while not self._admin_stop.wait(self.config.admin_sync_interval_s):
            try:
                self._sync_admin_once()
            except Exception:  # noqa: BLE001 — a torn read is the next pass's problem
                logger.exception("admin-state sync failed")
            try:
                # experiment lifecycle ticks ride this Event.wait loop
                # (the controller never sleeps on its own)
                self.experiment.tick()
            except Exception:  # noqa: BLE001
                logger.exception("experiment tick failed")

    def _sync_admin_once(self) -> None:
        hub = self.worker_hub
        if hub is None:
            return
        doc = hub.read_admin()
        if doc is None:
            return
        with self._admin_lock:
            if doc["seq"] <= self._admin_seq:
                return
            self._admin_seq = doc["seq"]
        self._apply_admin(doc)

    def _apply_admin(self, doc: dict) -> None:
        # cumulative engine-table documents (fleet/gateway.py): every
        # publish carries the WHOLE table (specs + per-engine canary
        # state), so a respawned worker adopts everything from the one
        # latest document — register/retire/quota/weight/abort all ride
        # the same diff-apply. The legacy action fields remain for
        # operator readability (and the pinned abort-doc shape).
        experiment = doc.get("experiment")
        if isinstance(experiment, dict):
            try:
                if self.experiment.adopt_state(experiment):
                    logger.info("adopted shared experiment state "
                                "(seq %s): %s", doc.get("seq"),
                                experiment.get("state"))
            except Exception:  # noqa: BLE001 — a bad doc must not kill the sync loop
                logger.exception("adopting shared experiment state "
                                 "failed (seq %s)", doc.get("seq"))
        fleet = doc.get("fleet")
        if isinstance(fleet, dict):
            try:
                changed = self.gateway.adopt_table(fleet)
            except Exception:  # noqa: BLE001 — a bad doc must not kill the sync loop
                logger.exception("adopting shared engine table failed "
                                 "(seq %s)", doc.get("seq"))
                return
            self._wire_abort_hooks()
            if changed:
                logger.info("adopted shared engine table (seq %d): %s",
                            doc["seq"], doc.get("action"))
            return
        action = doc.get("action")
        target = self.gateway.get(
            str(doc.get("engine") or self.gateway.default_engine))
        canary = (target or self.gateway.default_group).router.canary
        if action == "set_weight":
            try:
                weight = float(doc["weight"])
            except (KeyError, TypeError, ValueError):
                logger.warning("ignoring malformed admin doc: %r", doc)
                return
            guardrail = None
            g = doc.get("guardrail")
            if isinstance(g, dict):
                try:
                    guardrail = GuardrailConfig(
                        min_requests=int(g["minRequests"]),
                        max_error_rate=float(g["maxErrorRate"]),
                        max_p99_ms=float(g["maxP99Ms"]),
                        window=int(g["window"]))
                except (KeyError, TypeError, ValueError):
                    guardrail = None
            canary.set_weight(weight, guardrail=guardrail)
            logger.info("adopted shared canary weight %.1f%% (seq %d)",
                        weight, doc["seq"])
        elif action == "abort":
            canary.abort(
                str(doc.get("reason") or "sibling abort"))
            logger.warning("adopted sibling canary abort (seq %d): %s",
                           doc["seq"], doc.get("reason"))
        else:
            logger.warning("unknown admin action %r (seq %s)", action,
                           doc.get("seq"))

    def _publish_admin(self, doc: dict) -> None:
        hub = self.worker_hub
        if hub is None:
            return
        # every publish is CUMULATIVE: the whole engine table (specs +
        # per-engine canary state) and the experiment state ride along,
        # so the LATEST document alone is sufficient for a respawned
        # sibling — an action log would strand whichever mutation was
        # published second-to-last
        doc = {**doc, "fleet": self.gateway.table_doc()}
        experiment_doc = self.experiment.state_doc()
        if experiment_doc is not None:
            doc["experiment"] = experiment_doc
        # publish AND advance _admin_seq under the one lock: the sync
        # loop compares seq under the same lock, so it can never read
        # the freshly-committed document in a gap before the seq
        # advances and re-apply our own mutation (a re-applied
        # set_weight would clear the guardrail window a second time)
        with self._admin_lock:
            try:
                seq = hub.publish_admin(doc)
            except OSError:
                logger.exception("publishing admin state failed")
                return
            self._admin_seq = max(self._admin_seq, seq)

    def _publish_canary_abort(self) -> None:
        """FleetRouter.on_canary_abort hook: a guardrail verdict on
        THIS worker latches every sibling too — one worker's window
        seeing the breach first must not leave the others happily
        routing canary traffic. Shared by every engine group's hook:
        the published table carries EVERY canary's state, the legacy
        reason field names the (most recently) aborted one."""
        reason = None
        engine = None
        for group in self.gateway.groups():
            snap = group.router.canary.snapshot()
            if snap["aborted"] and snap.get("abortReason"):
                reason = snap["abortReason"]
                engine = group.name
                if group.name == self.gateway.default_engine:
                    break
        self._publish_admin({
            "action": "abort",
            "reason": reason or "guardrail abort",
            **({"engine": engine} if engine else {}),
        })

    # -- auth ---------------------------------------------------------------
    def _check_router_key(self, params: Mapping[str, str]) -> None:
        if self.config.router_key is None:
            return
        if params.get("accessKey") != self.config.router_key:
            raise _Reject(401, "invalid accessKey")

    # -- routes -------------------------------------------------------------
    def handle(self, method: str, path: str, params: Mapping[str, str],
               headers: Mapping[str, str], body: bytes,
               request_id: str) -> RouterResponse | tuple:
        """Returns a RouterResponse (raw passthrough) or the engine
        server's ``(status, payload[, headers])`` tuple shape."""
        try:
            if method == "POST" and self.gateway.is_query_path(path):
                # experiment split first: a bare-path query with no
                # explicit engine selection may be assigned to a
                # variant (experiment/controller.py) — the assignment
                # rides the X-PIO-Engine header into the same O(1)
                # resolution everything else uses, and the attribution
                # pair is forwarded to the replica + stamped on the
                # response
                assigned = self._experiment_assign(path, headers)
                if assigned is not None:
                    experiment_id, variant = assigned
                    headers = {**headers,
                               "x-pio-engine": variant,
                               "x-pio-experiment": experiment_id,
                               "x-pio-variant": variant}
                # O(1) engine resolution on the path (bare
                # /queries.json → default engine or X-PIO-Engine
                # header), per-engine quota, then the engine's own
                # pick/forward/retry/hedge (fleet/gateway.py)
                out = self.gateway.route(path, body, headers,
                                         request_id)
                if assigned is not None:
                    self._stamp_attribution(out, experiment_id, variant)
                return out
            if method == "GET" and path in ("/", "/fleet"):
                return (200, self.fleet_doc())
            if method == "GET" and path == "/stats.json":
                return (200, {"router": self.router.stats.snapshot(),
                              "canary": self.router.canary.snapshot(),
                              "engines": self.gateway.snapshot()})
            if path == "/fleet/engines":
                if method == "GET":
                    return (200, self.engines_doc())
                if method == "POST":
                    self._check_router_key(params)
                    return self.engines_admin(body)
            if method == "GET" and path == "/metrics":
                return (200, PlainTextPayload(
                    self.metrics_text(), PROMETHEUS_CONTENT_TYPE))
            if method == "GET" and path == "/fleet/metrics":
                return (200, PlainTextPayload(
                    self.fleet_metrics_text(), PROMETHEUS_CONTENT_TYPE))
            if method == "GET" and path == "/traces.json":
                trace_id = params.get("trace_id")
                if trace_id:
                    return self.stitched_trace(trace_id)
                return (200, {"tracing": self.tracing,
                              "traces": self.trace_log.snapshot()})
            if method == "GET" and path == "/healthz":
                return (200, {"status": "ok"})
            if method == "GET" and path == "/readyz":
                return self.readyz()
            if path == "/fleet/canary":
                if method == "GET":
                    return (200, self.router.canary.snapshot())
                if method == "POST":
                    self._check_router_key(params)
                    return self.canary_admin(body)
            if path == "/fleet/experiments":
                if method == "GET":
                    self.experiment.tick()
                    return (200,
                            {"experiment": self.experiment.snapshot()})
                if method == "POST":
                    self._check_router_key(params)
                    return self.experiments_admin(body)
            if method == "POST" and path == "/stop":
                self._check_router_key(params)
                threading.Thread(target=self.on_stop, daemon=True).start()
                return (200, {"message": "Shutting down"})
            return (404, {"message": f"no route for {method} {path}"})
        except _Reject as r:
            if r.headers:
                return (r.status, {"message": r.message}, r.headers)
            return (r.status, {"message": r.message})
        except Exception as e:
            logger.exception("unhandled error in %s %s", method, path)
            return (500, {"message": f"internal error: {e}"})

    # -- scrape-time aggregation (docs/fleet.md) ----------------------------
    def metrics_text(self) -> str:
        """This worker's exposition — merged with every live sibling's
        when `--workers N` peering is on (counters summed, histograms
        bucket-merged, gauges labeled per worker), so a scrape landing
        on one SO_REUSEPORT worker reports fleet-of-workers truth."""
        own = self.registry.collect()
        hub = self.worker_hub
        if hub is None:
            return render_metrics(own)
        sources: list[tuple[str, list]] = [(hub.worker_id, own)]
        for worker_id, body in hub.fetch_peer_bodies("/metrics"):
            try:
                sources.append((worker_id,
                                parse_exposition(body.decode())))
            except (ExpositionParseError, UnicodeDecodeError) as exc:
                logger.warning("worker %s exposition unparseable: %s",
                               worker_id, exc)
        merged = merge_sources(sources, source_label="worker")
        merged.append(source_count_metric(
            "pio_router_workers",
            "Live router worker processes folded into this scrape",
            len(sources)))
        return render_metrics(merged)

    def fleet_metrics_text(self) -> str:
        return render_metrics(self.fleet_metrics_families())

    def fleet_metrics_families(self) -> list[Metric]:
        """Scrape every replica's ``/metrics`` (bounded per replica by
        ``scrape_timeout_s``) across EVERY engine group and re-export
        with ``replica``/``group`` labels — plus ``engine=<name>`` when
        the deployment is explicitly multi-engine (the single implicit
        engine keeps the pre-gateway label set; obs/aggregate.relabel
        never overwrites a label a replica already exports, so a
        replica's own ``engine`` label survives the annotation). The
        fleet-wide ``pio_fleet_pressure`` gauge derives from the
        bucket-merged queue-wait/device-dispatch histograms, with a
        per-engine sample per group in multi-engine mode (the signal
        the ScaleController needs to scale engines independently).
        Scrapes bypass the data-path breakers on purpose: a failed
        scrape must not mark a replica down for traffic, it just
        reports ``pio_fleet_scrape_ok 0``. Returned as Metric families
        so the scale controller reads the same contract WITHOUT a
        render→reparse round-trip per tick (``GET /fleet/metrics``
        renders them)."""
        labeled = self.gateway.labeled
        scrape_ok = Metric(
            name="pio_fleet_scrape_ok", kind="gauge",
            help="1 when the replica answered the fan-out scrape")

        def scrape(item) -> tuple[dict, list | None]:
            engine, backend = item
            labels = {"replica": backend.id, "group": backend.group,
                      **({"engine": engine} if labeled else {})}
            try:
                response = backend.transport.request(
                    "GET", "/metrics",
                    timeout=self.config.scrape_timeout_s)
                if response.status != 200:
                    raise ExpositionParseError(
                        f"HTTP {response.status}")
                return labels, parse_exposition(response.body.decode())
            except Exception as exc:  # noqa: BLE001 — degrade per replica
                logger.warning("fleet scrape of %s failed: %s",
                               backend.id, exc)
                return labels, None

        sources: list[tuple[str, list]] = []
        # queue/device histograms accumulate per ENGINE (plus the
        # fleet-wide merge across all of them)
        queue_snaps: dict[str, list] = {}
        device_snaps: dict[str, list] = {}
        # ONE membership snapshot per group for both the fan-out and
        # the zip: `backends` is a per-call copy and the scale
        # controller mutates the underlying list at runtime — a second
        # read could be shorter/shifted and attribute scrape results to
        # the wrong replica
        targets = [
            (group.name, backend)
            for group in self.gateway.groups()
            for backend in group.router.membership.backends
        ]
        # concurrent per replica (fan_out): the scrape pays the slowest
        # replica's timeout, not the sum over black-holed ones
        scraped = fan_out(targets, scrape)
        for (engine, backend), result in zip(targets, scraped):
            if result is None:
                continue
            labels, families = result
            if families is None:
                scrape_ok.samples.append((labels, 0.0))
                continue
            scrape_ok.samples.append((labels, 1.0))
            for fam in families:
                if fam.name == "pio_serving_queue_wait_seconds":
                    queue_snaps.setdefault(engine, []).extend(
                        s for _, s in fam.histograms)
                elif fam.name == "pio_serving_device_dispatch_seconds":
                    device_snaps.setdefault(engine, []).extend(
                        s for _, s in fam.histograms)
            sources.append((backend.id, relabel(families, labels)))
        merged = merge_sources(sources, source_label="replica")
        merged.append(scrape_ok)
        all_queue = [s for snaps in queue_snaps.values() for s in snaps]
        all_device = [s for snaps in device_snaps.values() for s in snaps]
        if all_queue and all_device:
            pressure = pressure_metric(
                merge_snapshots(all_queue), merge_snapshots(all_device))
            if labeled:
                for engine in queue_snaps:
                    if engine not in device_snaps:
                        continue
                    per = pressure_metric(
                        merge_snapshots(queue_snaps[engine]),
                        merge_snapshots(device_snaps[engine]),
                        labels={"engine": engine})
                    pressure.samples.extend(per.samples)
            merged.append(pressure)
        if self.scale_set is not None:
            # the per-tenant elasticity families ride the fleet-facing
            # exposition too: every scale decision is attributed
            # `engine=` right next to the pressure signal it answered
            # (the acceptance contract; also in /metrics via the
            # registry). The scale set's own sweep only reads
            # pio_fleet_pressure from this list — no recursion.
            from predictionio_tpu.fleet.controller import (
                scale_set_collector,
            )

            merged.extend(scale_set_collector(self.scale_set)())
        return merged

    def stitched_trace(self, trace_id: str) -> tuple:
        """``GET /traces.json?trace_id=`` — fan out to every replica's
        (and worker sibling's) trace ring, join the segments that share
        ``trace_id`` into one tree (obs/stitch.py)."""
        segments = self.trace_log.find(trace_id)
        hub = self.worker_hub
        if hub is not None:
            for worker_id, body in hub.fetch_peer_bodies("/traces.json"):
                try:
                    docs = json.loads(body).get("traces", [])
                except (json.JSONDecodeError, UnicodeDecodeError):
                    continue
                for doc in docs:
                    if doc.get("traceId") == trace_id:
                        doc.setdefault("source", f"worker:{worker_id}")
                        segments.append(doc)
        def fetch_ring(backend) -> list | None:
            try:
                response = backend.transport.request(
                    "GET", "/traces.json",
                    timeout=self.config.scrape_timeout_s)
                return json.loads(response.body).get("traces", [])
            except Exception:  # noqa: BLE001 — a dead replica's ring is gone anyway
                return None

        scrape_errors = 0
        # concurrent per replica ACROSS every engine group: the merge
        # pays the slowest replica's timeout, not the sum
        # (fleet/transport.fan_out); one snapshot for fan-out AND zip —
        # the backend lists mutate at runtime
        backends = [
            backend
            for group in self.gateway.groups()
            for backend in group.router.membership.backends
        ]
        rings = fan_out(backends, fetch_ring)
        for backend, docs in zip(backends, rings):
            if docs is None:
                scrape_errors += 1
                continue
            for doc in docs:
                if doc.get("traceId") == trace_id:
                    doc.setdefault("source", backend.id)
                    segments.append(doc)
        tree = stitch(segments)
        if tree is None:
            return (404, {"traceId": trace_id, "found": False,
                          "scrapeErrors": scrape_errors,
                          "message": f"no segment of trace {trace_id} "
                                     "found on router or replicas"})
        return (200, {"traceId": trace_id, "found": True,
                      "segments": len(segments),
                      "scrapeErrors": scrape_errors,
                      "trace": tree})

    def readyz(self) -> tuple:
        """Ready iff at least one replica is routable in ANY engine
        group — a router with no serveable engine at all must drain
        from ITS OWN load balancer too (one dark tenant does not; its
        requests answer fast 503s while the siblings keep serving)."""
        by_engine = {
            group.name: len(group.router.membership.routable())
            for group in self.gateway.groups()
        }
        routable = sum(by_engine.values())
        extra = ({"routableByEngine": by_engine}
                 if self.gateway.labeled else {})
        if routable > 0:
            return (200, {"status": "ready",
                          "routableBackends": routable, **extra})
        return (503, {"status": "unavailable", "routableBackends": 0,
                      **extra},
                {"Retry-After": retry_after_header(
                    max(1.0, self.router.membership.probe_interval_s))})

    def fleet_doc(self) -> dict:
        return {
            "status": "alive",
            # flattened across engine groups: identical to the
            # pre-gateway doc for the single implicit engine (each
            # backend snapshot carries its engine name when a gateway
            # stamped one); canary/router keys stay the DEFAULT
            # engine's — per-engine views live on /fleet/engines
            "backends": [
                doc
                for group in self.gateway.groups()
                for doc in group.router.membership.snapshot()
            ],
            "canary": self.router.canary.snapshot(),
            "router": self.router.stats.snapshot(),
            "defaultEngine": self.gateway.default_engine,
            "engines": self.gateway.engine_names(),
            "inflight": self.router.inflight,
            "maxInflight": self.config.max_inflight,
            "hedge": self.config.hedge,
            "probe": {
                "intervalS": self.router.membership.probe_interval_s,
                "timeoutS": self.router.membership.probe_timeout_s,
                "downAfter": self.router.membership.down_after,
                "upAfter": self.router.membership.up_after,
            },
            **({"supervisor": self.supervisor.snapshot()}
               if self.supervisor is not None else {}),
            **({"scaleController": self.controller.snapshot()}
               if self.controller is not None else {}),
            **({"elasticity": self.scale_set.snapshot()}
               if self.scale_set is not None else {}),
            **({"experiment": exp_snap}
               if (exp_snap := self.experiment.snapshot()) is not None
               else {}),
        }

    def engines_doc(self) -> dict:
        """``GET /fleet/engines``: the gateway table, each engine
        annotated with its scale state (bounds, desired/actual, last
        decision+reason) when an elasticity loop — per-tenant scale
        set or the single PR 9 controller — is attached. Storage-free:
        everything comes from in-process snapshots."""
        doc = self.gateway.snapshot()
        scales: dict[str, dict] = {}
        if self.scale_set is not None:
            scales = self.scale_set.snapshot()["engines"]
        elif self.controller is not None:
            scales = {self.gateway.default_engine:
                      self.controller.snapshot()}
        if scales:
            for entry in doc["engines"]:
                snap = scales.get(entry.get("name"))
                if snap is None:
                    continue
                entry["scale"] = {
                    "minReplicas": snap["minReplicas"],
                    "maxReplicas": snap["maxReplicas"],
                    "desiredReplicas": snap["desiredReplicas"],
                    "actualReplicas": snap["actualReplicas"],
                    "dryRun": snap["dryRun"],
                    "lastDecision": snap.get("lastDecision"),
                    "lastReason": snap.get("lastReason"),
                }
        exp_snap = self.experiment.snapshot()
        if exp_snap is not None:
            # `pio status --router` reads this key for the experiment
            # block (cli/pio.py)
            doc["experiment"] = exp_snap
        return doc

    def engines_admin(self, body: bytes) -> tuple:
        """POST /fleet/engines (key-authed): mutate the engine table at
        runtime — ``{"action": "register", "engine": {...}}``,
        ``{"action": "retire"|"quota"|"weight"|"default",
        "name": <engine>, ...}`` (fleet/gateway.py). Every mutation
        publishes the cumulative table to the worker spool so siblings
        and respawned workers adopt it."""
        try:
            doc = json.loads(body or b"{}")
        except json.JSONDecodeError:
            raise _Reject(400, "the request body is not valid JSON")
        if not isinstance(doc, dict):
            raise _Reject(400, "the request body must be a JSON object")
        # adopt the latest sibling state BEFORE applying the local
        # mutation: the publish below is CUMULATIVE (the whole table),
        # so publishing from a stale view would silently erase a
        # sibling's not-yet-synced mutation fleet-wide (e.g. a tenant
        # registered through another worker inside the sync interval,
        # retired everywhere by this publish). This shrinks the
        # last-writer-wins window from admin_sync_interval_s to the
        # mutation handling itself; truly simultaneous conflicting
        # publishes remain last-writer-wins — the documented contract
        # for human-speed admin (fleet/workers.py)
        self._sync_admin_once()
        try:
            snap = self.gateway.admin_mutate(doc)
        except ValueError as exc:
            raise _Reject(400, str(exc))
        self._wire_abort_hooks()
        self._publish_admin(
            {"action": f"engines_{doc.get('action')}"})
        logger.info("engine table mutated: %s", doc.get("action"))
        return (200, snap)

    def canary_admin(self, body: bytes) -> tuple:
        """POST /fleet/canary: ``{"weight": <0..100>[, "guardrail":
        {...}]}`` starts/resizes a rollout (clearing any abort latch);
        ``{"action": "abort"}`` kills it. An optional ``"engine"`` key
        targets a named engine's canary; absent, the DEFAULT engine —
        the single-engine contract unchanged."""
        try:
            doc = json.loads(body or b"{}")
        except json.JSONDecodeError:
            raise _Reject(400, "the request body is not valid JSON")
        if not isinstance(doc, dict):
            raise _Reject(400, "the request body must be a JSON object")
        # sync-before-mutate, same reason as engines_admin: this
        # mutation's publish carries the WHOLE table
        self._sync_admin_once()
        engine = doc.get("engine")
        if engine is None:
            group = self.gateway.default_group
        else:
            group = self.gateway.get(str(engine))
            if group is None:
                raise _Reject(400, f"unknown engine {engine!r}")
        canary = group.router.canary
        engine_field = ({"engine": group.name}
                        if group.name != self.gateway.default_engine
                        else {})
        if doc.get("action") == "abort":
            canary.abort()
            self._publish_admin({"action": "abort",
                                 "reason": "operator abort",
                                 **engine_field})
            return (200, canary.snapshot())
        if "weight" not in doc:
            raise _Reject(400, 'expected {"weight": <0..100>} or '
                               '{"action": "abort"}')
        try:
            weight = float(doc["weight"])
        except (TypeError, ValueError):
            raise _Reject(400, f"invalid weight: {doc['weight']!r}")
        if not 0.0 <= weight <= 100.0:
            raise _Reject(400, "weight must be within 0..100")
        guardrail = None
        if isinstance(doc.get("guardrail"), dict):
            g = doc["guardrail"]
            current = canary.guardrail
            try:
                guardrail = GuardrailConfig(
                    min_requests=int(g.get("minRequests",
                                           current.min_requests)),
                    max_error_rate=float(g.get("maxErrorRate",
                                               current.max_error_rate)),
                    max_p99_ms=float(g.get("maxP99Ms", current.max_p99_ms)),
                    window=int(g.get("window", current.window)),
                )
            except (TypeError, ValueError) as exc:
                raise _Reject(400, f"invalid guardrail: {exc}")
        canary.set_weight(weight, guardrail=guardrail)
        admin_doc: dict = {"action": "set_weight", "weight": weight,
                          **engine_field}
        if guardrail is not None:
            admin_doc["guardrail"] = {
                "minRequests": guardrail.min_requests,
                "maxErrorRate": guardrail.max_error_rate,
                "maxP99Ms": guardrail.max_p99_ms,
                "window": guardrail.window,
            }
        self._publish_admin(admin_doc)
        logger.info("canary weight set to %.1f%% (engine %s)", weight,
                    group.name)
        return (200, canary.snapshot())

    # -- experimentation (experiment/controller.py) --------------------------
    def _experiment_assign(self, path: str,
                           headers: Mapping[str, str]) -> tuple | None:
        """A bare-path query with no explicit engine selection is
        eligible for the experiment split; path- or header-addressed
        queries keep their explicit routing — an experiment must never
        hijack a client that asked for a specific tenant."""
        if path != QUERIES_PATH or headers.get("x-pio-engine"):
            return None
        return self.experiment.assign()

    def _stamp_attribution(self, out: RouterResponse, experiment_id: str,
                           variant: str) -> None:
        """Attribution on the way out: headers always; the prId-style
        body fields only when the replica didn't already stamp them
        (it does when the forwarded attribution headers reached it).
        Only experiment-ASSIGNED responses pay this parse — the normal
        hot path keeps its bytes-through-untouched contract."""
        out.headers[EXPERIMENT_HEADER] = experiment_id
        out.headers[VARIANT_HEADER] = variant
        if out.status != 200 or not out.body \
                or "json" not in (out.content_type or ""):
            return
        try:
            doc = json.loads(out.body)
        except ValueError:
            return
        if not isinstance(doc, dict) or EXPERIMENT_FIELD in doc:
            return
        doc[EXPERIMENT_FIELD] = experiment_id
        doc[VARIANT_FIELD] = variant
        out.body = json.dumps(doc).encode()

    def experiments_admin(self, body: bytes) -> tuple:
        """POST /fleet/experiments (key-authed):

        - ``{"action": "define", "experiment": {...}, "variants":
          [...]}`` starts THE experiment over already-registered
          gateway engines (``pio experiment start`` registers them
          first via POST /fleet/engines);
        - ``{"action": "conversions", "experiment": <name>,
          "conversions": {<variant>: <total>, ...}}`` folds attributed
          conversion totals into the online score (cumulative totals —
          replays never double-count);
        - ``{"action": "abort"[, "reason": ...]}`` kills it.

        Every mutation publishes the seq'd cumulative experiment doc
        to the worker spool (sync-before-mutate, same as the engine
        table) so siblings and respawns agree."""
        try:
            doc = json.loads(body or b"{}")
        except json.JSONDecodeError:
            raise _Reject(400, "the request body is not valid JSON")
        if not isinstance(doc, dict):
            raise _Reject(400, "the request body must be a JSON object")
        self._sync_admin_once()
        action = doc.get("action", "define")
        if action == "define":
            try:
                config = ExperimentConfig.from_doc(doc["experiment"])
                variants = [VariantSpec.from_doc(v)
                            for v in doc["variants"]]
            except (KeyError, TypeError, ValueError) as exc:
                raise _Reject(400, f"invalid experiment definition: {exc}")
            missing = [v.name for v in variants
                       if self.gateway.get(v.name) is None]
            if missing:
                raise _Reject(400, "variants are not registered engines: "
                                   f"{missing} (POST /fleet/engines first)")
            try:
                self.experiment.define(config, variants)
            except ValueError as exc:
                raise _Reject(400, str(exc))
        elif action == "conversions":
            counts = doc.get("conversions")
            if not isinstance(counts, dict):
                raise _Reject(400, 'expected {"conversions": '
                                   '{<variant>: <total>}}')
            for variant, count in counts.items():
                try:
                    self.experiment.record_conversions(
                        str(variant), int(count))
                except (TypeError, ValueError):
                    raise _Reject(400, f"invalid conversion count for "
                                       f"{variant!r}: {count!r}")
        elif action == "abort":
            self.experiment.abort(str(doc.get("reason")
                                      or "operator abort"))
        else:
            raise _Reject(400, f"unknown experiment action {action!r}")
        self.experiment.tick()
        return (200, {"experiment": self.experiment.snapshot()})


#: canned reason phrases for the statuses the router emits (the full
#: http.HTTPStatus table costs a lookup per response; this is a dict hit)
_REASONS = {200: "OK", 400: "Bad Request", 401: "Unauthorized",
            404: "Not Found", 411: "Length Required",
            429: "Too Many Requests",
            500: "Internal Server Error", 502: "Bad Gateway",
            503: "Service Unavailable"}

_MAX_HEADER_BYTES = 64 * 1024


class _BadRequest(Exception):
    def __init__(self, status: int, message: str):
        self.status = status
        self.message = message


def _read_request(sock, buf: bytearray):
    """One inbound request off a keep-alive socket: ``(method, target,
    lower-cased header dict, body bytes)``; None on clean EOF at a
    message boundary. Raises ``_BadRequest`` (answer-and-close) on a
    malformed message, ``OSError``/``TimeoutError`` on transport death.

    The stdlib ``BaseHTTPRequestHandler`` costs ~1-2ms CPU per request
    (readline loop + email-parser headers + per-response strftime) —
    the same measurement that drove bench_serving.py's raw-socket
    clients. The router sits on EVERY fleet query, so its inbound hot
    path uses the same minimal single-buffer parse as its upstream
    transport; the engine server keeps the stdlib handler (its predict
    work dwarfs the parse; the router's doesn't)."""
    while True:
        head_end = buf.find(b"\r\n\r\n")
        if head_end >= 0:
            break
        if len(buf) > _MAX_HEADER_BYTES:
            raise _BadRequest(400, "oversized request headers")
        chunk = sock.recv(65536)
        if not chunk:
            if buf:
                raise _BadRequest(400, "truncated request")
            return None
        buf += chunk
    head = bytes(buf[:head_end]).decode("latin-1")
    lines = head.split("\r\n")
    parts = lines[0].split(" ")
    if len(parts) != 3 or not parts[2].startswith("HTTP/"):
        raise _BadRequest(400, f"malformed request line {lines[0]!r}")
    method, target = parts[0], parts[1]
    headers: dict[str, str] = {}
    for line in lines[1:]:
        name, sep, value = line.partition(":")
        if sep:
            headers[name.strip().lower()] = value.strip()
    if headers.get("transfer-encoding"):
        # chunked bodies would desync every later request on the
        # socket — 411 and close (RFC 9112 §6.3)
        raise _BadRequest(
            411, "chunked request bodies are not supported; "
                 "send Content-Length")
    length_raw = headers.get("content-length", "0")
    if not length_raw.isdigit():
        raise _BadRequest(400, "invalid Content-Length")
    need = head_end + 4 + int(length_raw)
    while len(buf) < need:
        chunk = sock.recv(65536)
        if not chunk:
            raise _BadRequest(400, "request body truncated")
        buf += chunk
    body = bytes(buf[head_end + 4:need])
    del buf[:need]
    return method, target, headers, body


class _Handler(socketserver.StreamRequestHandler):
    """Lean connection loop: minimal parse → service → ONE buffered
    write per response (status line, headers, body in a single
    sendall), keep-alive by default, 30s idle reap. Bound to the
    service by RestServer exactly like the stdlib handlers."""

    service: RouterService  # bound per server
    timeout = 30
    disable_nagle_algorithm = True

    _ROUTE_LABELS = {
        "/queries.json": "queries",
        "/fleet": "fleet",
        "/fleet/canary": "fleet",
        "/fleet/engines": "fleet",
        "/fleet/experiments": "fleet",
        "/metrics": "metrics",
        "/fleet/metrics": "metrics",
        "/traces.json": "traces",
        "/": "status",
    }

    def handle(self) -> None:
        sock = self.connection
        buf = bytearray()
        while True:
            try:
                parsed = _read_request(sock, buf)
            except _BadRequest as bad:
                self._send(sock, bad.status,
                           json.dumps({"message": bad.message}).encode(),
                           "application/json; charset=UTF-8",
                           {"Connection": "close"}, None)
                return
            except OSError:     # incl. the 30s idle-timeout reap
                return
            if parsed is None:
                return          # clean close between requests
            if not self._dispatch(sock, *parsed):
                return

    def _dispatch(self, sock, method: str, target: str,
                  headers: Mapping[str, str], body: bytes) -> bool:
        """Route one request; returns False when the connection must
        close (client asked, or the write failed). Observability
        envelope (docs/observability.md): optional ROOT trace segment
        for the query path (inbound context adopted when well-formed —
        a malformed/oversized header falls back to fresh local ids,
        never a 500), SLO outcome recording, and the access log with
        the routing metadata (replica, attempts, hedge/retry flags)."""
        t_start = time.perf_counter()
        path, _, query = target.partition("?")
        request_id = resolve_request_id(headers)
        params = ({k: v[0] for k, v in parse_qs(query).items()}
                  if query else {})
        status = 500
        # O(1) on the raw request path: one dict hit against the
        # precompiled engine route table (bare /queries.json and every
        # /engines/<name>/queries.json — fleet/gateway.py)
        routed = method == "POST" \
            and self.service.gateway.is_query_path(path)
        engine: str | None = None
        trace = None
        if routed and self.service.tracing:
            inbound_id, inbound_parent = parse_trace_context(headers)
            trace = start_trace(
                "queries.json", request_id=request_id,
                trace_id=inbound_id, parent_span_id=inbound_parent,
                service="router")
        log_extra: dict = {}
        try:
            if trace is not None:
                with use_trace(trace):
                    result = self.service.handle(
                        method, path, params, headers, body, request_id)
            else:
                result = self.service.handle(
                    method, path, params, headers, body, request_id)
            if isinstance(result, RouterResponse):
                status = result.status
                engine = result.engine
                if routed:
                    log_extra = {
                        **({"engine": result.engine}
                           if result.engine else {}),
                        **({"replica": result.backend_id}
                           if result.backend_id else {}),
                        **({"group": result.group}
                           if result.group else {}),
                        "attempts": result.attempts,
                        "retried": result.retried,
                        "hedged": result.hedged,
                    }
                if trace is not None:
                    # the router's trace id wins the response header:
                    # it equals the replica's when the replica adopted
                    # the forwarded context, and it is the only id a
                    # client can stitch by when the replica traced
                    # nothing
                    result.headers = {
                        k: v for k, v in result.headers.items()
                        if k.lower() != "x-pio-trace-id"}
                    result.headers[TRACE_ID_HEADER] = trace.trace_id
                ok = self._send(sock, status, result.body,
                                result.content_type, result.headers,
                                request_id)
            else:
                status, payload, *extra = result
                if isinstance(payload, PlainTextPayload):
                    data = str(payload).encode()
                    ctype = payload.content_type
                else:
                    data = json.dumps(payload).encode()
                    ctype = "application/json; charset=UTF-8"
                ok = self._send(sock, status, data, ctype,
                                extra[0] if extra else None, request_id)
        finally:
            dt = time.perf_counter() - t_start
            self.service.request_latency.observe(
                "queries" if routed
                else self._ROUTE_LABELS.get(path, "other"), dt)
            if routed and status != 429:
                # SLO truth at the router = what the CLIENT saw: any
                # 5xx (shed, expired, all-replicas-failed included)
                # spends error budget — globally AND on the resolved
                # engine's own ring (the per-tenant burn gauges).
                # Quota 429s are EXCLUDED from both rings: a throttled
                # request is the per-tenant contract working, not
                # service failure — and recording it as a microsecond
                # "success" would flatter a tenant's latency SLO
                # exactly when it is both throttled and slow (the same
                # reason the gateway bench keeps 429s out of its
                # latency percentiles); the throttle volume has its own
                # signal, pio_router_quota_throttled_total{engine}
                self.service.slo.record(ok=status < 500, latency_s=dt)
                self.service.gateway.record_outcome(
                    engine, ok=status < 500, latency_s=dt)
                if engine:
                    # same outcome feeds the experiment plane: the
                    # controller ignores engines that are not variants
                    # of a live experiment (experiment/controller.py)
                    self.service.experiment.record(
                        engine, ok=status < 500, latency_s=dt)
            if trace is not None:
                trace.finish(status=status, **{
                    k: v for k, v in log_extra.items() if v or k == "attempts"})
                self.service.trace_log.record(trace)
            if self.service.access_log:
                emit_access_log(
                    "router", method, path, status, dt, request_id,
                    client=self.client_address[0], **log_extra)
        return ok and headers.get("connection", "").lower() != "close"

    def _send(self, sock, status: int, body: bytes, ctype: str,
              extra_headers: Mapping[str, str] | None,
              request_id: str | None) -> bool:
        reason = _REASONS.get(status, "Unknown")
        lines = [f"HTTP/1.1 {status} {reason}",
                 f"Content-Type: {ctype}",
                 f"Content-Length: {len(body)}"]
        if request_id:
            lines.append(f"{REQUEST_ID_HEADER}: {request_id}")
        for k, v in (extra_headers or {}).items():
            lines.append(f"{k}: {v}")
        blob = ("\r\n".join(lines) + "\r\n\r\n").encode("latin-1") + body
        try:
            sock.sendall(blob)
            return True
        except OSError:
            return False


class RouterServer(RestServer):
    """HTTP lifecycle around :class:`RouterService` — starts every
    engine group's membership probe loop with the listener, stops them
    all on shutdown. ``router`` (when passed explicitly) becomes the
    DEFAULT engine's FleetRouter; ``config.engines`` declares the rest
    of the table (fleet/gateway.py)."""

    log_label = "Fleet Router"
    thread_name = "pio-routerserver"

    def __init__(self, config: RouterConfig,
                 router: FleetRouter | None = None):
        self.config = config
        self.gateway = EngineGateway(config, default_router=router)
        super().__init__(_Handler, RouterService(self.gateway),
                         config.ip, config.port,
                         reuse_port=config.reuse_port)
        self.service.on_stop = self.stop

    @property
    def router(self) -> FleetRouter:
        """The CURRENT default engine's router (see
        RouterService.router)."""
        return self.gateway.default_group.router

    def start(self) -> None:
        self.gateway.start()
        super().start()

    def serve_forever(self) -> None:
        self.gateway.start()
        super().serve_forever()

    def _on_close(self) -> None:
        self.service.close()
        self.gateway.close()
