"""Shared REST-server lifecycle for the serving plane.

The four reference servers (event server :7070, engine server :8000,
dashboard :9000, admin API :7071) all ran on spray/Akka HTTP; here they
share one stdlib scaffold: a handler class bound to a transport-free
service object, optional TLS (utils/ssl_config), ephemeral-port support,
background-thread or blocking serve, and clean shutdown.

Observability plumbing shared by every handler (docs/observability.md):

- **request ids** — :func:`resolve_request_id` accepts an inbound
  ``X-PIO-Request-Id`` (sanitized: a hostile header must not inject
  into logs) or mints one; every response echoes it, so a client, a
  proxy log, and this server's access log correlate one request;
- **structured access logs** — :func:`emit_access_log` writes one JSON
  object per request (method, path, status, latency_ms, request_id) on
  the ``pio.access`` logger, gated by :func:`access_log_enabled`
  (``PIO_ACCESS_LOG`` env / per-server ``--access-log`` flag) — the
  replacement for the blanket ``log_message`` suppression the handlers
  used to ship;
- **plain-text payloads** — :class:`PlainTextPayload` marks a response
  body (the Prometheus ``/metrics`` text) that must not be
  JSON-encoded.
"""

from __future__ import annotations

import itertools
import json
import logging
import math
import os
import random
import re
import socket
import sys
import threading
import time
import uuid
from http.server import ThreadingHTTPServer
from typing import Mapping

from predictionio_tpu.utils.resilience import RetryPolicy
from predictionio_tpu.utils.ssl_config import maybe_enable_ssl

logger = logging.getLogger(__name__)

#: dedicated access-log stream: operators route it separately from the
#: framework's diagnostic logging (a JSON-lines file, a sidecar, ...)
access_logger = logging.getLogger("pio.access")

REQUEST_ID_HEADER = "X-PIO-Request-Id"

#: inbound request ids are propagated only when they look like ids —
#: anything else (spaces, quotes, control bytes, unbounded length) is
#: replaced, never logged verbatim
_REQUEST_ID_RE = re.compile(r"^[A-Za-z0-9._:-]{1,128}$")

#: minted request ids are a per-process random prefix + a sequence —
#: the same uniqueness story as uuid4 for correlation purposes without
#: an os.urandom read (a getrandom syscall) on EVERY request, the same
#: reasoning as obs/trace.py's trace ids. itertools.count is a single
#: C call, safe under the GIL.
_REQUEST_ID_PREFIX = uuid.uuid4().hex[:8]
_REQUEST_ID_SEQ = itertools.count(1)


class PlainTextPayload(str):
    """Marker: respond with this body as ``text/plain`` (optionally a
    specific content type), not JSON — the ``GET /metrics`` path."""

    content_type = "text/plain; charset=utf-8"

    def __new__(cls, body: str, content_type: str | None = None):
        self = super().__new__(cls, body)
        if content_type is not None:
            self.content_type = content_type
        return self


def resolve_request_id(headers: Mapping[str, str]) -> str:
    """The request's correlation id: a well-formed inbound
    ``X-PIO-Request-Id`` wins (callers correlate across services),
    otherwise a fresh one is minted. ``headers`` may be an
    ``email.Message`` (case-insensitive get) or a plain lowercased
    dict — both header spellings are tried."""
    raw = headers.get(REQUEST_ID_HEADER) or headers.get("x-pio-request-id")
    if raw and _REQUEST_ID_RE.match(raw):
        return raw
    return f"{_REQUEST_ID_PREFIX}{next(_REQUEST_ID_SEQ):08x}"


#: seeded jitter source for Retry-After hints — seeded so the draw
#: sequence is reproducible per process (tests may also pass their own
#: rng); the POINT is that two clients shed in the same instant get
#: DIFFERENT hints
_RETRY_AFTER_RNG = random.Random(0x9E3779B9)
_RETRY_AFTER_JITTER = 0.25


def retry_after_header(seconds: float,
                       rng: random.Random | None = None) -> str:
    """A ``Retry-After`` header value with ±25% jitter.

    A fleet of clients that all shed (or all hit one dying backend) in
    the same instant and obey a CONSTANT integer hint come back in
    lockstep — a synchronized thundering herd landing exactly when the
    server is weakest. Jittering the hint decorrelates them, the same
    full-jitter reasoning as RetryPolicy's backoff
    (utils/resilience.py). The value is emitted with decimal precision
    — a CONSCIOUS RFC 9110 deviation (delta-seconds is an integer):
    rounding ±25% of the dominant 1s hint to an integer erases the
    jitter entirely, and this framework's own clients/tests parse
    floats. Strict stacks (urllib3's ``Retry`` header parser rejects
    non-integers) should derive their backoff client-side instead of
    honoring the header verbatim; docs/operations-resilience.md
    documents the contract."""
    base = max(0.1, float(seconds))
    draw = (rng or _RETRY_AFTER_RNG).uniform(1.0 - _RETRY_AFTER_JITTER,
                                             1.0 + _RETRY_AFTER_JITTER)
    return f"{base * draw:.2f}"


def parse_deadline_budget(config_deadline_ms: float,
                          headers: Mapping[str, str]) -> float | None:
    """THE per-request deadline contract, shared by the engine server
    and the fleet router: seconds of budget from the configured
    ``request_deadline_ms`` (0 = none), which an ``X-PIO-Deadline-Ms``
    header may only TIGHTEN. Malformed headers (non-numeric, nan/inf,
    <= 0) raise ``ValueError`` — a silent 1ms budget would 503 forever,
    so the caller maps it to a 400."""
    budget = (config_deadline_ms / 1e3 if config_deadline_ms > 0 else None)
    raw = headers.get("x-pio-deadline-ms")
    if raw:
        try:
            value = float(raw)
        except ValueError:
            value = float("nan")
        if not math.isfinite(value) or value <= 0:
            raise ValueError(f"invalid X-PIO-Deadline-Ms: {raw!r}")
        client = max(0.001, value / 1e3)
        budget = client if budget is None else min(budget, client)
    return budget


def access_log_enabled(override: bool | None = None) -> bool:
    """Per-server config wins when set; otherwise the ``PIO_ACCESS_LOG``
    env var decides (read at call time — server construction — never
    frozen at import)."""
    if override is not None:
        return override
    return os.environ.get("PIO_ACCESS_LOG", "").strip().lower() in (
        "1", "true", "yes", "on")


def ensure_access_log_handler() -> None:
    """Make an enabled access log actually emit: the flag was set, so
    INFO must flow regardless of the root logger's level (a root at
    WARNING would otherwise silently drop every line), and when
    nothing has configured ``pio.access`` (no handlers anywhere up its
    tree) it gets a stderr JSON-lines handler. Deployments that
    configured logging themselves keep their handlers."""
    access_logger.setLevel(logging.INFO)
    lg = access_logger
    while lg is not None:
        if lg.handlers:
            return
        if not lg.propagate:
            break
        lg = lg.parent
    handler = logging.StreamHandler()
    handler.setFormatter(logging.Formatter("%(message)s"))
    access_logger.addHandler(handler)
    access_logger.propagate = False


def emit_access_log(server: str, method: str, path: str, status: int,
                    latency_s: float, request_id: str,
                    client: str | None = None, **extra) -> None:
    """One structured JSON access-log line. Key order is stable
    (method, path, status first) so the lines grep cleanly."""
    record = {
        "ts": round(time.time(), 3),
        "server": server,
        "method": method,
        "path": path,
        "status": status,
        "latency_ms": round(latency_s * 1e3, 3),
        "request_id": request_id,
    }
    if client:
        record["client"] = client
    record.update(extra)
    access_logger.info("%s", json.dumps(record))


class _PioHTTPServer(ThreadingHTTPServer):
    # default listen backlog (5) RSTs concurrent connection bursts —
    # ingest clients batch-fire dozens of posts (confirmed by a 16-thread
    # stress test); match a production accept queue
    request_queue_size = 128

    def __init__(self, addr, handler, reuse_port: bool = False):
        # set BEFORE super().__init__: TCPServer binds inside it and
        # server_bind reads the flag
        self.reuse_port = reuse_port
        super().__init__(addr, handler)
        self.client_disconnects = 0
        self._disconnect_lock = threading.Lock()

    def server_bind(self):
        if self.reuse_port:
            # SO_REUSEPORT: N worker processes share one listen port,
            # the kernel spreads connections across them — how the
            # fleet router scales past one interpreter's GIL
            # (`pio router --workers N`; docs/fleet.md)
            self.socket.setsockopt(socket.SOL_SOCKET,
                                   socket.SO_REUSEPORT, 1)
        super().server_bind()

    def handle_error(self, request, client_address):
        # A client that goes away mid-request/response is a non-event in
        # the serving plane (reference: fire-and-forget discipline,
        # CreateServer.scala:557-566) — log at debug and count, never
        # traceback-and-die on the handler thread.
        exc = sys.exc_info()[1]
        if isinstance(exc, (BrokenPipeError, ConnectionResetError)):
            with self._disconnect_lock:
                self.client_disconnects += 1
            logger.debug("client %s disconnected mid-request: %r",
                         client_address, exc)
            return
        super().handle_error(request, client_address)


def bounded_probe(fn, timeout: float = 1.0) -> BaseException | None:
    """Run a readiness probe with a HARD wall-clock bound.

    ``deadline_scope`` only suppresses retry sleeps — a blackholed
    backend still blocks one attempt for its own socket timeout (10-60s
    on these backends), which would park a handler thread per probe.
    The probe runs on a daemon thread instead; this returns within
    ``timeout`` regardless. Returns None on success, the probe's
    exception on failure, or a TimeoutError if it outlived the bound
    (the abandoned thread unblocks on its socket timeout and exits)."""
    result: list[BaseException | None] = []

    def run() -> None:
        try:
            fn()
            result.append(None)
        except Exception as exc:  # noqa: BLE001 — reported, not raised
            result.append(exc)

    t = threading.Thread(target=run, name="pio-readyz-probe", daemon=True)
    t.start()
    t.join(timeout)
    if not result:
        return TimeoutError(f"probe exceeded {timeout:.1f}s")
    return result[0]


class RestServer:
    """Subclasses set ``log_label``/``thread_name`` and may override the
    bind-failure and close hooks."""

    log_label = "Server"
    thread_name = "pio-server"
    bind_retries = 1
    #: jittered exponential DELAY SCHEDULE between bind attempts (equal
    #: jitter: uniform(cap/2, cap) — parallel servers racing for the
    #: same port don't retry in lockstep the way the old fixed 1s sleep
    #: made them, while the floor still guarantees enough total wait,
    #: >=1.5s over two retries, for a stopping predecessor to release
    #: the port). The attempt COUNT is ``bind_retries`` above; this
    #: policy's max_attempts is not consulted.
    bind_backoff = RetryPolicy(base_delay=1.0, max_delay=2.0,
                               jitter_floor=0.5)

    def __init__(self, handler_cls: type, service, ip: str, port: int,
                 reuse_port: bool = False):
        self.ip = ip
        self.service = service
        handler = type("BoundHandler", (handler_cls,), {"service": service})
        rng = random.Random()
        for attempt in range(self.bind_retries):
            try:
                self._httpd = _PioHTTPServer((ip, port), handler,
                                             reuse_port=reuse_port)
                break
            except OSError:
                if attempt == self.bind_retries - 1:
                    raise
                self._on_bind_failure(attempt, ip, port)
                delay = self.bind_backoff.backoff(attempt, rng)
                logger.info("%s bind attempt %d failed; retrying in %.2fs",
                            self.log_label, attempt + 1, delay)
                time.sleep(delay)
        maybe_enable_ssl(self._httpd)
        self._thread: threading.Thread | None = None

    # -- hooks ---------------------------------------------------------------
    def _on_bind_failure(self, attempt: int, ip: str, port: int) -> None:
        """Called between bind retries (when bind_retries > 1)."""

    def _on_close(self) -> None:
        """Called after the socket closes during stop()."""

    # -- lifecycle -----------------------------------------------------------
    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    @property
    def client_disconnects(self) -> int:
        """How many clients vanished mid-request (never an error)."""
        return self._httpd.client_disconnects

    def start(self) -> None:
        """Serve on a background thread (returns immediately)."""
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name=self.thread_name, daemon=True
        )
        self._thread.start()
        logger.info("%s listening on %s:%s", self.log_label, self.ip, self.port)

    def serve_forever(self) -> None:
        logger.info("%s listening on %s:%s", self.log_label, self.ip, self.port)
        self._httpd.serve_forever()

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        self._on_close()
        if self._thread:
            self._thread.join(timeout=5)
            self._thread = None
