"""Shared REST-server lifecycle for the serving plane.

The four reference servers (event server :7070, engine server :8000,
dashboard :9000, admin API :7071) all ran on spray/Akka HTTP; here they
share one stdlib scaffold: a handler class bound to a transport-free
service object, optional TLS (utils/ssl_config), ephemeral-port support,
background-thread or blocking serve, and clean shutdown.
"""

from __future__ import annotations

import logging
import sys
import threading
import time
from http.server import ThreadingHTTPServer

from predictionio_tpu.utils.ssl_config import maybe_enable_ssl

logger = logging.getLogger(__name__)


class _PioHTTPServer(ThreadingHTTPServer):
    # default listen backlog (5) RSTs concurrent connection bursts —
    # ingest clients batch-fire dozens of posts (confirmed by a 16-thread
    # stress test); match a production accept queue
    request_queue_size = 128

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self.client_disconnects = 0
        self._disconnect_lock = threading.Lock()

    def handle_error(self, request, client_address):
        # A client that goes away mid-request/response is a non-event in
        # the serving plane (reference: fire-and-forget discipline,
        # CreateServer.scala:557-566) — log at debug and count, never
        # traceback-and-die on the handler thread.
        exc = sys.exc_info()[1]
        if isinstance(exc, (BrokenPipeError, ConnectionResetError)):
            with self._disconnect_lock:
                self.client_disconnects += 1
            logger.debug("client %s disconnected mid-request: %r",
                         client_address, exc)
            return
        super().handle_error(request, client_address)


class RestServer:
    """Subclasses set ``log_label``/``thread_name`` and may override the
    bind-failure and close hooks."""

    log_label = "Server"
    thread_name = "pio-server"
    bind_retries = 1

    def __init__(self, handler_cls: type, service, ip: str, port: int):
        self.ip = ip
        self.service = service
        handler = type("BoundHandler", (handler_cls,), {"service": service})
        for attempt in range(self.bind_retries):
            try:
                self._httpd = _PioHTTPServer((ip, port), handler)
                break
            except OSError:
                if attempt == self.bind_retries - 1:
                    raise
                self._on_bind_failure(attempt, ip, port)
                time.sleep(1.0)
        maybe_enable_ssl(self._httpd)
        self._thread: threading.Thread | None = None

    # -- hooks ---------------------------------------------------------------
    def _on_bind_failure(self, attempt: int, ip: str, port: int) -> None:
        """Called between bind retries (when bind_retries > 1)."""

    def _on_close(self) -> None:
        """Called after the socket closes during stop()."""

    # -- lifecycle -----------------------------------------------------------
    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    @property
    def client_disconnects(self) -> int:
        """How many clients vanished mid-request (never an error)."""
        return self._httpd.client_disconnects

    def start(self) -> None:
        """Serve on a background thread (returns immediately)."""
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name=self.thread_name, daemon=True
        )
        self._thread.start()
        logger.info("%s listening on %s:%s", self.log_label, self.ip, self.port)

    def serve_forever(self) -> None:
        logger.info("%s listening on %s:%s", self.log_label, self.ip, self.port)
        self._httpd.serve_forever()

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        self._on_close()
        if self._thread:
            self._thread.join(timeout=5)
            self._thread = None
