"""The Engine Server: prediction serving on :8000.

Route and behavior parity with the reference deploy server
(reference: core/src/main/scala/.../workflow/CreateServer.scala):

- ``GET /``              status document (:442-469 — twirl HTML page;
                         here JSON, plus HTML when Accept asks for it)
- ``POST /queries.json`` the query path (:470-621): bind query JSON →
                         ``serving.supplement`` → sequential per-algorithm
                         ``predict`` → ``serving.serve`` → optional
                         feedback events → output-blocker plugins →
                         latency bookkeeping
- ``GET|POST /reload``   hot-swap to the latest completed instance
                         (:316-342; key-authenticated)
- ``POST /stop``         shutdown (:633-646; key-authenticated)
- ``GET /plugins.json``  plugin listing (:648-671)
- ``GET /healthz``       liveness (beyond reference; k8s-style contract)
- ``GET /readyz``        readiness: model loaded + storage reachable
- ``GET /stats.json``    serving hot-path internals (beyond reference):
                         batch-size histogram, adaptive-wait EWMA,
                         cache hit ratio, dedup count, resilience
- ``POST /retrieval``    runtime retrieval reconfig (brute <-> ann,
                         nprobe/rescore; key-authenticated)

Prefork worker pool (``pio deploy --workers N``; docs/
serving-performance.md "Multi-process serving"): N of these servers
run as separate processes sharing one SO_REUSEPORT listen port. Each
holds its own model/batcher/cache/registry; a ``/metrics`` or
``/stats.json`` scrape landing on any worker merges every sibling
(fleet/workers.WorkerHub + obs/aggregate.merge_sources),
``/traces.json`` folds sibling rings in, and the admin surfaces
(``/reload``, ``/drain``, ``POST /retrieval``) publish a sequenced
admin-state document every sibling's sync loop applies
(serving/workers.WorkerCoherence) — so a reload bumps the result-cache
generation on ALL workers, not the 1/N the connection hash happened to
pick.

Graceful degradation (beyond reference, docs/operations-resilience.md):
storage-unavailable failures map to ``503`` + ``Retry-After`` instead of
``500``; a failed ``/reload`` keeps serving the last-known-good model;
``ServerConfig.request_deadline_ms`` (or an ``X-PIO-Deadline-Ms``
request header) bounds each query's time budget, propagated to the
micro-batcher and the storage resilience layer.

The reference's MasterActor/ServerActor pair collapses to
``EngineServer`` (HTTP lifecycle, bind retry ×3 — :347-357) over
``EngineService`` (transport-free request logic). The feedback loop
(:514-576) POSTs ``predict`` events to the event server from a
fire-and-forget thread, tagging responses with a ``prId``.

Serving hot path (docs/serving-performance.md): the query envelope
binds/encodes through the precompiled codecs (core/json_codec.
compile_wire_decoder / encode_wire) instead of the per-request
reflective binder; an opt-in result cache (ServerConfig.cache_enabled)
answers repeated queries without a dispatch and invalidates atomically
on /reload; the micro-batcher is policy-driven
(ServerConfig.batch_policy — adaptive EWMA wait by default) with
per-batch dedup of identical concurrent queries.
"""

from __future__ import annotations

import abc
import contextlib
import contextvars
import dataclasses
import json
import logging
import queue
import threading
import time
import uuid
from concurrent.futures import ThreadPoolExecutor
from concurrent.futures import TimeoutError as FuturesTimeoutError
from http.server import BaseHTTPRequestHandler
from typing import Any, Mapping
from urllib.parse import parse_qs, urlparse

from predictionio_tpu.api.http_base import (
    REQUEST_ID_HEADER,
    PlainTextPayload,
    RestServer,
    access_log_enabled,
    bounded_probe,
    emit_access_log,
    ensure_access_log_handler,
    parse_deadline_budget,
    resolve_request_id,
    retry_after_header,
)
from predictionio_tpu.api.stats import ServingStats, resilience_snapshot
from predictionio_tpu.core.json_codec import (
    canonical_json,
    compile_wire_decoder,
    encode_wire,
)
from predictionio_tpu.obs.aggregate import (
    ExpositionParseError,
    merge_sources,
    parse_exposition,
    source_count_metric,
)
from predictionio_tpu.obs.compile import compile_metrics_collector
from predictionio_tpu.obs.compile import recorder as compile_recorder
from predictionio_tpu.obs.device import (
    device_memory_collector,
    train_report_collector,
)
from predictionio_tpu.obs.exporter import CONTENT_TYPE as PROMETHEUS_CONTENT_TYPE
from predictionio_tpu.obs.exporter import render_metrics, render_prometheus
from predictionio_tpu.obs.registry import (
    HistogramFamily,
    Metric,
    MetricRegistry,
    online_collector,
    resilience_collector,
    server_info_collector,
    serving_collector,
)
from predictionio_tpu.obs.slo import SLOEngine, serving_pressure_collector
from predictionio_tpu.obs.trace import (
    PARENT_SPAN_HEADER,
    TRACE_ID_HEADER,
    TraceLog,
    active_trace,
    parse_trace_context,
    span,
    start_trace,
    tracing_default,
    use_trace,
)
from predictionio_tpu.serving.batch_policy import make_batch_policy
from predictionio_tpu.serving.result_cache import ResultCache
from predictionio_tpu.serving.workers import WorkerCoherence
from predictionio_tpu.storage.registry import Storage
from predictionio_tpu.utils.resilience import (
    STORAGE_UNAVAILABLE_ERRORS,
    deadline_scope,
    record_fallback,
    retry_after_hint,
)
from predictionio_tpu.workflow.context import EngineContext
from predictionio_tpu.workflow.deploy import (
    DeployedEngine,
    QueryBatcher,
    QueryDeadlineExceeded,
    ServerConfig,
    apply_retrieval_config,
    load_deployed_engine,
    retrieval_targets,
)

logger = logging.getLogger(__name__)

OUTPUT_BLOCKER = "outputblocker"
OUTPUT_SNIFFER = "outputsniffer"


@dataclasses.dataclass(frozen=True)
class QueryInfo:
    """What engine-server plugins observe per query
    (EngineServerPlugin.scala:33-41)."""
    query: Any
    prediction: Any
    engine_instance_id: str


class EngineServerPlugin(abc.ABC):
    """Parity: EngineServerPlugin (workflow/EngineServerPlugin.scala:22-41).
    Output blockers run synchronously and may transform (or reject, by
    raising) the prediction; sniffers observe asynchronously."""

    plugin_name: str = "plugin"
    plugin_description: str = ""
    plugin_type: str = OUTPUT_SNIFFER

    @abc.abstractmethod
    def process(self, info: QueryInfo, context: "EngineServerPluginContext") -> Any:
        """Blockers return the (possibly transformed) prediction."""


class EngineServerPluginContext:
    """Parity: EngineServerPluginContext.scala:39-91 +
    EngineServerPluginsActor (async sniffer fan-out as a worker thread)."""

    def __init__(self, plugins: list[EngineServerPlugin] | None = None):
        plugins = list(plugins or [])
        self.output_blockers = {
            p.plugin_name: p for p in plugins if p.plugin_type == OUTPUT_BLOCKER
        }
        self.output_sniffers = {
            p.plugin_name: p for p in plugins if p.plugin_type == OUTPUT_SNIFFER
        }
        # one daemon worker drains sniffer notifications off the serving
        # hot path (the EngineServerPluginsActor role)
        self._queue: "queue.Queue[QueryInfo | None]" = queue.Queue()
        self._worker: threading.Thread | None = None
        if self.output_sniffers:
            self._worker = threading.Thread(
                target=self._drain, name="pio-output-sniffers", daemon=True
            )
            self._worker.start()

    def run_blockers(self, info: QueryInfo) -> Any:
        """Fold the prediction through all blockers
        (CreateServer.scala:578-581). Exceptions propagate and reject the
        query (the caller maps them to an HTTP error)."""
        prediction = info.prediction
        for blocker in self.output_blockers.values():
            prediction = blocker.process(
                dataclasses.replace(info, prediction=prediction), self
            )
        return prediction

    def notify_sniffers(self, info: QueryInfo) -> None:
        if self._worker is not None:
            self._queue.put(info)

    def _drain(self) -> None:
        while True:
            info = self._queue.get()
            if info is None:
                return
            for sniffer in self.output_sniffers.values():
                try:
                    sniffer.process(info, self)
                except Exception:
                    logger.exception("output sniffer %s failed", sniffer.plugin_name)

    def close(self) -> None:
        if self._worker is not None:
            self._queue.put(None)
            self._worker.join(timeout=5)
            self._worker = None

    def describe(self) -> dict:
        def block(plugins: dict[str, EngineServerPlugin]) -> dict:
            return {
                name: {
                    "name": p.plugin_name,
                    "description": p.plugin_description,
                    "class": type(p).__qualname__,
                }
                for name, p in plugins.items()
            }

        return {
            "plugins": {
                "outputblockers": block(self.output_blockers),
                "outputsniffers": block(self.output_sniffers),
            }
        }


class _HtmlPage(str):
    """Marker: payload is a rendered HTML page, not JSON."""


class _Reject(Exception):
    def __init__(self, status: int, message: str,
                 headers: dict[str, str] | None = None):
        self.status = status
        self.message = message
        self.headers = headers


class EngineService:
    """Transport-free request logic — the ServerActor routes
    (CreateServer.scala:405-683)."""

    def __init__(
        self,
        deployed: DeployedEngine,
        config: ServerConfig | None = None,
        storage: Storage | None = None,
        ctx: EngineContext | None = None,
        plugin_context: EngineServerPluginContext | None = None,
    ):
        # built at CALL time: a module-level default instance would
        # freeze the PIO_SERVING_* env reads at import
        config = config if config is not None else ServerConfig()
        self.deployed = deployed
        self.config = config
        self.storage = storage
        self.ctx = ctx
        self.plugins = plugin_context or EngineServerPluginContext()
        #: set by the HTTP wrapper; called on authorized POST /stop
        self.on_stop = lambda: None
        #: set by the HTTP wrapper; mid-request client-disconnect count
        self.client_disconnects = lambda: 0
        #: one counter set shared by batcher + cache (GET /stats.json)
        self.serving_stats = ServingStats()
        #: opt-in result cache: canonical-query-JSON -> prediction,
        #: invalidated on successful /reload (ResultCache docs). With
        #: --shm-cache the pool shares ONE seqlock-slotted segment
        #: (serving/shm_cache) behind the same interface; a platform
        #: without shared memory warns and falls back to the private
        #: LRU — same contract, worker-local warmth
        self.cache = None
        if config.cache_enabled:
            if config.shm_cache:
                from predictionio_tpu.serving.shm_cache import open_shm_cache

                self.cache = open_shm_cache(config,
                                            stats=self.serving_stats)
                if self.cache is not None:
                    # the pool-reload put fence (ShmResultCache
                    # docstring): between a sibling's /reload bump and
                    # THIS worker's own model swap (up to one admin
                    # sync interval), local computations are old-model
                    # results — the cache must refuse to publish them
                    # into the new generation
                    self.cache.model_generation_fn = (
                        lambda: self.model_generation)
            if self.cache is None:
                self.cache = ResultCache(
                    max_entries=config.cache_max_entries,
                    ttl_s=config.cache_ttl_s,
                    stats=self.serving_stats)
        #: opt-in micro-batching: concurrent queries coalesce into one
        #: device dispatch (ServerConfig.batching; QueryBatcher docs);
        #: the wait/target per batch comes from the configured policy
        self.batcher: QueryBatcher | None = (
            QueryBatcher(lambda: self.deployed,
                         policy=make_batch_policy(config.batch_policy,
                                                  config.batch_max,
                                                  config.batch_wait_ms),
                         stats=self.serving_stats)
            if config.batching else None
        )
        #: precompiled query binder — refreshed on /reload with the new
        #: instance's query class (core/json_codec fast path)
        self._query_decoder = (
            compile_wire_decoder(qc)
            if (qc := deployed.query_class) is not None else None)
        #: observability plane (docs/observability.md): per-request
        #: tracing (opt-in; config wins, else PIO_TRACE), structured
        #: access logs (config wins, else PIO_ACCESS_LOG), and the
        #: per-server metric registry GET /metrics renders
        self.tracing = (config.tracing if config.tracing is not None
                        else tracing_default())
        self.access_log = access_log_enabled(config.access_log)
        if self.access_log:
            ensure_access_log_handler()
        self.trace_log = TraceLog()
        self.request_latency = HistogramFamily(
            "pio_http_request_seconds",
            "HTTP request walltime by route (handler-measured)",
            "route", ("queries", "stats", "metrics", "status"))
        self.registry = MetricRegistry()
        self.registry.register(self.request_latency.collect)
        self.registry.register(serving_collector(self.serving_stats))
        self.registry.register(resilience_collector())
        self.registry.register(server_info_collector("engine"))
        #: SLO burn-rate gauges + the queue-pressure autoscaler signal
        #: (obs/slo.py; docs/fleet.md): outcomes recorded per query by
        #: the handler, evaluated at scrape time only
        self.slo = SLOEngine()
        self.registry.register(self.slo.collector())
        self.registry.register(
            serving_pressure_collector(self.serving_stats))
        #: sublinear-retrieval observability (docs/serving-performance.md):
        #: ANN-capable models report their dispatches into ServingStats
        #: (pio_serving_ann_* on /metrics, annShortlistHistogram on
        #: /stats.json); re-wired on every /reload since the swap brings
        #: fresh model objects
        self._wire_ann_observers()
        self.registry.register(self._ann_mode_collector)
        #: device/compiler observability (docs/observability.md "Device
        #: and compiler observability"): the recompile sentinel's
        #: counters (pio_jit_compiles_total / pio_serving_recompile_total),
        #: device memory gauges (absent on backends without
        #: memory_stats), and the last profiled train's MFU/HBM gauges.
        #: Warmup is marked when this deployment answers its FIRST
        #: query — every later compile is a live request paying the
        #: XLA cliff and counts as a serving recompile with a WARN
        #: (operators warm their batch widths before fronting traffic;
        #: runbook in docs/observability.md)
        self.registry.register(compile_metrics_collector())
        self.registry.register(device_memory_collector())
        self.registry.register(train_report_collector())
        self._compile_warmup_marked = False
        #: deadline enforcement for the NON-batched path: the query runs
        #: on a pool thread so a blown budget returns 503 instead of
        #: holding the socket (threads spawn lazily; idle pool is free)
        self._query_pool = ThreadPoolExecutor(
            max_workers=64, thread_name_prefix="pio-query-deadline")
        #: /reload-in-flight count: while > 0, /readyz reports 503 so a
        #: fleet router's membership loop stops routing here mid-model-
        #: swap instead of racing the hot swap (docs/fleet.md); queries
        #: already in flight still answer (last-known-good semantics on
        #: reload failure are unchanged). Lock-guarded at writer and
        #: readers (handler threads on both sides).
        self._reload_lock = threading.Lock()
        self._reloads_in_flight = 0
        #: drain latch (POST /drain): while set, /readyz answers 503
        #: "draining" so every router's membership loop stops routing
        #: here — the fleet supervisor's drain-before-SIGTERM step
        #: (fleet/supervisor.py, docs/fleet.md "Supervision"). Queries
        #: already in flight still answer; the latch only refuses NEW
        #: placement. Guarded by _reload_lock at writer and readers.
        self._draining = False
        #: `pio deploy --workers N` peering + shared admin state
        #: (fleet/workers.py spool + serving/workers.WorkerCoherence;
        #: docs/serving-performance.md "Multi-process serving"): a
        #: /metrics or /stats.json scrape landing on THIS worker
        #: reports fleet-of-workers truth, /traces.json folds sibling
        #: rings in, and /reload, /drain, POST /retrieval landing
        #: anywhere reach every sibling through the sequenced
        #: admin.state document
        self.worker_hub = None
        self.coherence: WorkerCoherence | None = None
        #: base-model generation: bumped on every successful /reload
        #: (to the pool's shared reload sequence under --workers, so
        #: generations are comparable across siblings). The online
        #: fold-in plane fences on it: a delta computed against
        #: generation G is discarded, never applied, once a reload
        #: lands G+1 (online/overlay.py; docs/freshness.md)
        self.model_generation = 0
        if config.worker_spool_dir:
            from predictionio_tpu.fleet.workers import WorkerHub

            self.worker_hub = WorkerHub(
                config.worker_spool_dir,
                metrics_text=lambda: render_prometheus(self.registry),
                traces_snapshot=self.trace_log.snapshot,
                timeout_s=config.worker_peer_timeout_s,
                # LOCAL stats for sibling fan-out: a peer callback that
                # itself fanned out would recurse across the pool
                extra_paths={"/stats.json":
                             lambda: self.stats_doc(include_workers=False)})
            self.coherence = WorkerCoherence(
                self.worker_hub, on_state=self._on_admin_state,
                interval_s=config.admin_sync_interval_s)
            adopted = self.coherence.adopt()
            # respawn adoption: a fresh boot already loaded the latest
            # completed instance, so reloadSeq is history (the cache —
            # empty anyway — aligns its generation with the pool's);
            # the drain latch and retrieval config apply for real
            if self.cache is not None and adopted["reloadSeq"] > 0:
                self.cache.invalidate(generation=adopted["reloadSeq"])
            self.model_generation = adopted["reloadSeq"]
            if adopted["draining"]:
                with self._reload_lock:
                    self._draining = True
            if adopted["retrieval"]:
                # guarded like the sync path: an unappliable adopted
                # doc (index-less model, version skew) must degrade,
                # not abort boot — under --supervise a boot abort
                # respawns into the same document until the
                # crash-loop latch permanently shrinks the pool
                try:
                    self._apply_retrieval_doc(adopted["retrieval"])
                except Exception:
                    logger.exception(
                        "adopted retrieval config %s failed to "
                        "apply; serving %s retrieval",
                        adopted["retrieval"], self.config.retrieval)
            self.coherence.start()
        #: real-time freshness plane (`pio deploy --online`; online/,
        #: docs/freshness.md): tails the event store, folds touched
        #: users' ALS vectors closed-form between retrains, publishes
        #: generation-fenced deltas into the serving overlay with
        #: per-user result-cache invalidation, and propagates across
        #: `--workers` siblings over the spool plane
        self.online = None
        if config.online:
            from predictionio_tpu.online.service import OnlineFoldIn

            self.online = OnlineFoldIn(
                storage=storage,
                deployed_fn=lambda: self.deployed,
                generation_fn=lambda: self.model_generation,
                interval_s=config.online_interval_s,
                overlay_max=config.online_overlay_max,
                state_dir=config.online_state_dir or None,
                invalidate_user=self._invalidate_user_results,
                trace_log=self.trace_log,
                tracing=self.tracing,
                worker_hub=self.worker_hub,
            )
            self.online.start()
            self.registry.register(online_collector(self.online))

    def _invalidate_user_results(self, user_id: str) -> None:
        """Drop exactly one user's result-cache entries after their
        vector was re-folded — targeted, instead of the pool-wide
        generation bump a /reload takes (every OTHER user's warm
        entries stay warm; the whole point of a speed layer is that
        freshness does not cost the fleet its cache)."""
        if self.cache is not None:
            from predictionio_tpu.online.service import user_key_fragment

            self.cache.invalidate_matching(user_key_fragment(user_id))

    @property
    def worker_id(self) -> str | None:
        """This worker's spool identity (None outside a worker pool) —
        stamped into access-log lines so per-worker skew is visible."""
        return self.worker_hub.worker_id if self.worker_hub else None

    def _publish_admin(self, applied_note: str, **changes) -> None:
        """Publish admin ``changes`` to the worker pool and VERIFY they
        committed: ``WorkerCoherence.publish`` swallows spool I/O
        failures (returning the previous state), and answering 200
        while N-1 siblings silently stay on the old state would
        contradict the coherence contract. The local mutation stands
        either way — the 500 tells the operator the pool is split and
        a retry (every admin mutation here is idempotent) heals it."""
        if self.coherence is None:
            return
        published = self.coherence.publish(**changes)
        for key, value in changes.items():
            if published.get(key) != value:
                raise _Reject(
                    500, f"{applied_note}, but publishing to the "
                         "worker pool failed; sibling workers are "
                         "unchanged — check the spool directory and "
                         "retry")

    def _on_admin_state(self, new: dict, prev: dict) -> None:
        """WorkerCoherence apply callback: perform whatever changed
        between two cumulative admin states (serving/workers.py). A
        sibling's /reload becomes a local reload adopting the shared
        sequence as the cache generation — a failed local reload keeps
        last-known-good exactly like a direct /reload failure (the
        sibling that succeeded is ahead; this one answers /readyz
        truthfully and retries on the next seq bump)."""
        if new["draining"] != prev["draining"]:
            with self._reload_lock:
                self._draining = new["draining"]
            logger.info("adopted sibling drain latch: %s",
                        "set" if new["draining"] else "cleared")
        # reload BEFORE retrieval: a cumulative document can carry both
        # (operator reloaded onto an index-bearing model, then flipped
        # to ann, inside one sync interval) — a lagging sibling that
        # applied retrieval against the still-deployed OLD model would
        # reject the mode and never retry it
        if new["reloadSeq"] > prev["reloadSeq"]:
            try:
                self.reload(generation=new["reloadSeq"])
                logger.info("adopted sibling reload (seq %d): now "
                            "serving %s", new["reloadSeq"],
                            self.deployed.instance.id)
            except Exception:
                record_fallback("serving/reload")
                logger.exception(
                    "sibling-triggered reload failed; still serving "
                    "instance %s", self.deployed.instance.id)
        if new["retrieval"] != prev["retrieval"] and new["retrieval"]:
            # guarded like the reload above: a failed local apply must
            # not abort the remaining deltas in this document (the
            # sequence has already advanced — an aborted callback would
            # silently desync this worker from the pool forever)
            try:
                self._apply_retrieval_doc(new["retrieval"])
                logger.info("adopted sibling retrieval config: %s",
                            new["retrieval"])
            except Exception:
                logger.exception(
                    "sibling retrieval config %s failed to apply; "
                    "still serving %s retrieval", new["retrieval"],
                    self.config.retrieval)

    # -- sublinear retrieval wiring (ops/ann) -------------------------------
    def _wire_ann_observers(self) -> None:
        # getattr: test doubles and minimal deployments may not carry a
        # models list — they simply have no ANN-capable targets
        for target in retrieval_targets(
                getattr(self.deployed, "models", ())):
            if hasattr(target, "set_ann_observer"):
                target.set_ann_observer(self.serving_stats.record_ann)

    def _missing_index_targets(self) -> list:
        """ANN-capable deployed models WITHOUT a ready index — the
        runtime-switch blocker: configure-time fallback builds (fine at
        deploy) would run a full k-means on whatever thread applies the
        change, and on the single admin-sync thread that stalls every
        later /drain//reload for minutes."""
        return [t for t in retrieval_targets(
                    getattr(self.deployed, "models", ()))
                if getattr(t, "ann_index", None) is None]

    def _apply_retrieval_doc(self, doc: Mapping[str, Any]) -> None:
        """Apply a runtime retrieval reconfiguration (POST /retrieval,
        a sibling's admin document, or respawn adoption): push the
        knobs onto every ANN-capable model, re-wire the dispatch
        observers, invalidate the cache — ann and brute answer the
        same query with (potentially) different rankings, so entries
        computed under the old mode must die with it — and only then
        commit the new ServerConfig (a mid-apply failure must not
        leave the config claiming a mode the models don't serve)."""
        mode = str(doc.get("retrieval", self.config.retrieval))
        if mode not in ("brute", "ann"):
            raise ValueError(f"invalid retrieval mode {mode!r}")

        def _int(key: str, current: int) -> int:
            value = doc.get(key, current)
            if not isinstance(value, int) or value < 0:
                raise ValueError(f"invalid {key}: {value!r}")
            return value

        if mode == "ann" and self._missing_index_targets():
            # guarded HERE so every apply path (HTTP, sibling sync,
            # respawn adoption) refuses the build — this worker may be
            # on an older last-known-good model without an index even
            # when the publishing sibling had one
            raise ValueError(
                "no persisted ANN index on the deployed model: build "
                "it at train/persist time (PIO_SERVING_ANN_BUILD) or "
                "deploy with --retrieval ann; the runtime switch only "
                "flips between ready modes")
        candidate = dataclasses.replace(
            self.config, retrieval=mode,
            ann_nprobe=_int("annNprobe", self.config.ann_nprobe),
            ann_rescore=_int("annRescore", self.config.ann_rescore),
            ann_nlist=_int("annNlist", self.config.ann_nlist))
        apply_retrieval_config(getattr(self.deployed, "models", ()),
                               candidate)
        self._wire_ann_observers()
        if self.cache is not None:
            self.cache.invalidate()
        self.config = candidate

    def retrieval_admin(self, body: Any) -> tuple:
        """``POST /retrieval`` — runtime retrieval reconfig without a
        restart: ``{"retrieval": "ann"|"brute"[, "annNprobe": N,
        "annRescore": N, "annNlist": N]}``. Key-authenticated like
        /reload; under ``--workers N`` the change publishes to the
        admin spool so every sibling reconfigures too."""
        if not isinstance(body, dict) or "retrieval" not in body:
            raise _Reject(400, 'expected {"retrieval": "ann"|"brute", ...}')
        if body.get("retrieval") == "ann" and self._missing_index_targets():
            # a state conflict, not a malformed request: the model has
            # no ready index to flip onto (the same guard inside
            # _apply_retrieval_doc protects the sibling/adoption paths)
            raise _Reject(
                409, "no persisted ANN index on the deployed model: "
                     "build it at train/persist time "
                     "(PIO_SERVING_ANN_BUILD) or deploy with "
                     "--retrieval ann; the runtime switch only flips "
                     "between ready modes")
        try:
            self._apply_retrieval_doc(body)
        except ValueError as exc:
            raise _Reject(400, str(exc))
        self._publish_admin("retrieval applied on this worker",
                            retrieval={
                                "retrieval": self.config.retrieval,
                                "annNprobe": self.config.ann_nprobe,
                                "annRescore": self.config.ann_rescore,
                                "annNlist": self.config.ann_nlist,
                            })
        logger.info("retrieval reconfigured: %s (nprobe=%d rescore=%d)",
                    self.config.retrieval, self.config.ann_nprobe,
                    self.config.ann_rescore)
        return (200, {"retrieval": self.config.retrieval,
                      "annEnabled": self.ann_enabled()})

    def ann_enabled(self) -> bool:
        """True when any deployed model answers queries through its ANN
        index (retrieval mode applied AND an index present)."""
        return any(getattr(t, "ann_enabled", False)
                   for t in retrieval_targets(
                       getattr(self.deployed, "models", ())))

    def _ann_mode_collector(self) -> list:
        return [Metric(
            name="pio_serving_ann_enabled", kind="gauge",
            help="1 when queries are served through the ANN MIPS index, "
                 "0 for brute-force retrieval",
            samples=[({}, 1.0 if self.ann_enabled() else 0.0)],
        )]

    # -- auth (KeyAuthentication.withAccessKeyFromFile) ---------------------
    def _check_server_key(self, params: Mapping[str, str]) -> None:
        if self.config.server_key is None:
            return
        if params.get("accessKey") != self.config.server_key:
            raise _Reject(401, "invalid accessKey")

    # -- routes -------------------------------------------------------------
    def handle(
        self,
        method: str,
        path: str,
        params: Mapping[str, str],
        headers: Mapping[str, str],
        body: Any,
    ) -> tuple:
        """Returns ``(status, payload)`` or ``(status, payload, headers)``
        (the 3-tuple form carries e.g. ``Retry-After`` on 503s)."""
        try:
            if method == "GET" and path == "/":
                if "text/html" in headers.get("accept", ""):
                    return (200, _HtmlPage(self.status_html()))
                return (200, self.status_doc())
            if method == "POST" and path == "/queries.json":
                return self.handle_query(body, headers)
            if method == "GET" and path == "/plugins.json":
                return (200, self.plugins.describe())
            if method == "GET" and path == "/stats.json":
                return (200, self.stats_doc())
            if method == "GET" and path == "/metrics":
                # Prometheus exposition: serving counters + latency
                # histograms + resilience state (docs/observability.md);
                # under `--workers N` merged with every live sibling
                return (200, PlainTextPayload(
                    self.metrics_text(), PROMETHEUS_CONTENT_TYPE))
            if method == "GET" and path == "/traces.json":
                return (200, {"tracing": self.tracing,
                              "traces": self.traces_merged()})
            if method == "GET" and path == "/healthz":
                # liveness: the process answers; nothing else implied
                return (200, {"status": "ok"})
            if method == "GET" and path == "/readyz":
                return self.readyz()
            if path == "/reload" and method in ("GET", "POST"):
                self._check_server_key(params)
                # the shared reload sequence doubles as the new cache
                # generation, so every sibling's private cache lands on
                # the SAME generation (serving/workers.py); reload
                # FIRST, publish only on success — a failed swap keeps
                # last-known-good and announces nothing to the pool
                reload_seq = (self.coherence.next_reload_seq()
                              if self.coherence is not None else None)
                try:
                    self.reload(generation=reload_seq)
                except LookupError as e:
                    raise _Reject(404, str(e))
                except Exception as e:
                    # keep serving the last-known-good model instead of
                    # wedging: the old instance stays deployed
                    keep = self.deployed.instance.id
                    logger.exception(
                        "reload failed; still serving instance %s", keep)
                    record_fallback("serving/reload")
                    raise _Reject(
                        503,
                        f"reload failed ({e}); still serving instance {keep}",
                        {"Retry-After": retry_after_header(retry_after_hint(e))})
                self._publish_admin("reloaded on this worker",
                                    **({"reloadSeq": reload_seq}
                                       if reload_seq is not None else {}))
                return (200, {"message": "Reloading"})
            if method == "POST" and path == "/retrieval":
                self._check_server_key(params)
                return self.retrieval_admin(body)
            if method == "POST" and path == "/drain":
                self._check_server_key(params)
                return self.drain(body)
            if method == "POST" and path == "/stop":
                self._check_server_key(params)
                threading.Thread(target=self.on_stop, daemon=True).start()
                return (200, {"message": "Shutting down"})
            return (404, {"message": f"no route for {method} {path}"})
        except _Reject as r:
            if r.headers:
                return (r.status, {"message": r.message}, r.headers)
            return (r.status, {"message": r.message})
        except STORAGE_UNAVAILABLE_ERRORS as e:
            logger.warning("storage unavailable in %s %s: %s", method, path, e)
            return (503, {"message": f"storage unavailable: {e}"},
                    {"Retry-After": retry_after_header(retry_after_hint(e))})
        except Exception as e:
            logger.exception("unhandled error in %s %s", method, path)
            return (500, {"message": f"internal error: {e}"})

    _ROUTE_LABELS = {
        "/queries.json": "queries",
        "/stats.json": "stats",
        "/metrics": "metrics",
        "/": "status",
    }

    def observe_request(self, path: str, dt: float,
                        status: int | None = None) -> None:
        """Handler-measured request walltime into the per-route
        latency family (unknown paths fold into ``other``); query
        outcomes additionally feed the SLO ring (5xx = error-budget
        spend; a shed 503 is budget spend too — the SLO measures what
        callers experienced, not who was at fault)."""
        self.request_latency.observe(
            self._ROUTE_LABELS.get(path, "other"), dt)
        if status is not None and path == "/queries.json":
            self.slo.record(ok=status < 500, latency_s=dt)

    def drain(self, body: Any = None) -> tuple:
        """``POST /drain`` — flip this replica's readiness off so the
        fleet drains it before a planned stop (the supervisor's
        drain-before-SIGTERM step; docs/fleet.md "Supervision"):
        ``/readyz`` answers 503 "draining" while the latch holds, every
        router's membership loop stops routing here within its
        ``down_after`` probes, and in-flight queries still answer.
        ``{"action": "undrain"}`` clears the latch (an operator who
        drained for a look and changed their mind)."""
        undrain = isinstance(body, dict) and body.get("action") == "undrain"
        with self._reload_lock:
            self._draining = not undrain
        # workers share ONE public port, so an operator draining "the
        # deployment" cannot address one process — the latch propagates
        # to every sibling through the admin spool (verified: a
        # swallowed spool failure must not read as a drained pool)
        self._publish_admin(
            f"drain latch {'cleared' if undrain else 'set'} on this "
            "worker", draining=not undrain)
        logger.info("drain latch %s", "cleared" if undrain else "set")
        return (200, {"status": "ready" if undrain else "draining"})

    def readyz(self) -> tuple:
        """Readiness: a deployed model AND reachable storage. 503 (with
        Retry-After) until both hold — load balancers drain, clients
        back off, and a wedged dependency never looks like a live
        replica."""
        with self._reload_lock:
            reloading = self._reloads_in_flight > 0
            draining = self._draining
        if draining:
            # a planned drain (POST /drain): deliberately not-ready
            # until the supervisor stops the process or an operator
            # undrains — routers must NOT send new work here (deployed
            # may be None: the missing-model state readyz handles below
            # can be drained too)
            return (503, {"status": "draining",
                          "model": (self.deployed.instance.id
                                    if self.deployed is not None
                                    else "missing")},
                    {"Retry-After": retry_after_header(1.0)})
        if reloading:
            # a replica mid-model-swap must drain from routers/load
            # balancers: not-ready (NOT ready-with-stale) until the
            # swap commits or fails back to last-known-good
            return (503, {"status": "reloading",
                          "model": self.deployed.instance.id},
                    {"Retry-After": retry_after_header(1.0)})
        checks: dict[str, str] = {}
        ready = True
        if self.deployed is not None:
            checks["model"] = self.deployed.instance.id
        else:
            checks["model"] = "missing"
            ready = False
        if self.storage is not None:
            probe_id = checks["model"]  # a cheap keyed metadata read

            def probe() -> None:
                # inner deadline stops retry sleeps; bounded_probe walls
                # off a blackholed backend's socket timeout
                with deadline_scope(1.0):
                    self.storage.get_meta_data_engine_instances().get(probe_id)

            err = bounded_probe(probe, timeout=1.0)
            if err is None:
                checks["storage"] = "ok"
            else:
                checks["storage"] = f"unavailable: {err}"
                ready = False
        else:
            checks["storage"] = "skipped"
        if ready:
            return (200, {"status": "ready", **checks})
        return (503, {"status": "unavailable", **checks},
                {"Retry-After": retry_after_header(1.0)})

    def status_doc(self) -> dict:
        """The GET / status page content (CreateServer.scala:442-469)."""
        d = self.deployed
        inst = d.instance
        return {
            "status": "alive",
            "engineInstanceId": inst.id,
            "engineFactory": inst.engine_factory,
            "engineVariant": inst.engine_variant,
            "startTime": inst.start_time.isoformat(),
            "completionTime": inst.completion_time.isoformat(),
            "algorithms": [type(a).__name__ for a in d.algorithms],
            "serving": type(d.serving).__name__,
            "requestCount": d.request_count,
            "avgServingSec": d.avg_serving_sec,
            "lastServingSec": d.last_serving_sec,
            "clientDisconnects": self.client_disconnects(),
            **({"batching": {
                "batches": self.batcher.batches,
                "batchedQueries": self.batcher.batched_queries,
                # batchMax comes from the policy snapshot below — the
                # EFFECTIVE (menu-clamped) value, not the raw config
                "batchWaitMs": self.config.batch_wait_ms,
                **self.batcher.policy.snapshot(),
            }} if self.batcher is not None else {}),
            **({"resilience": snap} if (snap := resilience_snapshot()) else {}),
        }

    # -- `--workers N` scrape-time aggregation ------------------------------
    def metrics_text(self) -> str:
        """This worker's exposition — merged with every live sibling's
        when the worker pool is on (counters summed, histograms
        bucket-merged, gauges labeled ``worker=<id>`` per the
        merge_sources convention), plus the ``pio_serving_workers``
        gauge, so a scrape landing on one SO_REUSEPORT worker reports
        fleet-of-workers truth instead of a 1/N sample."""
        own = self.registry.collect()
        hub = self.worker_hub
        if hub is None:
            return render_metrics(own + [source_count_metric(
                "pio_serving_workers",
                "Live engine-server worker processes folded into this "
                "scrape (1 outside a worker pool)", 1)])
        sources: list[tuple[str, list]] = [(hub.worker_id, own)]
        for worker_id, body in hub.fetch_peer_bodies("/metrics"):
            try:
                sources.append((worker_id,
                                parse_exposition(body.decode())))
            except (ExpositionParseError, UnicodeDecodeError) as exc:
                logger.warning("worker %s exposition unparseable: %s",
                               worker_id, exc)
        merged = merge_sources(sources, source_label="worker")
        merged.append(source_count_metric(
            "pio_serving_workers",
            "Live engine-server worker processes folded into this "
            "scrape (1 outside a worker pool)", len(sources)))
        return render_metrics(merged)

    def traces_merged(self) -> list:
        """The local trace ring, with every live sibling's ring folded
        in (tagged ``source: worker:<id>``) under the worker pool —
        one ``GET /traces.json`` sees the whole pool's recent traces
        wherever the SO_REUSEPORT hash landed it."""
        traces = self.trace_log.snapshot()
        hub = self.worker_hub
        if hub is None:
            return traces
        for worker_id, body in hub.fetch_peer_bodies("/traces.json"):
            try:
                docs = json.loads(body).get("traces", [])
            except (json.JSONDecodeError, UnicodeDecodeError):
                continue
            for doc in docs:
                doc.setdefault("source", f"worker:{worker_id}")
                traces.append(doc)
        return traces

    def _workers_doc(self) -> dict:
        """The /stats.json ``workers`` section: per-worker request
        counts (this worker's live, siblings' fetched) plus pool
        totals — the sum is the number an operator wants, the split is
        where SO_REUSEPORT skew shows."""
        hub = self.worker_hub
        per_worker: dict[str, int] = {
            hub.worker_id: self.deployed.request_count}
        for worker_id, body in hub.fetch_peer_bodies("/stats.json"):
            try:
                doc = json.loads(body)
                per_worker[worker_id] = int(doc.get("requestCount", 0))
            except (json.JSONDecodeError, UnicodeDecodeError,
                    TypeError, ValueError):
                continue
        return {
            "worker": hub.worker_id,
            "count": len(per_worker),
            "requestCount": sum(per_worker.values()),
            "perWorker": per_worker,
        }

    def stats_doc(self, include_workers: bool = True) -> dict:
        """GET /stats.json — the serving hot path's internals (beyond
        reference; docs/serving-performance.md): batch-size histogram,
        the adaptive policy's inter-arrival EWMA and last plan, cache
        hit/miss/eviction counters and dedup count, per-backend
        resilience state. All counters are read under their own locks
        (ServingStats), so a concurrent burst never tears the doc.
        Under ``--workers N`` a ``workers`` section reports pool-wide
        request totals; ``include_workers=False`` is the sibling
        fan-out view (fetching peers from a peer callback would recurse
        across the pool)."""
        d = self.deployed
        return {
            **({"workers": self._workers_doc()}
               if include_workers and self.worker_hub is not None else {}),
            "engineInstanceId": d.instance.id,
            "requestCount": d.request_count,
            "avgServingSec": d.avg_serving_sec,
            "lastServingSec": d.last_serving_sec,
            "clientDisconnects": self.client_disconnects(),
            "annEnabled": self.ann_enabled(),
            "retrieval": self.config.retrieval,
            # the recompile sentinel's view (docs/observability.md):
            # compiles, cumulative compile seconds, post-warmup
            # serving recompiles — per-process like the jit caches
            "compile": compile_recorder().stats_doc(),
            "serving": self.serving_stats.snapshot(),
            "batching": (
                {"enabled": True, **self.batcher.policy.snapshot()}
                if self.batcher is not None else {"enabled": False}),
            "cache": (
                {"enabled": True, **self.cache.snapshot()}
                if self.cache is not None else {"enabled": False}),
            # the freshness plane's view (docs/freshness.md): overlay
            # occupancy, fold counters, event→serving lag, tail cursor
            **({"online": self.online.stats_doc()}
               if self.online is not None else {}),
            **({"resilience": snap} if (snap := resilience_snapshot()) else {}),
        }

    def status_html(self) -> str:
        """Browser-facing status page — the Twirl html.index render of the
        reference engine server (core/src/main/twirl/.../index.scala.html,
        served at CreateServer.scala:442-469)."""
        import html

        doc = self.status_doc()
        rows = "".join(
            f"<tr><th>{html.escape(str(k))}</th>"
            f"<td>{html.escape(str(v))}</td></tr>"
            for k, v in doc.items()
        )
        return (
            "<!DOCTYPE html><html><head><title>predictionio_tpu engine "
            f"server</title></head><body><h1>Engine instance "
            f"{html.escape(str(doc['engineInstanceId']))}</h1>"
            f"<table>{rows}</table></body></html>"
        )

    def _deadline_budget(self, headers: Mapping[str, str]) -> float | None:
        """Per-request budget (seconds) via the shared contract
        (http_base.parse_deadline_budget — the fleet router applies the
        same parse, so both tiers agree on every header): the
        X-PIO-Deadline-Ms header may only TIGHTEN the configured
        request_deadline_ms; malformed values are a 400."""
        try:
            return parse_deadline_budget(self.config.request_deadline_ms,
                                         headers)
        except ValueError as exc:
            raise _Reject(400, str(exc))

    def handle_query(self, body: Any,
                     headers: Mapping[str, str] = {}) -> tuple[int, Any]:
        """POST /queries.json (CreateServer.scala:470-621)."""
        if body is None or not isinstance(body, dict):
            raise _Reject(400, "the request body must be a JSON object")
        # prId is feedback-loop metadata carried alongside any query
        # (CreateServer.scala:506-512), not a query field — strip before
        # binding so the strict binder doesn't reject it
        body = dict(body)
        pr_id_in = body.pop("prId", None)
        decoder = self._query_decoder
        try:
            # span() is the ambient-trace helper: a shared no-op when
            # the handler started no trace (the near-free disabled path)
            with span("bind"):
                query = decoder(body) if decoder is not None else body
        except (ValueError, TypeError) as e:
            raise _Reject(400, f"invalid query: {e}")

        budget = self._deadline_budget(headers)
        # one canonical key serves both the result cache and the
        # batcher's dedup pass; None when neither wants it. Keyed on
        # the BOUND query's wire form, not the raw body, so camelCase
        # and snake_case spellings of the same query share an entry
        # (the ResultCache contract)
        with span("codec_key"):
            key = (canonical_json(encode_wire(query))
                   if (self.cache is not None or self.batcher is not None)
                   else None)
        hit, generation = False, None
        if self.cache is not None:
            t0 = time.perf_counter()
            with span("cache_lookup"):
                hit, cached, generation = self.cache.lookup(key)
        if hit:
            prediction = cached
            # a hit IS an answered query: requestCount / serving-time
            # bookkeeping must not report a hot cache as an idle server
            self.deployed.record_served(time.perf_counter() - t0)
        else:
            try:
                with deadline_scope(budget) if budget is not None \
                        else contextlib.nullcontext():
                    if self.batcher is not None:
                        # the ambient trace rides the queue entry: the
                        # dispatcher thread records queue-wait and
                        # device-dispatch spans onto it (batcher.py)
                        prediction = self.batcher.submit(
                            query,
                            timeout=budget if budget is not None else 300.0,
                            key=key, trace=active_trace())
                    elif budget is not None:
                        # _query_with_deadline copies this request's
                        # contextvars, so the ambient trace follows
                        # onto the pool thread by construction
                        with span("predict"):
                            prediction = self._query_with_deadline(
                                query, budget)
                    else:
                        with span("predict"):
                            prediction = self.deployed.query(query)
            except QueryDeadlineExceeded as e:
                # a blown deadline is overload/degradation, not an
                # application error: 503 so the client retries later
                raise _Reject(503, str(e), {"Retry-After": retry_after_header(1.0)})
            except STORAGE_UNAVAILABLE_ERRORS as e:
                logger.warning("query failed on unavailable storage: %s", e)
                raise _Reject(503, f"storage unavailable: {e}",
                              {"Retry-After": retry_after_header(retry_after_hint(e))})
            except Exception as e:
                logger.exception("query failed")
                raise _Reject(500, f"query failed: {e}")
            if self.cache is not None:
                # generational put: a result computed against a model
                # that /reload swapped out mid-flight is dropped, not
                # cached into the new model's generation
                self.cache.put(key, prediction, generation=generation)

        info = QueryInfo(
            query=query,
            prediction=prediction,
            engine_instance_id=self.deployed.instance.id,
        )
        try:
            prediction = self.plugins.run_blockers(info)
        except Exception as e:
            # a raising blocker rejects the prediction (plugin contract);
            # same mapping the event server uses for input blockers
            logger.warning("output blocker rejected query: %s", e)
            raise _Reject(403, f"prediction rejected: {e}")
        self.plugins.notify_sniffers(info)

        with span("encode"):
            response = encode_wire(prediction)
        if not isinstance(response, dict):
            response = {"result": response}
        # experiment attribution (experiment/controller.py): the router
        # stamps the assigned variant on the forwarded request; echo it
        # as prId-style response fields so the client can attach the
        # ids to conversion events — the loop serving → event store →
        # online score closes on exactly these two fields
        attribution = None
        experiment_id = headers.get("x-pio-experiment")
        if experiment_id:
            attribution = {"experimentId": experiment_id,
                           "variantId": headers.get("x-pio-variant", "")}
            response.update(attribution)
        if self.config.feedback:
            # feedback loop (CreateServer.scala:514-576): tag the response
            # with a prId and post the (query, prediction) as events
            pr_id = pr_id_in or uuid.uuid4().hex
            response["prId"] = pr_id
            self._post_feedback(pr_id, body, response,
                                attribution=attribution)
        if not self._compile_warmup_marked:
            # the first answered query ends serving warmup: from here
            # on, any jit compile under a request is an incident the
            # recompile sentinel WARNs about (a benign double-mark race
            # is fine — mark_warmup_complete is idempotent)
            self._compile_warmup_marked = True
            compile_recorder().mark_warmup_complete()
        return (200, response)

    def _query_with_deadline(self, query: Any, budget: float) -> Any:
        """Non-batched predict under a hard budget: run on a pool thread
        (copying this request's contextvars so the ambient deadline
        still reaches storage retries) and 503 when the wait expires —
        an in-flight slow predict cannot be interrupted, but it must
        not hold the client socket past the budget."""
        ctx = contextvars.copy_context()
        fut = self._query_pool.submit(ctx.run, self.deployed.query, query)
        try:
            return fut.result(timeout=budget)
        except FuturesTimeoutError:
            if not fut.done():
                fut.cancel()
                raise QueryDeadlineExceeded(budget) from None
            raise  # the work itself raised a TimeoutError (3.11 alias)

    def reload(self, generation: int | None = None) -> None:
        """Hot-swap to the latest completed instance
        (CreateServer.scala:316-342). While the reload is in flight
        /readyz reports not-ready (503 "reloading") so fleet membership
        drains this replica; failure semantics are unchanged — the
        last-known-good model keeps serving and the caller maps the
        error to 503. ``generation`` pins the post-swap result-cache
        generation (the shared reload sequence under ``--workers N``,
        so sibling caches stay generationally comparable)."""
        with self._reload_lock:
            self._reloads_in_flight += 1
        try:
            new = load_deployed_engine(
                storage=self.storage,
                config=dataclasses.replace(self.config,
                                           engine_instance_id=None),
                ctx=self.ctx,
                engine=self.deployed.engine,
            )
            old_id = self.deployed.instance.id
            self.deployed = new
            # the swap brought fresh model objects: re-install the
            # ServingStats ANN dispatch counter on each of them
            self._wire_ann_observers()
            self._query_decoder = (
                compile_wire_decoder(qc)
                if (qc := new.query_class) is not None else None)
            if self.cache is not None:
                # swap THEN invalidate: entries computed against the old
                # model die with its generation (ResultCache docstring); a
                # FAILED reload never reaches here, so last-known-good
                # keeps its warm cache
                self.cache.invalidate(generation=generation)
            # the generation fence: advance BEFORE the online plane
            # hears about the swap, so any fold-in racing this reload
            # publishes against a generation that no longer exists and
            # is discarded (overlay.put_* returns False)
            self.model_generation = (generation if generation is not None
                                     else self.model_generation + 1)
            if self.online is not None:
                self.online.on_model_swapped(self.model_generation)
            logger.info("reloaded: instance %s -> %s", old_id, new.instance.id)
        finally:
            with self._reload_lock:
                self._reloads_in_flight -= 1

    # -- feedback loop ------------------------------------------------------
    def _post_feedback(self, pr_id: str, query_json: dict, response: dict,
                       attribution: dict | None = None) -> None:
        """Fire-and-forget POST to the event server
        (CreateServer.scala:550-566). Forwards the ambient trace
        context (captured HERE, on the handler thread — the posting
        thread has no contextvars) so the event server's segment nests
        under this query's feedback span in the stitched tree."""
        trace = active_trace()
        feedback_span_id = trace.reserve_span_id() if trace else None

        def post() -> None:
            import urllib.request

            from predictionio_tpu.utils.ssl_config import client_transport

            scheme, ssl_ctx = client_transport()
            url = (
                f"{scheme}://{self.config.event_server_ip}:{self.config.event_server_port}"
                f"/events.json?accessKey={self.config.access_key}"
            )
            event = {
                "event": "predict",
                "entityType": "pio_pr",
                "entityId": pr_id,
                # attribution rides as top-level properties so the
                # conversion-count sweep (`pio experiment conversions`)
                # never has to dig through prediction payloads
                "properties": {"query": query_json, "prediction": response,
                               **(attribution or {})},
            }
            headers = {"Content-Type": "application/json"}
            if trace is not None:
                headers[TRACE_ID_HEADER] = trace.trace_id
                headers[PARENT_SPAN_HEADER] = feedback_span_id
            t0 = time.perf_counter()
            try:
                req = urllib.request.Request(
                    url,
                    data=json.dumps(event).encode(),
                    headers=headers,
                    method="POST",
                )
                with urllib.request.urlopen(
                        req, timeout=self.config.feedback_timeout_s,
                        context=ssl_ctx):
                    pass
            except Exception as e:
                logger.warning("feedback event POST failed: %s", e)
            finally:
                if trace is not None:
                    # best-effort: the handler has usually finished the
                    # trace by now, but TraceLog serializes at READ time
                    # and list.append is atomic, so the span still lands
                    # in later scrapes (Trace's lock-free contract)
                    trace.add_span("feedback", t0, time.perf_counter(),
                                   span_id=feedback_span_id)

        threading.Thread(target=post, name="pio-feedback", daemon=True).start()


class _Handler(BaseHTTPRequestHandler):
    service: EngineService  # bound per server

    # HTTP/1.1 keep-alive: the stdlib default (1.0) closes the socket
    # after every response, so each query paid a TCP connect + a fresh
    # ThreadingHTTPServer thread — measured as the dominant serving
    # cost at high concurrency (bench_serving.py). Persistent
    # connections make the per-request cost one read/write on a
    # long-lived thread. Requires the Content-Length header on every
    # response, which _respond always sends.
    protocol_version = "HTTP/1.1"

    # ...and a read timeout, or every idle persistent connection pins
    # its handler thread (and fd) for the life of the process —
    # handle_one_request treats the timeout as close_connection, so an
    # idle client is simply hung up on and reconnects transparently
    timeout = 30

    # buffer the response: the stdlib default (wbufsize=0) issues one
    # write() syscall PER HEADER LINE, and with Nagle enabled those
    # small segments can stall behind delayed ACKs; one buffered write
    # per response (handle_one_request flushes) + TCP_NODELAY keeps a
    # response to a single segment
    wbufsize = 64 * 1024
    disable_nagle_algorithm = True

    def _params(self) -> dict[str, str]:
        return {k: v[0] for k, v in parse_qs(urlparse(self.path).query).items()}

    def _dispatch(self, method: str) -> None:
        """Observability envelope around the real dispatch: request-id
        resolution (echoed by _respond), optional trace creation for
        the query hot path, handler-measured route latency, and the
        structured access log (all docs/observability.md)."""
        t_start = time.perf_counter()
        path = urlparse(self.path).path
        self._request_id = resolve_request_id(self.headers)
        self._last_status = 0
        self._trace = None
        if (method == "POST" and path == "/queries.json"
                and self.service.tracing):
            # adopt inbound cross-process context (the router's trace
            # id + its attempt span id) when well-formed; malformed or
            # oversized headers fall back to fresh local ids — never a
            # rejected request (obs/trace.parse_trace_context)
            inbound_id, inbound_parent = parse_trace_context(self.headers)
            self._trace = start_trace(
                "queries.json", request_id=self._request_id,
                trace_id=inbound_id, parent_span_id=inbound_parent,
                service="engine")
        try:
            self._dispatch_inner(method, path)
        finally:
            dt = time.perf_counter() - t_start
            self.service.observe_request(path, dt, self._last_status)
            if self._trace is not None:
                self._trace.finish(status=self._last_status)
                self.service.trace_log.record(self._trace)
            if self.service.access_log:
                # the worker id (satellite of the prefork pool): with N
                # processes behind one port, per-worker skew is only
                # visible when each line says WHICH worker served it
                wid = self.service.worker_id
                emit_access_log(
                    "engine", method, path, self._last_status, dt,
                    self._request_id, client=self.address_string(),
                    **({"worker": wid} if wid else {}))

    def _dispatch_inner(self, method: str, path: str) -> None:
        body: Any = None
        if self.headers.get("Transfer-Encoding"):
            # chunked bodies are not decoded here; on a keep-alive
            # (HTTP/1.1) connection the unread chunks would desync
            # every later request on the socket — 411 and CLOSE
            # (RFC 9112 §6.3 allows rejecting chunked with 411)
            self.close_connection = True
            self._respond(411, {
                "message": "chunked request bodies are not supported; "
                           "send Content-Length"},
                {"Connection": "close"})
            return
        # drain a Content-Length body for EVERY method: on a keep-alive
        # connection unread body bytes would be parsed as the next
        # request line (non-POST bodies are drained and ignored). A
        # malformed/negative length cannot be drained reliably — 400
        # and CLOSE (read(-1) would block to EOF and pin the thread)
        try:
            length = int(self.headers.get("Content-Length") or 0)
        except ValueError:
            length = -1
        if length < 0:
            self.close_connection = True
            self._respond(400, {"message": "invalid Content-Length"},
                          {"Connection": "close"})
            return
        raw = self.rfile.read(length) if length else b""
        if method == "POST" and raw:
            try:
                if self._trace is not None:
                    with self._trace.span("parse"):
                        body = json.loads(raw)
                else:
                    body = json.loads(raw)
            except json.JSONDecodeError:
                self._respond(400, {"message": "the request body is not valid JSON"})
                return
        # header names are case-insensitive (RFC 9110); normalise once
        headers = {k.lower(): v for k, v in self.headers.items()}
        if self._trace is not None:
            # ambient binding: spans opened anywhere under handle()
            # (bind, cache lookup, predict, encode) land on this trace
            with use_trace(self._trace):
                result = self.service.handle(
                    method, path, self._params(), headers, body)
        else:
            result = self.service.handle(
                method, path, self._params(), headers, body)
        self._respond(*result)

    def _respond(self, status: int, payload: Any,
                 extra_headers: Mapping[str, str] | None = None) -> None:
        self._last_status = status
        if isinstance(payload, _HtmlPage):
            data = str(payload).encode()
            ctype = "text/html; charset=UTF-8"
        elif isinstance(payload, PlainTextPayload):
            data = str(payload).encode()
            ctype = payload.content_type
        else:
            data = json.dumps(payload).encode()
            ctype = "application/json; charset=UTF-8"
        self.send_response(status)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(data)))
        # every response carries the correlation id (inbound
        # X-PIO-Request-Id propagated, else minted — http_base)
        if getattr(self, "_request_id", None):
            self.send_header(REQUEST_ID_HEADER, self._request_id)
        if getattr(self, "_trace", None) is not None:
            self.send_header("X-PIO-Trace-Id", self._trace.trace_id)
        for k, v in (extra_headers or {}).items():
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(data)

    def do_GET(self) -> None:  # noqa: N802
        self._dispatch("GET")

    def do_POST(self) -> None:  # noqa: N802
        self._dispatch("POST")

    def log_message(self, format: str, *args) -> None:
        logger.debug("%s - %s", self.address_string(), format % args)


def undeploy(ip: str, port: int, server_key: str | None = None) -> bool:
    """POST /stop to a running engine server on (ip, port) — the
    MasterActor undeploy of a previous instance (CreateServer.scala:260-294)
    and the CLI `pio undeploy` (commands/Engine.scala:240-276)."""
    import urllib.error
    import urllib.request

    from predictionio_tpu.utils.ssl_config import client_transport

    scheme, ssl_ctx = client_transport()
    host = "127.0.0.1" if ip == "0.0.0.0" else ip
    url = f"{scheme}://{host}:{port}/stop"
    if server_key:
        url += f"?accessKey={server_key}"
    try:
        req = urllib.request.Request(url, data=b"", method="POST")
        with urllib.request.urlopen(req, timeout=5, context=ssl_ctx):
            return True
    except (urllib.error.URLError, OSError):
        return False


class EngineServer(RestServer):
    """HTTP lifecycle around EngineService — the MasterActor
    (CreateServer.scala:247-382): undeploys any previous server on the
    port, binds with retry ×3, owns shutdown."""

    log_label = "Engine Server"
    thread_name = "pio-engineserver"
    bind_retries = 3

    def __init__(
        self,
        deployed: DeployedEngine,
        config: ServerConfig | None = None,
        storage: Storage | None = None,
        ctx: EngineContext | None = None,
        plugin_context: EngineServerPluginContext | None = None,
    ):
        config = config if config is not None else ServerConfig()
        self.config = config
        super().__init__(
            _Handler,
            EngineService(deployed, config, storage, ctx, plugin_context),
            config.ip, config.port,
            # N prefork workers share one listen port (`pio deploy
            # --workers N`); the CLI pool path sets the flag explicitly
            # — deliberately NOT derived from config.workers, which is
            # env-overridable: a standalone server constructed under a
            # stray PIO_SERVING_WORKERS=2 must not bind SO_REUSEPORT
            # (a later unrelated bind would silently siphon traffic)
            reuse_port=config.reuse_port,
        )
        self.service.on_stop = self.stop
        self.service.client_disconnects = lambda: self.client_disconnects

    def _on_bind_failure(self, attempt: int, ip: str, port: int) -> None:
        if attempt == 0 and port:
            # a previous instance may hold the port — undeploy it
            undeploy(ip, port, self.config.server_key)

    def _on_close(self) -> None:
        if self.service.online is not None:
            self.service.online.close()
        if self.service.coherence is not None:
            self.service.coherence.close()
        if self.service.worker_hub is not None:
            self.service.worker_hub.close()
        # the shm cache detaches (and unlinks iff this process created
        # the segment — the standalone case; pool workers only attach,
        # the deploy CLI owns the pool segment's lifetime) strictly
        # AFTER the online fold-in thread and the coherence loop stop:
        # both call into the cache (per-user invalidation, reload
        # adoption), and releasing the segment buffer under a live
        # caller raises mid-shutdown
        cache_close = getattr(self.service.cache, "close", None)
        if cache_close is not None:
            cache_close()
        if self.service.batcher is not None:
            self.service.batcher.close()
        self.service._query_pool.shutdown(wait=False)
        self.service.plugins.close()


def create_engine_server(
    storage: Storage | None = None,
    config: ServerConfig | None = None,
    ctx: EngineContext | None = None,
    engine: Any = None,
    plugin_context: EngineServerPluginContext | None = None,
) -> EngineServer:
    """Load the engine instance and bind the server — CreateServer.main
    (CreateServer.scala:105-180)."""
    config = config if config is not None else ServerConfig()
    storage = storage or Storage.default()
    deployed = load_deployed_engine(storage=storage, config=config, ctx=ctx, engine=engine)
    return EngineServer(deployed, config, storage, ctx, plugin_context)
