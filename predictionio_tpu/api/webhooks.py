"""Webhooks framework: adapt third-party JSON/form payloads into events.

Parity: data/src/main/scala/.../data/webhooks/
{JsonConnector,FormConnector,ConnectorUtil}.scala and
data/.../api/Webhooks.scala:45-154 — per-site connectors registered under
``/webhooks/<site>.json`` (JSON) and ``/webhooks/<site>.form``
(form-encoded). Ships the same two example connectors the reference does:
SegmentIO (JSON; segmentio/SegmentIOConnector.scala) and MailChimp (form;
mailchimp/MailChimpConnector.scala).
"""

from __future__ import annotations

import abc
from typing import Any, Mapping

from predictionio_tpu.core.event import Event
from predictionio_tpu.core.json_codec import event_from_json


class ConnectorError(ValueError):
    """Parity: ConnectorException."""


class JsonConnector(abc.ABC):
    """Converts a site's JSON payload to event JSON
    (JsonConnector.toEventJson, webhooks/JsonConnector.scala:24-32)."""

    @abc.abstractmethod
    def to_event_json(self, data: Mapping[str, Any]) -> dict[str, Any]: ...


class FormConnector(abc.ABC):
    """Converts a site's form payload to event JSON
    (FormConnector.toEventJson, webhooks/FormConnector.scala:25-33)."""

    @abc.abstractmethod
    def to_event_json(self, data: Mapping[str, str]) -> dict[str, Any]: ...


def connector_to_event(connector, data: Mapping) -> Event:
    """Parity: ConnectorUtil.toEvent (webhooks/ConnectorUtil.scala:41-45)."""
    return event_from_json(connector.to_event_json(data))


class SegmentIOConnector(JsonConnector):
    """segment.io spec v2 payloads -> events.

    Parity: webhooks/segmentio/SegmentIOConnector.scala:25-270. Maps the
    six message types (identify/track/alias/page/screen/group) to events
    named after the type, entityType "user", entityId = userId (or
    anonymousId), eventTime = timestamp/sentAt.
    """

    _TYPES = ("identify", "track", "alias", "page", "screen", "group")

    def to_event_json(self, data: Mapping[str, Any]) -> dict[str, Any]:
        if "version" not in data:
            raise ConnectorError("Failed to get segment.io API version.")
        msg_type = data.get("type")
        if msg_type not in self._TYPES:
            raise ConnectorError(
                f"Cannot convert unknown type {msg_type} to event JSON."
            )
        entity_id = data.get("userId") or data.get("anonymousId")
        if not entity_id:
            raise ConnectorError("there is no userId or anonymousId in the message")
        properties: dict[str, Any]
        if msg_type == "identify":
            properties = {"traits": data.get("traits", {})}
        elif msg_type == "track":
            properties = {
                "event": data.get("event"),
                "properties": data.get("properties", {}),
            }
        elif msg_type == "alias":
            properties = {"previousId": data.get("previousId")}
        elif msg_type in ("page", "screen"):
            properties = {
                "name": data.get("name"),
                "properties": data.get("properties", {}),
            }
        else:  # group
            properties = {
                "groupId": data.get("groupId"),
                "traits": data.get("traits", {}),
            }
        context = data.get("context")
        if context:
            properties["context"] = context
        out: dict[str, Any] = {
            "event": msg_type,
            "entityType": "user",
            "entityId": str(entity_id),
            "properties": {k: v for k, v in properties.items() if v is not None},
        }
        timestamp = data.get("timestamp") or data.get("sentAt")
        if timestamp:
            out["eventTime"] = timestamp
        return out


class MailChimpConnector(FormConnector):
    """MailChimp webhook form payloads -> events.

    Parity: webhooks/mailchimp/MailChimpConnector.scala:28-290. Supported
    types: subscribe, unsubscribe, profile, upemail, cleaned, campaign.
    entityType "user", entityId = the subscriber email/id.
    """

    _SUPPORTED = ("subscribe", "unsubscribe", "profile", "upemail", "cleaned", "campaign")

    def to_event_json(self, data: Mapping[str, str]) -> dict[str, Any]:
        msg_type = data.get("type")
        if msg_type not in self._SUPPORTED:
            raise ConnectorError(
                f"Cannot convert unknown type {msg_type} to event JSON."
            )
        def field(name: str) -> str | None:
            return data.get(f"data[{name}]")

        if msg_type == "cleaned":
            entity_id = field("email")
        elif msg_type == "upemail":
            entity_id = field("new_email")
        else:
            entity_id = field("email") or field("id")
        if not entity_id:
            raise ConnectorError(f"missing subscriber email/id in {msg_type} payload")
        properties = {
            k[len("data["):-1]: v for k, v in data.items()
            if k.startswith("data[") and k.endswith("]")
        }
        out: dict[str, Any] = {
            "event": msg_type,
            "entityType": "user",
            "entityId": entity_id,
            "properties": properties,
        }
        fired_at = data.get("fired_at")
        if fired_at:
            # MailChimp sends "2009-03-26 21:35:57" (UTC, no zone)
            out["eventTime"] = fired_at.replace(" ", "T")
        return out


#: Parity: WebhooksConnectors (webhooks/WebhooksConnectors.scala): the
#: registered site -> connector maps.
JSON_CONNECTORS: dict[str, JsonConnector] = {"segmentio": SegmentIOConnector()}
FORM_CONNECTORS: dict[str, FormConnector] = {"mailchimp": MailChimpConnector()}
