"""The Event Server: REST event collection on :7070.

Route and status-code parity with the reference
(reference: data/src/main/scala/.../data/api/EventServer.scala):

- ``GET /``                      alive check (:148-155)
- ``GET /plugins.json``          plugin listing (:157-177)
- ``GET|DELETE /events/{id}.json``  single event (:210-259)
- ``POST /events.json``          insert, 201 + eventId (:261-299)
- ``GET /events.json``           filtered query, default limit 20 (:300-375)
- ``POST /batch/events.json``    ≤50 events, per-event statuses (:376-460)
- ``GET /stats.json``            hourly stats when enabled (:463-489)
- ``POST|GET /webhooks/{site}.json|.form``  connectors (:491-592)
- ``GET /healthz``               liveness (beyond reference)
- ``GET /readyz``                readiness: storage reachable

Graceful degradation (beyond reference, docs/operations-resilience.md):
storage-backend failures on the ingest/read paths map to ``503`` +
``Retry-After`` — clients can distinguish a retryable outage from a bad
request — instead of a generic ``500``.

Auth (:88-131): ``accessKey`` query param, else HTTP Basic user part;
``channel`` query param selects a named channel. Event-name whitelists on
access keys are enforced (403).

Architecture: ``EventService`` is transport-free request logic (the
spray-route equivalent, testable like spray-testkit specs);
``EventServer`` adapts it onto a stdlib ThreadingHTTPServer — the
reference's spray/Akka HTTP stack maps to plain threaded HTTP since the
serving plane carries no TPU compute.
"""

from __future__ import annotations

import base64
import dataclasses
import json
import logging
import os
import re
import threading
import time
from http.server import BaseHTTPRequestHandler
from typing import Any, Mapping
from urllib.parse import parse_qs, urlparse

from predictionio_tpu.api.http_base import (
    REQUEST_ID_HEADER,
    PlainTextPayload,
    RestServer,
    access_log_enabled,
    bounded_probe,
    emit_access_log,
    ensure_access_log_handler,
    resolve_request_id,
    retry_after_header,
)
from predictionio_tpu.api.plugins import EventInfo, EventServerPluginContext
from predictionio_tpu.api.stats import IngestStats, StatsKeeper, resilience_snapshot
from predictionio_tpu.api.webhooks import (
    FORM_CONNECTORS,
    JSON_CONNECTORS,
    ConnectorError,
    connector_to_event,
)
from predictionio_tpu.core.event import EventValidationError
from predictionio_tpu.core.json_codec import (
    event_from_json,
    event_to_json,
    parse_datetime,
)
from predictionio_tpu.obs.exporter import CONTENT_TYPE as PROMETHEUS_CONTENT_TYPE
from predictionio_tpu.obs.exporter import render_prometheus
from predictionio_tpu.obs.registry import (
    HistogramFamily,
    Metric,
    MetricRegistry,
    ingest_collector,
    resilience_collector,
    server_info_collector,
    wal_collector,
)
from predictionio_tpu.obs.slo import SLOEngine
from predictionio_tpu.obs.trace import (
    TraceLog,
    parse_trace_context,
    span,
    start_trace,
    tracing_default,
    use_trace,
)
from predictionio_tpu.data.wal import (
    WalDrainer,
    WalFullError,
    WriteAheadLog,
    encode_record,
    make_storage_unavailable,
)
from predictionio_tpu.storage.base import EventFilter
from predictionio_tpu.storage.registry import Storage
from predictionio_tpu.utils.resilience import (
    STORAGE_UNAVAILABLE_ERRORS,
    StorageUnavailableError,
    deadline_scope,
    retry_after_hint,
)

logger = logging.getLogger(__name__)

#: Reference-parity default batch cap: MaxNumberOfEventsPerBatchRequest
#: (EventServer.scala:51). The effective limit is
#: ``EventServerConfig.max_batch_events`` (``PIO_EVENTSERVER_MAX_BATCH``
#: env overrides the default); this constant stays as the parity anchor.
MAX_EVENTS_PER_BATCH = 50


def _default_max_batch() -> int:
    """Built at config-construction time (never import time, same rule
    as ServerConfig's PIO_SERVING_* fields): a malformed or non-positive
    env value degrades to the reference default instead of killing the
    server at startup."""
    raw = os.environ.get("PIO_EVENTSERVER_MAX_BATCH")
    if raw is None:
        return MAX_EVENTS_PER_BATCH
    try:
        value = int(raw)
    except ValueError:
        value = 0
    if value <= 0:
        logger.warning("ignoring malformed PIO_EVENTSERVER_MAX_BATCH=%r "
                       "(using %d)", raw, MAX_EVENTS_PER_BATCH)
        return MAX_EVENTS_PER_BATCH
    return value


#: journal disk budget past which ingest reverts to 503 backpressure
DEFAULT_WAL_MAX_BYTES = 256 << 20


def _env_str(name: str, default: str | None,
             allowed: tuple[str, ...] | None = None):
    """Env-defaulted string field (read at construction time); a value
    outside ``allowed`` degrades to the default with a warning."""
    def build() -> str | None:
        raw = os.environ.get(name)
        if raw is None or raw == "":
            return default
        if allowed is not None and raw not in allowed:
            logger.warning("ignoring malformed %s=%r (using %r)",
                           name, raw, default)
            return default
        return raw
    return build


def _env_int(name: str, default: int):
    """Env-defaulted positive-int field: malformed/non-positive values
    degrade to the default with a warning (never kill startup)."""
    def build() -> int:
        raw = os.environ.get(name)
        if raw is None:
            return default
        try:
            value = int(raw)
        except ValueError:
            value = 0
        if value <= 0:
            logger.warning("ignoring malformed %s=%r (using %d)",
                           name, raw, default)
            return default
        return value
    return build


@dataclasses.dataclass(frozen=True)
class EventServerConfig:
    """Parity: EventServerConfig (EventServer.scala:626-630), plus the
    ingest tuning knob ``max_batch_events`` (docs/data-pipeline.md)."""
    ip: str = "0.0.0.0"
    port: int = 7070
    plugins: str = "plugins"
    stats: bool = False
    #: ``POST /batch/events.json`` cap; default 50 for reference parity,
    #: overridable per deployment via ``PIO_EVENTSERVER_MAX_BATCH``
    max_batch_events: int = dataclasses.field(
        default_factory=_default_max_batch)
    #: observability plane (docs/observability.md): per-request spans
    #: on the ingest hot paths (None defers to PIO_TRACE at server
    #: construction) and structured JSON access logs (None defers to
    #: PIO_ACCESS_LOG)
    tracing: bool | None = None
    access_log: bool | None = None
    #: -- durable ingest (docs/operations-resilience.md "The ingest
    #: durability ladder") -------------------------------------------
    #: journal directory; None (the default) disables the WAL — the
    #: pre-PR-13 503-only rung of the ladder
    wal_dir: str | None = dataclasses.field(
        default_factory=_env_str("PIO_EVENTSERVER_WAL_DIR", None))
    #: ``always`` | ``interval`` | ``off`` (data/wal.py)
    wal_fsync: str = dataclasses.field(
        default_factory=_env_str("PIO_EVENTSERVER_WAL_FSYNC", "interval",
                                 allowed=("always", "interval", "off")))
    #: disk budget: past this many pending journal bytes, ingest sheds
    #: 503s again (bounded ride-through, never a full disk)
    wal_max_bytes: int = dataclasses.field(
        default_factory=_env_int("PIO_EVENTSERVER_WAL_MAX_BYTES",
                                 DEFAULT_WAL_MAX_BYTES))
    #: ``ride-through`` journals only when storage is down (202 during
    #: the outage, 201 otherwise); ``write-through`` journals EVERY
    #: accepted event and answers 202 always — storage is written
    #: exclusively by the drainer (the top rung: max ingest throughput,
    #: reads lag by the drain depth)
    wal_policy: str = dataclasses.field(
        default_factory=_env_str(
            "PIO_EVENTSERVER_WAL_POLICY", "ride-through",
            allowed=("ride-through", "write-through")))
    #: application-level replay failures before a record is quarantined
    #: to the dead-letter series
    wal_replay_attempts: int = dataclasses.field(
        default_factory=_env_int("PIO_EVENTSERVER_WAL_REPLAY_ATTEMPTS", 5))


@dataclasses.dataclass(frozen=True)
class AuthData:
    """Parity: AuthData (EventServer.scala:88)."""
    app_id: int
    channel_id: int | None
    events: tuple[str, ...]


class _Reject(Exception):
    def __init__(self, status: int, message: str):
        self.status = status
        self.message = message


#: (HTTP status, JSON body) or (status, body, extra response headers)
Response = tuple


class EventService:
    """Transport-free event-server request logic."""

    def __init__(
        self,
        storage: Storage | None = None,
        config: EventServerConfig = EventServerConfig(),
        plugin_context: EventServerPluginContext | None = None,
    ):
        self.storage = storage or Storage.default()
        self.config = config
        self.events = self.storage.get_events()
        self.access_keys = self.storage.get_meta_data_access_keys()
        self.channels = self.storage.get_meta_data_channels()
        self.plugin_context = plugin_context or EventServerPluginContext()
        self.stats = StatsKeeper() if config.stats else None
        #: ingest-path counters (batch sizes, events/sec EWMA +
        #: windowed rate) — always kept (O(1) per batch under one lock,
        #: the ServingStats discipline); surfaced via GET /stats.json
        #: when --stats is on and GET /metrics always
        self.ingest_stats = IngestStats()
        #: observability plane (docs/observability.md)
        self.tracing = (config.tracing if config.tracing is not None
                        else tracing_default())
        self.access_log = access_log_enabled(config.access_log)
        if self.access_log:
            ensure_access_log_handler()
        self.trace_log = TraceLog()
        self.request_latency = HistogramFamily(
            "pio_http_request_seconds",
            "HTTP request walltime by route (handler-measured)",
            "route", ("events_post", "events_get", "batch", "webhooks",
                      "stats", "metrics"))
        self.registry = MetricRegistry()
        self.registry.register(self.request_latency.collect)
        self.registry.register(ingest_collector(self.ingest_stats))
        self.registry.register(resilience_collector())
        self.registry.register(server_info_collector("event"))
        #: SLO burn-rate gauges over the ingest write paths
        #: (obs/slo.py; docs/fleet.md autoscaler contract)
        self.slo = SLOEngine()
        self.registry.register(self.slo.collector())
        #: conversion attribution (experiment/controller.py): accepted
        #: client events carrying the served experimentId/variantId
        #: stamp, counted per variant — what `pio experiment
        #: conversions` sweeps into the online score. The server's own
        #: "predict" feedback events are excluded: serving a rec is
        #: not the user acting on it.
        self._conversion_lock = threading.Lock()
        self._conversions: dict[tuple[str, str], int] = {}
        self.registry.register(self._conversions_collector)
        #: auth results served while the metadata store was REACHABLE,
        #: replayed stale during an outage: without this every POST of
        #: the ride-through dies at authenticate() before the journal
        #: is ever reached. Storage stays authoritative while healthy
        #: (revocation honored); only STORAGE_UNAVAILABLE falls back.
        self._auth_cache: dict[str, Any] = {}
        self._auth_cache_lock = threading.Lock()
        #: durable ingest (data/wal.py; docs/operations-resilience.md
        #: "The ingest durability ladder")
        self.wal = None
        self.wal_drainer = None
        if config.wal_dir:
            self.wal = WriteAheadLog(
                config.wal_dir, fsync=config.wal_fsync,
                max_bytes=config.wal_max_bytes)
            self.wal_drainer = WalDrainer(
                self.wal, self._drain_insert_batch,
                max_replay_attempts=config.wal_replay_attempts,
                trace_factory=(self._wal_trace if self.tracing else None),
                trace_sink=(self.trace_log.record if self.tracing
                            else None))
            self.registry.register(wal_collector(self.wal,
                                                 self.wal_drainer))
            self.wal_drainer.start()
            logger.info(
                "durable ingest: WAL at %s (fsync=%s, budget=%d bytes, "
                "policy=%s, %d pending record(s) recovered)",
                config.wal_dir, config.wal_fsync, config.wal_max_bytes,
                config.wal_policy, self.wal.pending_records())

    def _drain_insert_batch(self, events, app_id, channel_id):
        """The drainer's storage write: the DAO's idempotent
        pre-assigned-id ``insert_batch``, counted into IngestStats so
        ``pio_ingest_events_total`` keeps meaning "landed in storage"."""
        t0 = time.perf_counter()
        ids = self.events.insert_batch(list(events), app_id, channel_id)
        self.ingest_stats.insert_latency.observe(time.perf_counter() - t0)
        self.ingest_stats.record_batch(len(events))
        return ids

    def _wal_trace(self):
        """One trace per replay pass: decode → insert_batch → commit
        spans land in the same /traces.json ring as the request paths."""
        return start_trace("wal.replay", service="event")

    # -- auth (EventServer.scala:92-131) ------------------------------------
    def authenticate(
        self, params: Mapping[str, str], headers: Mapping[str, str]
    ) -> AuthData:
        key = params.get("accessKey")
        if not key:
            auth = headers.get("Authorization", "")
            if auth.startswith("Basic "):
                try:
                    decoded = base64.b64decode(auth[len("Basic "):]).decode()
                    key = decoded.strip().split(":")[0]
                except Exception:
                    raise _Reject(401, "Invalid accessKey.")
        if not key:
            raise _Reject(401, "Missing accessKey.")
        access_key = self._cached_lookup(
            ("key", key), lambda: self.access_keys.get(key))
        if access_key is None:
            raise _Reject(401, "Invalid accessKey.")
        channel_id: int | None = None
        channel_name = params.get("channel")
        if channel_name:
            channel_map = self._cached_lookup(
                ("channels", access_key.appid),
                lambda: {c.name: c.id
                         for c in self.channels.get_by_app_id(
                             access_key.appid)})
            if channel_name not in channel_map:
                raise _Reject(401, f"Invalid channel '{channel_name}'.")
            channel_id = channel_map[channel_name]
        return AuthData(access_key.appid, channel_id, tuple(access_key.events))

    def _cached_lookup(self, cache_key, fetch):
        """Metadata lookup with STALE fallback: storage stays
        authoritative while reachable (key revocation takes effect
        immediately); during an outage the last-known answer is served
        so the WAL ride-through can authenticate the clients it was
        built for. A key never seen while storage was healthy still
        503s — the server must not invent credentials."""
        try:
            value = fetch()
        except STORAGE_UNAVAILABLE_ERRORS:
            with self._auth_cache_lock:
                if cache_key in self._auth_cache:
                    return self._auth_cache[cache_key]
            raise
        with self._auth_cache_lock:
            if value is None:
                # negative results are NOT cached: an attacker cycling
                # bogus keys must not grow this dict one entry per
                # guess (the positive set is bounded by the app's real
                # keys/channels), and a key deleted while storage is
                # healthy must drop out of the stale set too
                self._auth_cache.pop(cache_key, None)
            else:
                self._auth_cache[cache_key] = value
        return value

    # -- route handlers ------------------------------------------------------
    def alive(self) -> Response:
        return 200, {"status": "alive"}

    def healthz(self) -> Response:
        """Liveness: the process answers; nothing else implied."""
        return 200, {"status": "ok"}

    def readyz(self) -> Response:
        """Readiness: the metadata store answers a cheap keyed read.
        503 + Retry-After while the backend is down (or its breaker
        open) so load balancers drain this replica instead of feeding
        it traffic that will 503 anyway."""
        def probe() -> None:
            # inner deadline stops retry sleeps; bounded_probe walls off
            # a blackholed backend's socket timeout
            with deadline_scope(1.0):
                self.access_keys.get("__readyz_probe__")

        err = bounded_probe(probe, timeout=1.0)
        if err is not None:
            if self.wal is not None and not self.wal.is_full():
                # the WAL ride-through IS the ready state during an
                # outage: draining this replica would shed exactly the
                # writes the journal was built to keep accepting. Only
                # a journal at its disk budget makes ingest truly
                # unready (docs/operations-resilience.md).
                return 200, {"status": "ready", "storage": "unavailable",
                             "durability": "journaling"}
            return (503,
                    {"status": "unavailable", "storage": f"{err}"},
                    {"Retry-After": retry_after_header(retry_after_hint(err))})
        return 200, {"status": "ready", "storage": "ok"}

    def plugins_json(self) -> Response:
        return 200, self.plugin_context.describe()

    def post_event(
        self, params: Mapping[str, str], headers: Mapping[str, str], body: Any
    ) -> Response:
        auth = self.authenticate(params, headers)
        if not isinstance(body, Mapping):
            return 400, {"message": "request body must be a JSON object"}
        try:
            # span() records against the handler's ambient trace and is
            # a shared no-op when tracing is off (obs/trace.py)
            with span("validate"):
                event = event_from_json(body)
        except EventValidationError as exc:
            return 400, {"message": str(exc)}
        if auth.events and event.event not in auth.events:
            return 403, {"message": f"{event.event} events are not allowed"}
        try:
            self.plugin_context.run_blockers(
                EventInfo(auth.app_id, auth.channel_id, event)
            )
        except Exception as exc:
            return 403, {"message": str(exc)}
        return self._insert_or_journal(event, auth)

    # -- durable ingest (docs/operations-resilience.md) ----------------------
    def _insert_or_journal(self, event, auth: AuthData) -> Response:
        """The single-event write path of the durability ladder: direct
        insert (201) with WAL ride-through on a storage outage (202 +
        durability marker), or journal-first under ``write-through``.
        Sniffers and the hourly stats fire on ACCEPTANCE (201 and 202
        alike — the event is durably owned by the server either way)."""
        if self.wal is not None and self.config.wal_policy == "write-through":
            status, body = self._journal(event, auth)
        else:
            try:
                t0 = time.perf_counter()
                with span("insert"):
                    event_id = self.events.insert(
                        event, auth.app_id, auth.channel_id)
                self.ingest_stats.insert_latency.observe(
                    time.perf_counter() - t0)
                self.ingest_stats.record_batch(1)
                status, body = 201, {"eventId": event_id}
            except STORAGE_UNAVAILABLE_ERRORS as exc:
                if self.wal is None:
                    raise
                status, body = self._journal(event, auth, cause=exc)
        self.plugin_context.notify_sniffers(
            EventInfo(auth.app_id, auth.channel_id, event))
        if self.stats:
            self.stats.update(auth.app_id, status, event)
        if status < 300:
            self._count_conversion(event)
        return status, body

    def _count_conversion(self, event) -> None:
        """Fold one ACCEPTED event into the per-variant conversion
        counters when it carries the served attribution stamp
        (experimentId/variantId properties)."""
        if event.event == "predict":
            return
        try:
            experiment = event.properties.get("experimentId")
            variant = event.properties.get("variantId")
        except Exception:  # noqa: BLE001 — properties are client data
            return
        if not experiment or not variant:
            return
        key = (str(experiment), str(variant))
        with self._conversion_lock:
            self._conversions[key] = self._conversions.get(key, 0) + 1

    def _conversions_collector(self) -> list[Metric]:
        with self._conversion_lock:
            samples = [({"experiment": e, "variant": v}, float(n))
                       for (e, v), n in sorted(self._conversions.items())]
        return [Metric(
            "pio_experiment_conversions_ingested_total", "counter",
            "Accepted events carrying experiment attribution "
            "(conversion candidates), per variant.", samples=samples)]

    def conversion_counts(self, experiment: str) -> dict[str, int]:
        """Per-variant conversion totals for one experiment — what
        ``pio experiment conversions`` sweeps into the router's online
        score."""
        with self._conversion_lock:
            return {v: n for (e, v), n in self._conversions.items()
                    if e == experiment}

    def _journal(self, event, auth: AuthData,
                 cause: BaseException | None = None) -> tuple[int, dict]:
        """Append one accepted event to the WAL → ``202`` with a
        durability marker. At the disk budget the journal refuses and
        this degrades to the ladder's 503 rung, with a Retry-After hint
        that tracks drain progress (shrinks as the backlog drains)."""
        import uuid as _uuid

        if not event.event_id:
            # replay idempotency: the id the client gets acknowledged
            # IS the id the drainer upserts under
            event = event.with_event_id(_uuid.uuid4().hex)
        try:
            with span("journal"):
                self.wal.append(
                    encode_record(event, auth.app_id, auth.channel_id))
        except WalFullError as exc:
            hint = self.wal_drainer.backpressure_hint()
            if hint is None and cause is not None:
                hint = retry_after_hint(cause)
            raise make_storage_unavailable(exc, hint) from exc
        except OSError as exc:
            # a sick journal DISK (ENOSPC before the budget, EIO) is an
            # availability problem, not a server bug: the ladder's
            # honest answer stays 503 + Retry-After, never a 500
            logger.warning("WAL append failed (%s); shedding 503", exc)
            raise StorageUnavailableError("wal", str(exc)) from exc
        self.wal_drainer.notify()
        return 202, {"eventId": event.event_id, "durability": "journaled"}

    def _journal_result(self, event, auth: AuthData,
                        cause: BaseException | None) -> dict[str, Any]:
        """Per-event batch status for the ride-through: 202 journaled,
        or the honest 503 when no WAL is configured / it is at budget."""
        if self.wal is None:
            return {"status": 503, "message": str(cause)}
        try:
            status, body = self._journal(event, auth, cause=cause)
        except STORAGE_UNAVAILABLE_ERRORS as exc:
            return {"status": 503, "message": str(exc)}
        self.plugin_context.notify_sniffers(
            EventInfo(auth.app_id, auth.channel_id, event))
        if self.stats:
            self.stats.update(auth.app_id, status, event)
        if status < 300:
            self._count_conversion(event)
        return {"status": status, **body}

    def get_event(
        self, event_id: str, params: Mapping[str, str], headers: Mapping[str, str]
    ) -> Response:
        auth = self.authenticate(params, headers)
        event = self.events.get(event_id, auth.app_id, auth.channel_id)
        if event is None:
            return 404, {"message": "Not Found"}
        return 200, event_to_json(event)

    def delete_event(
        self, event_id: str, params: Mapping[str, str], headers: Mapping[str, str]
    ) -> Response:
        auth = self.authenticate(params, headers)
        found = self.events.delete(event_id, auth.app_id, auth.channel_id)
        if found:
            return 200, {"message": "Found"}
        return 404, {"message": "Not Found"}

    def get_events(
        self, params: Mapping[str, str], headers: Mapping[str, str]
    ) -> Response:
        """Query contract parity: EventServer.scala:300-375."""
        auth = self.authenticate(params, headers)
        try:
            reversed_ = params.get("reversed", "false").lower() == "true"
            entity_type = params.get("entityType")
            entity_id = params.get("entityId")
            if reversed_ and not (entity_type and entity_id):
                return 400, {
                    "message": "the parameter reversed can only be used with "
                    "both entityType and entityId specified."
                }
            limit = int(params.get("limit", 20))
            event_name = params.get("event")
            filter = EventFilter(
                start_time=(
                    parse_datetime(params["startTime"])
                    if "startTime" in params else None
                ),
                until_time=(
                    parse_datetime(params["untilTime"])
                    if "untilTime" in params else None
                ),
                entity_type=entity_type,
                entity_id=entity_id,
                event_names=[event_name] if event_name else None,
                target_entity_type=params.get("targetEntityType", ...),
                target_entity_id=params.get("targetEntityId", ...),
                limit=limit,
                reversed=reversed_,
            )
        except (ValueError, KeyError) as exc:
            return 400, {"message": str(exc)}
        found = [
            event_to_json(e)
            for e in self.events.find(auth.app_id, auth.channel_id, filter)
        ]
        if not found:
            return 404, {"message": "Not Found"}
        return 200, found

    def post_batch(
        self, params: Mapping[str, str], headers: Mapping[str, str], body: Any
    ) -> Response:
        """Batch contract parity: EventServer.scala:376-460 — per-event
        statuses in original order; whole request rejected only when over
        the configured cap. Beyond reference: the events that survive
        validation/auth/blockers land via ONE ``insert_batch`` call (a
        single storage transaction — sqlite executemany under one
        commit, one lock pass in memory, one append window in the logs)
        instead of per-event inserts; a storage outage therefore fails
        those events together as retryable 503s, never half a batch."""
        auth = self.authenticate(params, headers)
        if not isinstance(body, list):
            return 400, {"message": "request body must be a JSON array"}
        max_batch = self.config.max_batch_events
        if len(body) > max_batch:
            return 400, {
                "message": "Batch request must have less than or equal to "
                f"{max_batch} events"
            }
        results: list[dict[str, Any] | None] = [None] * len(body)
        pending: list[tuple[int, Any]] = []   # (original position, Event)
        with span("validate"):
            for pos, item in enumerate(body):
                try:
                    if not isinstance(item, Mapping):
                        raise EventValidationError(
                            "event must be a JSON object")
                    event = event_from_json(item)
                except EventValidationError as exc:
                    results[pos] = {"status": 400, "message": str(exc)}
                    continue
                if auth.events and event.event not in auth.events:
                    results[pos] = {
                        "status": 403,
                        "message": f"{event.event} events are not allowed",
                    }
                    continue
                try:
                    self.plugin_context.run_blockers(
                        EventInfo(auth.app_id, auth.channel_id, event)
                    )
                except Exception as exc:
                    results[pos] = {"status": 403, "message": str(exc)}
                    continue
                pending.append((pos, event))
        if pending:
            # pre-assign event ids so the per-event fallback below is
            # IDEMPOTENT: every backend honors a caller-set event_id
            # with upsert semantics (`event.event_id or uuid4` + put),
            # so re-inserting a prefix the failed batch already
            # committed overwrites rather than duplicates
            import uuid as _uuid

            pending = [
                (pos, e if e.event_id else e.with_event_id(_uuid.uuid4().hex))
                for pos, e in pending
            ]
            events = [e for _, e in pending]
            if (self.wal is not None
                    and self.config.wal_policy == "write-through"):
                # the top durability rung: storage is written only by
                # the drainer — the whole valid subset journals
                for pos, event in pending:
                    results[pos] = self._journal_result(event, auth,
                                                        cause=None)
                return 200, results
            try:
                t0 = time.perf_counter()
                with span("insert_batch"):
                    ids = self.events.insert_batch(
                        events, auth.app_id, auth.channel_id)
                self.ingest_stats.insert_latency.observe(
                    time.perf_counter() - t0)
                if len(ids) != len(events):
                    # a backend returning a short id list is a partial
                    # failure in disguise — zip would silently leave
                    # null statuses in the 200 response
                    ids = None
            except STORAGE_UNAVAILABLE_ERRORS as exc:
                # the resilience layer already retried the batch; the
                # backend is DOWN — re-walking up to max_batch_events
                # per-event inserts would multiply load on an outage
                # and hold the handler thread through more retry
                # cycles for the same all-503 answer. With a WAL the
                # pending events ride the outage out as journaled 202s
                # (position-correct: invalid events kept their 400/403
                # above); without one they fail together as retryable
                # 503s.
                for pos, event in pending:
                    results[pos] = self._journal_result(event, auth,
                                                        cause=exc)
                return 200, results
            except Exception:
                # insert_batch is one transaction on the backends that
                # can offer one (sqlite executemany under a single
                # commit, one lock pass in memory) but only best-effort
                # on append-log/remote backends, where a mid-batch
                # failure may have committed a prefix. Re-walking the
                # pending events per event (the reference behavior,
                # scala :440-444) yields an ACCURATE per-event status:
                # the pre-assigned ids make re-inserting the committed
                # prefix an overwrite, never a duplicate.
                ids = None
            if ids is None:
                down: Exception | None = None
                for pos, event in pending:
                    if down is not None:
                        # backend went down mid-fallback: later events
                        # cannot have landed — journal them (or fail
                        # 503) without hammering a dead store once per
                        # event
                        results[pos] = self._journal_result(event, auth,
                                                            cause=down)
                        continue
                    try:
                        event_id = self.events.insert(
                            event, auth.app_id, auth.channel_id)
                    except STORAGE_UNAVAILABLE_ERRORS as exc:
                        down = exc
                        results[pos] = self._journal_result(event, auth,
                                                            cause=exc)
                        continue
                    except Exception as exc:
                        results[pos] = {"status": 500, "message": str(exc)}
                        continue
                    results[pos] = {"status": 201, "eventId": event_id}
                    self.plugin_context.notify_sniffers(
                        EventInfo(auth.app_id, auth.channel_id, event))
                    if self.stats:
                        self.stats.update(auth.app_id, 201, event)
                    self._count_conversion(event)
                    # counted as size-1 inserts, which is what storage
                    # actually did on this path — folding them into one
                    # synthetic batch would skew the histogram exactly
                    # during the failure episodes an operator inspects
                    self.ingest_stats.record_batch(1)
            else:
                for (pos, event), event_id in zip(pending, ids):
                    self.plugin_context.notify_sniffers(
                        EventInfo(auth.app_id, auth.channel_id, event)
                    )
                    if self.stats:
                        self.stats.update(auth.app_id, 201, event)
                    self._count_conversion(event)
                    results[pos] = {"status": 201, "eventId": event_id}
                self.ingest_stats.record_batch(len(pending))
        return 200, results

    def stats_json(
        self, params: Mapping[str, str], headers: Mapping[str, str]
    ) -> Response:
        auth = self.authenticate(params, headers)
        if not self.stats:
            return 404, {
                "message": "To see stats, launch Event Server with --stats argument."
            }
        doc = self.stats.get(auth.app_id)
        doc["ingest"] = self.ingest_stats.snapshot()
        if self.wal_drainer is not None:
            doc["wal"] = self.wal_drainer.snapshot()
        snap = resilience_snapshot()
        if snap:
            doc["resilience"] = snap
        return 200, doc

    def post_webhook(
        self,
        site: str,
        form: bool,
        params: Mapping[str, str],
        headers: Mapping[str, str],
        body: Any,
    ) -> Response:
        """Parity: Webhooks.postJson/postForm (api/Webhooks.scala:45-114)."""
        auth = self.authenticate(params, headers)
        connectors = FORM_CONNECTORS if form else JSON_CONNECTORS
        connector = connectors.get(site)
        if connector is None:
            return 404, {"message": f"webhooks connection for {site} is not supported."}
        try:
            event = connector_to_event(connector, body)
        except (ConnectorError, EventValidationError) as exc:
            return 400, {"message": str(exc)}
        # webhook inserts ride the same durability ladder as
        # /events.json: 201 direct, 202 journaled during an outage
        return self._insert_or_journal(event, auth)

    def get_webhook(self, site: str, form: bool, params, headers) -> Response:
        """Existence check (Webhooks.getJson/getForm, api/Webhooks.scala:116-154)."""
        self.authenticate(params, headers)
        connectors = FORM_CONNECTORS if form else JSON_CONNECTORS
        if site not in connectors:
            return 404, {"message": f"webhooks connection for {site} is not supported."}
        return 200, {"message": f"Webhooks connection for {site} is supported."}

    # -- dispatch ------------------------------------------------------------
    _EVENT_PATH = re.compile(r"^/events/(?P<id>[^/]+)\.json$")
    _WEBHOOK_JSON = re.compile(r"^/webhooks/(?P<site>[^/.]+)\.json$")
    _WEBHOOK_FORM = re.compile(r"^/webhooks/(?P<site>[^/.]+)\.form$")

    def route_label(self, method: str, path: str) -> str:
        """Low-cardinality route label for the request-latency family
        (unknown paths fold into ``other`` at observe time)."""
        if path == "/events.json":
            return "events_post" if method == "POST" else "events_get"
        if path == "/batch/events.json":
            return "batch"
        if path.startswith("/webhooks/"):
            return "webhooks"
        if path == "/stats.json":
            return "stats"
        if path == "/metrics":
            return "metrics"
        return "other"

    def observe_request(self, method: str, path: str, dt: float,
                        status: int | None = None) -> None:
        self.request_latency.observe(self.route_label(method, path), dt)
        if status is not None and self.route_label(method, path) in (
                "events_post", "batch"):
            # ingest availability SLO: 5xx spends error budget; client
            # errors (bad JSON, bad key) do not
            self.slo.record(ok=status < 500, latency_s=dt)

    def handle(
        self,
        method: str,
        path: str,
        params: Mapping[str, str],
        headers: Mapping[str, str],
        body: Any = None,
    ) -> Response:
        """Single dispatch point for all transports."""
        try:
            if path == "/" and method == "GET":
                return self.alive()
            if path == "/healthz" and method == "GET":
                return self.healthz()
            if path == "/readyz" and method == "GET":
                return self.readyz()
            if path == "/plugins.json" and method == "GET":
                return self.plugins_json()
            if path == "/metrics" and method == "GET":
                # Prometheus exposition (docs/observability.md):
                # aggregate counters only, no per-app data — served
                # without an accessKey so a scraper needs no credential
                return 200, PlainTextPayload(
                    render_prometheus(self.registry),
                    PROMETHEUS_CONTENT_TYPE)
            if path == "/traces.json" and method == "GET":
                # UNLIKE /metrics this carries per-request data
                # (request ids, paths, timings) — it sits behind the
                # same accessKey auth as every event route
                self.authenticate(params, headers)
                return 200, {"tracing": self.tracing,
                             "traces": self.trace_log.snapshot()}
            if path == "/events.json":
                if method == "POST":
                    return self.post_event(params, headers, body)
                if method == "GET":
                    return self.get_events(params, headers)
            if path == "/batch/events.json" and method == "POST":
                return self.post_batch(params, headers, body)
            if path == "/stats.json" and method == "GET":
                return self.stats_json(params, headers)
            m = self._EVENT_PATH.match(path)
            if m:
                if method == "GET":
                    return self.get_event(m.group("id"), params, headers)
                if method == "DELETE":
                    return self.delete_event(m.group("id"), params, headers)
            m = self._WEBHOOK_JSON.match(path)
            if m:
                if method == "POST":
                    return self.post_webhook(m.group("site"), False, params, headers, body)
                if method == "GET":
                    return self.get_webhook(m.group("site"), False, params, headers)
            m = self._WEBHOOK_FORM.match(path)
            if m:
                if method == "POST":
                    return self.post_webhook(m.group("site"), True, params, headers, body)
                if method == "GET":
                    return self.get_webhook(m.group("site"), True, params, headers)
            return 404, {"message": "Not Found"}
        except _Reject as r:
            return r.status, {"message": r.message}
        except STORAGE_UNAVAILABLE_ERRORS as exc:
            # a flaky/unreachable backend is a retryable outage, not a
            # server bug: 503 + Retry-After (never a bare 500)
            logger.warning("storage unavailable handling %s %s: %s",
                           method, path, exc)
            return (503, {"message": f"storage unavailable: {exc}"},
                    {"Retry-After": retry_after_header(retry_after_hint(exc))})
        except Exception as exc:  # Common.exceptionHandler parity
            logger.exception("internal error handling %s %s", method, path)
            return 500, {"message": str(exc)}

    def close(self) -> None:
        if self.wal_drainer is not None:
            self.wal_drainer.stop()
        if self.wal is not None:
            self.wal.close()
        self.plugin_context.close()


class _Handler(BaseHTTPRequestHandler):
    service: EventService  # set on subclass

    protocol_version = "HTTP/1.1"

    def _params(self) -> dict[str, str]:
        q = parse_qs(urlparse(self.path).query)
        return {k: v[0] for k, v in q.items()}

    def _body(self) -> Any:
        length = int(self.headers.get("Content-Length") or 0)
        raw = self.rfile.read(length) if length else b""
        if not raw:
            return None
        content_type = (self.headers.get("Content-Type") or "").split(";")[0].strip()
        if content_type == "application/x-www-form-urlencoded":
            return {k: v[0] for k, v in parse_qs(raw.decode()).items()}
        try:
            return json.loads(raw)
        except json.JSONDecodeError:
            return _MALFORMED

    def _respond(self, status: int, payload: Any,
                 extra_headers: Mapping[str, str] | None = None) -> None:
        self._last_status = status
        if isinstance(payload, PlainTextPayload):
            data = str(payload).encode()
            ctype = payload.content_type
        else:
            data = json.dumps(payload).encode()
            ctype = "application/json; charset=UTF-8"
        self.send_response(status)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(data)))
        # every response carries the correlation id (inbound
        # X-PIO-Request-Id propagated, else minted — http_base)
        if getattr(self, "_request_id", None):
            self.send_header(REQUEST_ID_HEADER, self._request_id)
        if getattr(self, "_trace", None) is not None:
            self.send_header("X-PIO-Trace-Id", self._trace.trace_id)
        for k, v in (extra_headers or {}).items():
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(data)

    #: ingest hot paths that get a trace when tracing is on
    _TRACED_PATHS = ("/events.json", "/batch/events.json")

    def _dispatch(self, method: str) -> None:
        """Observability envelope (mirrors the engine server handler):
        request-id resolution, optional ingest-path traces, per-route
        latency, structured access log (docs/observability.md)."""
        t_start = time.perf_counter()
        path = urlparse(self.path).path
        self._request_id = resolve_request_id(self.headers)
        self._last_status = 0
        self._trace = None
        if (method == "POST" and path in self._TRACED_PATHS
                and self.service.tracing):
            # inbound cross-process context adopted when well-formed
            # (malformed falls back to fresh ids — obs/trace.py); the
            # feedback loop's engine→event POSTs stitch this way
            inbound_id, inbound_parent = parse_trace_context(self.headers)
            self._trace = start_trace(
                path.lstrip("/"), request_id=self._request_id,
                trace_id=inbound_id, parent_span_id=inbound_parent,
                service="event")
        try:
            self._dispatch_inner(method, path)
        finally:
            dt = time.perf_counter() - t_start
            self.service.observe_request(method, path, dt,
                                         self._last_status)
            if self._trace is not None:
                self._trace.finish(status=self._last_status)
                self.service.trace_log.record(self._trace)
            if self.service.access_log:
                emit_access_log(
                    "event", method, path, self._last_status, dt,
                    self._request_id, client=self.address_string())

    def _dispatch_inner(self, method: str, path: str) -> None:
        if method in ("POST", "PUT"):
            if self._trace is not None:
                with self._trace.span("parse"):
                    body = self._body()
            else:
                body = self._body()
        else:
            body = None
        if body is _MALFORMED:
            self._respond(400, {"message": "the request body is not valid JSON"})
            return
        if self._trace is not None:
            # ambient binding: validate/insert spans opened inside the
            # service land on this trace (obs/trace.py)
            with use_trace(self._trace):
                result = self.service.handle(
                    method, path, self._params(),
                    dict(self.headers.items()), body)
        else:
            result = self.service.handle(
                method, path, self._params(), dict(self.headers.items()), body)
        self._respond(*result)

    def do_GET(self) -> None:  # noqa: N802
        self._dispatch("GET")

    def do_POST(self) -> None:  # noqa: N802
        self._dispatch("POST")

    def do_DELETE(self) -> None:  # noqa: N802
        self._dispatch("DELETE")

    def log_message(self, format: str, *args) -> None:
        logger.debug("%s - %s", self.address_string(), format % args)


_MALFORMED = object()


class EventServer(RestServer):
    """HTTP wrapper. Parity: EventServer.createEventServer
    (EventServer.scala:632-654) — wires DAOs and binds the port."""

    log_label = "Event Server"
    thread_name = "pio-eventserver"

    def __init__(
        self,
        storage: Storage | None = None,
        config: EventServerConfig = EventServerConfig(),
        plugin_context: EventServerPluginContext | None = None,
    ):
        self.config = config
        super().__init__(
            _Handler, EventService(storage, config, plugin_context),
            config.ip, config.port,
        )

    def _on_close(self) -> None:
        self.service.close()


def create_event_server(
    storage: Storage | None = None,
    config: EventServerConfig = EventServerConfig(),
) -> EventServer:
    return EventServer(storage, config)
