"""The Event Server: REST event collection on :7070.

Route and status-code parity with the reference
(reference: data/src/main/scala/.../data/api/EventServer.scala):

- ``GET /``                      alive check (:148-155)
- ``GET /plugins.json``          plugin listing (:157-177)
- ``GET|DELETE /events/{id}.json``  single event (:210-259)
- ``POST /events.json``          insert, 201 + eventId (:261-299)
- ``GET /events.json``           filtered query, default limit 20 (:300-375)
- ``POST /batch/events.json``    ≤50 events, per-event statuses (:376-460)
- ``GET /stats.json``            hourly stats when enabled (:463-489)
- ``POST|GET /webhooks/{site}.json|.form``  connectors (:491-592)
- ``GET /healthz``               liveness (beyond reference)
- ``GET /readyz``                readiness: storage reachable

Graceful degradation (beyond reference, docs/operations-resilience.md):
storage-backend failures on the ingest/read paths map to ``503`` +
``Retry-After`` — clients can distinguish a retryable outage from a bad
request — instead of a generic ``500``.

Auth (:88-131): ``accessKey`` query param, else HTTP Basic user part;
``channel`` query param selects a named channel. Event-name whitelists on
access keys are enforced (403).

Architecture: ``EventService`` is transport-free request logic (the
spray-route equivalent, testable like spray-testkit specs);
``EventServer`` adapts it onto a stdlib ThreadingHTTPServer — the
reference's spray/Akka HTTP stack maps to plain threaded HTTP since the
serving plane carries no TPU compute.
"""

from __future__ import annotations

import base64
import dataclasses
import json
import logging
import os
import re
import threading
import time
from http.server import BaseHTTPRequestHandler
from typing import Any, Mapping
from urllib.parse import parse_qs, urlparse

from predictionio_tpu.api.http_base import (
    REQUEST_ID_HEADER,
    PlainTextPayload,
    RestServer,
    access_log_enabled,
    bounded_probe,
    emit_access_log,
    ensure_access_log_handler,
    resolve_request_id,
    retry_after_header,
)
from predictionio_tpu.api.plugins import EventInfo, EventServerPluginContext
from predictionio_tpu.api.stats import IngestStats, StatsKeeper, resilience_snapshot
from predictionio_tpu.api.webhooks import (
    FORM_CONNECTORS,
    JSON_CONNECTORS,
    ConnectorError,
    connector_to_event,
)
from predictionio_tpu.core.event import EventValidationError
from predictionio_tpu.core.json_codec import (
    event_from_json,
    event_to_json,
    parse_datetime,
)
from predictionio_tpu.obs.exporter import CONTENT_TYPE as PROMETHEUS_CONTENT_TYPE
from predictionio_tpu.obs.exporter import render_prometheus
from predictionio_tpu.obs.registry import (
    HistogramFamily,
    MetricRegistry,
    ingest_collector,
    resilience_collector,
    server_info_collector,
)
from predictionio_tpu.obs.slo import SLOEngine
from predictionio_tpu.obs.trace import (
    TraceLog,
    parse_trace_context,
    span,
    start_trace,
    tracing_default,
    use_trace,
)
from predictionio_tpu.storage.base import EventFilter
from predictionio_tpu.storage.registry import Storage
from predictionio_tpu.utils.resilience import (
    STORAGE_UNAVAILABLE_ERRORS,
    deadline_scope,
    retry_after_hint,
)

logger = logging.getLogger(__name__)

#: Reference-parity default batch cap: MaxNumberOfEventsPerBatchRequest
#: (EventServer.scala:51). The effective limit is
#: ``EventServerConfig.max_batch_events`` (``PIO_EVENTSERVER_MAX_BATCH``
#: env overrides the default); this constant stays as the parity anchor.
MAX_EVENTS_PER_BATCH = 50


def _default_max_batch() -> int:
    """Built at config-construction time (never import time, same rule
    as ServerConfig's PIO_SERVING_* fields): a malformed or non-positive
    env value degrades to the reference default instead of killing the
    server at startup."""
    raw = os.environ.get("PIO_EVENTSERVER_MAX_BATCH")
    if raw is None:
        return MAX_EVENTS_PER_BATCH
    try:
        value = int(raw)
    except ValueError:
        value = 0
    if value <= 0:
        logger.warning("ignoring malformed PIO_EVENTSERVER_MAX_BATCH=%r "
                       "(using %d)", raw, MAX_EVENTS_PER_BATCH)
        return MAX_EVENTS_PER_BATCH
    return value


@dataclasses.dataclass(frozen=True)
class EventServerConfig:
    """Parity: EventServerConfig (EventServer.scala:626-630), plus the
    ingest tuning knob ``max_batch_events`` (docs/data-pipeline.md)."""
    ip: str = "0.0.0.0"
    port: int = 7070
    plugins: str = "plugins"
    stats: bool = False
    #: ``POST /batch/events.json`` cap; default 50 for reference parity,
    #: overridable per deployment via ``PIO_EVENTSERVER_MAX_BATCH``
    max_batch_events: int = dataclasses.field(
        default_factory=_default_max_batch)
    #: observability plane (docs/observability.md): per-request spans
    #: on the ingest hot paths (None defers to PIO_TRACE at server
    #: construction) and structured JSON access logs (None defers to
    #: PIO_ACCESS_LOG)
    tracing: bool | None = None
    access_log: bool | None = None


@dataclasses.dataclass(frozen=True)
class AuthData:
    """Parity: AuthData (EventServer.scala:88)."""
    app_id: int
    channel_id: int | None
    events: tuple[str, ...]


class _Reject(Exception):
    def __init__(self, status: int, message: str):
        self.status = status
        self.message = message


#: (HTTP status, JSON body) or (status, body, extra response headers)
Response = tuple


class EventService:
    """Transport-free event-server request logic."""

    def __init__(
        self,
        storage: Storage | None = None,
        config: EventServerConfig = EventServerConfig(),
        plugin_context: EventServerPluginContext | None = None,
    ):
        self.storage = storage or Storage.default()
        self.config = config
        self.events = self.storage.get_events()
        self.access_keys = self.storage.get_meta_data_access_keys()
        self.channels = self.storage.get_meta_data_channels()
        self.plugin_context = plugin_context or EventServerPluginContext()
        self.stats = StatsKeeper() if config.stats else None
        #: ingest-path counters (batch sizes, events/sec EWMA +
        #: windowed rate) — always kept (O(1) per batch under one lock,
        #: the ServingStats discipline); surfaced via GET /stats.json
        #: when --stats is on and GET /metrics always
        self.ingest_stats = IngestStats()
        #: observability plane (docs/observability.md)
        self.tracing = (config.tracing if config.tracing is not None
                        else tracing_default())
        self.access_log = access_log_enabled(config.access_log)
        if self.access_log:
            ensure_access_log_handler()
        self.trace_log = TraceLog()
        self.request_latency = HistogramFamily(
            "pio_http_request_seconds",
            "HTTP request walltime by route (handler-measured)",
            "route", ("events_post", "events_get", "batch", "webhooks",
                      "stats", "metrics"))
        self.registry = MetricRegistry()
        self.registry.register(self.request_latency.collect)
        self.registry.register(ingest_collector(self.ingest_stats))
        self.registry.register(resilience_collector())
        self.registry.register(server_info_collector("event"))
        #: SLO burn-rate gauges over the ingest write paths
        #: (obs/slo.py; docs/fleet.md autoscaler contract)
        self.slo = SLOEngine()
        self.registry.register(self.slo.collector())

    # -- auth (EventServer.scala:92-131) ------------------------------------
    def authenticate(
        self, params: Mapping[str, str], headers: Mapping[str, str]
    ) -> AuthData:
        key = params.get("accessKey")
        if not key:
            auth = headers.get("Authorization", "")
            if auth.startswith("Basic "):
                try:
                    decoded = base64.b64decode(auth[len("Basic "):]).decode()
                    key = decoded.strip().split(":")[0]
                except Exception:
                    raise _Reject(401, "Invalid accessKey.")
        if not key:
            raise _Reject(401, "Missing accessKey.")
        access_key = self.access_keys.get(key)
        if access_key is None:
            raise _Reject(401, "Invalid accessKey.")
        channel_id: int | None = None
        channel_name = params.get("channel")
        if channel_name:
            channel_map = {
                c.name: c.id for c in self.channels.get_by_app_id(access_key.appid)
            }
            if channel_name not in channel_map:
                raise _Reject(401, f"Invalid channel '{channel_name}'.")
            channel_id = channel_map[channel_name]
        return AuthData(access_key.appid, channel_id, tuple(access_key.events))

    # -- route handlers ------------------------------------------------------
    def alive(self) -> Response:
        return 200, {"status": "alive"}

    def healthz(self) -> Response:
        """Liveness: the process answers; nothing else implied."""
        return 200, {"status": "ok"}

    def readyz(self) -> Response:
        """Readiness: the metadata store answers a cheap keyed read.
        503 + Retry-After while the backend is down (or its breaker
        open) so load balancers drain this replica instead of feeding
        it traffic that will 503 anyway."""
        def probe() -> None:
            # inner deadline stops retry sleeps; bounded_probe walls off
            # a blackholed backend's socket timeout
            with deadline_scope(1.0):
                self.access_keys.get("__readyz_probe__")

        err = bounded_probe(probe, timeout=1.0)
        if err is not None:
            return (503,
                    {"status": "unavailable", "storage": f"{err}"},
                    {"Retry-After": retry_after_header(retry_after_hint(err))})
        return 200, {"status": "ready", "storage": "ok"}

    def plugins_json(self) -> Response:
        return 200, self.plugin_context.describe()

    def post_event(
        self, params: Mapping[str, str], headers: Mapping[str, str], body: Any
    ) -> Response:
        auth = self.authenticate(params, headers)
        if not isinstance(body, Mapping):
            return 400, {"message": "request body must be a JSON object"}
        try:
            # span() records against the handler's ambient trace and is
            # a shared no-op when tracing is off (obs/trace.py)
            with span("validate"):
                event = event_from_json(body)
        except EventValidationError as exc:
            return 400, {"message": str(exc)}
        if auth.events and event.event not in auth.events:
            return 403, {"message": f"{event.event} events are not allowed"}
        try:
            self.plugin_context.run_blockers(
                EventInfo(auth.app_id, auth.channel_id, event)
            )
        except Exception as exc:
            return 403, {"message": str(exc)}
        t0 = time.perf_counter()
        with span("insert"):
            event_id = self.events.insert(event, auth.app_id, auth.channel_id)
        self.ingest_stats.insert_latency.observe(time.perf_counter() - t0)
        self.plugin_context.notify_sniffers(
            EventInfo(auth.app_id, auth.channel_id, event)
        )
        if self.stats:
            self.stats.update(auth.app_id, 201, event)
        self.ingest_stats.record_batch(1)
        return 201, {"eventId": event_id}

    def get_event(
        self, event_id: str, params: Mapping[str, str], headers: Mapping[str, str]
    ) -> Response:
        auth = self.authenticate(params, headers)
        event = self.events.get(event_id, auth.app_id, auth.channel_id)
        if event is None:
            return 404, {"message": "Not Found"}
        return 200, event_to_json(event)

    def delete_event(
        self, event_id: str, params: Mapping[str, str], headers: Mapping[str, str]
    ) -> Response:
        auth = self.authenticate(params, headers)
        found = self.events.delete(event_id, auth.app_id, auth.channel_id)
        if found:
            return 200, {"message": "Found"}
        return 404, {"message": "Not Found"}

    def get_events(
        self, params: Mapping[str, str], headers: Mapping[str, str]
    ) -> Response:
        """Query contract parity: EventServer.scala:300-375."""
        auth = self.authenticate(params, headers)
        try:
            reversed_ = params.get("reversed", "false").lower() == "true"
            entity_type = params.get("entityType")
            entity_id = params.get("entityId")
            if reversed_ and not (entity_type and entity_id):
                return 400, {
                    "message": "the parameter reversed can only be used with "
                    "both entityType and entityId specified."
                }
            limit = int(params.get("limit", 20))
            event_name = params.get("event")
            filter = EventFilter(
                start_time=(
                    parse_datetime(params["startTime"])
                    if "startTime" in params else None
                ),
                until_time=(
                    parse_datetime(params["untilTime"])
                    if "untilTime" in params else None
                ),
                entity_type=entity_type,
                entity_id=entity_id,
                event_names=[event_name] if event_name else None,
                target_entity_type=params.get("targetEntityType", ...),
                target_entity_id=params.get("targetEntityId", ...),
                limit=limit,
                reversed=reversed_,
            )
        except (ValueError, KeyError) as exc:
            return 400, {"message": str(exc)}
        found = [
            event_to_json(e)
            for e in self.events.find(auth.app_id, auth.channel_id, filter)
        ]
        if not found:
            return 404, {"message": "Not Found"}
        return 200, found

    def post_batch(
        self, params: Mapping[str, str], headers: Mapping[str, str], body: Any
    ) -> Response:
        """Batch contract parity: EventServer.scala:376-460 — per-event
        statuses in original order; whole request rejected only when over
        the configured cap. Beyond reference: the events that survive
        validation/auth/blockers land via ONE ``insert_batch`` call (a
        single storage transaction — sqlite executemany under one
        commit, one lock pass in memory, one append window in the logs)
        instead of per-event inserts; a storage outage therefore fails
        those events together as retryable 503s, never half a batch."""
        auth = self.authenticate(params, headers)
        if not isinstance(body, list):
            return 400, {"message": "request body must be a JSON array"}
        max_batch = self.config.max_batch_events
        if len(body) > max_batch:
            return 400, {
                "message": "Batch request must have less than or equal to "
                f"{max_batch} events"
            }
        results: list[dict[str, Any] | None] = [None] * len(body)
        pending: list[tuple[int, Any]] = []   # (original position, Event)
        with span("validate"):
            for pos, item in enumerate(body):
                try:
                    if not isinstance(item, Mapping):
                        raise EventValidationError(
                            "event must be a JSON object")
                    event = event_from_json(item)
                except EventValidationError as exc:
                    results[pos] = {"status": 400, "message": str(exc)}
                    continue
                if auth.events and event.event not in auth.events:
                    results[pos] = {
                        "status": 403,
                        "message": f"{event.event} events are not allowed",
                    }
                    continue
                try:
                    self.plugin_context.run_blockers(
                        EventInfo(auth.app_id, auth.channel_id, event)
                    )
                except Exception as exc:
                    results[pos] = {"status": 403, "message": str(exc)}
                    continue
                pending.append((pos, event))
        if pending:
            # pre-assign event ids so the per-event fallback below is
            # IDEMPOTENT: every backend honors a caller-set event_id
            # with upsert semantics (`event.event_id or uuid4` + put),
            # so re-inserting a prefix the failed batch already
            # committed overwrites rather than duplicates
            import uuid as _uuid

            pending = [
                (pos, e if e.event_id else e.with_event_id(_uuid.uuid4().hex))
                for pos, e in pending
            ]
            events = [e for _, e in pending]
            try:
                t0 = time.perf_counter()
                with span("insert_batch"):
                    ids = self.events.insert_batch(
                        events, auth.app_id, auth.channel_id)
                self.ingest_stats.insert_latency.observe(
                    time.perf_counter() - t0)
                if len(ids) != len(events):
                    # a backend returning a short id list is a partial
                    # failure in disguise — zip would silently leave
                    # null statuses in the 200 response
                    ids = None
            except STORAGE_UNAVAILABLE_ERRORS as exc:
                # the resilience layer already retried the batch; the
                # backend is DOWN — re-walking up to max_batch_events
                # per-event inserts would multiply load on an outage
                # and hold the handler thread through more retry
                # cycles for the same all-503 answer. Every pending
                # event fails together as a retryable 503.
                for pos, _ in pending:
                    results[pos] = {"status": 503, "message": str(exc)}
                return 200, results
            except Exception:
                # insert_batch is one transaction on the backends that
                # can offer one (sqlite executemany under a single
                # commit, one lock pass in memory) but only best-effort
                # on append-log/remote backends, where a mid-batch
                # failure may have committed a prefix. Re-walking the
                # pending events per event (the reference behavior,
                # scala :440-444) yields an ACCURATE per-event status:
                # the pre-assigned ids make re-inserting the committed
                # prefix an overwrite, never a duplicate.
                ids = None
            if ids is None:
                down: Exception | None = None
                for pos, event in pending:
                    if down is not None:
                        # backend went down mid-fallback: later events
                        # cannot have landed — fail them without
                        # hammering a dead store once per event
                        results[pos] = {"status": 503, "message": str(down)}
                        continue
                    try:
                        event_id = self.events.insert(
                            event, auth.app_id, auth.channel_id)
                    except STORAGE_UNAVAILABLE_ERRORS as exc:
                        down = exc
                        results[pos] = {"status": 503, "message": str(exc)}
                        continue
                    except Exception as exc:
                        results[pos] = {"status": 500, "message": str(exc)}
                        continue
                    results[pos] = {"status": 201, "eventId": event_id}
                    self.plugin_context.notify_sniffers(
                        EventInfo(auth.app_id, auth.channel_id, event))
                    if self.stats:
                        self.stats.update(auth.app_id, 201, event)
                    # counted as size-1 inserts, which is what storage
                    # actually did on this path — folding them into one
                    # synthetic batch would skew the histogram exactly
                    # during the failure episodes an operator inspects
                    self.ingest_stats.record_batch(1)
            else:
                for (pos, event), event_id in zip(pending, ids):
                    self.plugin_context.notify_sniffers(
                        EventInfo(auth.app_id, auth.channel_id, event)
                    )
                    if self.stats:
                        self.stats.update(auth.app_id, 201, event)
                    results[pos] = {"status": 201, "eventId": event_id}
                self.ingest_stats.record_batch(len(pending))
        return 200, results

    def stats_json(
        self, params: Mapping[str, str], headers: Mapping[str, str]
    ) -> Response:
        auth = self.authenticate(params, headers)
        if not self.stats:
            return 404, {
                "message": "To see stats, launch Event Server with --stats argument."
            }
        doc = self.stats.get(auth.app_id)
        doc["ingest"] = self.ingest_stats.snapshot()
        snap = resilience_snapshot()
        if snap:
            doc["resilience"] = snap
        return 200, doc

    def post_webhook(
        self,
        site: str,
        form: bool,
        params: Mapping[str, str],
        headers: Mapping[str, str],
        body: Any,
    ) -> Response:
        """Parity: Webhooks.postJson/postForm (api/Webhooks.scala:45-114)."""
        auth = self.authenticate(params, headers)
        connectors = FORM_CONNECTORS if form else JSON_CONNECTORS
        connector = connectors.get(site)
        if connector is None:
            return 404, {"message": f"webhooks connection for {site} is not supported."}
        try:
            event = connector_to_event(connector, body)
        except (ConnectorError, EventValidationError) as exc:
            return 400, {"message": str(exc)}
        event_id = self.events.insert(event, auth.app_id, auth.channel_id)
        if self.stats:
            self.stats.update(auth.app_id, 201, event)
        self.ingest_stats.record_batch(1)
        return 201, {"eventId": event_id}

    def get_webhook(self, site: str, form: bool, params, headers) -> Response:
        """Existence check (Webhooks.getJson/getForm, api/Webhooks.scala:116-154)."""
        self.authenticate(params, headers)
        connectors = FORM_CONNECTORS if form else JSON_CONNECTORS
        if site not in connectors:
            return 404, {"message": f"webhooks connection for {site} is not supported."}
        return 200, {"message": f"Webhooks connection for {site} is supported."}

    # -- dispatch ------------------------------------------------------------
    _EVENT_PATH = re.compile(r"^/events/(?P<id>[^/]+)\.json$")
    _WEBHOOK_JSON = re.compile(r"^/webhooks/(?P<site>[^/.]+)\.json$")
    _WEBHOOK_FORM = re.compile(r"^/webhooks/(?P<site>[^/.]+)\.form$")

    def route_label(self, method: str, path: str) -> str:
        """Low-cardinality route label for the request-latency family
        (unknown paths fold into ``other`` at observe time)."""
        if path == "/events.json":
            return "events_post" if method == "POST" else "events_get"
        if path == "/batch/events.json":
            return "batch"
        if path.startswith("/webhooks/"):
            return "webhooks"
        if path == "/stats.json":
            return "stats"
        if path == "/metrics":
            return "metrics"
        return "other"

    def observe_request(self, method: str, path: str, dt: float,
                        status: int | None = None) -> None:
        self.request_latency.observe(self.route_label(method, path), dt)
        if status is not None and self.route_label(method, path) in (
                "events_post", "batch"):
            # ingest availability SLO: 5xx spends error budget; client
            # errors (bad JSON, bad key) do not
            self.slo.record(ok=status < 500, latency_s=dt)

    def handle(
        self,
        method: str,
        path: str,
        params: Mapping[str, str],
        headers: Mapping[str, str],
        body: Any = None,
    ) -> Response:
        """Single dispatch point for all transports."""
        try:
            if path == "/" and method == "GET":
                return self.alive()
            if path == "/healthz" and method == "GET":
                return self.healthz()
            if path == "/readyz" and method == "GET":
                return self.readyz()
            if path == "/plugins.json" and method == "GET":
                return self.plugins_json()
            if path == "/metrics" and method == "GET":
                # Prometheus exposition (docs/observability.md):
                # aggregate counters only, no per-app data — served
                # without an accessKey so a scraper needs no credential
                return 200, PlainTextPayload(
                    render_prometheus(self.registry),
                    PROMETHEUS_CONTENT_TYPE)
            if path == "/traces.json" and method == "GET":
                # UNLIKE /metrics this carries per-request data
                # (request ids, paths, timings) — it sits behind the
                # same accessKey auth as every event route
                self.authenticate(params, headers)
                return 200, {"tracing": self.tracing,
                             "traces": self.trace_log.snapshot()}
            if path == "/events.json":
                if method == "POST":
                    return self.post_event(params, headers, body)
                if method == "GET":
                    return self.get_events(params, headers)
            if path == "/batch/events.json" and method == "POST":
                return self.post_batch(params, headers, body)
            if path == "/stats.json" and method == "GET":
                return self.stats_json(params, headers)
            m = self._EVENT_PATH.match(path)
            if m:
                if method == "GET":
                    return self.get_event(m.group("id"), params, headers)
                if method == "DELETE":
                    return self.delete_event(m.group("id"), params, headers)
            m = self._WEBHOOK_JSON.match(path)
            if m:
                if method == "POST":
                    return self.post_webhook(m.group("site"), False, params, headers, body)
                if method == "GET":
                    return self.get_webhook(m.group("site"), False, params, headers)
            m = self._WEBHOOK_FORM.match(path)
            if m:
                if method == "POST":
                    return self.post_webhook(m.group("site"), True, params, headers, body)
                if method == "GET":
                    return self.get_webhook(m.group("site"), True, params, headers)
            return 404, {"message": "Not Found"}
        except _Reject as r:
            return r.status, {"message": r.message}
        except STORAGE_UNAVAILABLE_ERRORS as exc:
            # a flaky/unreachable backend is a retryable outage, not a
            # server bug: 503 + Retry-After (never a bare 500)
            logger.warning("storage unavailable handling %s %s: %s",
                           method, path, exc)
            return (503, {"message": f"storage unavailable: {exc}"},
                    {"Retry-After": retry_after_header(retry_after_hint(exc))})
        except Exception as exc:  # Common.exceptionHandler parity
            logger.exception("internal error handling %s %s", method, path)
            return 500, {"message": str(exc)}

    def close(self) -> None:
        self.plugin_context.close()


class _Handler(BaseHTTPRequestHandler):
    service: EventService  # set on subclass

    protocol_version = "HTTP/1.1"

    def _params(self) -> dict[str, str]:
        q = parse_qs(urlparse(self.path).query)
        return {k: v[0] for k, v in q.items()}

    def _body(self) -> Any:
        length = int(self.headers.get("Content-Length") or 0)
        raw = self.rfile.read(length) if length else b""
        if not raw:
            return None
        content_type = (self.headers.get("Content-Type") or "").split(";")[0].strip()
        if content_type == "application/x-www-form-urlencoded":
            return {k: v[0] for k, v in parse_qs(raw.decode()).items()}
        try:
            return json.loads(raw)
        except json.JSONDecodeError:
            return _MALFORMED

    def _respond(self, status: int, payload: Any,
                 extra_headers: Mapping[str, str] | None = None) -> None:
        self._last_status = status
        if isinstance(payload, PlainTextPayload):
            data = str(payload).encode()
            ctype = payload.content_type
        else:
            data = json.dumps(payload).encode()
            ctype = "application/json; charset=UTF-8"
        self.send_response(status)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(data)))
        # every response carries the correlation id (inbound
        # X-PIO-Request-Id propagated, else minted — http_base)
        if getattr(self, "_request_id", None):
            self.send_header(REQUEST_ID_HEADER, self._request_id)
        if getattr(self, "_trace", None) is not None:
            self.send_header("X-PIO-Trace-Id", self._trace.trace_id)
        for k, v in (extra_headers or {}).items():
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(data)

    #: ingest hot paths that get a trace when tracing is on
    _TRACED_PATHS = ("/events.json", "/batch/events.json")

    def _dispatch(self, method: str) -> None:
        """Observability envelope (mirrors the engine server handler):
        request-id resolution, optional ingest-path traces, per-route
        latency, structured access log (docs/observability.md)."""
        t_start = time.perf_counter()
        path = urlparse(self.path).path
        self._request_id = resolve_request_id(self.headers)
        self._last_status = 0
        self._trace = None
        if (method == "POST" and path in self._TRACED_PATHS
                and self.service.tracing):
            # inbound cross-process context adopted when well-formed
            # (malformed falls back to fresh ids — obs/trace.py); the
            # feedback loop's engine→event POSTs stitch this way
            inbound_id, inbound_parent = parse_trace_context(self.headers)
            self._trace = start_trace(
                path.lstrip("/"), request_id=self._request_id,
                trace_id=inbound_id, parent_span_id=inbound_parent,
                service="event")
        try:
            self._dispatch_inner(method, path)
        finally:
            dt = time.perf_counter() - t_start
            self.service.observe_request(method, path, dt,
                                         self._last_status)
            if self._trace is not None:
                self._trace.finish(status=self._last_status)
                self.service.trace_log.record(self._trace)
            if self.service.access_log:
                emit_access_log(
                    "event", method, path, self._last_status, dt,
                    self._request_id, client=self.address_string())

    def _dispatch_inner(self, method: str, path: str) -> None:
        if method in ("POST", "PUT"):
            if self._trace is not None:
                with self._trace.span("parse"):
                    body = self._body()
            else:
                body = self._body()
        else:
            body = None
        if body is _MALFORMED:
            self._respond(400, {"message": "the request body is not valid JSON"})
            return
        if self._trace is not None:
            # ambient binding: validate/insert spans opened inside the
            # service land on this trace (obs/trace.py)
            with use_trace(self._trace):
                result = self.service.handle(
                    method, path, self._params(),
                    dict(self.headers.items()), body)
        else:
            result = self.service.handle(
                method, path, self._params(), dict(self.headers.items()), body)
        self._respond(*result)

    def do_GET(self) -> None:  # noqa: N802
        self._dispatch("GET")

    def do_POST(self) -> None:  # noqa: N802
        self._dispatch("POST")

    def do_DELETE(self) -> None:  # noqa: N802
        self._dispatch("DELETE")

    def log_message(self, format: str, *args) -> None:
        logger.debug("%s - %s", self.address_string(), format % args)


_MALFORMED = object()


class EventServer(RestServer):
    """HTTP wrapper. Parity: EventServer.createEventServer
    (EventServer.scala:632-654) — wires DAOs and binds the port."""

    log_label = "Event Server"
    thread_name = "pio-eventserver"

    def __init__(
        self,
        storage: Storage | None = None,
        config: EventServerConfig = EventServerConfig(),
        plugin_context: EventServerPluginContext | None = None,
    ):
        self.config = config
        super().__init__(
            _Handler, EventService(storage, config, plugin_context),
            config.ip, config.port,
        )

    def _on_close(self) -> None:
        self.service.close()


def create_event_server(
    storage: Storage | None = None,
    config: EventServerConfig = EventServerConfig(),
) -> EventServer:
    return EventServer(storage, config)
