"""Event-server bookkeeping: per-app counts of status codes and
(entityType, targetEntityType, event) triples, kept in hourly buckets.

Parity: data/src/main/scala/.../data/api/{Stats.scala:30-82,
StatsActor.scala} — the reference rotates a ``Stats`` per hour inside
``StatsActor``; here ``StatsKeeper`` owns the rotation under a lock
instead of an actor mailbox.

Beyond reference: :func:`resilience_snapshot` surfaces the per-backend
retry/circuit-breaker counters (utils/resilience registry) so both
servers' stats/status documents show backend health alongside traffic,
and :class:`ServingStats` carries the engine server's hot-path counters
(batch-size histogram, adaptive-wait EWMA input, result-cache hit/miss/
eviction, per-batch dedup) for ``GET /stats.json``.
"""

from __future__ import annotations

import dataclasses
import threading
from collections import Counter
from datetime import datetime, timezone

from predictionio_tpu.core.event import Event
from predictionio_tpu.core.json_codec import format_datetime
from predictionio_tpu.core.wire import snake_to_camel
from predictionio_tpu.obs.histogram import LatencyHistogram


def resilience_snapshot() -> dict:
    """Per-backend resilience counters: attempts, retries, failures,
    short-circuits, breaker state/opens — keyed by policy name
    (``<backend>/<source>``). Empty until a resilient backend is used."""
    from predictionio_tpu.utils.resilience import registry_snapshot

    return registry_snapshot()


class ServingStats:
    """Counters for the engine server's query hot path, written by the
    batcher dispatcher (batch records), the result cache (hit/miss/
    eviction), and handler threads (expiries) — one lock guards every
    field at writers AND readers, the same discipline as
    :class:`StatsKeeper`/``ResilienceMetrics``, so no reader ever sees a
    torn histogram and the lock-discipline lint needs no suppressions."""

    COUNTER_FIELDS = (
        "dispatches", "batched_queries", "deduped", "expired",
        "cache_hits", "cache_misses", "cache_evictions",
        "cache_expirations", "cache_invalidations",
        "cache_user_invalidations",
        "ann_queries", "ann_rescored",
    )

    def __init__(self):
        self._lock = threading.Lock()
        self._counts = dict.fromkeys(self.COUNTER_FIELDS, 0)
        #: dispatched (post-dedup) batch size -> count
        self._batch_hist: Counter[int] = Counter()
        #: ANN shortlist width (candidate columns rescored per query,
        #: pad included — the static jit width) -> query count
        self._ann_hist: Counter[int] = Counter()
        #: latency attribution (obs/histogram.py; each histogram owns
        #: its own lock): queue component vs device component of the
        #: batched serving path — the Clipper-style split GET /metrics
        #: and /traces.json surface (docs/observability.md)
        self.queue_wait = LatencyHistogram()
        self.device_time = LatencyHistogram()

    def bump(self, field: str, n: int = 1) -> None:
        with self._lock:
            self._counts[field] += n

    def observe_queue_waits(self, waits) -> None:
        """Per-entry enqueue→dispatch waits for one batch (one lock
        acquisition for the whole batch)."""
        self.queue_wait.observe_many(waits)

    def observe_device_time(self, dt: float) -> None:
        """One batch's query_batch walltime."""
        self.device_time.observe(dt)

    def record_batch(self, dispatched: int, coalesced: int) -> None:
        """One device dispatch: ``dispatched`` unique queries actually
        scored, ``coalesced`` queries answered by it (>= dispatched when
        the dedup pass folded identical concurrent queries)."""
        with self._lock:
            self._counts["dispatches"] += 1
            self._counts["batched_queries"] += coalesced
            self._counts["deduped"] += coalesced - dispatched
            self._batch_hist[dispatched] += 1

    def record_ann(self, shortlist_width: int, queries: int = 1) -> None:
        """One ANN retrieval dispatch: ``queries`` queries answered from
        a ``shortlist_width``-candidate rescore each (the ALSModel
        observer hook — models/als.set_ann_observer)."""
        with self._lock:
            self._counts["ann_queries"] += queries
            self._counts["ann_rescored"] += shortlist_width * queries
            self._ann_hist[shortlist_width] += queries

    def ann_histogram(self) -> dict[int, int]:
        """Shortlist width -> query count, read under the lock."""
        with self._lock:
            return dict(self._ann_hist)

    def count(self, field: str) -> int:
        with self._lock:
            return self._counts[field]

    def raw_counts(self) -> dict[str, int]:
        """All counters under ONE lock acquisition (snake_case keys) —
        the metric-registry adapter's read (obs/registry.py)."""
        with self._lock:
            return dict(self._counts)

    def batch_histogram(self) -> dict[int, int]:
        """Dispatched batch-size -> count, read under the lock."""
        with self._lock:
            return dict(self._batch_hist)

    def snapshot(self) -> dict:
        with self._lock:
            counts = dict(self._counts)
            hist = {str(k): v for k, v in sorted(self._batch_hist.items())}
            ann_hist = {str(k): v
                        for k, v in sorted(self._ann_hist.items())}
        hits, misses = counts["cache_hits"], counts["cache_misses"]
        looked = hits + misses
        return {
            **{snake_to_camel(k): v for k, v in counts.items()},
            "batchSizeHistogram": hist,
            "annShortlistHistogram": ann_hist,
            "cacheHitRatio": round(hits / looked, 4) if looked else None,
            "queueWait": self.queue_wait.snapshot().summary_ms(),
            "deviceDispatch": self.device_time.snapshot().summary_ms(),
        }


class IngestStats:
    """Counters for the event server's ingest path, written by the
    request handlers after each successful insert/insert_batch — the
    same one-lock-at-writers-AND-readers discipline as
    :class:`ServingStats`, so a ``GET /stats.json`` reader never sees a
    torn histogram and the lock-discipline lint needs no suppressions.

    ``events_per_sec_ewma`` smooths the instantaneous batch rate
    (batch size / time since the previous batch) with EWMA_ALPHA.
    Caveat (bench discipline): under a closed-loop load generator the
    EWMA tracks the generator's issue rate, not server capacity — treat
    it as an observability signal, not a benchmark number. The
    windowed rate below does NOT share that bias: a ring of per-second
    monotonic buckets counts what actually landed each wall second, so
    ``eventsPerSecWindowed`` is a true recent-throughput number
    (complete seconds only — the current partial second is excluded so
    a mid-second read never underreports)."""

    EWMA_ALPHA = 0.2
    #: SKIP (not clamp) the EWMA update for gaps below this: two
    #: handler threads landing in the same instant would otherwise
    #: divide by ~zero and fold a meaningless multi-million-events/sec
    #: spike into the average
    _MIN_DT = 1e-6
    #: per-second ring span: the windowed rate covers up to this many
    #: complete seconds (Prometheus-style "last minute" semantics)
    WINDOW_SECONDS = 60

    def __init__(self, clock=None):
        import time

        self._now = clock or time.monotonic
        self._lock = threading.Lock()
        self._batches = 0
        self._events = 0
        #: inserted batch size -> count (1 = single-event posts)
        self._batch_hist: Counter[int] = Counter()
        self._last_t: float | None = None
        self._ewma_rate: float | None = None
        #: per-second event counts: slot i holds the count for the
        #: monotonic second recorded in _ring_sec[i]; a slot whose
        #: second moved on is reset lazily at the next write
        self._ring = [0] * self.WINDOW_SECONDS
        self._ring_sec = [-1] * self.WINDOW_SECONDS
        self._first_sec: int | None = None
        #: storage insert/insert_batch walltime (obs/histogram.py;
        #: owns its own lock) — fed by the event server's ingest paths
        self.insert_latency = LatencyHistogram()

    def record_batch(self, n: int) -> None:
        """One successful storage insert of ``n`` events."""
        if n <= 0:
            return
        with self._lock:
            # clock read INSIDE the lock: a thread that read the clock
            # before losing the lock race would otherwise compute a
            # negative-then-clamped dt and spike the EWMA
            now = self._now()
            self._batches += 1
            self._events += n
            self._batch_hist[n] += 1
            sec = int(now)
            idx = sec % self.WINDOW_SECONDS
            if self._ring_sec[idx] != sec:
                self._ring[idx] = 0
                self._ring_sec[idx] = sec
            self._ring[idx] += n
            if self._first_sec is None:
                self._first_sec = sec
            if self._last_t is not None:
                dt = now - self._last_t
                if dt >= self._MIN_DT:
                    inst = n / dt
                    self._ewma_rate = (
                        inst if self._ewma_rate is None
                        else self.EWMA_ALPHA * inst
                        + (1.0 - self.EWMA_ALPHA) * self._ewma_rate)
            self._last_t = now

    def _windowed_rate_locked(self) -> tuple[float | None, int]:
        """(events/sec over complete seconds, window length) — caller
        holds the lock. None until one full second has elapsed."""
        if self._first_sec is None:
            return None, 0
        now_sec = int(self._now())
        # complete seconds only: [now_sec - window, now_sec)
        window = min(self.WINDOW_SECONDS - 1, now_sec - self._first_sec)
        if window <= 0:
            return None, 0
        lo = now_sec - window
        total = sum(
            count
            for count, sec in zip(self._ring, self._ring_sec)
            if lo <= sec < now_sec
        )
        return total / window, window

    def totals(self) -> tuple[int, int]:
        """(batches, events) under one lock — the registry adapter."""
        with self._lock:
            return self._batches, self._events

    def batch_histogram(self) -> dict[int, int]:
        with self._lock:
            return dict(self._batch_hist)

    def rates(self) -> tuple[float | None, float | None, int]:
        """(ewma, windowed, window_seconds) under one lock."""
        with self._lock:
            windowed, window = self._windowed_rate_locked()
            return self._ewma_rate, windowed, window

    def snapshot(self) -> dict:
        with self._lock:
            batches, events = self._batches, self._events
            hist = {str(k): v for k, v in sorted(self._batch_hist.items())}
            rate = self._ewma_rate
            windowed, window = self._windowed_rate_locked()
        return {
            "batches": batches,
            "events": events,
            "meanBatchSize": round(events / batches, 2) if batches else None,
            "batchSizeHistogram": hist,
            "eventsPerSecEwma": round(rate, 1) if rate is not None else None,
            "eventsPerSecWindowed": (
                round(windowed, 1) if windowed is not None else None),
            "windowSeconds": window,
            "insertLatency": self.insert_latency.snapshot().summary_ms(),
        }


@dataclasses.dataclass(frozen=True)
class EntityTypesEvent:
    """Parity: EntityTypesEvent (Stats.scala:30-39)."""
    entity_type: str
    target_entity_type: str | None
    event: str

    @staticmethod
    def of(e: Event) -> "EntityTypesEvent":
        return EntityTypesEvent(e.entity_type, e.target_entity_type, e.event)


class Stats:
    """One bucket of counts. Parity: Stats (Stats.scala:51-82)."""

    def __init__(self, start_time: datetime):
        self.start_time = start_time
        self.end_time: datetime | None = None
        self.status_code_count: Counter[tuple[int, int]] = Counter()
        self.ete_count: Counter[tuple[int, EntityTypesEvent]] = Counter()

    def cutoff(self, end_time: datetime) -> None:
        self.end_time = end_time

    def update(self, app_id: int, status_code: int, event: Event) -> None:
        self.status_code_count[(app_id, status_code)] += 1
        self.ete_count[(app_id, EntityTypesEvent.of(event))] += 1

    def get(self, app_id: int) -> dict:
        """JSON snapshot for one app (Stats.get -> StatsSnapshot)."""
        return {
            "startTime": format_datetime(self.start_time),
            "endTime": format_datetime(self.end_time) if self.end_time else None,
            "basic": [
                {
                    "key": {
                        "entityType": k[1].entity_type,
                        "targetEntityType": k[1].target_entity_type,
                        "event": k[1].event,
                    },
                    "value": v,
                }
                for k, v in sorted(self.ete_count.items(), key=lambda kv: repr(kv[0]))
                if k[0] == app_id
            ],
            "statusCode": [
                {"key": k[1], "value": v}
                for k, v in sorted(self.status_code_count.items())
                if k[0] == app_id
            ],
        }


def _hour_floor(t: datetime) -> datetime:
    return t.replace(minute=0, second=0, microsecond=0)


class StatsKeeper:
    """Thread-safe hourly rotation: current hour + previous hour.
    Parity: StatsActor's Bookkeeping/GetStats handling."""

    def __init__(self):
        now = datetime.now(timezone.utc)
        self._lock = threading.Lock()
        self._current = Stats(_hour_floor(now))
        self._previous = Stats(_hour_floor(now))

    def _rotate(self, now: datetime) -> None:
        hour = _hour_floor(now)
        if hour > self._current.start_time:
            self._current.cutoff(hour)
            self._previous = self._current
            self._current = Stats(hour)

    def update(self, app_id: int, status_code: int, event: Event) -> None:
        now = datetime.now(timezone.utc)
        with self._lock:
            self._rotate(now)
            self._current.update(app_id, status_code, event)

    def get(self, app_id: int) -> dict:
        """Both buckets, keyed like the reference's Map[String, StatsSnapshot]."""
        with self._lock:
            self._rotate(datetime.now(timezone.utc))
            return {
                "time": format_datetime(datetime.now(timezone.utc)),
                "currentHour": self._current.get(app_id),
                "prevHour": self._previous.get(app_id),
            }
