"""Event-server bookkeeping: per-app counts of status codes and
(entityType, targetEntityType, event) triples, kept in hourly buckets.

Parity: data/src/main/scala/.../data/api/{Stats.scala:30-82,
StatsActor.scala} — the reference rotates a ``Stats`` per hour inside
``StatsActor``; here ``StatsKeeper`` owns the rotation under a lock
instead of an actor mailbox.

Beyond reference: :func:`resilience_snapshot` surfaces the per-backend
retry/circuit-breaker counters (utils/resilience registry) so both
servers' stats/status documents show backend health alongside traffic,
and :class:`ServingStats` carries the engine server's hot-path counters
(batch-size histogram, adaptive-wait EWMA input, result-cache hit/miss/
eviction, per-batch dedup) for ``GET /stats.json``.
"""

from __future__ import annotations

import dataclasses
import threading
from collections import Counter
from datetime import datetime, timezone

from predictionio_tpu.core.event import Event
from predictionio_tpu.core.json_codec import format_datetime
from predictionio_tpu.core.wire import snake_to_camel


def resilience_snapshot() -> dict:
    """Per-backend resilience counters: attempts, retries, failures,
    short-circuits, breaker state/opens — keyed by policy name
    (``<backend>/<source>``). Empty until a resilient backend is used."""
    from predictionio_tpu.utils.resilience import registry_snapshot

    return registry_snapshot()


class ServingStats:
    """Counters for the engine server's query hot path, written by the
    batcher dispatcher (batch records), the result cache (hit/miss/
    eviction), and handler threads (expiries) — one lock guards every
    field at writers AND readers, the same discipline as
    :class:`StatsKeeper`/``ResilienceMetrics``, so no reader ever sees a
    torn histogram and the lock-discipline lint needs no suppressions."""

    COUNTER_FIELDS = (
        "dispatches", "batched_queries", "deduped", "expired",
        "cache_hits", "cache_misses", "cache_evictions",
        "cache_expirations", "cache_invalidations",
    )

    def __init__(self):
        self._lock = threading.Lock()
        self._counts = dict.fromkeys(self.COUNTER_FIELDS, 0)
        #: dispatched (post-dedup) batch size -> count
        self._batch_hist: Counter[int] = Counter()

    def bump(self, field: str, n: int = 1) -> None:
        with self._lock:
            self._counts[field] += n

    def record_batch(self, dispatched: int, coalesced: int) -> None:
        """One device dispatch: ``dispatched`` unique queries actually
        scored, ``coalesced`` queries answered by it (>= dispatched when
        the dedup pass folded identical concurrent queries)."""
        with self._lock:
            self._counts["dispatches"] += 1
            self._counts["batched_queries"] += coalesced
            self._counts["deduped"] += coalesced - dispatched
            self._batch_hist[dispatched] += 1

    def count(self, field: str) -> int:
        with self._lock:
            return self._counts[field]

    def snapshot(self) -> dict:
        with self._lock:
            counts = dict(self._counts)
            hist = {str(k): v for k, v in sorted(self._batch_hist.items())}
        hits, misses = counts["cache_hits"], counts["cache_misses"]
        looked = hits + misses
        return {
            **{snake_to_camel(k): v for k, v in counts.items()},
            "batchSizeHistogram": hist,
            "cacheHitRatio": round(hits / looked, 4) if looked else None,
        }


@dataclasses.dataclass(frozen=True)
class EntityTypesEvent:
    """Parity: EntityTypesEvent (Stats.scala:30-39)."""
    entity_type: str
    target_entity_type: str | None
    event: str

    @staticmethod
    def of(e: Event) -> "EntityTypesEvent":
        return EntityTypesEvent(e.entity_type, e.target_entity_type, e.event)


class Stats:
    """One bucket of counts. Parity: Stats (Stats.scala:51-82)."""

    def __init__(self, start_time: datetime):
        self.start_time = start_time
        self.end_time: datetime | None = None
        self.status_code_count: Counter[tuple[int, int]] = Counter()
        self.ete_count: Counter[tuple[int, EntityTypesEvent]] = Counter()

    def cutoff(self, end_time: datetime) -> None:
        self.end_time = end_time

    def update(self, app_id: int, status_code: int, event: Event) -> None:
        self.status_code_count[(app_id, status_code)] += 1
        self.ete_count[(app_id, EntityTypesEvent.of(event))] += 1

    def get(self, app_id: int) -> dict:
        """JSON snapshot for one app (Stats.get -> StatsSnapshot)."""
        return {
            "startTime": format_datetime(self.start_time),
            "endTime": format_datetime(self.end_time) if self.end_time else None,
            "basic": [
                {
                    "key": {
                        "entityType": k[1].entity_type,
                        "targetEntityType": k[1].target_entity_type,
                        "event": k[1].event,
                    },
                    "value": v,
                }
                for k, v in sorted(self.ete_count.items(), key=lambda kv: repr(kv[0]))
                if k[0] == app_id
            ],
            "statusCode": [
                {"key": k[1], "value": v}
                for k, v in sorted(self.status_code_count.items())
                if k[0] == app_id
            ],
        }


def _hour_floor(t: datetime) -> datetime:
    return t.replace(minute=0, second=0, microsecond=0)


class StatsKeeper:
    """Thread-safe hourly rotation: current hour + previous hour.
    Parity: StatsActor's Bookkeeping/GetStats handling."""

    def __init__(self):
        now = datetime.now(timezone.utc)
        self._lock = threading.Lock()
        self._current = Stats(_hour_floor(now))
        self._previous = Stats(_hour_floor(now))

    def _rotate(self, now: datetime) -> None:
        hour = _hour_floor(now)
        if hour > self._current.start_time:
            self._current.cutoff(hour)
            self._previous = self._current
            self._current = Stats(hour)

    def update(self, app_id: int, status_code: int, event: Event) -> None:
        now = datetime.now(timezone.utc)
        with self._lock:
            self._rotate(now)
            self._current.update(app_id, status_code, event)

    def get(self, app_id: int) -> dict:
        """Both buckets, keyed like the reference's Map[String, StatsSnapshot]."""
        with self._lock:
            self._rotate(datetime.now(timezone.utc))
            return {
                "time": format_datetime(datetime.now(timezone.utc)),
                "currentHour": self._current.get(app_id),
                "prevHour": self._previous.get(app_id),
            }
