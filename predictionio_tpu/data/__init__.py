"""Engine-facing data access: EventStore facade and columnar batching."""
