"""Segmented write-ahead event journal: the ingest plane's outage
ride-through (docs/operations-resilience.md "The ingest durability
ladder").

The Event Server is the front door of the Lambda architecture; before
this module a storage outage mapped straight to ``503 + Retry-After``,
making durability during the outage entirely the client's problem. The
WAL moves that burden server-side: when the backend is down (or its
breaker is open) accepted events are journaled to local disk and
acknowledged ``202``, and a background drainer replays them into
storage through the idempotent pre-assigned-id ``insert_batch`` path
(every backend honors caller-set event ids with upsert semantics —
PR 4 — so replay after a partial failure is exactly-once-effective).

Layout (one directory per event server):

- ``wal-<seq>.seg``  — journal segments: framed records, each
  ``<u32 payload length><u32 crc32><payload>`` (little-endian header).
  The active segment is the highest sequence number; rotation closes
  it (always fsynced — a segment boundary is a durability point) and
  creates the next sequence with ``O_EXCL``.
- ``dead-<seq>.seg`` — the dead-letter series: records the drainer
  gave up on after ``max_replay_attempts`` application-level failures,
  wrapped in a JSON envelope carrying the reason. Same framing, so
  ``pio wal dead-letter`` replays/requeues with the same reader.
- ``wal.cursor``     — the replay cursor ``{segment, offset}`` plus
  lifetime counters, written via tmp+fsync+``os.replace`` (atomic, the
  utils/checkpoint discipline). The cursor commits AFTER storage
  acknowledged a replayed run; a crash between insert and commit only
  re-inserts — idempotent by the pre-assigned ids.

Recovery (``WriteAheadLog.__init__``) truncates a torn tail of the
last segment (a ``kill -9`` mid-append leaves a partial frame; the
un-acknowledged record it held was never 202'd under ``fsync=always``)
and counts-and-skips CRC-corrupt records instead of crashing: one
flipped bit must cost one record, never the journal.

fsync policy (``always | interval | off``): ``always`` fsyncs every
append (every 202 is crash-durable — the honest mode for the
durability pin), ``interval`` fsyncs at most every
``fsync_interval_s`` on the appending thread (bounded loss window on
power failure, near-direct-insert throughput — the default),
``off`` leaves it to the OS (bench/bulk loads). Measured per policy in
``bench_ingest.py`` (BENCH_wal_r01.json).

The journal is bounded honestly: past ``max_bytes`` of pending frames
``append`` raises :class:`WalFullError` and the server reverts to
``503`` backpressure, with a Retry-After hint derived from observed
drain progress (:meth:`WalDrainer.backpressure_hint`).
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import struct
import threading
import zlib
from typing import Any, Callable, Iterator, Sequence

from predictionio_tpu.core.event import Event
from predictionio_tpu.core.json_codec import event_from_json, event_to_json
from predictionio_tpu.utils.resilience import (
    STORAGE_UNAVAILABLE_ERRORS,
    SYSTEM_CLOCK,
    Clock,
    RetryPolicy,
    StorageUnavailableError,
)

logger = logging.getLogger(__name__)

#: frame header: <u32 payload length><u32 crc32(payload)>
_HEADER = struct.Struct("<II")
#: sanity bound — a corrupt length field must not allocate gigabytes
MAX_RECORD_BYTES = 16 << 20

FSYNC_POLICIES = ("always", "interval", "off")

_SEGMENT_PREFIX = "wal-"
_DEAD_PREFIX = "dead-"
_SEGMENT_SUFFIX = ".seg"
_CURSOR_FILE = "wal.cursor"


class WalError(Exception):
    """A journal-level failure (I/O, malformed directory)."""


class WalFullError(WalError):
    """The journal is at its disk budget: the caller must shed
    (``503`` backpressure) instead of journaling."""

    def __init__(self, pending_bytes: int, max_bytes: int):
        super().__init__(
            f"write-ahead journal at disk budget "
            f"({pending_bytes} of {max_bytes} bytes pending)")
        self.pending_bytes = pending_bytes
        self.max_bytes = max_bytes


#: (segment sequence, byte offset of the next frame) — totally ordered
Position = tuple[int, int]


@dataclasses.dataclass(frozen=True)
class WalEntry:
    """One pending record as the drainer sees it."""

    position: Position        # frame start
    next_position: Position   # first byte after the frame
    payload: bytes


def encode_record(event: Event, app_id: int,
                  channel_id: int | None) -> bytes:
    """One journal payload: the event's API JSON (id pre-assigned by
    the caller — replay idempotency depends on it) plus its routing.
    Unlike the ms-truncated wire format, timestamps keep FULL µs
    precision — a replayed event must sort exactly where its direct
    insert would have (find() orders by (eventTime, id))."""
    if not event.event_id:
        raise ValueError("journaled events must carry a pre-assigned "
                         "event id (replay idempotency)")
    doc = event_to_json(event)
    doc["eventTime"] = event.event_time.isoformat()
    doc["creationTime"] = event.creation_time.isoformat()
    return json.dumps({"e": doc, "a": app_id, "c": channel_id},
                      separators=(",", ":")).encode()


def decode_record(payload: bytes) -> tuple[Event, int, int | None]:
    """Inverse of :func:`encode_record`. Raises on malformed payloads
    (the drainer quarantines those as undecodable)."""
    doc = json.loads(payload)
    # validate=False: the event passed ingest validation before it was
    # journaled; replay must not re-litigate (a validation-rule change
    # between journal and drain must not strand accepted events)
    event = event_from_json(doc["e"], validate=False)
    return event, int(doc["a"]), doc["c"]


def _segment_path(wal_dir: str, seq: int, dead: bool = False) -> str:
    prefix = _DEAD_PREFIX if dead else _SEGMENT_PREFIX
    return os.path.join(wal_dir, f"{prefix}{seq:08d}{_SEGMENT_SUFFIX}")


def _list_segments(wal_dir: str, dead: bool = False) -> list[int]:
    prefix = _DEAD_PREFIX if dead else _SEGMENT_PREFIX
    out = []
    for name in os.listdir(wal_dir):
        if name.startswith(prefix) and name.endswith(_SEGMENT_SUFFIX):
            try:
                out.append(int(name[len(prefix):-len(_SEGMENT_SUFFIX)]))
            except ValueError:
                continue
    return sorted(out)


def _scan_frames(path: str,
                 start: int = 0) -> Iterator[tuple[int, int, bytes | None]]:
    """Yield ``(offset, frame_length, payload-or-None)`` for each frame
    in one segment file from byte ``start`` (which must sit on a frame
    boundary — the cursor only ever commits to boundaries); ``None``
    payload marks a CRC-corrupt record. A torn tail (incomplete
    header/payload or an insane length) stops iteration — the caller
    decides between truncating (recovery) and waiting (a live reader
    racing the appender's buffered write). Reading from ``start``
    instead of 0 keeps a long outage's retry loop from re-reading and
    re-CRCing the consumed prefix of the cursor segment every pass."""
    with open(path, "rb") as f:
        if start:
            f.seek(start)
        data = f.read()
    offset = start
    n = start + len(data)
    while offset + _HEADER.size <= n:
        length, crc = _HEADER.unpack_from(data, offset - start)
        if length > MAX_RECORD_BYTES:
            # an insane length is indistinguishable from a torn/mangled
            # header — resync is impossible without a record boundary
            return
        end = offset + _HEADER.size + length
        if end > n:
            return  # torn tail
        payload = data[offset + _HEADER.size - start:end - start]
        if zlib.crc32(payload) != crc:
            yield offset, end - offset, None
        else:
            yield offset, end - offset, payload
        offset = end


class WriteAheadLog:
    """The segmented journal. Thread-safe: one lock guards the active
    segment handle, the cursor, and every counter (writers and readers
    — the lock-discipline contract)."""

    def __init__(
        self,
        wal_dir: str,
        fsync: str = "interval",
        fsync_interval_s: float = 0.05,
        segment_max_bytes: int = 8 << 20,
        max_bytes: int = 256 << 20,
        clock: Clock = SYSTEM_CLOCK,
    ):
        if fsync not in FSYNC_POLICIES:
            raise ValueError(
                f"unknown fsync policy {fsync!r} "
                f"(choose from {FSYNC_POLICIES})")
        self.wal_dir = wal_dir
        self.fsync = fsync
        self.fsync_interval_s = fsync_interval_s
        self.segment_max_bytes = segment_max_bytes
        self.max_bytes = max_bytes
        self._clock = clock
        self._lock = threading.Lock()
        self._closed = False
        os.makedirs(wal_dir, exist_ok=True)

        # -- cursor ----------------------------------------------------
        self._cursor: Position = (1, 0)
        self._replayed_total = 0
        self._dead_letter_total = 0
        cursor_path = os.path.join(wal_dir, _CURSOR_FILE)
        if os.path.exists(cursor_path):
            try:
                with open(cursor_path) as f:
                    doc = json.load(f)
                self._cursor = (int(doc["segment"]), int(doc["offset"]))
                self._replayed_total = int(doc.get("replayedTotal", 0))
                self._dead_letter_total = int(doc.get("deadLetterTotal", 0))
            except (OSError, ValueError, KeyError) as exc:
                # an unreadable cursor restarts replay from the oldest
                # retained segment: idempotent re-inserts, never loss
                logger.warning("unreadable WAL cursor %s (%s); replaying "
                               "from the oldest segment", cursor_path, exc)

        # -- recovery --------------------------------------------------
        self.corrupt_records = 0
        self.torn_bytes_truncated = 0
        segments = _list_segments(wal_dir)
        if segments:
            self._recover_tail(segments[-1])
            # a cursor pointing before the oldest retained segment
            # (segments already reaped) snaps forward
            if self._cursor[0] < segments[0]:
                self._cursor = (segments[0], 0)
        else:
            segments = [self._cursor[0]]
        self._active_seq = segments[-1]
        self._active = open(_segment_path(wal_dir, self._active_seq), "ab")
        self._last_fsync = clock.monotonic()

        # -- pending accounting ---------------------------------------
        self._pending_records = 0
        self._pending_bytes = 0
        self._full = False
        self.journaled_total = 0
        for seq in segments:
            path = _segment_path(wal_dir, seq)
            if seq < self._cursor[0]:
                continue
            start = self._cursor[1] if seq == self._cursor[0] else 0
            size = os.path.getsize(path)
            self._pending_bytes += max(0, size - start)
            for _, _, payload in _scan_frames(path, start=start):
                if payload is None:
                    self.corrupt_records += 1
                else:
                    self._pending_records += 1

    def _recover_tail(self, seq: int) -> None:
        """Truncate a torn tail of the last segment: the bytes after
        the last whole frame are a crash artifact (kill -9 mid-append)
        and were never acknowledged under ``fsync=always``."""
        path = _segment_path(self.wal_dir, seq)
        size = os.path.getsize(path)
        end = 0
        for off, frame_len, _ in _scan_frames(path):
            end = off + frame_len
        if end < size:
            self.torn_bytes_truncated = size - end
            logger.warning(
                "WAL recovery: truncating %d torn tail byte(s) of %s "
                "(crash mid-append; the partial record was never "
                "acknowledged)", size - end, path)
            with open(path, "r+b") as f:
                f.truncate(end)
                f.flush()
                os.fsync(f.fileno())

    # -- appends ------------------------------------------------------
    def append(self, payload: bytes) -> Position:
        """Journal one record; returns its position. Raises
        :class:`WalFullError` past the disk budget."""
        frame = _HEADER.pack(len(payload), zlib.crc32(payload)) + payload
        with self._lock:
            if self._closed:
                raise WalError("journal is closed")
            if self._pending_bytes + len(frame) > self.max_bytes:
                # latched until commit drains below the resume mark:
                # the mode gauge and /readyz read backpressure from
                # this, not from guessing a typical frame size
                self._full = True
                raise WalFullError(self._pending_bytes, self.max_bytes)
            offset = self._active.tell()
            position = (self._active_seq, offset)
            # ONE buffered write + flush per frame: a concurrent reader
            # sees whole frames except for a short racing window, which
            # read_pending treats as "stop and retry", never truncates
            self._active.write(frame)
            self._active.flush()
            if self.fsync == "always":
                os.fsync(self._active.fileno())
            elif self.fsync == "interval":
                now = self._clock.monotonic()
                if now - self._last_fsync >= self.fsync_interval_s:
                    os.fsync(self._active.fileno())
                    self._last_fsync = now
            self._pending_records += 1
            self._pending_bytes += len(frame)
            self.journaled_total += 1
            if offset + len(frame) >= self.segment_max_bytes:
                self._rotate_locked()
            return position

    def _rotate_locked(self) -> None:
        """Close the active segment (fsynced — a durability point) and
        open the next sequence with O_EXCL (atomic create)."""
        self._active.flush()
        os.fsync(self._active.fileno())
        self._active.close()
        self._active_seq += 1
        path = _segment_path(self.wal_dir, self._active_seq)
        fd = os.open(path, os.O_WRONLY | os.O_CREAT | os.O_EXCL | os.O_APPEND,
                     0o644)
        self._active = os.fdopen(fd, "ab")
        self._fsync_dir()
        self._last_fsync = self._clock.monotonic()

    def _fsync_dir(self) -> None:
        """Directory entry durability for newly created files (skipped
        under fsync=off: the operator opted out of crash durability)."""
        if self.fsync == "off":
            return
        try:
            dfd = os.open(self.wal_dir, os.O_RDONLY)
            try:
                os.fsync(dfd)
            finally:
                os.close(dfd)
        except OSError:  # pragma: no cover — platform-specific
            pass

    # -- reads --------------------------------------------------------
    def read_pending(self, max_records: int = 256) -> list[WalEntry]:
        """Up to ``max_records`` pending records from the cursor, in
        journal order. CRC-corrupt frames are skipped (counted once at
        recovery — in-process appends can't corrupt); a torn tail of
        the ACTIVE segment stops the read (it may be an append racing
        this reader — recovery, not the live reader, truncates)."""
        with self._lock:
            cursor = self._cursor
            active_seq = self._active_seq
            # the reader below re-opens the files; flush so every
            # fully-appended frame is visible to it
            self._active.flush()
        entries: list[WalEntry] = []
        for seq in range(cursor[0], active_seq + 1):
            path = _segment_path(self.wal_dir, seq)
            if not os.path.exists(path):
                continue
            start = cursor[1] if seq == cursor[0] else 0
            size = os.path.getsize(path)
            for off, frame_len, payload in _scan_frames(path, start=start):
                if payload is None:
                    continue
                # a record closing a ROTATED segment advances the
                # cursor into the next one, so commit() can reap the
                # finished file
                end = off + frame_len
                end_pos = ((seq, end) if seq == active_seq or end < size
                           else (seq + 1, 0))
                entries.append(WalEntry((seq, off), end_pos, payload))
                if len(entries) >= max_records:
                    return entries
        return entries

    # -- commit -------------------------------------------------------
    def commit(self, next_position: Position, records: int,
               replayed: int | None = None) -> None:
        """Advance the cursor past ``records`` consumed records (the
        drainer calls this AFTER storage acknowledged them — or after a
        quarantine), reap fully-consumed segments, persist the cursor
        atomically."""
        with self._lock:
            if next_position <= self._cursor:
                return
            consumed = self._bytes_between_locked(self._cursor,
                                                  next_position)
            self._cursor = next_position
            self._pending_bytes = max(0, self._pending_bytes - consumed)
            self._pending_records = max(0, self._pending_records - records)
            if self._full and self._pending_bytes <= self.max_bytes * 0.9:
                # hysteresis: un-latch only once real room exists, so
                # the 503/202 boundary doesn't flap per-append
                self._full = False
            self._replayed_total += (replayed if replayed is not None
                                     else records)
            for seq in _list_segments(self.wal_dir):
                if seq < self._cursor[0] and seq != self._active_seq:
                    try:
                        os.unlink(_segment_path(self.wal_dir, seq))
                    except OSError:  # pragma: no cover
                        pass
            self._write_cursor_locked()

    def _bytes_between_locked(self, a: Position, b: Position) -> int:
        if a >= b:
            return 0
        if a[0] == b[0]:
            return b[1] - a[1]
        total = 0
        for seq in range(a[0], b[0]):
            path = _segment_path(self.wal_dir, seq)
            if os.path.exists(path):
                total += os.path.getsize(path)
        return total - a[1] + b[1]

    def _write_cursor_locked(self) -> None:
        doc = {"segment": self._cursor[0], "offset": self._cursor[1],
               "replayedTotal": self._replayed_total,
               "deadLetterTotal": self._dead_letter_total}
        path = os.path.join(self.wal_dir, _CURSOR_FILE)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(doc, f)
            f.flush()
            if self.fsync != "off":
                os.fsync(f.fileno())
        os.replace(tmp, path)

    # -- dead letters -------------------------------------------------
    def quarantine(self, entry: WalEntry, reason: str,
                   attempts: int) -> None:
        """Append one poison record to the dead-letter series. The
        caller commits past it afterwards (consumed, not replayed)."""
        try:
            record: Any = json.loads(entry.payload)
        except ValueError:
            record = {"undecodable": entry.payload.hex()}
        envelope = json.dumps(
            {"reason": reason[:500], "attempts": attempts,
             "record": record}, separators=(",", ":")).encode()
        frame = _HEADER.pack(len(envelope), zlib.crc32(envelope)) + envelope
        with self._lock:
            dead = _list_segments(self.wal_dir, dead=True)
            seq = dead[-1] if dead else 1
            path = _segment_path(self.wal_dir, seq, dead=True)
            if (os.path.exists(path)
                    and os.path.getsize(path) >= self.segment_max_bytes):
                seq += 1
                path = _segment_path(self.wal_dir, seq, dead=True)
            with open(path, "ab") as f:
                f.write(frame)
                f.flush()
                if self.fsync != "off":
                    os.fsync(f.fileno())
            self._dead_letter_total += 1
            self._write_cursor_locked()

    def dead_letters(self) -> Iterator[dict[str, Any]]:
        """Yield dead-letter envelopes oldest first (corrupt frames in
        the dead series are skipped — they are already quarantine)."""
        for seq in _list_segments(self.wal_dir, dead=True):
            for _, _, payload in _scan_frames(
                    _segment_path(self.wal_dir, seq, dead=True)):
                if payload is None:
                    continue
                try:
                    yield json.loads(payload)
                except ValueError:
                    continue

    def requeue_dead_letters(self) -> tuple[int, int]:
        """Move every decodable dead-letter record back into the live
        journal (after the operator fixed the cause — the runbook
        path) and reap the consumed dead segments. Envelopes that
        CANNOT be requeued (quarantined-as-undecodable records,
        malformed envelopes) are preserved in a fresh dead segment —
        the quarantine series must never silently destroy evidence.
        Returns ``(requeued, kept)``."""
        requeued = 0
        kept: list[bytes] = []
        for env in self.dead_letters():
            record = env.get("record")
            if not isinstance(record, dict) or "e" not in record:
                kept.append(json.dumps(env, separators=(",", ":")).encode())
                continue
            self.append(json.dumps(record, separators=(",", ":")).encode())
            requeued += 1
        for seq in _list_segments(self.wal_dir, dead=True):
            try:
                os.unlink(_segment_path(self.wal_dir, seq, dead=True))
            except OSError:  # pragma: no cover
                pass
        if kept:
            path = _segment_path(self.wal_dir, 1, dead=True)
            with self._lock, open(path, "ab") as f:
                for envelope in kept:
                    f.write(_HEADER.pack(len(envelope),
                                         zlib.crc32(envelope)) + envelope)
                f.flush()
                if self.fsync != "off":
                    os.fsync(f.fileno())
        return requeued, len(kept)

    # -- introspection ------------------------------------------------
    def pending_records(self) -> int:
        with self._lock:
            return self._pending_records

    def pending_bytes(self) -> int:
        with self._lock:
            return self._pending_bytes

    def is_full(self) -> bool:
        """Backpressure latched: an append hit the disk budget and the
        backlog has not yet drained below the resume mark (90%) — the
        mode-2 definition shared by the gauge and ``/readyz``."""
        with self._lock:
            return self._full or self._pending_bytes >= self.max_bytes

    def counters(self) -> dict[str, int]:
        with self._lock:
            return {
                "depth": self._pending_records,
                "bytes": self._pending_bytes,
                "journaledTotal": self.journaled_total,
                "replayedTotal": self._replayed_total,
                "deadLetterTotal": self._dead_letter_total,
                "corruptRecords": self.corrupt_records,
                "tornBytesTruncated": self.torn_bytes_truncated,
            }

    def stats(self) -> dict[str, Any]:
        out = self.counters()
        out.update({
            "dir": self.wal_dir,
            "fsync": self.fsync,
            "maxBytes": self.max_bytes,
            "segments": len(_list_segments(self.wal_dir)),
            "deadLetterSegments": len(
                _list_segments(self.wal_dir, dead=True)),
        })
        return out

    def close(self) -> None:
        with self._lock:
            if self._closed:
                return
            self._closed = True
            self._active.flush()
            if self.fsync != "off":
                os.fsync(self._active.fileno())
            self._active.close()


def scan_status(wal_dir: str) -> dict[str, Any]:
    """A NON-mutating status scan for ``pio wal status``: unlike
    constructing :class:`WriteAheadLog` it neither truncates a torn
    tail nor creates files — safe to run against a LIVE server's
    directory."""
    if not os.path.isdir(wal_dir):
        raise WalError(f"no journal directory at {wal_dir}")
    cursor: Position = (1, 0)
    replayed = dead_total = 0
    cursor_path = os.path.join(wal_dir, _CURSOR_FILE)
    if os.path.exists(cursor_path):
        try:
            with open(cursor_path) as f:
                doc = json.load(f)
            cursor = (int(doc["segment"]), int(doc["offset"]))
            replayed = int(doc.get("replayedTotal", 0))
            dead_total = int(doc.get("deadLetterTotal", 0))
        except (OSError, ValueError, KeyError):
            pass
    depth = corrupt = 0
    pending_bytes = 0
    torn = False
    segments = _list_segments(wal_dir)
    for seq in segments:
        path = _segment_path(wal_dir, seq)
        size = os.path.getsize(path)
        if seq < cursor[0]:
            continue
        start = cursor[1] if seq == cursor[0] else 0
        pending_bytes += max(0, size - start)
        end = 0
        for off, frame_len, payload in _scan_frames(path):
            end = off + frame_len
            if off < start:
                continue
            if payload is None:
                corrupt += 1
            else:
                depth += 1
        if seq == segments[-1] and end < size:
            torn = True
    dead_pending = 0
    for seq in _list_segments(wal_dir, dead=True):
        dead_pending += sum(
            1 for _, _, p in _scan_frames(
                _segment_path(wal_dir, seq, dead=True)) if p is not None)
    return {
        "dir": wal_dir,
        "segments": len(segments),
        "depth": depth,
        "bytes": pending_bytes,
        "cursor": {"segment": cursor[0], "offset": cursor[1]},
        "replayedTotal": replayed,
        "corruptRecords": corrupt,
        "deadLetterTotal": dead_total,
        "deadLetterPending": dead_pending,
        "tornTail": torn,
    }


# ---------------------------------------------------------------------------
# the drainer
# ---------------------------------------------------------------------------

#: drain_once verdicts
EMPTY, PROGRESS, UNAVAILABLE, BLOCKED = (
    "empty", "progress", "unavailable", "blocked")


class WalDrainer:
    """Background replay of journaled events into storage.

    Strictly in journal order; consecutive records sharing an
    ``(app_id, channel_id)`` key ride ONE ``insert_batch`` call (the
    PR 4 single-transaction path). A transient storage failure backs
    off with full jitter (``RetryPolicy.backoff`` on the injected
    clock — the outage is ridden out, never given up on); an
    application-level failure isolates per record and quarantines the
    poison record to the dead-letter series after
    ``max_replay_attempts``.

    The loop waits on Events, never a bare ``time.sleep`` (the
    untimed-blocking-io lint bans it here): ``notify()`` from the
    append path wakes an idle drainer immediately.
    """

    def __init__(
        self,
        wal: WriteAheadLog,
        insert_batch: Callable[[Sequence[Event], int, int | None],
                               Sequence[str]],
        policy: RetryPolicy | None = None,
        clock: Clock = SYSTEM_CLOCK,
        rng=None,
        max_replay_attempts: int = 5,
        batch_max: int = 256,
        idle_wait_s: float = 0.25,
        trace_factory: Callable[[], Any] | None = None,
        trace_sink: Callable[[Any], None] | None = None,
    ):
        import random

        self.wal = wal
        self._insert_batch = insert_batch
        self.policy = policy or RetryPolicy(
            max_attempts=2**31, base_delay=0.05, max_delay=5.0)
        self._clock = clock
        self._rng = rng or random.Random()
        self.max_replay_attempts = max(1, max_replay_attempts)
        self.batch_max = batch_max
        self.idle_wait_s = idle_wait_s
        self._trace_factory = trace_factory
        self._trace_sink = trace_sink
        self._lock = threading.Lock()
        #: per-position application-failure counts (in-memory: a
        #: restart resets the attempt clock, documented in the runbook)
        self._attempts: dict[Position, int] = {}
        self._rate_ewma: float | None = None
        self._last_drain_t: float | None = None
        self._stop = threading.Event()
        self._work = threading.Event()
        self._thread: threading.Thread | None = None

    # -- lifecycle ----------------------------------------------------
    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._run, name="pio-wal-drainer", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        self._work.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    def notify(self) -> None:
        """Wake the drainer: a record was just journaled."""
        self._work.set()

    def _run(self) -> None:
        retry_index = 0
        while not self._stop.is_set():
            try:
                verdict = self.drain_once()
            except Exception:  # noqa: BLE001 — the loop must survive
                logger.exception("WAL drain pass failed")
                verdict = UNAVAILABLE
            if verdict == PROGRESS:
                retry_index = 0
                continue
            if verdict == EMPTY:
                retry_index = 0
                self._work.wait(self.idle_wait_s)
                self._work.clear()
                continue
            # UNAVAILABLE / BLOCKED: full-jitter backoff, capped index
            # so the delay saturates at policy.max_delay instead of
            # overflowing the multiplier
            delay = self.policy.backoff(min(retry_index, 16), self._rng)
            retry_index += 1
            self._stop.wait(delay)

    # -- one pass ------------------------------------------------------
    def drain_once(self) -> str:
        """One bounded replay pass; see class docstring for verdicts.
        Public: ``pio wal replay`` and the unit tests drive it
        synchronously."""
        entries = self.wal.read_pending(self.batch_max)
        if not entries:
            return EMPTY
        trace = self._trace_factory() if self._trace_factory else None
        try:
            return self._drain_entries(entries, trace)
        finally:
            if trace is not None:
                trace.finish()
                if self._trace_sink is not None:
                    self._trace_sink(trace)

    def _drain_entries(self, entries: list[WalEntry], trace) -> str:
        def tspan(name: str):
            import contextlib

            return (trace.span(name) if trace is not None
                    else contextlib.nullcontext())

        # decode up front but quarantine ONLY in journal order below:
        # committing past an undecodable record before the records
        # AHEAD of it replayed would advance the cursor over them
        decoded: list[tuple[WalEntry, Event | None, Any, Any]] = []
        with tspan("decode"):
            for entry in entries:
                try:
                    event, app_id, channel_id = decode_record(entry.payload)
                    decoded.append((entry, event, app_id, channel_id))
                except Exception as exc:  # noqa: BLE001 — poison record
                    decoded.append((entry, None, None, repr(exc)))
        progressed = False
        i = 0
        while i < len(decoded):
            if decoded[i][1] is None:  # undecodable, now at the head
                entry, _, _, reason = decoded[i]
                self.wal.quarantine(entry, f"undecodable: {reason}",
                                    attempts=1)
                self.wal.commit(entry.next_position, records=1, replayed=0)
                progressed = True
                i += 1
                continue
            # one consecutive (app, channel) run -> one insert_batch
            j = i
            key = decoded[i][2], decoded[i][3]
            while (j < len(decoded) and decoded[j][1] is not None
                   and (decoded[j][2], decoded[j][3]) == key):
                j += 1
            run = decoded[i:j]
            events = [e for _, e, _, _ in run]
            try:
                with tspan("insert_batch"):
                    self._insert_batch(events, key[0], key[1])
            except STORAGE_UNAVAILABLE_ERRORS:
                return PROGRESS if progressed else UNAVAILABLE
            except Exception:
                verdict = self._drain_run_per_record(run, tspan)
                if verdict is not None:
                    return PROGRESS if progressed else verdict
                progressed = True
                i = j
                continue
            with tspan("commit"):
                self.wal.commit(run[-1][0].next_position, records=len(run))
            for entry, _, _, _ in run:
                self._attempts.pop(entry.position, None)
            self._record_rate(len(run))
            progressed = True
            i = j
        return PROGRESS

    def _drain_run_per_record(self, run, tspan) -> str | None:
        """Per-record isolation after a failed batch: replay each
        record alone so ONE poison record cannot hold the run hostage.
        Returns None when the whole run was consumed (replayed or
        quarantined), else the verdict to surface."""
        for entry, event, app_id, channel_id in run:
            try:
                with tspan("insert"):
                    self._insert_batch([event], app_id, channel_id)
            except STORAGE_UNAVAILABLE_ERRORS:
                return UNAVAILABLE
            except Exception as exc:  # noqa: BLE001 — application error
                attempts = self._attempts.get(entry.position, 0) + 1
                if attempts >= self.max_replay_attempts:
                    logger.warning(
                        "WAL record %s quarantined to dead-letter after "
                        "%d attempts: %s", entry.position, attempts, exc)
                    self.wal.quarantine(entry, str(exc), attempts)
                    self.wal.commit(entry.next_position, records=1,
                                    replayed=0)
                    self._attempts.pop(entry.position, None)
                    continue
                self._attempts[entry.position] = attempts
                return BLOCKED
            self.wal.commit(entry.next_position, records=1)
            self._attempts.pop(entry.position, None)
            self._record_rate(1)
        return None

    # -- drain-rate observability -------------------------------------
    _RATE_ALPHA = 0.3

    def _record_rate(self, n: int) -> None:
        now = self._clock.monotonic()
        with self._lock:
            if self._last_drain_t is not None:
                dt = now - self._last_drain_t
                if dt > 1e-6:
                    inst = n / dt
                    self._rate_ewma = (
                        inst if self._rate_ewma is None
                        else self._RATE_ALPHA * inst
                        + (1 - self._RATE_ALPHA) * self._rate_ewma)
            self._last_drain_t = now

    def drain_rate(self) -> float | None:
        """Recent replay throughput (events/sec EWMA), None before the
        first two drained batches."""
        with self._lock:
            return self._rate_ewma

    #: backpressure hint targets draining this fraction of the backlog
    #: — enough freed budget for a client retry to land, not the whole
    #: outage's worth of waiting
    HINT_DRAIN_FRACTION = 0.25

    def backpressure_hint(self) -> float | None:
        """Retry-After seconds for a journal-at-budget 503, derived
        from observed drain progress: the hint SHRINKS as the backlog
        drains (time to free ~25% of the depth at the current rate),
        clamped to [0.5, 30]. None while no drain progress has been
        observed (backend still down — the caller falls back to the
        storage hint)."""
        with self._lock:
            rate = self._rate_ewma
        if rate is None or rate <= 0:
            return None
        depth = self.wal.pending_records()
        if depth <= 0:
            return None
        return min(30.0, max(0.5, depth * self.HINT_DRAIN_FRACTION / rate))

    def mode(self) -> int:
        """The ``pio_ingest_wal_mode`` gauge: 0 idle (journal empty,
        inserts going straight to storage), 1 draining (ride-through
        active: a backlog is replaying), 2 backpressure (journal at its
        disk budget; ingest is shedding 503s)."""
        if self.wal.is_full():
            return 2
        return 1 if self.wal.pending_records() > 0 else 0

    def snapshot(self) -> dict[str, Any]:
        """The ``wal`` section of ``GET /stats.json``."""
        out = self.wal.stats()
        rate = self.drain_rate()
        out.update({
            "mode": {0: "idle", 1: "draining",
                     2: "backpressure"}[self.mode()],
            "drainEventsPerSec": round(rate, 2) if rate else None,
        })
        return out


def make_storage_unavailable(exc: WalFullError,
                             hint: float | None) -> StorageUnavailableError:
    """Map a journal-at-budget condition onto the one exception class
    the serving plane turns into ``503 + Retry-After``, carrying the
    drain-aware hint when one exists."""
    return StorageUnavailableError(
        "wal", str(exc), retry_after=hint if hint is not None else 1.0)
