"""SelfCleaningDataSource — prune/compact the event store before training.

Parity: core/src/main/scala/.../core/SelfCleaningDataSource.scala:42-330:
a DataSource mixin that, given an ``EventWindow``, (1) drops events older
than the window, (2) compacts runs of ``$set`` events per entity into one
merged ``$set``, (3) removes duplicate events, and optionally (4) writes
the cleaned set back to the store (``clean_persisted_events``, the
cleanPersistedPEvents/wipe path :161-233).
"""

from __future__ import annotations

import dataclasses
import logging
from datetime import datetime, timedelta, timezone
from typing import Iterable, Sequence

from predictionio_tpu.core.event import Event
from predictionio_tpu.storage.registry import Storage

logger = logging.getLogger(__name__)


@dataclasses.dataclass(frozen=True)
class EventWindow:
    """Parity: EventWindow (SelfCleaningDataSource.scala:322-330);
    ``duration`` replaces the reference's "3 days"-style string."""

    duration: timedelta | None = None
    remove_duplicates: bool = False
    compress_properties: bool = False


class SelfCleaningDataSource:
    """Mixin for DataSources. Set ``event_window`` (and use
    ``clean_events``/``clean_persisted_events``) to train on a pruned,
    compacted view of the event log."""

    #: override in subclasses (SelfCleaningDataSource.scala:55-62)
    event_window: EventWindow | None = None

    # -- pure transforms ----------------------------------------------------
    def clean_events(
        self,
        events: Iterable[Event],
        now: datetime | None = None,
    ) -> list[Event]:
        """Window filter + compaction + dedup per the EventWindow
        (getCleanedPEvents :77-105)."""
        events = list(events)
        window = self.event_window
        if window is None:
            return events
        if window.duration is not None:
            cutoff = (now or datetime.now(timezone.utc)) - window.duration
            events = [e for e in events if e.event_time >= cutoff]
        if window.compress_properties:
            events = self._compress_properties(events)
        if window.remove_duplicates:
            events = self._remove_duplicates(events)
        return events

    @staticmethod
    def _compress_properties(events: Sequence[Event]) -> list[Event]:
        """Merge each entity's ``$set`` run into one event carrying the
        folded properties (later fields win), stamped with the latest
        event time (compressPProperties :107-126)."""
        sets: dict[tuple[str, str], list[Event]] = {}
        rest: list[Event] = []
        for e in events:
            if e.event == "$set":
                sets.setdefault((e.entity_type, e.entity_id), []).append(e)
            else:
                rest.append(e)
        compressed = []
        for run in sets.values():
            run.sort(key=lambda e: e.event_time)
            merged = run[0].properties
            for e in run[1:]:
                merged = merged.merge(e.properties)
            compressed.append(dataclasses.replace(run[-1], properties=merged))
        return rest + compressed

    @staticmethod
    def _remove_duplicates(events: Sequence[Event]) -> list[Event]:
        """Drop events identical up to identity fields, keeping the first
        (removePDuplicates :128-141)."""
        import json

        seen = set()
        out = []
        for e in events:
            key = (
                e.event, e.entity_type, e.entity_id,
                e.target_entity_type, e.target_entity_id,
                # canonical JSON: property values may be lists/dicts
                json.dumps(e.properties.fields, sort_keys=True, default=str),
                e.event_time,
            )
            if key in seen:
                continue
            seen.add(key)
            out.append(e)
        return out

    # -- persisted cleanup --------------------------------------------------
    def clean_persisted_events(
        self,
        storage: Storage,
        app_id: int,
        channel_id: int | None = None,
        now: datetime | None = None,
    ) -> int:
        """Replace the stored event set with its cleaned form; returns the
        cleaned count (cleanPersistedPEvents + wipe :161-233)."""
        if self.event_window is None:
            return 0
        from predictionio_tpu.storage.base import EventFilter

        events_dao = storage.get_events()
        original = list(events_dao.find(app_id, channel_id, EventFilter()))
        cleaned = self.clean_events(original, now=now)
        if len(cleaned) == len(original):
            return len(cleaned)
        events_dao.remove(app_id, channel_id)
        events_dao.init(app_id, channel_id)
        if cleaned:
            events_dao.insert_batch(cleaned, app_id, channel_id)
        logger.info(
            "cleaned persisted events for app %s: %d -> %d",
            app_id, len(original), len(cleaned),
        )
        return len(cleaned)
