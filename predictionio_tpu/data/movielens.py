"""MovieLens-format ratings loading + deterministic reconstruction.

Quality-parity support (BASELINE.md north star: "matching MAP@10").
The reference's quickstart downloads MovieLens-100k at test time
(reference: tests/pio_tests/scenarios/quickstart_test.py) — this
environment has no network egress, so quality evaluation runs on

1. the real sample dataset the reference bundles in-tree
   (reference: examples/experimental/data/movielens.txt, the Apache
   Spark `sample_movielens_data.txt` in `user::item::rating` format),
   vendored under ``examples/data/``; and
2. a deterministic reconstruction of MovieLens-100k's published
   marginals (943 users x 1682 items x 100,000 ratings, 1-5 stars,
   >=20 ratings/user) over a known low-rank latent ground truth, so
   ALS quality is measurable at the real dataset's scale and skew.

Both produce string-id rating triples in the shape the recommendation
template's DataSource emits, so they drop straight into the template
components or the raw ops.
"""

from __future__ import annotations

import dataclasses

import numpy as np

ML100K_USERS = 943
ML100K_ITEMS = 1682
ML100K_RATINGS = 100_000


@dataclasses.dataclass(frozen=True)
class RatingsDataset:
    """Dense-index rating triples plus the id vocabularies."""

    users: np.ndarray      # int32 (nnz,)
    items: np.ndarray      # int32 (nnz,)
    ratings: np.ndarray    # float32 (nnz,)
    num_users: int
    num_items: int

    @property
    def nnz(self) -> int:
        return len(self.users)

    def user_ids(self) -> np.ndarray:
        """String entity ids ("u1"...) as the event-store path would see."""
        return np.asarray([f"u{int(u)}" for u in self.users], dtype=object)

    def item_ids(self) -> np.ndarray:
        return np.asarray([f"i{int(i)}" for i in self.items], dtype=object)


def load_ratings_file(path: str) -> RatingsDataset:
    """Parse `user::item::rating` (Spark sample format) or the tab-separated
    MovieLens-100k `u.data` format (`user\titem\trating\ttimestamp`).

    Lines starting with ``#`` are treated as comments (provenance headers
    on vendored copies).
    """
    users, items, vals = [], [], []
    with open(path, "r", encoding="utf-8") as fh:
        for line in fh:
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            parts = line.split("::") if "::" in line else line.split()
            users.append(int(parts[0]))
            items.append(int(parts[1]))
            vals.append(float(parts[2]))
    u = np.asarray(users, dtype=np.int32)
    i = np.asarray(items, dtype=np.int32)
    # ids may be 1-based (ML-100k) or 0-based (Spark sample); densify
    u_uniq, u_ix = np.unique(u, return_inverse=True)
    i_uniq, i_ix = np.unique(i, return_inverse=True)
    return RatingsDataset(
        users=u_ix.astype(np.int32),
        items=i_ix.astype(np.int32),
        ratings=np.asarray(vals, dtype=np.float32),
        num_users=len(u_uniq),
        num_items=len(i_uniq),
    )


def synthesize_ml100k(
    seed: int = 3,
    num_users: int = ML100K_USERS,
    num_items: int = ML100K_ITEMS,
    num_ratings: int = ML100K_RATINGS,
    latent_rank: int = 12,
    noise: float = 0.6,
    selection_gamma: float = 1.0,
) -> RatingsDataset:
    """Deterministic MovieLens-100k-statistics reconstruction.

    Matches the real dataset's marginals — 943x1682, 100k ratings, every
    user >=20 ratings, heavy item-popularity skew, 1-5 integer stars with
    mean ~3.53 — over a *known* latent model: ratings are
    ``clip(round(mu + b_u + b_i + p_u.q_i + eps), 1, 5)`` with rank-12
    gaussian factors. Because the ground truth is genuinely low-rank,
    measured MAP@10 reflects how well a factorizer recovers structure
    (the quality axis of the north-star gate) rather than fitting noise.

    ``selection_gamma`` couples WHICH items a user rates to the same
    latent preference that drives the rating value (selection keys are
    ``log_pop + gamma * (b_i + p_u.q_i) + gumbel``). Real-world rating
    data has exactly this coupling — people watch movies they expect to
    like — and without it (``selection_gamma=0``, the round-2 generator)
    item selection is user-independent, making ``popularity x
    like-rate`` the information-theoretic optimum ranker: no
    personalized top-N model *can* beat the popularity baseline, so the
    benchmark could not measure personalization at all (measured: best
    implicit-ALS MAP@10 0.126 vs popularity 0.132, converging from
    below as rank -> 1). With the coupling, implicit ALS has real
    signal to find (it beats popularity; bench key ``map10_implicit``)
    while the marginals above still hold — the pre-round/clip rating
    mean is re-centered on 3.53 after the selection bias shifts it
    (rounding and clipping then move the realized mean a few
    hundredths, as in the round-2 generator).

    Sensitivity (round-4 sweep, implicit rank 10/alpha 5/lam 0.1 vs
    popularity, 5-fold MAP@10, this generator's defaults otherwise)::

        gamma            map10_implicit  map10_popularity  ratio
        0.00 (r2 gen.)   0.1114          0.1331            0.84
        0.25             0.1188          0.1168            1.02
        0.50             0.1329          0.1017            1.31
        0.75             0.1550          0.0901            1.72
        1.00 (default)   0.1706          0.0825            2.07

    The win crosses over at gamma ~0.25 and grows monotonically — the
    gate does not hinge on the specific default, only on SOME
    preference-selection coupling existing. And the coupling is not a
    modeling choice smuggled into the benchmark: on the vendored REAL
    Spark sample dataset (examples/data/sample_movielens.txt, 30x100,
    1.5k ratings — no generator involved) implicit ALS beats popularity
    on every one of 5 folds, MAP@10 mean 0.0989 vs 0.0435 (bench keys
    ``map10_implicit_real``/``map10_popularity_real``; wide error bars
    at that size, reported honestly).
    """
    # degrees live in [20, num_items - 1]; the rescale/adjust below can
    # only terminate when num_ratings is achievable inside that box
    if not 20 * num_users <= num_ratings <= num_users * (num_items - 1):
        raise ValueError(
            f"num_ratings={num_ratings} outside the feasible range "
            f"[{20 * num_users}, {num_users * (num_items - 1)}] for "
            f"{num_users} users x {num_items} items (>=20 ratings/user)"
        )
    rng = np.random.default_rng(seed)

    # --- per-user degree: lognormal, clipped to [20, ~740], summing to nnz
    deg = np.exp(rng.normal(4.2, 0.9, size=num_users))
    deg = np.clip(deg, 20, num_items // 2 - 1)
    deg = np.maximum(20, np.round(deg * num_ratings / deg.sum())).astype(np.int64)
    deg = np.minimum(deg, num_items - 1)
    # trim/grow to hit num_ratings exactly, never dropping below 20
    diff = int(deg.sum()) - num_ratings
    order = np.argsort(-deg)
    j = 0
    while diff != 0:
        u = order[j % num_users]
        if diff > 0 and deg[u] > 20:
            deg[u] -= 1
            diff -= 1
        elif diff < 0 and deg[u] < num_items - 1:
            deg[u] += 1
            diff += 1
        j += 1

    # --- item popularity: zipf-like skew as in the real dataset
    pop = 1.0 / np.arange(1, num_items + 1) ** 0.9
    pop = pop[rng.permutation(num_items)]
    log_pop = np.log(pop)

    # --- latent ground truth
    scale = 1.0 / np.sqrt(latent_rank)
    P = rng.normal(0.0, scale, size=(num_users, latent_rank))
    Q = rng.normal(0.0, 1.0, size=(num_items, latent_rank))
    b_u = rng.normal(0.0, 0.35, size=num_users)
    b_i = rng.normal(0.0, 0.5, size=num_items)
    mu = 3.53

    # --- per-user distinct item draws: Gumbel top-k on popularity plus
    # (selection_gamma-weighted) latent affinity — see docstring
    gumbel = rng.gumbel(size=(num_users, num_items))
    keys = log_pop[None, :] + gumbel
    if selection_gamma:
        keys = keys + selection_gamma * (b_i[None, :] + P @ Q.T)
    ranked = np.argsort(-keys, axis=1)

    users = np.repeat(np.arange(num_users, dtype=np.int32), deg)
    items = np.concatenate(
        [ranked[u, : deg[u]] for u in range(num_users)]
    ).astype(np.int32)

    raw = (
        mu
        + b_u[users]
        + b_i[items]
        + np.einsum("nk,nk->n", P[users], Q[items])
        + rng.normal(0.0, noise, size=len(users))
    )
    # selection bias (liked items over-selected) shifts the mean up;
    # re-center the continuous scores (round/clip still move the
    # realized mean slightly — see docstring)
    raw = raw - (raw.mean() - mu)
    vals = np.clip(np.round(raw), 1.0, 5.0).astype(np.float32)

    return RatingsDataset(
        users=users,
        items=items,
        ratings=vals,
        num_users=num_users,
        num_items=num_items,
    )
