"""Legacy batch-view helpers (deprecated in the reference, kept for parity).

Parity: data/src/main/scala/.../data/view/{LBatchView.scala,
PBatchView.scala, DataView.scala} — predicate-combinator queries over an
event batch: filter chains, property aggregation to a point in time, and
fold/group reductions. The reference deprecated these in favor of
PEventStore; this module exists so users migrating view-based engines
have a drop-in, but new code should use EventStore + the Preparator.
"""

from __future__ import annotations

import dataclasses
import hashlib
import inspect
import logging
import os
import warnings
from datetime import datetime
from typing import Any, Callable, Iterable, TypeVar

from predictionio_tpu.core.aggregation import aggregate_properties
from predictionio_tpu.core.datamap import DataMap, PropertyMap
from predictionio_tpu.core.event import Event

T = TypeVar("T")
logger = logging.getLogger(__name__)


def data_map_aggregator() -> Callable[[DataMap | None, Event], DataMap | None]:
    """The $set/$unset/$delete step function over an optional DataMap —
    ViewAggregators.getDataMapAggregator (LBatchView.scala:77-101)."""

    def op(acc: DataMap | None, e: Event) -> DataMap | None:
        if e.event == "$set":
            return e.properties if acc is None else acc + e.properties
        if e.event == "$unset":
            return None if acc is None else acc - e.properties.keys()
        if e.event == "$delete":
            return None
        return acc

    return op


class BatchView:
    """An in-memory event batch with combinator queries.

    Parity: LBatchView.LEventStore/ViewPredicates (LBatchView.scala:33+).
    """

    def __init__(self, events: Iterable[Event], _warned: bool = False):
        if not _warned:
            warnings.warn(
                "BatchView is a legacy API (deprecated in the reference); "
                "use EventStore.find/aggregate_properties",
                DeprecationWarning,
                stacklevel=2,
            )
        self._events = list(events)

    # -- predicates (ViewPredicates parity) ---------------------------------
    def filter(self, predicate: Callable[[Event], bool]) -> "BatchView":
        return BatchView((e for e in self._events if predicate(e)), _warned=True)

    def filter_by(
        self,
        event: str | None = None,
        entity_type: str | None = None,
        start_time: datetime | None = None,
        until_time: datetime | None = None,
    ) -> "BatchView":
        """Keyword-predicate filter — EventSeq.filter(eventOpt,
        entityTypeOpt, startTimeOpt, untilTimeOpt) (LBatchView.scala:
        117-128); ``None`` matches everything, times are [start, until)."""
        return self.filter(
            lambda e: (event is None or e.event == event)
            and (entity_type is None or e.entity_type == entity_type)
            and (start_time is None or e.event_time >= start_time)
            and (until_time is None or e.event_time < until_time)
        )

    def event_name(self, name: str) -> "BatchView":
        return self.filter(lambda e: e.event == name)

    def entity_type(self, entity_type: str) -> "BatchView":
        return self.filter(lambda e: e.entity_type == entity_type)

    def before(self, t: datetime) -> "BatchView":
        return self.filter(lambda e: e.event_time < t)

    def after(self, t: datetime) -> "BatchView":
        return self.filter(lambda e: e.event_time >= t)

    # -- terminal operations ------------------------------------------------
    def events(self) -> list[Event]:
        return list(self._events)

    def __len__(self) -> int:
        return len(self._events)

    def aggregate_properties(
        self, entity_type: str, until_time: datetime | None = None
    ) -> dict[str, PropertyMap]:
        """$set/$unset/$delete fold per entity, optionally up to a point in
        time (LBatchView.aggregateProperties parity)."""
        selected = (
            e for e in self._events
            if e.entity_type == entity_type
            and (until_time is None or e.event_time < until_time)
        )
        return aggregate_properties(selected)

    def group_by_entity(self) -> dict[tuple[str, str], list[Event]]:
        out: dict[tuple[str, str], list[Event]] = {}
        for e in self._events:
            out.setdefault((e.entity_type, e.entity_id), []).append(e)
        return out

    def fold(self, init: T, op: Callable[[T, Event], T]) -> T:
        acc = init
        for e in self._events:
            acc = op(acc, e)
        return acc

    def aggregate_by_entity_ordered(
        self, init: T, op: Callable[[T, Event], T]
    ) -> dict[str, T]:
        """Per-entityId time-ordered fold — EventSeq.
        aggregateByEntityOrdered (LBatchView.scala:134-140): group by
        entity id, sort each group by event time, foldLeft with ``op``."""
        groups: dict[str, list[Event]] = {}
        for e in self._events:
            groups.setdefault(e.entity_id, []).append(e)
        out: dict[str, T] = {}
        for entity_id, evs in groups.items():
            acc = init
            for e in sorted(evs, key=lambda e: e.event_time):
                acc = op(acc, e)
            out[entity_id] = acc
        return out


def create_data_view(
    app_name: str,
    conversion: Callable[[Event], Any | None],
    *,
    name: str = "",
    version: str = "",
    channel_name: str | None = None,
    start_time: datetime | None = None,
    until_time: datetime | None = None,
    storage=None,
    base_dir: str | None = None,
):
    """Cached columnar view of converted events — DataView.create
    (DataView.scala:61-112): read events, map each through
    ``conversion`` (``None`` results are dropped), persist the result as
    a Parquet file fingerprinted by (time range, ``version``, and the
    conversion function's source), and return the cached
    ``pyarrow.Table`` on later calls.

    ``conversion`` may return a dataclass, mapping, or tuple; rows must
    be homogeneous. Divergence from the reference: DataView.scala keys
    the cache on ``DateTime.now()`` when ``untilTime`` is absent, so its
    cache can never hit; here an absent ``until_time`` simply bypasses
    the cache (fresh read every call) and caching requires an explicit,
    stable ``until_time``. The conversion fingerprint uses the
    function's source text (via inspect) where Scala used the case
    class serialVersionUID."""
    import pyarrow as pa
    import pyarrow.parquet as pq

    from predictionio_tpu.data.store import EventStore

    store = EventStore(storage) if storage is not None else EventStore()

    cache_path = None
    if until_time is not None:
        try:
            src = inspect.getsource(conversion)
        except (OSError, TypeError):
            # source unavailable (REPL/stdin/builtin): key on the stable
            # qualified name — never repr(), whose memory address would
            # defeat the cache across processes
            src = (f"{getattr(conversion, '__module__', '?')}."
                   f"{getattr(conversion, '__qualname__', repr(type(conversion)))}")
        key = hashlib.md5(
            f"{channel_name}-{start_time}-{until_time}-{version}-{src}".encode()
        ).hexdigest()[:16]
        base = base_dir or os.path.join(
            os.environ.get("PIO_FS_BASEDIR",
                           os.path.expanduser("~/.pio_store")), "view")
        cache_path = os.path.join(base, f"{name}-{app_name}-{key}.parquet")
        if os.path.exists(cache_path):
            return pq.read_table(cache_path)
        logger.info("cached copy not found, reading from the event store")

    # stream the event scan into per-chunk record batches (the columnar
    # scan underneath bounds what is resident: one EventColumns batch +
    # one converted chunk, never the whole result set as a Python list)
    batches: list[pa.RecordBatch] = []
    for cols in store.scan(app_name, channel_name=channel_name,
                           start_time=start_time, until_time=until_time):
        chunk = []
        for e in cols.to_events():
            row = conversion(e)
            if row is None:
                continue
            if dataclasses.is_dataclass(row):
                row = dataclasses.asdict(row)
            elif not isinstance(row, dict):
                row = {f"f{i}": v for i, v in enumerate(row)}
            chunk.append(row)
        if chunk:
            batches.append(pa.RecordBatch.from_pylist(chunk))
    if not batches:
        table = pa.Table.from_pylist([])
    else:
        # per-chunk inferred schemas can disagree (ints then floats);
        # promoted concat unifies them the way one global from_pylist did
        tables = [pa.Table.from_batches([b]) for b in batches]
        try:
            table = pa.concat_tables(tables, promote_options="permissive")
        except TypeError:
            # pyarrow < 14 spells type promotion promote=True (the
            # parquet extra does not pin a floor)
            table = pa.concat_tables(tables, promote=True)
    if cache_path is not None:
        os.makedirs(os.path.dirname(cache_path), exist_ok=True)
        tmp = f"{cache_path}.tmp.{os.getpid()}"
        pq.write_table(table, tmp)
        os.replace(tmp, cache_path)
        return pq.read_table(cache_path)
    return table
