"""Legacy batch-view helpers (deprecated in the reference, kept for parity).

Parity: data/src/main/scala/.../data/view/{LBatchView.scala,
PBatchView.scala, DataView.scala} — predicate-combinator queries over an
event batch: filter chains, property aggregation to a point in time, and
fold/group reductions. The reference deprecated these in favor of
PEventStore; this module exists so users migrating view-based engines
have a drop-in, but new code should use EventStore + the Preparator.
"""

from __future__ import annotations

import warnings
from datetime import datetime
from typing import Any, Callable, Iterable, TypeVar

from predictionio_tpu.core.aggregation import aggregate_properties
from predictionio_tpu.core.datamap import PropertyMap
from predictionio_tpu.core.event import Event

T = TypeVar("T")


class BatchView:
    """An in-memory event batch with combinator queries.

    Parity: LBatchView.LEventStore/ViewPredicates (LBatchView.scala:33+).
    """

    def __init__(self, events: Iterable[Event], _warned: bool = False):
        if not _warned:
            warnings.warn(
                "BatchView is a legacy API (deprecated in the reference); "
                "use EventStore.find/aggregate_properties",
                DeprecationWarning,
                stacklevel=2,
            )
        self._events = list(events)

    # -- predicates (ViewPredicates parity) ---------------------------------
    def filter(self, predicate: Callable[[Event], bool]) -> "BatchView":
        return BatchView((e for e in self._events if predicate(e)), _warned=True)

    def event_name(self, name: str) -> "BatchView":
        return self.filter(lambda e: e.event == name)

    def entity_type(self, entity_type: str) -> "BatchView":
        return self.filter(lambda e: e.entity_type == entity_type)

    def before(self, t: datetime) -> "BatchView":
        return self.filter(lambda e: e.event_time < t)

    def after(self, t: datetime) -> "BatchView":
        return self.filter(lambda e: e.event_time >= t)

    # -- terminal operations ------------------------------------------------
    def events(self) -> list[Event]:
        return list(self._events)

    def __len__(self) -> int:
        return len(self._events)

    def aggregate_properties(
        self, entity_type: str, until_time: datetime | None = None
    ) -> dict[str, PropertyMap]:
        """$set/$unset/$delete fold per entity, optionally up to a point in
        time (LBatchView.aggregateProperties parity)."""
        selected = (
            e for e in self._events
            if e.entity_type == entity_type
            and (until_time is None or e.event_time < until_time)
        )
        return aggregate_properties(selected)

    def group_by_entity(self) -> dict[tuple[str, str], list[Event]]:
        out: dict[tuple[str, str], list[Event]] = {}
        for e in self._events:
            out.setdefault((e.entity_type, e.entity_id), []).append(e)
        return out

    def fold(self, init: T, op: Callable[[T, Event], T]) -> T:
        acc = init
        for e in self._events:
            acc = op(acc, e)
        return acc
