"""EventStore — the engine-facing, name-based facade over the event DAOs.

Parity: data/src/main/scala/.../data/store/{PEventStore.scala:35-121,
LEventStore.scala:33-145, Common.scala}. One facade serves both roles:
training-time bulk reads (PEventStore.find/aggregateProperties) and
serving-time low-latency entity reads (LEventStore.findByEntity).
"""

from __future__ import annotations

from datetime import datetime
from typing import Iterator, Sequence

from predictionio_tpu.core.datamap import PropertyMap
from predictionio_tpu.core.event import Event
from predictionio_tpu.storage.base import EventFilter
from predictionio_tpu.storage.registry import Storage


class AppNotFoundError(KeyError):
    pass


class EventStore:
    def __init__(self, storage: Storage | None = None):
        self.storage = storage or Storage.default()

    def app_name_to_id(self, app_name: str, channel_name: str | None = None) -> tuple[int, int | None]:
        """Parity: Common.appNameToId (store/Common.scala)."""
        app = self.storage.get_meta_data_apps().get_by_name(app_name)
        if app is None:
            raise AppNotFoundError(f"App {app_name!r} does not exist.")
        channel_id = None
        if channel_name is not None:
            channels = self.storage.get_meta_data_channels().get_by_app_id(app.id)
            match = next((c for c in channels if c.name == channel_name), None)
            if match is None:
                raise AppNotFoundError(
                    f"Channel {channel_name!r} does not exist in app {app_name!r}."
                )
            channel_id = match.id
        return app.id, channel_id

    def find(
        self,
        app_name: str,
        channel_name: str | None = None,
        start_time: datetime | None = None,
        until_time: datetime | None = None,
        entity_type: str | None = None,
        entity_id: str | None = None,
        event_names: Sequence[str] | None = None,
        target_entity_type: str | None | type(...) = ...,
        target_entity_id: str | None | type(...) = ...,
        limit: int | None = None,
        reversed: bool = False,
    ) -> Iterator[Event]:
        """Training-time bulk read. Parity: PEventStore.find
        (PEventStore.scala:59-97) / LEventStore.find (:117-145)."""
        app_id, channel_id = self.app_name_to_id(app_name, channel_name)
        return self.storage.get_events().find(
            app_id,
            channel_id,
            EventFilter(
                start_time=start_time,
                until_time=until_time,
                entity_type=entity_type,
                entity_id=entity_id,
                event_names=event_names,
                target_entity_type=target_entity_type,
                target_entity_id=target_entity_id,
                limit=limit,
                reversed=reversed,
            ),
        )

    def scan(
        self,
        app_name: str,
        channel_name: str | None = None,
        start_time: datetime | None = None,
        until_time: datetime | None = None,
        entity_type: str | None = None,
        entity_id: str | None = None,
        event_names: Sequence[str] | None = None,
        target_entity_type: str | None | type(...) = ...,
        target_entity_id: str | None | type(...) = ...,
        limit: int | None = None,
        reversed: bool = False,
        batch_size: int | None = None,
    ) -> "Iterator[EventColumns]":
        """Training-time bulk read as columnar batches — the same filter
        surface as :meth:`find`, yielding ``EventColumns``
        (core/columns.py) instead of per-event objects. This is the
        train-path analogue of the reference's PEvents RDD read: engines
        consume numpy columns per batch and never touch an Event in the
        hot loop (docs/data-pipeline.md)."""
        from predictionio_tpu.storage.base import Events

        app_id, channel_id = self.app_name_to_id(app_name, channel_name)
        return self.storage.get_events().find_columnar(
            app_id,
            channel_id,
            EventFilter(
                start_time=start_time,
                until_time=until_time,
                entity_type=entity_type,
                entity_id=entity_id,
                event_names=event_names,
                target_entity_type=target_entity_type,
                target_entity_id=target_entity_id,
                limit=limit,
                reversed=reversed,
            ),
            batch_size=(Events.COLUMNAR_BATCH_SIZE if batch_size is None
                        else batch_size),
        )

    def aggregate_properties(
        self,
        app_name: str,
        entity_type: str,
        channel_name: str | None = None,
        start_time: datetime | None = None,
        until_time: datetime | None = None,
        required: Sequence[str] | None = None,
    ) -> dict[str, PropertyMap]:
        """Parity: PEventStore.aggregateProperties (PEventStore.scala:99-121)."""
        app_id, channel_id = self.app_name_to_id(app_name, channel_name)
        return self.storage.get_events().aggregate_properties(
            app_id,
            entity_type,
            channel_id,
            start_time=start_time,
            until_time=until_time,
            required=required,
        )

    def find_by_entity(
        self,
        app_name: str,
        entity_type: str,
        entity_id: str,
        channel_name: str | None = None,
        event_names: Sequence[str] | None = None,
        target_entity_type: str | None | type(...) = ...,
        target_entity_id: str | None | type(...) = ...,
        start_time: datetime | None = None,
        until_time: datetime | None = None,
        limit: int | None = None,
        latest: bool = True,
    ) -> Iterator[Event]:
        """Serving-time single-entity read. Parity: LEventStore.findByEntity
        (LEventStore.scala:61-115)."""
        app_id, channel_id = self.app_name_to_id(app_name, channel_name)
        return self.storage.get_events().find_single_entity(
            app_id,
            entity_type,
            entity_id,
            channel_id,
            event_names=event_names,
            target_entity_type=target_entity_type,
            target_entity_id=target_entity_id,
            start_time=start_time,
            until_time=until_time,
            limit=limit,
            latest=latest,
        )
