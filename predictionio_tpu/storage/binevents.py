"""binevents backend — binary append-only event log with a native scanner.

The high-throughput event store (the reference's HBase role,
SURVEY.md §2.4) with the scan hot path in C++: records are framed
(length + CRC32) with the filterable fixed fields (event time, names,
entity/target ids) stored in binary ahead of the JSON payload, so the
native library (predictionio_tpu/native/eventlog.cc) can replay,
compact tombstones, and filter without JSON parsing — Python decodes
only the events that survive the filter. This mirrors how the
reference's HBase backend pushes time-range/entity filtering into
region-server scans (HBEventsUtil.createScan, HBEventsUtil.scala:289)
instead of filtering client-side.

When no C++ toolchain is available the pure-Python codec below (same
byte format, interoperable files) takes over.

Config: ``PIO_STORAGE_SOURCES_<NAME>_TYPE=binevents``,
``PIO_STORAGE_SOURCES_<NAME>_PATH=/dir``. Layout: one log
``events_<app>[_<ch>].bin`` per (app, channel), matching HBase's
table-per-app/channel naming (HBEventsUtil.eventTableName).
"""

from __future__ import annotations

import ctypes
import json
import os
import struct
import threading
import uuid
import zlib
from datetime import datetime, timezone
from typing import Iterator, Sequence

from predictionio_tpu.core.event import Event
from predictionio_tpu.core.json_codec import event_from_json, event_to_json
from predictionio_tpu.storage import base
from predictionio_tpu.storage.base import EventFilter, StorageClientConfig

_MAGIC = b"PIOEVT1\n"
_ABSENT = 0xFFFF
_EPOCH = datetime(1970, 1, 1, tzinfo=timezone.utc)


def _to_us(t: datetime) -> int:
    """Exact microseconds since epoch (datetime resolution is µs)."""
    delta = t - _EPOCH
    return (delta.days * 86_400 + delta.seconds) * 1_000_000 + delta.microseconds


def _table_name(app_id: int, channel_id: int | None) -> str:
    suffix = f"_{channel_id}" if channel_id is not None else ""
    return f"events_{app_id}{suffix}.bin"


# ---------------------------------------------------------------------------
# Pure-Python codec (same byte format as eventlog.cc)
# ---------------------------------------------------------------------------

def _pack_str16(s: str | None) -> bytes:
    if s is None:
        return struct.pack("<H", _ABSENT)
    b = s.encode("utf-8")[: _ABSENT - 1]
    return struct.pack("<H", len(b)) + b


def _put_body(event: Event) -> bytes:
    payload = json.dumps(event_to_json(event)).encode("utf-8")
    return (
        b"\x00"
        + struct.pack("<q", _to_us(event.event_time))
        + _pack_str16(event.event_id)
        + _pack_str16(event.event)
        + _pack_str16(event.entity_type)
        + _pack_str16(event.entity_id)
        + _pack_str16(event.target_entity_type)
        + _pack_str16(event.target_entity_id)
        + struct.pack("<I", len(payload))
        + payload
    )


def _del_body(event_id: str) -> bytes:
    return b"\x01" + _pack_str16(event_id)


def _frame(body: bytes) -> bytes:
    return struct.pack("<II", len(body), zlib.crc32(body)) + body


def _py_replay(path: str) -> dict[str, tuple]:
    """id -> (t_us, name, etype, eid, tet, tei, json_bytes); last put wins,
    del removes; stops at a torn/corrupt tail like the native scanner."""
    live: dict[str, tuple] = {}
    try:
        data = open(path, "rb").read()
    except OSError:
        return live
    if len(data) < 8 or data[:8] != _MAGIC:
        return live
    off = 8
    while off + 8 <= len(data):
        body_len, crc = struct.unpack_from("<II", data, off)
        off += 8
        if body_len > (1 << 30) or off + body_len > len(data):
            break
        body = data[off : off + body_len]
        off += body_len
        if zlib.crc32(body) != crc:
            break
        op = body[0]
        pos = 1
        if op == 1:
            (idl,) = struct.unpack_from("<H", body, pos)
            pos += 2
            live.pop(body[pos : pos + idl].decode("utf-8"), None)
            continue
        (t_us,) = struct.unpack_from("<q", body, pos)
        pos += 8
        fields: list[str | None] = []
        for _ in range(6):  # id, name, etype, eid, tet, tei
            (n,) = struct.unpack_from("<H", body, pos)
            pos += 2
            if n == _ABSENT:
                fields.append(None)
            else:
                fields.append(body[pos : pos + n].decode("utf-8"))
                pos += n
        (jlen,) = struct.unpack_from("<I", body, pos)
        pos += 4
        payload = body[pos : pos + jlen]
        eid_key, name, etype, eid, tet, tei = fields
        live[eid_key or ""] = (t_us, name, etype, eid, tet, tei, payload)
    return live


def _py_valid_prefix(path: str) -> int:
    """Byte length of the valid record prefix; -1 on foreign header."""
    try:
        data = open(path, "rb").read()
    except OSError:
        return 0
    if len(data) == 0:
        return 0
    if len(data) < 8 or data[:8] != _MAGIC:
        return -1
    good = 8
    off = 8
    while off + 8 <= len(data):
        body_len, crc = struct.unpack_from("<II", data, off)
        if body_len > (1 << 30) or off + 8 + body_len > len(data):
            break
        body = data[off + 8 : off + 8 + body_len]
        if zlib.crc32(body) != crc:
            break
        off += 8 + body_len
        good = off
    return good


def _py_scan_records(path: str, flt: EventFilter) -> list[tuple]:
    """Live records surviving the filter, as the frame's decoded fields:
    ``(id, t_us, name, etype, eid, tet, tei, payload)``."""
    start_us = _to_us(flt.start_time) if flt.start_time is not None else None
    until_us = _to_us(flt.until_time) if flt.until_time is not None else None
    names = set(flt.event_names) if flt.event_names is not None else None
    out = []
    for rid, rec in _py_replay(path).items():
        t_us, name, etype, eid, tet, tei, payload = rec
        if start_us is not None and t_us < start_us:
            continue
        if until_us is not None and t_us >= until_us:
            continue
        if flt.entity_type is not None and etype != flt.entity_type:
            continue
        if flt.entity_id is not None and eid != flt.entity_id:
            continue
        if names is not None and name not in names:
            continue
        if flt.target_entity_type is not ... and tet != flt.target_entity_type:
            continue
        if flt.target_entity_id is not ... and tei != flt.target_entity_id:
            continue
        out.append((rid, *rec))
    return out


def _py_scan(path: str, flt: EventFilter) -> list[bytes]:
    return [rec[-1] for rec in _py_scan_records(path, flt)]


# ---------------------------------------------------------------------------
# Events DAO
# ---------------------------------------------------------------------------

class BinEvents(base.Events):
    #: ordering granularity for the tail-cursor contract
    #: (base.Events.CURSOR_TIME_RESOLUTION_US): find()/find_columnar
    #: order by the PAYLOAD's ms-truncated eventTime (+ id tiebreak),
    #: so the cursor comparison must truncate to ms too — a µs-exact
    #: key would mis-split sub-millisecond ties ordered by id here
    CURSOR_TIME_RESOLUTION_US = 1000

    def __init__(self, path: str, use_native: bool = True):
        from predictionio_tpu import native

        self._path = path
        self._lock = threading.RLock()
        self._lib = native.load_eventlog() if use_native else None
        self._handles: dict[tuple[int, int | None], int] = {}
        #: files already tail-repaired by this instance (Python write path)
        self._repaired: set[str] = set()
        os.makedirs(path, exist_ok=True)

    @property
    def native_active(self) -> bool:
        return self._lib is not None

    def _file(self, app_id: int, channel_id: int | None) -> str:
        return os.path.join(self._path, _table_name(app_id, channel_id))

    # -- write path ---------------------------------------------------------
    def _py_append(self, path: str, body: bytes) -> None:
        # First write per file: truncate any torn/corrupt tail (same crash
        # repair pio_open does) so new records stay readable.
        if path not in self._repaired:
            good = _py_valid_prefix(path)
            if good < 0:
                raise OSError(f"not an event log: {path}")
            if os.path.exists(path) and os.path.getsize(path) > good > 0:
                with open(path, "r+b") as f:
                    f.truncate(good)
            self._repaired.add(path)
        new = not os.path.exists(path) or os.path.getsize(path) == 0
        with open(path, "ab") as f:
            if new:
                f.write(_MAGIC)
            f.write(_frame(body))

    def _handle(self, app_id: int, channel_id: int | None):
        key = (app_id, channel_id)
        h = self._handles.get(key)
        if h is None:
            h = self._lib.pio_open(self._file(app_id, channel_id).encode())
            if not h:
                raise OSError(f"pio_open failed: {self._file(app_id, channel_id)}")
            self._handles[key] = h
        return h

    def _write_put(self, event: Event, app_id: int, channel_id: int | None) -> None:
        if self._lib is None:
            self._py_append(self._file(app_id, channel_id), _put_body(event))
            return
        payload = json.dumps(event_to_json(event)).encode("utf-8")
        enc = lambda s: None if s is None else s.encode("utf-8")
        rc = self._lib.pio_write_put(
            self._handle(app_id, channel_id),
            _to_us(event.event_time),
            event.event_id.encode("utf-8"),
            event.event.encode("utf-8"),
            event.entity_type.encode("utf-8"),
            event.entity_id.encode("utf-8"),
            enc(event.target_entity_type),
            enc(event.target_entity_id),
            payload,
            len(payload),
        )
        if rc != 0:
            raise OSError(f"pio_write_put rc={rc}")

    def _write_del(self, event_id: str, app_id: int, channel_id: int | None) -> None:
        if self._lib is None:
            self._py_append(self._file(app_id, channel_id), _del_body(event_id))
            return
        rc = self._lib.pio_write_del(
            self._handle(app_id, channel_id), event_id.encode("utf-8")
        )
        if rc != 0:
            raise OSError(f"pio_write_del rc={rc}")

    # -- read path ----------------------------------------------------------
    def _scan_payloads(self, app_id: int, channel_id: int | None,
                       flt: EventFilter) -> list[bytes]:
        path = self._file(app_id, channel_id)
        if not os.path.exists(path):
            return []
        # event_names=[] means "match nothing" (EventFilter.matches
        # semantics); the native scan treats an empty list as unfiltered,
        # so short-circuit here.
        if flt.event_names is not None and len(flt.event_names) == 0:
            return []
        if self._lib is None:
            return _py_scan(path, flt)
        names = None
        n_names = 0
        if flt.event_names is not None:
            arr = [n.encode("utf-8") for n in flt.event_names]
            names = (ctypes.c_char_p * len(arr))(*arr)
            n_names = len(arr)
        tet_mode, tet = 0, None
        if flt.target_entity_type is not ...:
            if flt.target_entity_type is None:
                tet_mode = 1
            else:
                tet_mode, tet = 2, flt.target_entity_type.encode("utf-8")
        tei_mode, tei = 0, None
        if flt.target_entity_id is not ...:
            if flt.target_entity_id is None:
                tei_mode = 1
            else:
                tei_mode, tei = 2, flt.target_entity_id.encode("utf-8")
        out = ctypes.POINTER(ctypes.c_uint8)()
        out_len = ctypes.c_uint64()
        rc = self._lib.pio_scan(
            path.encode(),
            1 if flt.start_time is not None else 0,
            _to_us(flt.start_time) if flt.start_time is not None else 0,
            1 if flt.until_time is not None else 0,
            _to_us(flt.until_time) if flt.until_time is not None else 0,
            flt.entity_type.encode("utf-8") if flt.entity_type is not None else None,
            flt.entity_id.encode("utf-8") if flt.entity_id is not None else None,
            names,
            n_names,
            tet_mode,
            tet,
            tei_mode,
            tei,
            ctypes.byref(out),
            ctypes.byref(out_len),
        )
        if rc != 0:
            raise OSError(f"pio_scan rc={rc}")
        try:
            raw = ctypes.string_at(out, out_len.value)
        finally:
            self._lib.pio_free(out)
        (count,) = struct.unpack_from("<I", raw, 0)
        payloads = []
        off = 4
        for _ in range(count):
            (n,) = struct.unpack_from("<I", raw, off)
            off += 4
            payloads.append(raw[off : off + n])
            off += n
        return payloads

    # -- Events DAO ---------------------------------------------------------
    def init(self, app_id: int, channel_id: int | None = None) -> bool:
        with self._lock:
            path = self._file(app_id, channel_id)
            if not os.path.exists(path):
                with open(path, "wb") as f:
                    f.write(_MAGIC)
        return True

    def remove(self, app_id: int, channel_id: int | None = None) -> bool:
        with self._lock:
            key = (app_id, channel_id)
            if self._lib is not None and key in self._handles:
                self._lib.pio_close(self._handles.pop(key))
            path = self._file(app_id, channel_id)
            if os.path.exists(path):
                os.remove(path)
                return True
            return False

    def close(self) -> None:
        with self._lock:
            if self._lib is not None:
                for h in self._handles.values():
                    self._lib.pio_close(h)
                self._handles.clear()

    def insert(self, event: Event, app_id: int, channel_id: int | None = None) -> str:
        event_id = event.event_id or uuid.uuid4().hex
        event = event.with_event_id(event_id)
        with self._lock:
            self._write_put(event, app_id, channel_id)
        return event_id

    def insert_batch(
        self, events: Sequence[Event], app_id: int, channel_id: int | None = None
    ) -> list[str]:
        ids = []
        with self._lock:
            for event in events:
                ids.append(self.insert(event, app_id, channel_id))
        return ids

    def get(self, event_id: str, app_id: int, channel_id: int | None = None) -> Event | None:
        with self._lock:
            path = self._file(app_id, channel_id)
            if not os.path.exists(path):
                return None
            if self._lib is None:
                rec = _py_replay(path).get(event_id)
                if rec is None:
                    return None
                return event_from_json(json.loads(rec[6]), validate=False)
            out = ctypes.POINTER(ctypes.c_uint8)()
            out_len = ctypes.c_uint64()
            rc = self._lib.pio_get(
                path.encode(), event_id.encode("utf-8"),
                ctypes.byref(out), ctypes.byref(out_len),
            )
            if rc == 1:
                return None
            if rc != 0:
                raise OSError(f"pio_get rc={rc}")
            try:
                raw = ctypes.string_at(out, out_len.value)
            finally:
                self._lib.pio_free(out)
            return event_from_json(json.loads(raw), validate=False)

    def delete(self, event_id: str, app_id: int, channel_id: int | None = None) -> bool:
        with self._lock:
            if self.get(event_id, app_id, channel_id) is None:
                return False
            self._write_del(event_id, app_id, channel_id)
            return True

    def find(
        self,
        app_id: int,
        channel_id: int | None = None,
        filter: EventFilter = EventFilter(),
    ) -> Iterator[Event]:
        with self._lock:
            payloads = self._scan_payloads(app_id, channel_id, filter)
        events = [event_from_json(json.loads(p), validate=False) for p in payloads]
        # event_id tiebreaker: equal-timestamp order (and who survives a
        # limit cut) must not depend on which codec produced the scan
        events.sort(key=lambda e: (e.event_time, e.event_id or ""),
                    reverse=filter.reversed)
        if filter.limit is not None and filter.limit >= 0:
            events = events[: filter.limit]
        return iter(events)

    def find_columnar(
        self,
        app_id: int,
        channel_id: int | None = None,
        filter: EventFilter = EventFilter(),
        batch_size: int = base.Events.COLUMNAR_BATCH_SIZE,
    ):
        """Native path: the binary log's frame headers decode straight
        into arrays — time/name/entity/target live in fixed binary
        fields ahead of the JSON payload, so no Event object and no
        JSON parse happens for the hot columns (the payload rides along
        as the lazy cold column). Same (event_time, event_id) ordering
        and limit cut as ``find``. The per-record fflush in the native
        writer (native/eventlog.cc pio_write_put) is what makes reading
        the file directly safe while a native handle is open."""
        from predictionio_tpu.core.columns import check_batch_size

        check_batch_size(batch_size)
        return self._find_columnar(app_id, channel_id, filter, batch_size)

    def _find_columnar(self, app_id, channel_id, filter, batch_size):
        import numpy as np

        from predictionio_tpu.core.columns import EventColumns, encode_column

        with self._lock:
            path = self._file(app_id, channel_id)
            if not os.path.exists(path):
                return
            if filter.event_names is not None and len(filter.event_names) == 0:
                return
            records = _py_scan_records(path, filter)
        # same total order as find(): find sorts by the PAYLOAD's
        # event_time (wire JSON, millisecond-truncated) with event_id
        # tiebreak, so the columnar sort key truncates t_us to ms —
        # sorting by raw µs could order sub-millisecond neighbors
        # differently from the row path; ids are unique so
        # ascending-sort + reverse equals a descending sort
        records.sort(key=lambda r: (r[1] // 1000, r[0]),
                     reverse=filter.reversed)
        if filter.limit is not None and filter.limit >= 0:
            records = records[: filter.limit]
        for at in range(0, len(records), batch_size):
            chunk = records[at:at + batch_size]
            ids, t_us, names, etypes, eids, tets, teis, payloads = zip(*chunk)
            yield EventColumns.from_event_json(
                times_us=np.asarray(t_us, dtype=np.int64),
                event=encode_column(names),
                entity_type=encode_column(etypes),
                entity_id=encode_column(eids),
                target_entity_type=encode_column(tets),
                target_entity_id=encode_column(teis),
                event_ids=ids,
                payloads=payloads,
            )


class BinEventsStorageClient(base.BaseStorageClient):
    """Events-only client (HBase role), native scan when available."""

    def __init__(self, config: StorageClientConfig = StorageClientConfig()):
        super().__init__(config)
        path = config.properties.get(
            "PATH",
            os.path.join(
                os.environ.get("PIO_FS_BASEDIR",
                               os.path.join(os.path.expanduser("~"), ".pio_store")),
                "binevents",
            ),
        )
        use_native = config.properties.get("NATIVE", "true").lower() != "false"
        self._events = BinEvents(path, use_native=use_native)

    def events(self) -> BinEvents:
        return self._events
