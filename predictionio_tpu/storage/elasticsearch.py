"""Elasticsearch-role storage backend: metadata + events over a REST
JSON document-store protocol.

Parity: storage/elasticsearch/src/main/scala/.../elasticsearch/
{StorageClient.scala:27-43, ESApps, ESAccessKeys, ESChannels,
ESEngineInstances, ESEvaluationInstances, ESSequences, ESLEvents,
ESUtils} — the reference's ES 5.x REST backend. The client speaks the
same document-CRUD subset of the ES REST API over stdlib HTTP:

- ``PUT /{index}/{type}/{id}`` index a doc (response carries ``_version``),
- ``GET /{index}/{type}/{id}`` → ``{found, _source, _version}``,
- ``DELETE /{index}/{type}/{id}`` → ``{found}``,
- ``POST /{index}/{type}/_search`` with ``match_all`` (+ ``from``/``size``
  paging) → ``{hits: {hits: [{_id, _source}]}}``,
- ``DELETE /{index}`` drop an index.

Like the reference, sequences (auto-increment ids for apps/channels) are
implemented by re-indexing a trivial doc and reading back ``_version``
(ESSequences.genNext), and one index serves each purpose:
``<INDEX>_meta`` for the five metadata types and
``<INDEX>_events_<app>[_<ch>]`` per app/channel (ESUtils table naming).
Query-side filtering richer than match_all is applied client-side on the
scrolled pages — the conformance semantics match every other backend.

Config properties: ``HOSTS`` (comma list, default ``localhost``),
``PORTS`` (default ``9200``), ``SCHEMES`` (default ``http``), ``INDEX``
(prefix, default ``pio``), ``USERNAME``/``PASSWORD`` (basic auth), plus
the ``RETRY_*``/``BREAKER_*`` resilience knobs
(docs/operations-resilience.md). Every HTTP round trip routes through
``resilient()``: connection errors and 5xx responses retry with jittered
backoff and feed the per-source circuit breaker; non-transient HTTP
errors (4xx) surface unchanged as :class:`ESError`.
"""

from __future__ import annotations

import base64
import dataclasses
import json
import threading
import urllib.error
import urllib.request
import uuid
from datetime import datetime
from typing import Any, Iterator, Sequence

from predictionio_tpu.core.event import Event
from predictionio_tpu.core.json_codec import event_from_json, event_to_json
from predictionio_tpu.storage import base
from predictionio_tpu.storage.base import (
    AccessKey,
    App,
    Channel,
    EngineInstance,
    EvaluationInstance,
    EventFilter,
    StorageClientConfig,
)
from predictionio_tpu.utils.resilience import (
    Resilience,
    TransientError,
    is_transient_http_status,
    resilient,
)


class ESError(RuntimeError):
    pass


class ESClient:
    """Minimal ES REST client over stdlib HTTP (one base URL, basic auth)."""

    def __init__(
        self,
        host: str = "localhost",
        port: int = 9200,
        scheme: str = "http",
        username: str = "",
        password: str = "",
        timeout: float = 10.0,
        resilience: Resilience | None = None,
    ):
        self._base = f"{scheme}://{host}:{port}"
        self._timeout = timeout
        self._headers = {"Content-Type": "application/json"}
        if username:
            token = base64.b64encode(f"{username}:{password}".encode()).decode()
            self._headers["Authorization"] = f"Basic {token}"
        self._resilience = resilience or Resilience("elasticsearch")

    def request(self, method: str, path: str, body: Any = None) -> dict | None:
        return resilient(self._resilience, self._raw_request, method, path, body)

    def _raw_request(self, method: str, path: str, body: Any = None) -> dict | None:
        """One HTTP round trip. Only reachable through ``resilient()``:
        transport failures and 5xx raise TransientError (retried under
        the policy), 4xx raise ESError (application errors, no retry)."""
        data = json.dumps(body).encode() if body is not None else None
        req = urllib.request.Request(
            self._base + path, data=data, method=method, headers=self._headers
        )
        try:
            with urllib.request.urlopen(req, timeout=self._timeout) as resp:
                payload = resp.read()
        except urllib.error.HTTPError as exc:
            if exc.code == 404:
                return None
            if is_transient_http_status(exc.code):
                raise TransientError(
                    f"{method} {path}: HTTP {exc.code}") from exc
            raise ESError(f"{method} {path}: HTTP {exc.code}") from exc
        except urllib.error.URLError as exc:
            # connection refused / DNS / timeout: the retryable class
            raise TransientError(f"{method} {path}: {exc.reason}") from exc
        return json.loads(payload) if payload else {}

    # -- document ops -------------------------------------------------------
    def index_doc(self, index: str, type_: str, doc_id: str, doc: dict) -> dict:
        out = self.request("PUT", f"/{index}/{type_}/{doc_id}", doc)
        if out is None:
            raise ESError(f"index {index}/{type_}/{doc_id} failed")
        return out

    def get_doc(self, index: str, type_: str, doc_id: str) -> dict | None:
        out = self.request("GET", f"/{index}/{type_}/{doc_id}")
        if out is None or not out.get("found"):
            return None
        return out.get("_source")

    def delete_doc(self, index: str, type_: str, doc_id: str) -> bool:
        out = self.request("DELETE", f"/{index}/{type_}/{doc_id}")
        return bool(out and out.get("found"))

    def search_all(self, index: str, type_: str, page: int = 1000) -> Iterator[tuple[str, dict]]:
        """match_all scan with from/size paging (ESUtils.getAll scroll)."""
        start = 0
        while True:
            out = self.request(
                "POST",
                f"/{index}/{type_}/_search",
                {"query": {"match_all": {}}, "from": start, "size": page},
            )
            hits = (out or {}).get("hits", {}).get("hits", [])
            for h in hits:
                yield h["_id"], h["_source"]
            if len(hits) < page:
                return
            start += page

    def delete_index(self, index: str) -> bool:
        out = self.request("DELETE", f"/{index}")
        return out is not None


class ESSequences:
    """Auto-increment ids via doc re-index ``_version`` (ESSequences.genNext)."""

    def __init__(self, client: ESClient, index: str):
        self._client = client
        self._index = index
        self._lock = threading.Lock()

    def gen_next(self, name: str) -> int:
        with self._lock:
            out = self._client.index_doc(self._index, "sequences", name, {"n": 1})
            version = out.get("_version")
            if version is None:
                raise ESError(f"sequence {name}: no _version in response")
            return int(version)


# ---------------------------------------------------------------------------
# doc codecs (datetimes ↔ ISO strings)
# ---------------------------------------------------------------------------

def _to_doc(obj: Any) -> dict:
    def conv(v: Any) -> Any:
        if isinstance(v, datetime):
            return v.isoformat()
        if isinstance(v, (list, tuple)):
            return [conv(x) for x in v]
        if isinstance(v, dict):
            return {k: conv(x) for k, x in v.items()}
        return v

    return {f.name: conv(getattr(obj, f.name)) for f in dataclasses.fields(obj)}


def _from_doc(cls: type, doc: dict) -> Any:
    kwargs = {}
    for f in dataclasses.fields(cls):
        if f.name not in doc:
            continue
        v = doc[f.name]
        if f.name in ("start_time", "completion_time") and isinstance(v, str):
            v = datetime.fromisoformat(v)
        if f.name == "events" and isinstance(v, list):
            v = tuple(v)
        kwargs[f.name] = v
    return cls(**kwargs)


# ---------------------------------------------------------------------------
# metadata DAOs
# ---------------------------------------------------------------------------

class ESApps(base.Apps):
    def __init__(self, client: ESClient, index: str, seq: ESSequences):
        self._c, self._index, self._seq = client, index, seq

    def insert(self, app: App) -> int | None:
        if self.get_by_name(app.name) is not None:
            return None
        app_id = app.id or self._seq.gen_next("apps")
        if app.id and self.get(app.id) is not None:
            return None
        self._c.index_doc(self._index, "apps", str(app_id),
                          _to_doc(dataclasses.replace(app, id=app_id)))
        return app_id

    def get(self, app_id: int) -> App | None:
        doc = self._c.get_doc(self._index, "apps", str(app_id))
        return _from_doc(App, doc) if doc else None

    def get_by_name(self, name: str) -> App | None:
        return next((a for a in self.get_all() if a.name == name), None)

    def get_all(self) -> list[App]:
        return [_from_doc(App, d) for _, d in self._c.search_all(self._index, "apps")]

    def update(self, app: App) -> None:
        self._c.index_doc(self._index, "apps", str(app.id), _to_doc(app))

    def delete(self, app_id: int) -> None:
        self._c.delete_doc(self._index, "apps", str(app_id))


class ESAccessKeys(base.AccessKeys):
    def __init__(self, client: ESClient, index: str):
        self._c, self._index = client, index

    def insert(self, access_key: AccessKey) -> str | None:
        key = access_key.key or self.generate_key()
        if self.get(key) is not None:
            return None
        self._c.index_doc(self._index, "accesskeys", key,
                          _to_doc(dataclasses.replace(access_key, key=key)))
        return key

    def get(self, key: str) -> AccessKey | None:
        doc = self._c.get_doc(self._index, "accesskeys", key)
        return _from_doc(AccessKey, doc) if doc else None

    def get_all(self) -> list[AccessKey]:
        return [_from_doc(AccessKey, d)
                for _, d in self._c.search_all(self._index, "accesskeys")]

    def get_by_app_id(self, app_id: int) -> list[AccessKey]:
        return [k for k in self.get_all() if k.appid == app_id]

    def update(self, access_key: AccessKey) -> None:
        self._c.index_doc(self._index, "accesskeys", access_key.key,
                          _to_doc(access_key))

    def delete(self, key: str) -> None:
        self._c.delete_doc(self._index, "accesskeys", key)


class ESChannels(base.Channels):
    def __init__(self, client: ESClient, index: str, seq: ESSequences):
        self._c, self._index, self._seq = client, index, seq

    def insert(self, channel: Channel) -> int | None:
        if not Channel.is_valid_name(channel.name):
            return None
        channel_id = channel.id or self._seq.gen_next("channels")
        self._c.index_doc(self._index, "channels", str(channel_id),
                          _to_doc(dataclasses.replace(channel, id=channel_id)))
        return channel_id

    def get(self, channel_id: int) -> Channel | None:
        doc = self._c.get_doc(self._index, "channels", str(channel_id))
        return _from_doc(Channel, doc) if doc else None

    def get_by_app_id(self, app_id: int) -> list[Channel]:
        return [c for c in
                (_from_doc(Channel, d)
                 for _, d in self._c.search_all(self._index, "channels"))
                if c.appid == app_id]

    def delete(self, channel_id: int) -> None:
        self._c.delete_doc(self._index, "channels", str(channel_id))


class ESEngineInstances(base.EngineInstances):
    def __init__(self, client: ESClient, index: str):
        self._c, self._index = client, index

    def insert(self, instance: EngineInstance) -> str:
        instance_id = instance.id or uuid.uuid4().hex
        self._c.index_doc(self._index, "engine_instances", instance_id,
                          _to_doc(dataclasses.replace(instance, id=instance_id)))
        return instance_id

    def get(self, instance_id: str) -> EngineInstance | None:
        doc = self._c.get_doc(self._index, "engine_instances", instance_id)
        return _from_doc(EngineInstance, doc) if doc else None

    def get_all(self) -> list[EngineInstance]:
        return [_from_doc(EngineInstance, d)
                for _, d in self._c.search_all(self._index, "engine_instances")]

    def get_completed(
        self, engine_id: str, engine_version: str, engine_variant: str
    ) -> list[EngineInstance]:
        hits = [
            i for i in self.get_all()
            if i.status == "COMPLETED"
            and i.engine_id == engine_id
            and i.engine_version == engine_version
            and i.engine_variant == engine_variant
        ]
        hits.sort(key=lambda i: i.start_time, reverse=True)
        return hits

    def update(self, instance: EngineInstance) -> None:
        self._c.index_doc(self._index, "engine_instances", instance.id,
                          _to_doc(instance))

    def delete(self, instance_id: str) -> None:
        self._c.delete_doc(self._index, "engine_instances", instance_id)


class ESEvaluationInstances(base.EvaluationInstances):
    def __init__(self, client: ESClient, index: str):
        self._c, self._index = client, index

    def insert(self, instance: EvaluationInstance) -> str:
        instance_id = instance.id or uuid.uuid4().hex
        self._c.index_doc(self._index, "evaluation_instances", instance_id,
                          _to_doc(dataclasses.replace(instance, id=instance_id)))
        return instance_id

    def get(self, instance_id: str) -> EvaluationInstance | None:
        doc = self._c.get_doc(self._index, "evaluation_instances", instance_id)
        return _from_doc(EvaluationInstance, doc) if doc else None

    def get_all(self) -> list[EvaluationInstance]:
        return [_from_doc(EvaluationInstance, d)
                for _, d in self._c.search_all(self._index, "evaluation_instances")]

    def get_completed(self) -> list[EvaluationInstance]:
        hits = [i for i in self.get_all() if i.status == "EVALCOMPLETED"]
        hits.sort(key=lambda i: i.start_time, reverse=True)
        return hits

    def update(self, instance: EvaluationInstance) -> None:
        self._c.index_doc(self._index, "evaluation_instances", instance.id,
                          _to_doc(instance))

    def delete(self, instance_id: str) -> None:
        self._c.delete_doc(self._index, "evaluation_instances", instance_id)


# ---------------------------------------------------------------------------
# events DAO
# ---------------------------------------------------------------------------

class ESEvents(base.Events):
    """Per-app/channel event index (ESLEvents; index naming per ESUtils)."""

    def __init__(self, client: ESClient, index_prefix: str):
        self._c = client
        self._prefix = index_prefix

    def _index(self, app_id: int, channel_id: int | None) -> str:
        suffix = f"_{channel_id}" if channel_id is not None else ""
        return f"{self._prefix}_events_{app_id}{suffix}"

    def init(self, app_id: int, channel_id: int | None = None) -> bool:
        # indices are created implicitly on first doc; touch with a probe
        return True

    def remove(self, app_id: int, channel_id: int | None = None) -> bool:
        return self._c.delete_index(self._index(app_id, channel_id))

    def close(self) -> None:
        pass

    def insert(self, event: Event, app_id: int, channel_id: int | None = None) -> str:
        event_id = event.event_id or uuid.uuid4().hex
        event = event.with_event_id(event_id)
        self._c.index_doc(self._index(app_id, channel_id), "events", event_id,
                          event_to_json(event))
        return event_id

    def get(self, event_id: str, app_id: int, channel_id: int | None = None) -> Event | None:
        doc = self._c.get_doc(self._index(app_id, channel_id), "events", event_id)
        return event_from_json(doc, validate=False) if doc else None

    def delete(self, event_id: str, app_id: int, channel_id: int | None = None) -> bool:
        return self._c.delete_doc(self._index(app_id, channel_id), "events", event_id)

    def find(
        self,
        app_id: int,
        channel_id: int | None = None,
        filter: EventFilter = EventFilter(),
    ) -> Iterator[Event]:
        events = [
            e
            for _, d in self._c.search_all(self._index(app_id, channel_id), "events")
            if filter.matches(e := event_from_json(d, validate=False))
        ]
        events.sort(key=lambda e: (e.event_time, e.event_id or ""),
                    reverse=filter.reversed)
        if filter.limit is not None and filter.limit >= 0:
            events = events[: filter.limit]
        return iter(events)


class ESStorageClient(base.BaseStorageClient):
    prefix = "ES"

    def __init__(self, config: StorageClientConfig = StorageClientConfig()):
        super().__init__(config)
        props = config.properties
        host = props.get("HOSTS", "localhost").split(",")[0]
        port = int(props.get("PORTS", "9200").split(",")[0])
        scheme = props.get("SCHEMES", "http").split(",")[0]
        source = props.get("SOURCE_NAME", f"{host}:{port}")
        self._client = ESClient(
            host=host,
            port=port,
            scheme=scheme,
            username=props.get("USERNAME", ""),
            password=props.get("PASSWORD", ""),
            resilience=Resilience.from_properties(
                f"elasticsearch/{source}", props),
        )
        prefix = props.get("INDEX", "pio")
        meta = f"{prefix}_meta"
        self._seq = ESSequences(self._client, meta)
        self._apps = ESApps(self._client, meta, self._seq)
        self._access_keys = ESAccessKeys(self._client, meta)
        self._channels = ESChannels(self._client, meta, self._seq)
        self._engine_instances = ESEngineInstances(self._client, meta)
        self._evaluation_instances = ESEvaluationInstances(self._client, meta)
        self._events = ESEvents(self._client, prefix)

    def events(self) -> ESEvents:
        return self._events

    def apps(self) -> ESApps:
        return self._apps

    def access_keys(self) -> ESAccessKeys:
        return self._access_keys

    def channels(self) -> ESChannels:
        return self._channels

    def engine_instances(self) -> ESEngineInstances:
        return self._engine_instances

    def evaluation_instances(self) -> ESEvaluationInstances:
        return self._evaluation_instances

    def models(self) -> base.Models:
        raise NotImplementedError(
            "elasticsearch source serves metadata/event data; bind MODELDATA "
            "to localfs/hdfs/s3 (the reference's ES backend likewise has no "
            "Models DAO)"
        )
