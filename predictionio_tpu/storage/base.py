"""Storage abstraction: metadata records and DAO interfaces.

Parity with the reference's DAO traits
(reference: data/src/main/scala/.../data/storage/{Apps,AccessKeys,Channels,
EngineInstances,EvaluationInstances,Models,LEvents,PEvents}.scala). Three
repositories sit behind these interfaces: METADATA (apps/keys/channels/
engine+evaluation instances), EVENTDATA (events), MODELDATA (model blobs).

Differences from the reference, by design:
- Async Futures (LEvents.futureInsert etc., LEvents.scala:79-215) are
  dropped: Python backends here are synchronous; the event server wraps
  them in a thread pool where concurrency matters.
- PEvents' RDD-returning reads (PEvents.scala:38-189) become
  ``Events.find(...)`` iterators plus the columnar shard reader in
  ``predictionio_tpu.data.batch`` that feeds the TPU path.
"""

from __future__ import annotations

import abc
import dataclasses
import secrets
import string
from datetime import datetime
from typing import Any, Iterable, Iterator, Sequence

from predictionio_tpu.core.datamap import PropertyMap
from predictionio_tpu.core.event import Event


# ---------------------------------------------------------------------------
# Metadata records
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class App:
    """An app with a unique integer id. Parity: Apps.scala:32-40."""
    id: int
    name: str
    description: str | None = None


@dataclasses.dataclass(frozen=True)
class AccessKey:
    """Access key for an app; empty ``events`` = all events allowed.
    Parity: AccessKeys.scala:35-44."""
    key: str
    appid: int
    events: Sequence[str] = ()


@dataclasses.dataclass(frozen=True)
class Channel:
    """A named event channel within an app. Parity: Channels.scala:32-48."""
    id: int
    name: str
    appid: int

    @staticmethod
    def is_valid_name(s: str) -> bool:
        """Channel names: 1-16 chars of [a-zA-Z0-9-] (Channels.scala:41-48)."""
        allowed = set(string.ascii_letters + string.digits + "-")
        return 0 < len(s) <= 16 and all(c in allowed for c in s)


@dataclasses.dataclass(frozen=True)
class EngineInstance:
    """One row per training run. Parity: EngineInstances.scala:26-60.

    ``mesh_conf`` replaces the reference's ``sparkConf`` blob: it records
    the device-mesh topology/sharding config the run used.
    """
    id: str
    status: str              # INIT | TRAINING | COMPLETED | FAILED
    start_time: datetime
    completion_time: datetime
    engine_id: str
    engine_version: str
    engine_variant: str
    engine_factory: str
    batch: str = ""
    env: dict[str, str] = dataclasses.field(default_factory=dict)
    mesh_conf: dict[str, Any] = dataclasses.field(default_factory=dict)
    data_source_params: str = ""
    preparator_params: str = ""
    algorithms_params: str = ""
    serving_params: str = ""


@dataclasses.dataclass(frozen=True)
class EvaluationInstance:
    """One row per evaluation run. Parity: EvaluationInstances.scala:42-60."""
    id: str
    #: INIT | EVALUATING | EVALCOMPLETED | FAILED — EVALUATING rows
    #: carry the partial grid (readable mid-run), FAILED rows carry
    #: the crash that would otherwise strand them at INIT forever
    status: str
    start_time: datetime
    completion_time: datetime
    evaluation_class: str = ""
    engine_params_generator_class: str = ""
    batch: str = ""
    env: dict[str, str] = dataclasses.field(default_factory=dict)
    mesh_conf: dict[str, Any] = dataclasses.field(default_factory=dict)
    evaluator_results: str = ""
    evaluator_results_html: str = ""
    evaluator_results_json: str = ""


@dataclasses.dataclass(frozen=True)
class Model:
    """A serialized model blob keyed by engine-instance id.
    Parity: Models.scala:33-41."""
    id: str
    models: bytes


# ---------------------------------------------------------------------------
# Event query filter
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class EventFilter:
    """The find() filter set shared by local and parallel reads.
    Parity: LEvents.futureFind params (LEvents.scala:188-214) and
    PEvents.find (PEvents.scala:80-103)."""
    start_time: datetime | None = None        # inclusive
    until_time: datetime | None = None        # exclusive
    entity_type: str | None = None
    entity_id: str | None = None
    event_names: Sequence[str] | None = None
    target_entity_type: str | None | type(...) = ...  # ... = any; None = must be absent
    target_entity_id: str | None | type(...) = ...
    limit: int | None = None                  # None = all; reference used -1 for all
    reversed: bool = False                    # newest first (needs entity filter in ref)

    def __post_init__(self):
        # Normalize naive bounds to UTC exactly like Event.__post_init__,
        # so every backend interprets the same filter identically.
        from datetime import timezone

        for name in ("start_time", "until_time"):
            t = getattr(self, name)
            if t is not None and t.tzinfo is None:
                object.__setattr__(self, name, t.replace(tzinfo=timezone.utc))

    def matches(self, e: Event) -> bool:
        if self.start_time is not None and e.event_time < self.start_time:
            return False
        if self.until_time is not None and e.event_time >= self.until_time:
            return False
        if self.entity_type is not None and e.entity_type != self.entity_type:
            return False
        if self.entity_id is not None and e.entity_id != self.entity_id:
            return False
        if self.event_names is not None and e.event not in self.event_names:
            return False
        if self.target_entity_type is not ... and e.target_entity_type != self.target_entity_type:
            return False
        if self.target_entity_id is not ... and e.target_entity_id != self.target_entity_id:
            return False
        return True


# ---------------------------------------------------------------------------
# DAO interfaces
# ---------------------------------------------------------------------------

class Events(abc.ABC):
    """Event CRUD + queries for one storage backend.

    Parity: LEvents trait (LEvents.scala:40-512). Implementations are keyed
    by (app_id, channel_id); channel_id None = default channel.
    """

    @abc.abstractmethod
    def init(self, app_id: int, channel_id: int | None = None) -> bool:
        """Create the backing table/namespace for an app/channel (LEvents.scala:53)."""

    @abc.abstractmethod
    def remove(self, app_id: int, channel_id: int | None = None) -> bool:
        """Drop all events of an app/channel (LEvents.scala:61)."""

    @abc.abstractmethod
    def close(self) -> None:
        """Release client connections (LEvents.scala:69)."""

    @abc.abstractmethod
    def insert(self, event: Event, app_id: int, channel_id: int | None = None) -> str:
        """Insert one event, returning its id (LEvents.scala:79-88)."""

    def insert_batch(
        self, events: Sequence[Event], app_id: int, channel_id: int | None = None
    ) -> list[str]:
        """Insert many events, returning ids (LEvents.scala:106-115)."""
        return [self.insert(e, app_id, channel_id) for e in events]

    @abc.abstractmethod
    def get(self, event_id: str, app_id: int, channel_id: int | None = None) -> Event | None:
        """Get event by id (LEvents.scala:131)."""

    @abc.abstractmethod
    def delete(self, event_id: str, app_id: int, channel_id: int | None = None) -> bool:
        """Delete event by id, returning whether it existed (LEvents.scala:147)."""

    @abc.abstractmethod
    def find(
        self,
        app_id: int,
        channel_id: int | None = None,
        filter: EventFilter = EventFilter(),
    ) -> Iterator[Event]:
        """Filtered scan (LEvents.futureFind, LEvents.scala:188-214)."""

    #: default ``find_columnar`` batch size — large enough that the
    #: per-batch fixed cost (vocab build, array allocation) amortizes,
    #: small enough that a batch stays cache- and memory-friendly
    COLUMNAR_BATCH_SIZE = 4096

    #: the granularity (in µs) of this backend's ``(eventTime, id)``
    #: total order — the tail-cursor comparison key
    #: (online/follower.resume_columnar) must mirror the backend's OWN
    #: sort, not invent a finer one that would mis-split equal-time
    #: ties. µs for stores that order on exact instants (memory, the
    #: SQL text format); the binary event log overrides to 1000 (its
    #: payload order is the ms-truncated wire spelling). Conformance:
    #: tests/test_storage_conformance.py::TestColumnarCursorResume.
    CURSOR_TIME_RESOLUTION_US = 1

    def find_columnar(
        self,
        app_id: int,
        channel_id: int | None = None,
        filter: EventFilter = EventFilter(),
        batch_size: int = COLUMNAR_BATCH_SIZE,
    ) -> "Iterator[EventColumns]":
        """Filtered scan as struct-of-arrays batches (core/columns.py):
        the training-read path of the columnar data plane (the role
        PEvents' RDD reads play in the reference, PEvents.scala:80-103).

        Contract: concatenating the yielded batches reproduces EXACTLY
        the event sequence ``find`` returns for the same filter — order,
        ties, and limit cuts included (pinned per backend by the
        conformance suite). This generic implementation chunks ``find``
        through the rows->columns builder; backends with a cheaper
        native representation (memory, sqlite, binevents) override it.
        """
        from predictionio_tpu.core.columns import iter_batches

        return iter_batches(self.find(app_id, channel_id, filter), batch_size)

    def aggregate_properties(
        self,
        app_id: int,
        entity_type: str,
        channel_id: int | None = None,
        start_time: datetime | None = None,
        until_time: datetime | None = None,
        required: Sequence[str] | None = None,
    ) -> dict[str, PropertyMap]:
        """Aggregate $set/$unset/$delete into per-entity PropertyMaps
        (LEvents.futureAggregateProperties, LEvents.scala:215-260)."""
        from predictionio_tpu.core.aggregation import (
            AGGREGATION_EVENT_NAMES,
            aggregate_properties,
        )

        events = self.find(
            app_id,
            channel_id,
            EventFilter(
                start_time=start_time,
                until_time=until_time,
                entity_type=entity_type,
                event_names=list(AGGREGATION_EVENT_NAMES),
            ),
        )
        result = aggregate_properties(events)
        if required:
            result = {
                k: v for k, v in result.items() if all(v.contains(r) for r in required)
            }
        return result

    def find_single_entity(
        self,
        app_id: int,
        entity_type: str,
        entity_id: str,
        channel_id: int | None = None,
        event_names: Sequence[str] | None = None,
        target_entity_type: str | None | type(...) = ...,
        target_entity_id: str | None | type(...) = ...,
        start_time: datetime | None = None,
        until_time: datetime | None = None,
        limit: int | None = None,
        latest: bool = True,
    ) -> Iterator[Event]:
        """Time-descending single-entity read used at serving time
        (LEvents.findSingleEntity, LEvents.scala:414-459)."""
        return self.find(
            app_id,
            channel_id,
            EventFilter(
                start_time=start_time,
                until_time=until_time,
                entity_type=entity_type,
                entity_id=entity_id,
                event_names=event_names,
                target_entity_type=target_entity_type,
                target_entity_id=target_entity_id,
                limit=limit,
                reversed=latest,
            ),
        )


class Apps(abc.ABC):
    """App metadata DAO. Parity: Apps trait (Apps.scala:43-61)."""

    @abc.abstractmethod
    def insert(self, app: App) -> int | None:
        """Insert; id 0 means auto-assign. Returns assigned id."""

    @abc.abstractmethod
    def get(self, app_id: int) -> App | None: ...

    @abc.abstractmethod
    def get_by_name(self, name: str) -> App | None: ...

    @abc.abstractmethod
    def get_all(self) -> list[App]: ...

    @abc.abstractmethod
    def update(self, app: App) -> None: ...

    @abc.abstractmethod
    def delete(self, app_id: int) -> None: ...


class AccessKeys(abc.ABC):
    """Access-key DAO. Parity: AccessKeys trait (AccessKeys.scala:46-77)."""

    @abc.abstractmethod
    def insert(self, access_key: AccessKey) -> str | None:
        """Insert; empty key means generate. Returns the key."""

    @abc.abstractmethod
    def get(self, key: str) -> AccessKey | None: ...

    @abc.abstractmethod
    def get_all(self) -> list[AccessKey]: ...

    @abc.abstractmethod
    def get_by_app_id(self, app_id: int) -> list[AccessKey]: ...

    @abc.abstractmethod
    def update(self, access_key: AccessKey) -> None: ...

    @abc.abstractmethod
    def delete(self, key: str) -> None: ...

    @staticmethod
    def generate_key() -> str:
        """64 url-safe chars (AccessKeys.generateKey hashes a UUID to
        base64, AccessKeys.scala:68-76)."""
        return secrets.token_urlsafe(48)[:64]


class Channels(abc.ABC):
    """Channel DAO. Parity: Channels trait (Channels.scala:70-82)."""

    @abc.abstractmethod
    def insert(self, channel: Channel) -> int | None:
        """Insert; id 0 means auto-assign. Returns assigned id."""

    @abc.abstractmethod
    def get(self, channel_id: int) -> Channel | None: ...

    @abc.abstractmethod
    def get_by_app_id(self, app_id: int) -> list[Channel]: ...

    @abc.abstractmethod
    def delete(self, channel_id: int) -> None: ...


class EngineInstances(abc.ABC):
    """Engine-instance DAO. Parity: EngineInstances trait
    (EngineInstances.scala:69-110)."""

    @abc.abstractmethod
    def insert(self, instance: EngineInstance) -> str:
        """Insert with auto-assigned id; returns id."""

    @abc.abstractmethod
    def get(self, instance_id: str) -> EngineInstance | None: ...

    @abc.abstractmethod
    def get_all(self) -> list[EngineInstance]: ...

    def get_latest_completed(
        self, engine_id: str, engine_version: str, engine_variant: str
    ) -> EngineInstance | None:
        """Parity: EngineInstances.getLatestCompleted (:82-88)."""
        completed = self.get_completed(engine_id, engine_version, engine_variant)
        return completed[0] if completed else None

    @abc.abstractmethod
    def get_completed(
        self, engine_id: str, engine_version: str, engine_variant: str
    ) -> list[EngineInstance]:
        """COMPLETED instances, newest startTime first (:90-96)."""

    @abc.abstractmethod
    def update(self, instance: EngineInstance) -> None: ...

    @abc.abstractmethod
    def delete(self, instance_id: str) -> None: ...


class EvaluationInstances(abc.ABC):
    """Evaluation-instance DAO. Parity: EvaluationInstances trait
    (EvaluationInstances.scala:62-95)."""

    @abc.abstractmethod
    def insert(self, instance: EvaluationInstance) -> str: ...

    @abc.abstractmethod
    def get(self, instance_id: str) -> EvaluationInstance | None: ...

    @abc.abstractmethod
    def get_all(self) -> list[EvaluationInstance]: ...

    @abc.abstractmethod
    def get_completed(self) -> list[EvaluationInstance]:
        """EVALCOMPLETED instances, newest first."""

    @abc.abstractmethod
    def update(self, instance: EvaluationInstance) -> None: ...

    @abc.abstractmethod
    def delete(self, instance_id: str) -> None: ...


class Models(abc.ABC):
    """Model-blob DAO. Parity: Models trait (Models.scala:43-60)."""

    @abc.abstractmethod
    def insert(self, model: Model) -> None: ...

    @abc.abstractmethod
    def get(self, model_id: str) -> Model | None: ...

    @abc.abstractmethod
    def delete(self, model_id: str) -> None: ...


class BaseStorageClient(abc.ABC):
    """A connection to one configured storage source.

    Parity: BaseStorageClient (Storage.scala:39-53). Backends subclass this
    and expose DAO factory methods for the repositories they support; a
    NotImplementedError mirrors the reference's reflection failure for a
    (backend, trait) pair the backend doesn't provide (e.g. localfs only
    stores models, storage/localfs/.../LocalFSModels.scala)."""

    def __init__(self, config: "StorageClientConfig"):
        self.config = config

    prefix: str = ""

    def events(self) -> Events:
        raise NotImplementedError(f"{type(self).__name__} does not support event data")

    def apps(self) -> Apps:
        raise NotImplementedError(f"{type(self).__name__} does not support metadata")

    def access_keys(self) -> AccessKeys:
        raise NotImplementedError(f"{type(self).__name__} does not support metadata")

    def channels(self) -> Channels:
        raise NotImplementedError(f"{type(self).__name__} does not support metadata")

    def engine_instances(self) -> EngineInstances:
        raise NotImplementedError(f"{type(self).__name__} does not support metadata")

    def evaluation_instances(self) -> EvaluationInstances:
        raise NotImplementedError(f"{type(self).__name__} does not support metadata")

    def models(self) -> Models:
        raise NotImplementedError(f"{type(self).__name__} does not support model data")

    def close(self) -> None:
        pass


@dataclasses.dataclass(frozen=True)
class StorageClientConfig:
    """Per-source config parsed from env (Storage.scala:78-81)."""
    parallel: bool = False
    test: bool = False
    properties: dict[str, str] = dataclasses.field(default_factory=dict)
