"""Embedded SQL storage backend (sqlite3) — the "jdbc" analogue.

Mirrors the reference's JDBC backend design
(reference: storage/jdbc/src/main/scala/.../jdbc/{StorageClient,JDBCLEvents,
JDBCPEvents,JDBCUtils,JDBCApps,JDBCAccessKeys,JDBCChannels,
JDBCEngineInstances,JDBCEvaluationInstances,JDBCModels}.scala): one event
table per (app, channel) named ``pio_event_<app>[_<channel>]``
(JDBCUtils.eventTableName), metadata tables ``pio_meta_*``, model blobs in
``pio_model_data``. Implemented on Python's stdlib sqlite3 with WAL mode;
serves as both the embedded default store and the conformance model for
external SQL backends.
"""

from __future__ import annotations

import json
import os
import queue
import sqlite3
import threading
import uuid
from contextlib import contextmanager
from datetime import datetime
from typing import Iterator

from predictionio_tpu.core.datamap import DataMap
from predictionio_tpu.core.event import Event
from predictionio_tpu.core.json_codec import format_datetime, parse_datetime
from predictionio_tpu.storage import base
from predictionio_tpu.storage.base import (
    AccessKey,
    App,
    Channel,
    EngineInstance,
    EvaluationInstance,
    EventFilter,
    Model,
    StorageClientConfig,
)


def event_table_name(app_id: int, channel_id: int | None) -> str:
    """Parity: JDBCUtils.eventTableName."""
    suffix = f"_{channel_id}" if channel_id is not None else ""
    return f"pio_event_{app_id}{suffix}"


class _Connection:
    """A bounded connection pool over one sqlite database.

    Per-request threads (ThreadingHTTPServer spawns one per request) borrow
    a pooled connection instead of opening their own, so connection count
    is bounded regardless of thread churn. ``:memory:`` databases use a
    single shared connection (a second connection would see a different,
    empty database).
    """

    POOL_SIZE = 8

    def __init__(self, path: str):
        self.path = path
        self._closed = False
        if path != ":memory:":
            os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        self._pool: "queue.Queue[sqlite3.Connection]" = queue.Queue()
        self._created = 0
        self._created_lock = threading.Lock()
        self._max = 1 if path == ":memory:" else self.POOL_SIZE

    def _new_conn(self) -> sqlite3.Connection:
        # check_same_thread=False: connections move between borrowing
        # threads, but only one thread uses a connection at a time.
        conn = sqlite3.connect(self.path, timeout=30.0, check_same_thread=False)
        if self.path != ":memory:":
            conn.execute("PRAGMA journal_mode=WAL")
            conn.execute("PRAGMA synchronous=NORMAL")
        return conn

    @contextmanager
    def _borrow(self):
        if self._closed:
            raise sqlite3.ProgrammingError("storage connection is closed")
        conn: sqlite3.Connection | None = None
        try:
            conn = self._pool.get_nowait()
        except queue.Empty:
            with self._created_lock:
                below_cap = self._created < self._max
                if below_cap:
                    self._created += 1
            if below_cap:
                try:
                    conn = self._new_conn()
                except Exception:
                    with self._created_lock:
                        self._created -= 1  # free the slot for a retry
                    raise
            else:
                conn = self._pool.get(timeout=60)
        returnable = True
        try:
            yield conn
        except BaseException:
            # never return a connection with a half-applied transaction
            try:
                conn.rollback()
            except sqlite3.Error:
                returnable = False
            raise
        finally:
            if self._closed or not returnable:
                conn.close()
                if not returnable:
                    with self._created_lock:
                        self._created -= 1
            else:
                self._pool.put(conn)

    def execute(self, sql: str, params: tuple = ()) -> list[tuple]:
        with self._borrow() as conn:
            cur = conn.execute(sql, params)
            rows = cur.fetchall()
            conn.commit()
            return rows

    def executemany(self, sql: str, seq: list[tuple]) -> None:
        with self._borrow() as conn:
            conn.executemany(sql, seq)
            conn.commit()

    @property
    def can_stream(self) -> bool:
        """Streaming holds a pooled connection across the consumer's
        whole scan loop; on a single-connection pool (``:memory:``)
        any nested DAO call from inside that loop would starve waiting
        for the one connection — such pools must take the buffered
        read path instead."""
        return self._max > 1

    def execute_stream(self, sql: str, params: tuple = (),
                       arraysize: int = 1024):
        """One query, rows yielded in ``fetchmany``-sized chunks while
        the borrowed connection is held — the columnar scan's streaming
        read (a full ``fetchall`` would hold every row of a training
        scan in Python lists at once). The generator must be exhausted
        or closed for the connection to return to the pool; closing it
        early (consumer break) releases via GeneratorExit. Callers must
        honor :attr:`can_stream` (see there for the pool hazard)."""
        with self._borrow() as conn:
            cur = conn.execute(sql, params)
            while True:
                rows = cur.fetchmany(arraysize)
                if not rows:
                    break
                yield rows
            conn.commit()

    def close(self) -> None:
        self._closed = True
        while True:
            try:
                self._pool.get_nowait().close()
            except queue.Empty:
                break


def _is_no_table(err: sqlite3.OperationalError) -> bool:
    return "no such table" in str(err)


_EVENT_COLUMNS = (
    "id, event, entityType, entityId, targetEntityType, targetEntityId, "
    "properties, eventTime, tags, prId, creationTime"
)


def _fmt_utc(t: datetime) -> str:
    """Storage time format: UTC, fixed-width microseconds — lexicographic
    order equals instant order, and no precision is lost (the millisecond
    wire format in json_codec is only for the REST API)."""
    from datetime import timezone

    return t.astimezone(timezone.utc).strftime("%Y-%m-%dT%H:%M:%S.%fZ")


def _event_to_row(event_id: str, e: Event) -> tuple:
    return (
        event_id,
        e.event,
        e.entity_type,
        e.entity_id,
        e.target_entity_type,
        e.target_entity_id,
        json.dumps(e.properties.to_json()),
        _fmt_utc(e.event_time),
        json.dumps(list(e.tags)),
        e.pr_id,
        _fmt_utc(e.creation_time),
    )


def _row_to_event(row: tuple) -> Event:
    return Event(
        event_id=row[0],
        event=row[1],
        entity_type=row[2],
        entity_id=row[3],
        target_entity_type=row[4],
        target_entity_id=row[5],
        properties=DataMap.from_json(json.loads(row[6])),
        event_time=parse_datetime(row[7]),
        tags=tuple(json.loads(row[8])),
        pr_id=row[9],
        creation_time=parse_datetime(row[10]),
    )


def _times_to_us(raw: list[str]) -> "np.ndarray":
    """Vectorized fixed-width-UTC text -> int64 epoch-micros. The
    storage format (``_fmt_utc``) is always ``...%fZ``; anything else
    (hand-written rows) falls back to per-row ISO parsing. The Z check
    must come FIRST: blindly stripping the last char of a non-Z string
    can still parse (dropping a fractional digit) and return a silently
    wrong instant instead of a ValueError."""
    import numpy as np

    arr = np.asarray(raw)
    if bool(np.all(np.char.endswith(arr, "Z"))):
        try:
            return (np.char.rstrip(arr, "Z")
                    .astype("datetime64[us]").astype(np.int64))
        except ValueError:
            pass
    from predictionio_tpu.core.columns import datetime_to_us

    return np.asarray([datetime_to_us(parse_datetime(s)) for s in raw],
                      dtype=np.int64)


def _rows_to_columns(rows: list[tuple]):
    """One fetchmany chunk -> EventColumns, no Event materialization:
    ``zip(*rows)`` transposes at C speed, the dictionary encoding is the
    C-level ``encode_column``, and properties/tags stay the row's JSON
    text (the lazy column)."""
    from predictionio_tpu.core.columns import EventColumns, encode_column

    (ids, ev_names, etypes, eids, tets, teis, props, times, tags, pr_ids,
     ctimes) = zip(*rows)
    return EventColumns.from_sql_columns(
        times_us=_times_to_us(times),
        event=encode_column(ev_names),
        entity_type=encode_column(etypes),
        entity_id=encode_column(eids),
        target_entity_type=encode_column(tets),
        target_entity_id=encode_column(teis),
        event_ids=ids,
        props_json=props,
        tags_json=tags,
        pr_ids=pr_ids,
        creation_raw=ctimes,
    )


class SQLiteEvents(base.Events):
    """Event DAO on sqlite. Parity: JDBCLEvents.scala:37-289."""

    def __init__(self, conn: _Connection):
        self._conn = conn

    def init(self, app_id: int, channel_id: int | None = None) -> bool:
        t = event_table_name(app_id, channel_id)
        self._conn.execute(
            f"""CREATE TABLE IF NOT EXISTS {t} (
                id TEXT NOT NULL PRIMARY KEY,
                event TEXT NOT NULL,
                entityType TEXT NOT NULL,
                entityId TEXT NOT NULL,
                targetEntityType TEXT,
                targetEntityId TEXT,
                properties TEXT,
                eventTime TEXT NOT NULL,
                tags TEXT,
                prId TEXT,
                creationTime TEXT NOT NULL)"""
        )
        # entity-clustered time-ordered access path, the role the HBase
        # backend gives its rowkey design (HBEventsUtil.scala:84-131).
        # Both indexes end in (eventTime, id) because the scan SQL
        # orders by exactly that pair (the plan-independent tie order,
        # _scan_sql): with id in the index, ordered+limited reads walk
        # the index and skip the temp B-tree sort. Pre-existing tables
        # keep their narrower indexes (IF NOT EXISTS) and simply pay
        # the sort.
        self._conn.execute(
            f"CREATE INDEX IF NOT EXISTS {t}_entity ON {t} "
            "(entityType, entityId, eventTime, id)"
        )
        self._conn.execute(
            f"CREATE INDEX IF NOT EXISTS {t}_time ON {t} (eventTime, id)"
        )
        return True

    def remove(self, app_id: int, channel_id: int | None = None) -> bool:
        self._conn.execute(f"DROP TABLE IF EXISTS {event_table_name(app_id, channel_id)}")
        return True

    def close(self) -> None:
        self._conn.close()

    def insert(self, event: Event, app_id: int, channel_id: int | None = None) -> str:
        event_id = event.event_id or uuid.uuid4().hex
        t = event_table_name(app_id, channel_id)
        sql = (
            f"INSERT OR REPLACE INTO {t} ({_EVENT_COLUMNS}) "
            "VALUES (?,?,?,?,?,?,?,?,?,?,?)"
        )
        row = _event_to_row(event_id, event)
        try:
            self._conn.execute(sql, row)
        except sqlite3.OperationalError as err:
            if not _is_no_table(err):
                raise
            # auto-init on first insert: same contract as the memory backend
            self.init(app_id, channel_id)
            self._conn.execute(sql, row)
        return event_id

    def insert_batch(
        self, events, app_id: int, channel_id: int | None = None
    ) -> list[str]:
        ids = [e.event_id or uuid.uuid4().hex for e in events]
        t = event_table_name(app_id, channel_id)
        sql = (
            f"INSERT OR REPLACE INTO {t} ({_EVENT_COLUMNS}) "
            "VALUES (?,?,?,?,?,?,?,?,?,?,?)"
        )
        rows = [_event_to_row(i, e) for i, e in zip(ids, events)]
        try:
            self._conn.executemany(sql, rows)
        except sqlite3.OperationalError as err:
            if not _is_no_table(err):
                raise
            self.init(app_id, channel_id)
            self._conn.executemany(sql, rows)
        return ids

    def get(self, event_id: str, app_id: int, channel_id: int | None = None) -> Event | None:
        t = event_table_name(app_id, channel_id)
        try:
            rows = self._conn.execute(
                f"SELECT {_EVENT_COLUMNS} FROM {t} WHERE id = ?", (event_id,)
            )
        except sqlite3.OperationalError as err:
            if _is_no_table(err):
                return None
            raise
        return _row_to_event(rows[0]) if rows else None

    def delete(self, event_id: str, app_id: int, channel_id: int | None = None) -> bool:
        t = event_table_name(app_id, channel_id)
        try:
            existed = bool(
                self._conn.execute(f"SELECT 1 FROM {t} WHERE id = ?", (event_id,))
            )
            self._conn.execute(f"DELETE FROM {t} WHERE id = ?", (event_id,))
        except sqlite3.OperationalError as err:
            if _is_no_table(err):
                return False
            raise
        return existed

    @staticmethod
    def _scan_sql(app_id: int, channel_id: int | None,
                  filter: EventFilter) -> tuple[str, tuple]:
        """WHERE-clause assembly parity: JDBCPEvents.find:33-120. Shared
        by the row iterator and the columnar scan so both read the SAME
        sequence (order, ties, limit) from the database."""
        t = event_table_name(app_id, channel_id)
        clauses, params = [], []
        f = filter
        if f.start_time is not None:
            clauses.append("eventTime >= ?")
            params.append(_fmt_utc(f.start_time))
        if f.until_time is not None:
            clauses.append("eventTime < ?")
            params.append(_fmt_utc(f.until_time))
        if f.entity_type is not None:
            clauses.append("entityType = ?")
            params.append(f.entity_type)
        if f.entity_id is not None:
            clauses.append("entityId = ?")
            params.append(f.entity_id)
        if f.event_names is not None:
            placeholders = ",".join("?" * len(f.event_names))
            clauses.append(f"event IN ({placeholders})")
            params.extend(f.event_names)
        if f.target_entity_type is not ...:
            if f.target_entity_type is None:
                clauses.append("targetEntityType IS NULL")
            else:
                clauses.append("targetEntityType = ?")
                params.append(f.target_entity_type)
        if f.target_entity_id is not ...:
            if f.target_entity_id is None:
                clauses.append("targetEntityId IS NULL")
            else:
                clauses.append("targetEntityId = ?")
                params.append(f.target_entity_id)
        where = f" WHERE {' AND '.join(clauses)}" if clauses else ""
        # id tiebreak: equal-timestamp order must not depend on which
        # query plan ran the scan (the planner picks different index
        # strategies for find vs the hinted columnar scan, and SQL
        # gives ties no order at all without this — measured divergence
        # on reversed entity-filtered scans); same contract as the
        # binevents event_id tiebreaker
        order = (" ORDER BY eventTime DESC, id DESC" if f.reversed
                 else " ORDER BY eventTime, id")
        limit = (
            f" LIMIT {int(f.limit)}" if f.limit is not None and f.limit >= 0 else ""
        )
        return (f"SELECT {_EVENT_COLUMNS} FROM {t}{where}{order}{limit}",
                tuple(params))

    def find(
        self,
        app_id: int,
        channel_id: int | None = None,
        filter: EventFilter = EventFilter(),
    ) -> Iterator[Event]:
        sql, params = self._scan_sql(app_id, channel_id, filter)
        try:
            rows = self._conn.execute(sql, params)
        except sqlite3.OperationalError as err:
            if _is_no_table(err):
                return iter(())
            raise
        return (_row_to_event(r) for r in rows)

    def find_columnar(
        self,
        app_id: int,
        channel_id: int | None = None,
        filter: EventFilter = EventFilter(),
        batch_size: int = base.Events.COLUMNAR_BATCH_SIZE,
    ):
        """Native path: ONE SQL scan streamed ``fetchmany`` -> columns.
        Rows become arrays without ``_row_to_event`` — no Event object,
        no properties/tags JSON parse (they stay the row's JSON text in
        the lazy column), and the two timestamps parse vectorized (the
        storage format is fixed-width UTC, ``_fmt_utc``). On a pool
        without ``execute_stream`` (the PostgreSQL adapter's _PGPool,
        storage/postgres.py reuses this DAO) the scan degrades to one
        ``execute`` chunked in Python — same rows, same columns."""
        from predictionio_tpu.core.columns import check_batch_size

        check_batch_size(batch_size)
        return self._find_columnar(app_id, channel_id, filter, batch_size)

    def _find_columnar(self, app_id, channel_id, filter, batch_size):
        sql, params = self._scan_sql(app_id, channel_id, filter)
        stream = getattr(self._conn, "execute_stream", None)
        if stream is not None and not getattr(self._conn, "can_stream", False):
            stream = None   # single-connection pool: see can_stream
        if stream is None:
            try:
                rows = self._conn.execute(sql, params)
            except sqlite3.OperationalError as err:
                if _is_no_table(err):
                    return
                raise
            for at in range(0, len(rows), batch_size):
                yield _rows_to_columns(rows[at:at + batch_size])
            return
        # bulk-scan plan hint (sqlite only — the PG adapter takes the
        # branch above): for a whole-table training read the planner
        # still picks the entity index off an entityType predicate and
        # pays a random rowid lookup per row plus a temp B-tree sort
        # (measured ~3x the sequential scan at 50k rows); NOT INDEXED
        # forces the table scan. Applied when nothing marks the scan
        # selective — no entity_id, no time bounds, no limit.
        # entity_type alone deliberately does NOT disable the hint:
        # a training scan always carries one (every event of a
        # recommendation app is entityType='user', which is precisely
        # the unselective predicate that baited the planner), at the
        # accepted cost that a scan over a genuinely rare entity type
        # also table-scans. Anything else keeps the planner's choice
        # (the extended (…, eventTime, id) indexes serve time ranges
        # and single-entity reads in index order, measured µs-to-ms).
        if (filter.entity_id is None and filter.start_time is None
                and filter.until_time is None and filter.limit is None):
            t = event_table_name(app_id, channel_id)
            sql = sql.replace(f"FROM {t} ", f"FROM {t} NOT INDEXED ", 1)
        try:
            for rows in stream(sql, params, arraysize=batch_size):
                yield _rows_to_columns(rows)
        except sqlite3.OperationalError as err:
            if _is_no_table(err):
                return
            raise


class SQLiteApps(base.Apps):
    def __init__(self, conn: _Connection):
        self._conn = conn
        self._conn.execute(
            """CREATE TABLE IF NOT EXISTS pio_meta_apps (
                id INTEGER PRIMARY KEY AUTOINCREMENT,
                name TEXT NOT NULL UNIQUE,
                description TEXT)"""
        )

    def insert(self, app: App) -> int | None:
        try:
            if app.id > 0:
                self._conn.execute(
                    "INSERT INTO pio_meta_apps (id, name, description) VALUES (?,?,?)",
                    (app.id, app.name, app.description),
                )
                return app.id
            self._conn.execute(
                "INSERT INTO pio_meta_apps (name, description) VALUES (?,?)",
                (app.name, app.description),
            )
            rows = self._conn.execute(
                "SELECT id FROM pio_meta_apps WHERE name = ?", (app.name,)
            )
            return int(rows[0][0])
        except sqlite3.IntegrityError:
            return None

    def get(self, app_id: int) -> App | None:
        rows = self._conn.execute(
            "SELECT id, name, description FROM pio_meta_apps WHERE id = ?", (app_id,)
        )
        return App(*rows[0]) if rows else None

    def get_by_name(self, name: str) -> App | None:
        rows = self._conn.execute(
            "SELECT id, name, description FROM pio_meta_apps WHERE name = ?", (name,)
        )
        return App(*rows[0]) if rows else None

    def get_all(self) -> list[App]:
        return [
            App(*r)
            for r in self._conn.execute(
                "SELECT id, name, description FROM pio_meta_apps ORDER BY id"
            )
        ]

    def update(self, app: App) -> None:
        self._conn.execute(
            "UPDATE pio_meta_apps SET name = ?, description = ? WHERE id = ?",
            (app.name, app.description, app.id),
        )

    def delete(self, app_id: int) -> None:
        self._conn.execute("DELETE FROM pio_meta_apps WHERE id = ?", (app_id,))


class SQLiteAccessKeys(base.AccessKeys):
    def __init__(self, conn: _Connection):
        self._conn = conn
        self._conn.execute(
            """CREATE TABLE IF NOT EXISTS pio_meta_accesskeys (
                accesskey TEXT NOT NULL PRIMARY KEY,
                appid INTEGER NOT NULL,
                events TEXT)"""
        )

    def insert(self, access_key: AccessKey) -> str | None:
        key = access_key.key or self.generate_key()
        try:
            self._conn.execute(
                "INSERT INTO pio_meta_accesskeys (accesskey, appid, events) VALUES (?,?,?)",
                (key, access_key.appid, json.dumps(list(access_key.events))),
            )
            return key
        except sqlite3.IntegrityError:
            return None

    def _row(self, r: tuple) -> AccessKey:
        return AccessKey(r[0], r[1], tuple(json.loads(r[2] or "[]")))

    def get(self, key: str) -> AccessKey | None:
        rows = self._conn.execute(
            "SELECT accesskey, appid, events FROM pio_meta_accesskeys WHERE accesskey = ?",
            (key,),
        )
        return self._row(rows[0]) if rows else None

    def get_all(self) -> list[AccessKey]:
        return [
            self._row(r)
            for r in self._conn.execute(
                "SELECT accesskey, appid, events FROM pio_meta_accesskeys"
            )
        ]

    def get_by_app_id(self, app_id: int) -> list[AccessKey]:
        return [
            self._row(r)
            for r in self._conn.execute(
                "SELECT accesskey, appid, events FROM pio_meta_accesskeys WHERE appid = ?",
                (app_id,),
            )
        ]

    def update(self, access_key: AccessKey) -> None:
        self._conn.execute(
            "UPDATE pio_meta_accesskeys SET appid = ?, events = ? WHERE accesskey = ?",
            (access_key.appid, json.dumps(list(access_key.events)), access_key.key),
        )

    def delete(self, key: str) -> None:
        self._conn.execute(
            "DELETE FROM pio_meta_accesskeys WHERE accesskey = ?", (key,)
        )


class SQLiteChannels(base.Channels):
    def __init__(self, conn: _Connection):
        self._conn = conn
        self._conn.execute(
            """CREATE TABLE IF NOT EXISTS pio_meta_channels (
                id INTEGER PRIMARY KEY AUTOINCREMENT,
                name TEXT NOT NULL,
                appid INTEGER NOT NULL)"""
        )

    def insert(self, channel: Channel) -> int | None:
        if not Channel.is_valid_name(channel.name):
            return None
        try:
            if channel.id > 0:
                self._conn.execute(
                    "INSERT INTO pio_meta_channels (id, name, appid) VALUES (?,?,?)",
                    (channel.id, channel.name, channel.appid),
                )
                return channel.id
            # RETURNING keeps the id fetch on the SAME pooled connection
            # as the insert — a separate `SELECT last_insert_rowid()`
            # call can borrow a different connection and return a stale
            # or zero id (and the function does not exist on PostgreSQL,
            # where this DAO also runs — storage/postgres.py)
            rows = self._conn.execute(
                "INSERT INTO pio_meta_channels (name, appid) VALUES (?,?) "
                "RETURNING id",
                (channel.name, channel.appid),
            )
        except sqlite3.IntegrityError:
            return None
        return int(rows[0][0])

    def get(self, channel_id: int) -> Channel | None:
        rows = self._conn.execute(
            "SELECT id, name, appid FROM pio_meta_channels WHERE id = ?", (channel_id,)
        )
        return Channel(*rows[0]) if rows else None

    def get_by_app_id(self, app_id: int) -> list[Channel]:
        return [
            Channel(*r)
            for r in self._conn.execute(
                "SELECT id, name, appid FROM pio_meta_channels WHERE appid = ?",
                (app_id,),
            )
        ]

    def delete(self, channel_id: int) -> None:
        self._conn.execute("DELETE FROM pio_meta_channels WHERE id = ?", (channel_id,))


class SQLiteEngineInstances(base.EngineInstances):
    def __init__(self, conn: _Connection):
        self._conn = conn
        self._conn.execute(
            """CREATE TABLE IF NOT EXISTS pio_meta_engineinstances (
                id TEXT NOT NULL PRIMARY KEY,
                status TEXT NOT NULL,
                startTime TEXT NOT NULL,
                completionTime TEXT NOT NULL,
                engineId TEXT NOT NULL,
                engineVersion TEXT NOT NULL,
                engineVariant TEXT NOT NULL,
                engineFactory TEXT NOT NULL,
                batch TEXT,
                env TEXT,
                meshConf TEXT,
                dataSourceParams TEXT,
                preparatorParams TEXT,
                algorithmsParams TEXT,
                servingParams TEXT)"""
        )

    _COLS = (
        "id, status, startTime, completionTime, engineId, engineVersion, "
        "engineVariant, engineFactory, batch, env, meshConf, dataSourceParams, "
        "preparatorParams, algorithmsParams, servingParams"
    )

    def _to_row(self, i: EngineInstance) -> tuple:
        return (
            i.id,
            i.status,
            _fmt_utc(i.start_time),
            _fmt_utc(i.completion_time),
            i.engine_id,
            i.engine_version,
            i.engine_variant,
            i.engine_factory,
            i.batch,
            json.dumps(i.env),
            json.dumps(i.mesh_conf),
            i.data_source_params,
            i.preparator_params,
            i.algorithms_params,
            i.serving_params,
        )

    def _from_row(self, r: tuple) -> EngineInstance:
        return EngineInstance(
            id=r[0],
            status=r[1],
            start_time=parse_datetime(r[2]),
            completion_time=parse_datetime(r[3]),
            engine_id=r[4],
            engine_version=r[5],
            engine_variant=r[6],
            engine_factory=r[7],
            batch=r[8] or "",
            env=json.loads(r[9] or "{}"),
            mesh_conf=json.loads(r[10] or "{}"),
            data_source_params=r[11] or "",
            preparator_params=r[12] or "",
            algorithms_params=r[13] or "",
            serving_params=r[14] or "",
        )

    def insert(self, instance: EngineInstance) -> str:
        import dataclasses as _dc

        instance_id = instance.id or uuid.uuid4().hex
        if not instance.id:
            instance = _dc.replace(instance, id=instance_id)
        self._conn.execute(
            f"INSERT OR REPLACE INTO pio_meta_engineinstances ({self._COLS}) "
            "VALUES (?,?,?,?,?,?,?,?,?,?,?,?,?,?,?)",
            self._to_row(instance),
        )
        return instance_id

    def get(self, instance_id: str) -> EngineInstance | None:
        rows = self._conn.execute(
            f"SELECT {self._COLS} FROM pio_meta_engineinstances WHERE id = ?",
            (instance_id,),
        )
        return self._from_row(rows[0]) if rows else None

    def get_all(self) -> list[EngineInstance]:
        return [
            self._from_row(r)
            for r in self._conn.execute(
                f"SELECT {self._COLS} FROM pio_meta_engineinstances"
            )
        ]

    def get_completed(
        self, engine_id: str, engine_version: str, engine_variant: str
    ) -> list[EngineInstance]:
        return [
            self._from_row(r)
            for r in self._conn.execute(
                f"SELECT {self._COLS} FROM pio_meta_engineinstances "
                "WHERE status = 'COMPLETED' AND engineId = ? AND "
                "engineVersion = ? AND engineVariant = ? ORDER BY startTime DESC",
                (engine_id, engine_version, engine_variant),
            )
        ]

    def update(self, instance: EngineInstance) -> None:
        self.insert(instance)

    def delete(self, instance_id: str) -> None:
        self._conn.execute(
            "DELETE FROM pio_meta_engineinstances WHERE id = ?", (instance_id,)
        )


class SQLiteEvaluationInstances(base.EvaluationInstances):
    def __init__(self, conn: _Connection):
        self._conn = conn
        self._conn.execute(
            """CREATE TABLE IF NOT EXISTS pio_meta_evaluationinstances (
                id TEXT NOT NULL PRIMARY KEY,
                status TEXT NOT NULL,
                startTime TEXT NOT NULL,
                completionTime TEXT NOT NULL,
                evaluationClass TEXT,
                engineParamsGeneratorClass TEXT,
                batch TEXT,
                env TEXT,
                meshConf TEXT,
                evaluatorResults TEXT,
                evaluatorResultsHTML TEXT,
                evaluatorResultsJSON TEXT)"""
        )

    _COLS = (
        "id, status, startTime, completionTime, evaluationClass, "
        "engineParamsGeneratorClass, batch, env, meshConf, evaluatorResults, "
        "evaluatorResultsHTML, evaluatorResultsJSON"
    )

    def _to_row(self, i: EvaluationInstance) -> tuple:
        return (
            i.id,
            i.status,
            _fmt_utc(i.start_time),
            _fmt_utc(i.completion_time),
            i.evaluation_class,
            i.engine_params_generator_class,
            i.batch,
            json.dumps(i.env),
            json.dumps(i.mesh_conf),
            i.evaluator_results,
            i.evaluator_results_html,
            i.evaluator_results_json,
        )

    def _from_row(self, r: tuple) -> EvaluationInstance:
        return EvaluationInstance(
            id=r[0],
            status=r[1],
            start_time=parse_datetime(r[2]),
            completion_time=parse_datetime(r[3]),
            evaluation_class=r[4] or "",
            engine_params_generator_class=r[5] or "",
            batch=r[6] or "",
            env=json.loads(r[7] or "{}"),
            mesh_conf=json.loads(r[8] or "{}"),
            evaluator_results=r[9] or "",
            evaluator_results_html=r[10] or "",
            evaluator_results_json=r[11] or "",
        )

    def insert(self, instance: EvaluationInstance) -> str:
        import dataclasses as _dc

        instance_id = instance.id or uuid.uuid4().hex
        if not instance.id:
            instance = _dc.replace(instance, id=instance_id)
        self._conn.execute(
            f"INSERT OR REPLACE INTO pio_meta_evaluationinstances ({self._COLS}) "
            "VALUES (?,?,?,?,?,?,?,?,?,?,?,?)",
            self._to_row(instance),
        )
        return instance_id

    def get(self, instance_id: str) -> EvaluationInstance | None:
        rows = self._conn.execute(
            f"SELECT {self._COLS} FROM pio_meta_evaluationinstances WHERE id = ?",
            (instance_id,),
        )
        return self._from_row(rows[0]) if rows else None

    def get_all(self) -> list[EvaluationInstance]:
        return [
            self._from_row(r)
            for r in self._conn.execute(
                f"SELECT {self._COLS} FROM pio_meta_evaluationinstances"
            )
        ]

    def get_completed(self) -> list[EvaluationInstance]:
        return [
            self._from_row(r)
            for r in self._conn.execute(
                f"SELECT {self._COLS} FROM pio_meta_evaluationinstances "
                "WHERE status = 'EVALCOMPLETED' ORDER BY startTime DESC"
            )
        ]

    def update(self, instance: EvaluationInstance) -> None:
        self.insert(instance)

    def delete(self, instance_id: str) -> None:
        self._conn.execute(
            "DELETE FROM pio_meta_evaluationinstances WHERE id = ?", (instance_id,)
        )


class SQLiteModels(base.Models):
    """Model blobs in SQL. Parity: JDBCModels.scala."""

    def __init__(self, conn: _Connection):
        self._conn = conn
        self._conn.execute(
            """CREATE TABLE IF NOT EXISTS pio_model_data (
                id TEXT NOT NULL PRIMARY KEY,
                models BLOB NOT NULL)"""
        )

    def insert(self, model: Model) -> None:
        self._conn.execute(
            "INSERT OR REPLACE INTO pio_model_data (id, models) VALUES (?,?)",
            (model.id, model.models),
        )

    def get(self, model_id: str) -> Model | None:
        rows = self._conn.execute(
            "SELECT id, models FROM pio_model_data WHERE id = ?", (model_id,)
        )
        return Model(rows[0][0], bytes(rows[0][1])) if rows else None

    def delete(self, model_id: str) -> None:
        self._conn.execute("DELETE FROM pio_model_data WHERE id = ?", (model_id,))


class SQLiteStorageClient(base.BaseStorageClient):
    """All three repositories on one sqlite database file.

    Config properties: PATH (db file; default pio.sqlite in cwd, or
    ":memory:" for tests). Parity role: storage/jdbc StorageClient.scala.
    """

    prefix = "SQLite"

    def __init__(self, config: StorageClientConfig = StorageClientConfig()):
        super().__init__(config)
        path = config.properties.get("PATH", "pio.sqlite")
        if config.test and "PATH" not in config.properties:
            path = ":memory:"
        self._conn = _Connection(path)
        self._lock = threading.RLock()
        self._cache: dict[str, object] = {}

    def _cached(self, key: str, factory):
        with self._lock:
            if key not in self._cache:
                self._cache[key] = factory(self._conn)
            return self._cache[key]

    def events(self) -> SQLiteEvents:
        return self._cached("events", SQLiteEvents)

    def apps(self) -> SQLiteApps:
        return self._cached("apps", SQLiteApps)

    def access_keys(self) -> SQLiteAccessKeys:
        return self._cached("access_keys", SQLiteAccessKeys)

    def channels(self) -> SQLiteChannels:
        return self._cached("channels", SQLiteChannels)

    def engine_instances(self) -> SQLiteEngineInstances:
        return self._cached("engine_instances", SQLiteEngineInstances)

    def evaluation_instances(self) -> SQLiteEvaluationInstances:
        return self._cached("evaluation_instances", SQLiteEvaluationInstances)

    def models(self) -> SQLiteModels:
        return self._cached("models", SQLiteModels)

    def close(self) -> None:
        self._conn.close()
