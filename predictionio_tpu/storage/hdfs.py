"""Network-filesystem model storage backend (the reference's HDFS role).

Parity: storage/hdfs/src/main/scala/.../hdfs/{StorageClient,
HDFSModels}.scala:31-60 — model blobs under a configured distributed
filesystem path. The reference reached HDFS through the Hadoop
``FileSystem`` client; the TPU-native deployment story is a mounted
network filesystem (NFS / GCS-FUSE / Lustre on Cloud TPU VMs), so this
backend addresses the store by path like ``localfs`` but adds the
durability discipline a shared filesystem needs:

- writes go to a tempfile, are fsync'd, then atomically renamed;
- the directory entry is fsync'd after rename so the blob survives a
  host crash (NFS close-to-open consistency makes this observable to
  other hosts — e.g. a trainer writing a model that a serving host on
  another VM loads);
- every operation routes through ``resilient()``: ESTALE/EIO-class
  transient errors retry with jittered backoff under the shared
  RetryPolicy (replacing the old hand-rolled retry-once) and feed the
  per-source circuit breaker.

Config properties: ``PATH`` (mount-point directory; default
``~/.pio_store/hdfs_models``), ``PREFIX`` (file-name prefix), plus the
``RETRY_*``/``BREAKER_*`` resilience knobs
(docs/operations-resilience.md).
"""

from __future__ import annotations

import errno
import os

from predictionio_tpu.storage import base
from predictionio_tpu.storage.base import Model, StorageClientConfig
from predictionio_tpu.utils.resilience import Resilience, resilient

#: errno values a shared network filesystem emits transiently (stale NFS
#: handle between open and read; EIO on a flapping mount)
_TRANSIENT_ERRNOS = (errno.ESTALE, errno.EIO)


def _is_transient_fs_error(exc: BaseException) -> bool:
    return isinstance(exc, OSError) and exc.errno in _TRANSIENT_ERRNOS


def _fsync_dir(path: str) -> None:
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return  # some filesystems refuse O_RDONLY on dirs; rename already done
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


class NetworkFSModels(base.Models):
    def __init__(self, path: str, prefix: str = "",
                 resilience: Resilience | None = None):
        self._path = path
        self._prefix = prefix
        self._resilience = resilience or Resilience(
            "hdfs", classify=_is_transient_fs_error)
        os.makedirs(path, exist_ok=True)

    def _file(self, model_id: str) -> str:
        safe = model_id.replace("/", "_").replace("..", "_")
        return os.path.join(self._path, f"{self._prefix}{safe}")

    def insert(self, model: Model) -> None:
        resilient(self._resilience, self._write, model)

    def _write(self, model: Model) -> None:
        target = self._file(model.id)
        tmp = target + ".tmp"
        with open(tmp, "wb") as f:
            f.write(model.models)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, target)
        _fsync_dir(self._path)

    def get(self, model_id: str) -> Model | None:
        return resilient(self._resilience, self._read, model_id)

    def _read(self, model_id: str) -> Model | None:
        try:
            with open(self._file(model_id), "rb") as f:
                return Model(model_id, f.read())
        except FileNotFoundError:
            return None

    def delete(self, model_id: str) -> None:
        resilient(self._resilience, self._remove, model_id)

    def _remove(self, model_id: str) -> None:
        try:
            os.remove(self._file(model_id))
        except FileNotFoundError:
            pass
        _fsync_dir(self._path)


class HDFSStorageClient(base.BaseStorageClient):
    """Config properties: PATH (mounted network-FS dir), PREFIX."""

    prefix = "HDFS"

    def __init__(self, config: StorageClientConfig = StorageClientConfig()):
        super().__init__(config)
        props = config.properties
        path = props.get(
            "PATH",
            os.path.join(os.path.expanduser("~"), ".pio_store", "hdfs_models"),
        )
        source = props.get("SOURCE_NAME", os.path.abspath(path))
        self._models = NetworkFSModels(
            os.path.abspath(path), props.get("PREFIX", ""),
            resilience=Resilience.from_properties(
                f"hdfs/{source}", props, classify=_is_transient_fs_error),
        )

    def models(self) -> NetworkFSModels:
        return self._models
