"""Network-filesystem model storage backend (the reference's HDFS role).

Parity: storage/hdfs/src/main/scala/.../hdfs/{StorageClient,
HDFSModels}.scala:31-60 — model blobs under a configured distributed
filesystem path. The reference reached HDFS through the Hadoop
``FileSystem`` client; the TPU-native deployment story is a mounted
network filesystem (NFS / GCS-FUSE / Lustre on Cloud TPU VMs), so this
backend addresses the store by path like ``localfs`` but adds the
durability discipline a shared filesystem needs:

- writes go to a tempfile, are fsync'd, then atomically renamed;
- the directory entry is fsync'd after rename so the blob survives a
  host crash (NFS close-to-open consistency makes this observable to
  other hosts — e.g. a trainer writing a model that a serving host on
  another VM loads);
- reads retry once on ESTALE-style transient errors.

Config properties: ``PATH`` (mount-point directory; default
``~/.pio_store/hdfs_models``), ``PREFIX`` (file-name prefix).
"""

from __future__ import annotations

import errno
import os

from predictionio_tpu.storage import base
from predictionio_tpu.storage.base import Model, StorageClientConfig


def _fsync_dir(path: str) -> None:
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return  # some filesystems refuse O_RDONLY on dirs; rename already done
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


class NetworkFSModels(base.Models):
    def __init__(self, path: str, prefix: str = ""):
        self._path = path
        self._prefix = prefix
        os.makedirs(path, exist_ok=True)

    def _file(self, model_id: str) -> str:
        safe = model_id.replace("/", "_").replace("..", "_")
        return os.path.join(self._path, f"{self._prefix}{safe}")

    def insert(self, model: Model) -> None:
        target = self._file(model.id)
        tmp = target + ".tmp"
        with open(tmp, "wb") as f:
            f.write(model.models)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, target)
        _fsync_dir(self._path)

    def get(self, model_id: str) -> Model | None:
        for attempt in (0, 1):
            try:
                with open(self._file(model_id), "rb") as f:
                    return Model(model_id, f.read())
            except FileNotFoundError:
                return None
            except OSError as exc:
                # NFS handle went stale between open and read — retry once
                if attempt == 0 and exc.errno in (errno.ESTALE, errno.EIO):
                    continue
                raise
        return None

    def delete(self, model_id: str) -> None:
        try:
            os.remove(self._file(model_id))
        except FileNotFoundError:
            pass
        _fsync_dir(self._path)


class HDFSStorageClient(base.BaseStorageClient):
    """Config properties: PATH (mounted network-FS dir), PREFIX."""

    prefix = "HDFS"

    def __init__(self, config: StorageClientConfig = StorageClientConfig()):
        super().__init__(config)
        path = config.properties.get(
            "PATH",
            os.path.join(os.path.expanduser("~"), ".pio_store", "hdfs_models"),
        )
        self._models = NetworkFSModels(
            os.path.abspath(path), config.properties.get("PREFIX", "")
        )

    def models(self) -> NetworkFSModels:
        return self._models
