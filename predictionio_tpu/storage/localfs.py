"""Local-filesystem model storage backend.

Parity: storage/localfs/src/main/scala/.../localfs/{StorageClient,
LocalFSModels}.scala:32-61 — one file per model blob under a configured
directory. This is also where orbax sharded checkpoints live when an
algorithm opts into sharded persistence (see controller/persistence).
"""

from __future__ import annotations

import os

from predictionio_tpu.storage import base
from predictionio_tpu.storage.base import Model, StorageClientConfig


class LocalFSModels(base.Models):
    def __init__(self, path: str, prefix: str = ""):
        self._path = path
        self._prefix = prefix
        os.makedirs(path, exist_ok=True)

    def _file(self, model_id: str) -> str:
        # model ids are uuid hex / instance ids; keep paths safe anyway
        safe = model_id.replace("/", "_").replace("..", "_")
        return os.path.join(self._path, f"{self._prefix}{safe}")

    def insert(self, model: Model) -> None:
        tmp = self._file(model.id) + ".tmp"
        with open(tmp, "wb") as f:
            f.write(model.models)
        os.replace(tmp, self._file(model.id))

    def get(self, model_id: str) -> Model | None:
        try:
            with open(self._file(model_id), "rb") as f:
                return Model(model_id, f.read())
        except FileNotFoundError:
            return None

    def delete(self, model_id: str) -> None:
        try:
            os.remove(self._file(model_id))
        except FileNotFoundError:
            pass


class LocalFSStorageClient(base.BaseStorageClient):
    """Config properties: PATH (directory; default ~/.pio_store/models)."""

    prefix = "LocalFS"

    def __init__(self, config: StorageClientConfig = StorageClientConfig()):
        super().__init__(config)
        path = config.properties.get(
            "PATH", os.path.join(os.path.expanduser("~"), ".pio_store", "models")
        )
        self._models = LocalFSModels(os.path.abspath(path))

    def models(self) -> LocalFSModels:
        return self._models
