"""In-memory storage backend — the test/dev backend.

The reference has no in-memory backend (its tests hit live dockerized
stores, SURVEY.md §4.2); this one exists so unit tests and quickstarts
run with zero services, while the same conformance suite also runs
against sqlite (tests/test_storage_conformance.py).
"""

from __future__ import annotations

import dataclasses
import threading
import uuid
from typing import Iterator

from predictionio_tpu.core.event import Event
from predictionio_tpu.storage import base
from predictionio_tpu.storage.base import (
    AccessKey,
    App,
    Channel,
    EngineInstance,
    EvaluationInstance,
    EventFilter,
    Model,
    StorageClientConfig,
)


def _sort_and_limit(events: list[Event], filter: EventFilter) -> list[Event]:
    # id tiebreak: equal-timestamp order must be a property of the DATA,
    # not of dict insertion order — the (eventTime, id) total order every
    # other backend pins (sqlite ORDER BY, binevents/fileevents sort
    # keys) is what the online tail's cursor resume stands on
    # (TestColumnarCursorResume)
    events.sort(key=lambda e: (e.event_time, e.event_id or ""),
                reverse=filter.reversed)
    if filter.limit is not None and filter.limit >= 0:
        events = events[: filter.limit]
    return events


class MemoryEvents(base.Events):
    def __init__(self):
        self._tables: dict[tuple[int, int | None], dict[str, Event]] = {}
        self._lock = threading.RLock()

    def init(self, app_id: int, channel_id: int | None = None) -> bool:
        with self._lock:
            self._tables.setdefault((app_id, channel_id), {})
        return True

    def remove(self, app_id: int, channel_id: int | None = None) -> bool:
        with self._lock:
            return self._tables.pop((app_id, channel_id), None) is not None

    def close(self) -> None:
        pass

    def insert(self, event: Event, app_id: int, channel_id: int | None = None) -> str:
        event_id = event.event_id or uuid.uuid4().hex
        with self._lock:
            self._tables.setdefault((app_id, channel_id), {})
            self._tables[(app_id, channel_id)][event_id] = event.with_event_id(event_id)
        return event_id

    def insert_batch(
        self, events, app_id: int, channel_id: int | None = None
    ) -> list[str]:
        # one lock acquisition per batch (the transactional analogue of
        # sqlite's single-commit executemany): a concurrent reader sees
        # the whole batch or none of it
        ids = [e.event_id or uuid.uuid4().hex for e in events]
        with self._lock:
            table = self._tables.setdefault((app_id, channel_id), {})
            for event_id, e in zip(ids, events):
                table[event_id] = e.with_event_id(event_id)
        return ids

    def get(self, event_id: str, app_id: int, channel_id: int | None = None) -> Event | None:
        with self._lock:
            return self._tables.get((app_id, channel_id), {}).get(event_id)

    def delete(self, event_id: str, app_id: int, channel_id: int | None = None) -> bool:
        with self._lock:
            return self._tables.get((app_id, channel_id), {}).pop(event_id, None) is not None

    def find(
        self,
        app_id: int,
        channel_id: int | None = None,
        filter: EventFilter = EventFilter(),
    ) -> Iterator[Event]:
        with self._lock:
            events = [
                e
                for e in self._tables.get((app_id, channel_id), {}).values()
                if filter.matches(e)
            ]
        return iter(_sort_and_limit(events, filter))

    def find_columnar(
        self,
        app_id: int,
        channel_id: int | None = None,
        filter: EventFilter = EventFilter(),
        batch_size: int = base.Events.COLUMNAR_BATCH_SIZE,
    ):
        """Native path: one lock acquisition + one filter/sort pass over
        the table, then a direct single-pass array build per batch —
        no per-batch re-entry into ``find`` and no iterator hops."""
        from predictionio_tpu.core.columns import check_batch_size

        check_batch_size(batch_size)
        return self._find_columnar(app_id, channel_id, filter, batch_size)

    def _find_columnar(self, app_id, channel_id, filter, batch_size):
        from predictionio_tpu.core.columns import EventColumns

        with self._lock:
            events = [
                e
                for e in self._tables.get((app_id, channel_id), {}).values()
                if filter.matches(e)
            ]
        events = _sort_and_limit(events, filter)
        for at in range(0, len(events), batch_size):
            yield EventColumns.from_events(events[at:at + batch_size])


class MemoryApps(base.Apps):
    def __init__(self):
        self._apps: dict[int, App] = {}
        self._next_id = 1
        self._lock = threading.RLock()

    def insert(self, app: App) -> int | None:
        with self._lock:
            if self.get_by_name(app.name) is not None:
                return None
            app_id = app.id if app.id > 0 else self._next_id
            if app_id in self._apps:
                return None
            self._next_id = max(self._next_id, app_id) + 1
            self._apps[app_id] = App(app_id, app.name, app.description)
            return app_id

    def get(self, app_id: int) -> App | None:
        return self._apps.get(app_id)

    def get_by_name(self, name: str) -> App | None:
        return next((a for a in self._apps.values() if a.name == name), None)

    def get_all(self) -> list[App]:
        return sorted(self._apps.values(), key=lambda a: a.id)

    def update(self, app: App) -> None:
        with self._lock:
            self._apps[app.id] = app

    def delete(self, app_id: int) -> None:
        with self._lock:
            self._apps.pop(app_id, None)


class MemoryAccessKeys(base.AccessKeys):
    def __init__(self):
        self._keys: dict[str, AccessKey] = {}
        self._lock = threading.RLock()

    def insert(self, access_key: AccessKey) -> str | None:
        key = access_key.key or self.generate_key()
        with self._lock:
            if key in self._keys:
                return None
            self._keys[key] = AccessKey(key, access_key.appid, tuple(access_key.events))
            return key

    def get(self, key: str) -> AccessKey | None:
        return self._keys.get(key)

    def get_all(self) -> list[AccessKey]:
        return list(self._keys.values())

    def get_by_app_id(self, app_id: int) -> list[AccessKey]:
        return [k for k in self._keys.values() if k.appid == app_id]

    def update(self, access_key: AccessKey) -> None:
        with self._lock:
            self._keys[access_key.key] = access_key

    def delete(self, key: str) -> None:
        with self._lock:
            self._keys.pop(key, None)


class MemoryChannels(base.Channels):
    def __init__(self):
        self._channels: dict[int, Channel] = {}
        self._next_id = 1
        self._lock = threading.RLock()

    def insert(self, channel: Channel) -> int | None:
        if not Channel.is_valid_name(channel.name):
            return None
        with self._lock:
            channel_id = channel.id if channel.id > 0 else self._next_id
            if channel_id in self._channels:
                return None
            self._next_id = max(self._next_id, channel_id) + 1
            self._channels[channel_id] = Channel(channel_id, channel.name, channel.appid)
            return channel_id

    def get(self, channel_id: int) -> Channel | None:
        return self._channels.get(channel_id)

    def get_by_app_id(self, app_id: int) -> list[Channel]:
        return [c for c in self._channels.values() if c.appid == app_id]

    def delete(self, channel_id: int) -> None:
        with self._lock:
            self._channels.pop(channel_id, None)


class MemoryEngineInstances(base.EngineInstances):
    def __init__(self):
        self._instances: dict[str, EngineInstance] = {}
        self._lock = threading.RLock()

    def insert(self, instance: EngineInstance) -> str:
        instance_id = instance.id or uuid.uuid4().hex
        with self._lock:
            self._instances[instance_id] = (
                instance if instance.id else dataclasses.replace(instance, id=instance_id)
            )
        return instance_id

    def get(self, instance_id: str) -> EngineInstance | None:
        return self._instances.get(instance_id)

    def get_all(self) -> list[EngineInstance]:
        return list(self._instances.values())

    def get_completed(
        self, engine_id: str, engine_version: str, engine_variant: str
    ) -> list[EngineInstance]:
        out = [
            i
            for i in self._instances.values()
            if i.status == "COMPLETED"
            and i.engine_id == engine_id
            and i.engine_version == engine_version
            and i.engine_variant == engine_variant
        ]
        return sorted(out, key=lambda i: i.start_time, reverse=True)

    def update(self, instance: EngineInstance) -> None:
        with self._lock:
            self._instances[instance.id] = instance

    def delete(self, instance_id: str) -> None:
        with self._lock:
            self._instances.pop(instance_id, None)


class MemoryEvaluationInstances(base.EvaluationInstances):
    def __init__(self):
        self._instances: dict[str, EvaluationInstance] = {}
        self._lock = threading.RLock()

    def insert(self, instance: EvaluationInstance) -> str:
        instance_id = instance.id or uuid.uuid4().hex
        with self._lock:
            self._instances[instance_id] = (
                instance if instance.id else dataclasses.replace(instance, id=instance_id)
            )
        return instance_id

    def get(self, instance_id: str) -> EvaluationInstance | None:
        return self._instances.get(instance_id)

    def get_all(self) -> list[EvaluationInstance]:
        return list(self._instances.values())

    def get_completed(self) -> list[EvaluationInstance]:
        out = [i for i in self._instances.values() if i.status == "EVALCOMPLETED"]
        return sorted(out, key=lambda i: i.start_time, reverse=True)

    def update(self, instance: EvaluationInstance) -> None:
        with self._lock:
            self._instances[instance.id] = instance

    def delete(self, instance_id: str) -> None:
        with self._lock:
            self._instances.pop(instance_id, None)


class MemoryModels(base.Models):
    def __init__(self):
        self._models: dict[str, Model] = {}
        self._lock = threading.RLock()

    def insert(self, model: Model) -> None:
        with self._lock:
            self._models[model.id] = model

    def get(self, model_id: str) -> Model | None:
        return self._models.get(model_id)

    def delete(self, model_id: str) -> None:
        with self._lock:
            self._models.pop(model_id, None)


class MemoryStorageClient(base.BaseStorageClient):
    """All repositories in process memory."""

    def __init__(self, config: StorageClientConfig = StorageClientConfig()):
        super().__init__(config)
        self._events = MemoryEvents()
        self._apps = MemoryApps()
        self._access_keys = MemoryAccessKeys()
        self._channels = MemoryChannels()
        self._engine_instances = MemoryEngineInstances()
        self._evaluation_instances = MemoryEvaluationInstances()
        self._models = MemoryModels()

    def events(self) -> MemoryEvents:
        return self._events

    def apps(self) -> MemoryApps:
        return self._apps

    def access_keys(self) -> MemoryAccessKeys:
        return self._access_keys

    def channels(self) -> MemoryChannels:
        return self._channels

    def engine_instances(self) -> MemoryEngineInstances:
        return self._engine_instances

    def evaluation_instances(self) -> MemoryEvaluationInstances:
        return self._evaluation_instances

    def models(self) -> MemoryModels:
        return self._models
