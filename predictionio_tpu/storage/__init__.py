"""Pluggable storage: registry, DAO interfaces, backends.

Reference: data/src/main/scala/.../data/storage/ (abstraction) and
storage/* modules (backends).
"""

from predictionio_tpu.storage.base import (
    AccessKey,
    AccessKeys,
    App,
    Apps,
    BaseStorageClient,
    Channel,
    Channels,
    EngineInstance,
    EngineInstances,
    EvaluationInstance,
    EvaluationInstances,
    EventFilter,
    Events,
    Model,
    Models,
    StorageClientConfig,
)
from predictionio_tpu.storage.registry import (
    EVENT_DATA,
    META_DATA,
    MODEL_DATA,
    Storage,
    StorageError,
    register_backend,
)

__all__ = [
    "AccessKey", "AccessKeys", "App", "Apps", "BaseStorageClient",
    "Channel", "Channels", "EngineInstance", "EngineInstances",
    "EvaluationInstance", "EvaluationInstances", "EventFilter", "Events",
    "Model", "Models", "StorageClientConfig",
    "EVENT_DATA", "META_DATA", "MODEL_DATA",
    "Storage", "StorageError", "register_backend",
]
