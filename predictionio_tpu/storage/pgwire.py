"""PostgreSQL v3 wire-protocol client — pure stdlib sockets.

The networked-SQL client the reference's JDBC backend role calls for
(reference: storage/jdbc/src/main/scala/.../jdbc/StorageClient.scala —
scalikejdbc ConnectionPool over a postgresql:// URL). There is no JVM
and no JDBC here, so the wire layer is implemented directly against the
public PostgreSQL frontend/backend protocol (v3.0): StartupMessage,
trust / cleartext / MD5 / SCRAM-SHA-256 authentication (RFC 5802/7677
— the modern server default, with server-signature verification), the
simple query cycle (Query -> RowDescription / DataRow* /
CommandComplete / ReadyForQuery), and typed text-format decoding by
column OID.

Scope, stated plainly (docs/storage.md "networked-SQL story"): this
client implements the protocol from its public specification and is
exercised in-tree against a wire-faithful in-process emulator
(tests/pg_emulator.py) — zero egress means no real PostgreSQL server
exists in this environment to integration-test against. TLS
negotiation and SCRAM channel binding (-PLUS) are not implemented
(documented gaps).

Queries use the SIMPLE protocol with client-side literal binding (the
extended protocol's Parse/Bind adds round trips the DAO layer never
amortizes); see :func:`quote_literal` for the escaping rules.
"""

from __future__ import annotations

import base64
import hashlib
import hmac
import os
import socket
import struct
import threading


def saslprep(value: str) -> str:
    """RFC 4013 SASLprep (the stringprep profile SCRAM requires for
    passwords). Real PostgreSQL stores SCRAM verifiers from the
    prepared form, so an unprepared password with e.g. a non-breaking
    space would derive the wrong proof. Implemented on the stdlib
    ``stringprep`` tables: map (B.1 -> nothing, C.1.2 -> space),
    NFKC-normalize, reject prohibited output, enforce the RFC 3454
    bidi rules."""
    import stringprep
    import unicodedata

    mapped = []
    for ch in value:
        if stringprep.in_table_b1(ch):
            continue                       # map to nothing
        if stringprep.in_table_c12(ch):
            mapped.append(" ")             # non-ASCII space -> space
        else:
            mapped.append(ch)
    out = unicodedata.normalize("NFKC", "".join(mapped))
    if not out:
        return out
    for ch in out:
        if (stringprep.in_table_c12(ch) or stringprep.in_table_c21_c22(ch)
                or stringprep.in_table_c3(ch) or stringprep.in_table_c4(ch)
                or stringprep.in_table_c5(ch) or stringprep.in_table_c6(ch)
                or stringprep.in_table_c7(ch) or stringprep.in_table_c8(ch)
                or stringprep.in_table_c9(ch)):
            raise ValueError(
                f"prohibited character {ch!r} in SASLprep input")
    has_randal = any(stringprep.in_table_d1(ch) for ch in out)
    if has_randal:
        if any(stringprep.in_table_d2(ch) for ch in out):
            raise ValueError("mixed bidi categories in SASLprep input")
        if not (stringprep.in_table_d1(out[0])
                and stringprep.in_table_d1(out[-1])):
            raise ValueError("RandALCat string must start/end RandALCat")
    return out


class PGError(Exception):
    """Server ErrorResponse: carries the SQLSTATE in ``code``."""

    def __init__(self, code: str, message: str):
        super().__init__(f"[{code}] {message}")
        self.code = code
        self.message = message


class PGProtocolError(Exception):
    """Malformed or unexpected protocol traffic."""


def _open_socket(host: str, port: int, timeout: float) -> socket.socket:
    """The module's single raw network call site. Connection
    establishment is routed through ``resilient()`` by the pool layer
    (storage/postgres.py ``_PGPool._connect``) — the retry/breaker
    policy lives there, not here, so one policy covers socket + auth
    (enforced by tests/test_resilience_static.py)."""
    return socket.create_connection((host, port), timeout=timeout)


def quote_literal(value) -> str:
    """SQL literal for client-side binding under the simple protocol.

    Strings use standard_conforming escaping (doubled single quotes;
    backslash is literal). Bytes become a hex bytea cast. NUL bytes are
    rejected — PostgreSQL text values cannot carry them and silently
    truncating would corrupt data."""
    if value is None:
        return "NULL"
    if isinstance(value, bool):
        return "TRUE" if value else "FALSE"
    if isinstance(value, int):
        return str(value)
    if isinstance(value, float):
        if value != value or value in (float("inf"), float("-inf")):
            return f"'{value}'::float8"
        return repr(value)
    if isinstance(value, (bytes, bytearray, memoryview)):
        return "'\\x" + bytes(value).hex() + "'::bytea"
    s = str(value)
    if "\x00" in s:
        raise ValueError("NUL byte in SQL string literal")
    return "'" + s.replace("'", "''") + "'"


def bind_placeholders(sql: str, params: tuple) -> str:
    """Replace ``?`` placeholders with quoted literals, skipping quoted
    regions of the SQL text itself. Placeholder/param count mismatches
    raise (even for zero params — a bare ``?`` must never ship)."""
    out = []
    it = iter(params)
    i, n = 0, len(sql)
    used = 0
    while i < n:
        ch = sql[i]
        if ch == "'":
            j = i + 1
            while j < n:
                if sql[j] == "'":
                    if j + 1 < n and sql[j + 1] == "'":
                        j += 2
                        continue
                    break
                j += 1
            out.append(sql[i:j + 1])
            i = j + 1
        elif ch == "?":
            try:
                out.append(quote_literal(next(it)))
            except StopIteration:
                raise PGProtocolError(
                    f"more placeholders than params in {sql!r}")
            used += 1
            i += 1
        else:
            out.append(ch)
            i += 1
    if used != len(params):
        raise PGProtocolError(
            f"{len(params)} params for {used} placeholders in {sql!r}")
    return "".join(out)


def _decode_value(oid: int, raw: bytes | None):
    """Text-format value decode by type OID (the ones our SQL surface
    produces; unknown OIDs come back as str)."""
    if raw is None:
        return None
    text = raw.decode("utf-8")
    if oid in (20, 21, 23, 26):      # int8/int2/int4/oid
        return int(text)
    if oid in (700, 701, 1700):      # float4/float8/numeric
        return float(text)
    if oid == 16:                    # bool
        return text == "t"
    if oid == 17:                    # bytea (hex form)
        if text.startswith("\\x"):
            return bytes.fromhex(text[2:])
        raise PGProtocolError("bytea escape format not supported; "
                              "set bytea_output=hex")
    return text


class PGConnection:
    """One authenticated protocol-v3 session; thread-safe via a lock
    (one in-flight query cycle at a time — the simple protocol is
    strictly request/response)."""

    def __init__(self, host: str, port: int, user: str, database: str,
                 password: str | None = None, timeout: float = 30.0):
        self.user = user
        self.password = password
        self._lock = threading.Lock()
        self._sock = _open_socket(host, port, timeout)
        self._buf = b""
        self.parameters: dict[str, str] = {}   # ParameterStatus reports
        try:
            self._startup(user, database)
        except BaseException:
            # a rejected startup (bad auth, scs=off, protocol error)
            # must not leak the socket
            try:
                self._sock.close()
            except OSError:
                pass
            raise

    def _param_status(self, payload: bytes) -> None:
        """Track ParameterStatus ('S') reports. quote_literal assumes
        standard_conforming_strings=on (doubled quotes, literal
        backslash); under =off backslashes in user data become escapes
        — data corruption AND an injection vector (ADVICE r4) — so a
        server reporting off is rejected outright, at startup or on a
        mid-session SET."""
        parts = payload.split(b"\x00")
        if len(parts) < 2 or not parts[0]:
            return
        key = parts[0].decode("utf-8", "replace")
        val = parts[1].decode("utf-8", "replace")
        self.parameters[key] = val
        if key == "standard_conforming_strings" and val != "on":
            raise PGProtocolError(
                "server reports standard_conforming_strings=off; this "
                "client's literal quoting is only safe with it on "
                "(set standard_conforming_strings=on server-side)")

    # -- framing ----------------------------------------------------------

    def _send(self, data: bytes) -> None:
        self._sock.sendall(data)

    def _recv_exact(self, n: int) -> bytes:
        while len(self._buf) < n:
            chunk = self._sock.recv(65536)
            if not chunk:
                raise PGProtocolError("server closed the connection")
            self._buf += chunk
        out, self._buf = self._buf[:n], self._buf[n:]
        return out

    def _read_message(self) -> tuple[bytes, bytes]:
        head = self._recv_exact(5)
        tag = head[:1]
        (length,) = struct.unpack("!I", head[1:5])
        if length < 4:
            raise PGProtocolError(f"bad message length {length}")
        return tag, self._recv_exact(length - 4)

    @staticmethod
    def _message(tag: bytes, payload: bytes) -> bytes:
        return tag + struct.pack("!I", len(payload) + 4) + payload

    # -- session ----------------------------------------------------------

    def _startup(self, user: str, database: str) -> None:
        params = (f"user\x00{user}\x00database\x00{database}\x00\x00"
                  ).encode("utf-8")
        body = struct.pack("!I", 196608) + params     # protocol 3.0
        self._send(struct.pack("!I", len(body) + 4) + body)
        while True:
            tag, payload = self._read_message()
            if tag == b"R":
                (kind,) = struct.unpack("!I", payload[:4])
                if kind == 0:                          # AuthenticationOk
                    continue
                if kind == 3:                          # cleartext
                    self._password_message(self._require_password())
                    continue
                if kind == 5:                          # md5
                    salt = payload[4:8]
                    inner = hashlib.md5(
                        self._require_password().encode()
                        + self.user.encode()).hexdigest()
                    digest = hashlib.md5(
                        inner.encode() + salt).hexdigest()
                    self._password_message("md5" + digest)
                    continue
                if kind == 10:                         # SASL mechanisms
                    self._scram_start(payload[4:])
                    continue
                if kind in (11, 12):
                    raise PGProtocolError(
                        "SASL continuation outside a SCRAM exchange")
                raise PGProtocolError(
                    f"unsupported authentication request {kind} "
                    "(use scram-sha-256, md5, cleartext or trust)")
            elif tag == b"S":                          # ParameterStatus
                self._param_status(payload)
            elif tag in (b"K", b"N"):                  # key/notice
                continue
            elif tag == b"Z":                          # ReadyForQuery
                return
            elif tag == b"E":
                raise self._error(payload)
            else:
                raise PGProtocolError(
                    f"unexpected startup message {tag!r}")

    def _require_password(self) -> str:
        if self.password is None:
            raise PGError("28P01", "server requested a password but none "
                                   "was configured (set PASSWORD)")
        return self.password

    def _scram_start(self, mech_payload: bytes) -> None:
        """SCRAM-SHA-256 (RFC 5802/7677 via PG's SASL framing) — the
        modern server default (password_encryption=scram-sha-256).
        Channel binding is not offered (gs2 header "n,,"; SSL is not
        negotiated by this client), and the client VERIFIES the server
        signature, a mutual-authentication property MD5 lacks."""
        mechs = [m for m in mech_payload.split(b"\x00") if m]
        if b"SCRAM-SHA-256" not in mechs:
            raise PGProtocolError(
                f"no supported SASL mechanism in {mechs!r}")
        password = saslprep(self._require_password()).encode("utf-8")
        cnonce = base64.b64encode(os.urandom(18)).decode()
        gs2 = "n,,"
        client_first_bare = f"n=,r={cnonce}"
        initial = (gs2 + client_first_bare).encode("utf-8")
        self._send(self._message(
            b"p", b"SCRAM-SHA-256\x00"
            + struct.pack("!i", len(initial)) + initial))

        tag, payload = self._read_message()
        if tag == b"E":
            raise self._error(payload)
        if tag != b"R" or struct.unpack("!I", payload[:4])[0] != 11:
            raise PGProtocolError("expected SASLContinue")
        server_first = payload[4:].decode("utf-8")
        fields = dict(f.split("=", 1) for f in server_first.split(","))
        snonce, salt_b64, iters = fields["r"], fields["s"], int(fields["i"])
        if not snonce.startswith(cnonce):
            raise PGProtocolError("server nonce does not extend ours "
                                  "(possible MITM)")
        # bound the server-chosen PBKDF2 cost BEFORE doing the work: a
        # hostile peer could otherwise pin the client on ~2^31 SHA-256
        # rounds (no socket timeout covers local CPU), and an i=1
        # downgrade would extract a cheap-to-crack proof (RFC 5802
        # recommends >= 4096; PostgreSQL's default is 4096)
        if not 4096 <= iters <= 10_000_000:
            raise PGProtocolError(
                f"unreasonable SCRAM iteration count {iters} "
                "(accepting 4096..10000000)")

        salted = hashlib.pbkdf2_hmac(
            "sha256", password, base64.b64decode(salt_b64), iters)
        client_key = hmac.new(salted, b"Client Key", hashlib.sha256).digest()
        stored_key = hashlib.sha256(client_key).digest()
        channel = base64.b64encode(gs2.encode()).decode()   # "biws"
        client_final_bare = f"c={channel},r={snonce}"
        auth_message = ",".join(
            (client_first_bare, server_first, client_final_bare)).encode()
        client_sig = hmac.new(stored_key, auth_message,
                              hashlib.sha256).digest()
        proof = bytes(a ^ b for a, b in zip(client_key, client_sig))
        final = (client_final_bare
                 + ",p=" + base64.b64encode(proof).decode()).encode()
        self._send(self._message(b"p", final))

        tag, payload = self._read_message()
        if tag == b"E":
            raise self._error(payload)
        if tag != b"R" or struct.unpack("!I", payload[:4])[0] != 12:
            raise PGProtocolError("expected SASLFinal")
        sasl_final = payload[4:].decode("utf-8")
        server_key = hmac.new(salted, b"Server Key", hashlib.sha256).digest()
        server_sig = hmac.new(server_key, auth_message,
                              hashlib.sha256).digest()
        expect = "v=" + base64.b64encode(server_sig).decode()
        if not hmac.compare_digest(sasl_final, expect):
            raise PGProtocolError(
                "server signature verification failed (the server does "
                "not know the password — possible MITM)")

    def _password_message(self, secret: str) -> None:
        self._send(self._message(b"p", secret.encode("utf-8") + b"\x00"))

    @staticmethod
    def _error(payload: bytes) -> PGError:
        code, msg = "XX000", "unknown error"
        for field in payload.split(b"\x00"):
            if not field:
                continue
            k, v = field[:1], field[1:].decode("utf-8", "replace")
            if k == b"C":
                code = v
            elif k == b"M":
                msg = v
        return PGError(code, msg)

    # -- queries ----------------------------------------------------------

    def execute(self, sql: str, params: tuple = ()) -> list[tuple]:
        """One simple-query cycle; returns the LAST statement's rows."""
        return self.execute_raw(bind_placeholders(sql, tuple(params)))

    def execute_raw(self, bound: str) -> list[tuple]:
        """Run SQL whose literals are ALREADY bound — no placeholder
        scan (batch callers bind row-by-row and join)."""
        with self._lock:
            self._send(self._message(b"Q", bound.encode("utf-8") + b"\x00"))
            rows: list[tuple] = []      # current statement's result set
            last: list[tuple] = []      # last COMPLETED statement's rows
            saw_rowdesc = False
            oids: list[int] = []
            error: PGError | None = None
            while True:
                tag, payload = self._read_message()
                if tag == b"T":                        # RowDescription
                    (ncols,) = struct.unpack("!H", payload[:2])
                    oids, off = [], 2
                    for _ in range(ncols):
                        end = payload.index(b"\x00", off)
                        # name, table oid(4), attnum(2), TYPE OID(4),
                        # typlen(2), atttypmod(4), format(2)
                        (oid,) = struct.unpack(
                            "!I", payload[end + 7:end + 11])
                        oids.append(oid)
                        off = end + 19
                    rows, saw_rowdesc = [], True
                elif tag == b"D":                      # DataRow
                    (ncols,) = struct.unpack("!H", payload[:2])
                    vals, off = [], 2
                    for c in range(ncols):
                        (ln,) = struct.unpack(
                            "!i", payload[off:off + 4])
                        off += 4
                        if ln < 0:
                            vals.append(None)
                        else:
                            vals.append(_decode_value(
                                oids[c] if c < len(oids) else 25,
                                payload[off:off + ln]))
                            off += ln
                    rows.append(tuple(vals))
                elif tag in (b"C", b"I"):     # CommandComplete/EmptyQuery
                    # per-statement result boundary: only a statement
                    # that produced a RowDescription contributes rows,
                    # so a trailing row-less statement yields [] rather
                    # than an earlier SELECT's leftovers (ADVICE r4)
                    last = rows if saw_rowdesc else []
                    rows, saw_rowdesc = [], False
                elif tag == b"S":                      # ParameterStatus
                    self._param_status(payload)
                elif tag == b"N":                      # NoticeResponse
                    continue
                elif tag == b"E":
                    error = self._error(payload)       # Z still follows
                elif tag == b"Z":                      # ReadyForQuery
                    if error is not None:
                        raise error
                    return last
                else:
                    raise PGProtocolError(
                        f"unexpected message {tag!r} in query cycle")

    def close(self) -> None:
        try:
            self._send(self._message(b"X", b""))
        except OSError:
            pass
        try:
            self._sock.close()
        except OSError:
            pass
