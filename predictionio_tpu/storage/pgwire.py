"""PostgreSQL v3 wire-protocol client — pure stdlib sockets.

The networked-SQL client the reference's JDBC backend role calls for
(reference: storage/jdbc/src/main/scala/.../jdbc/StorageClient.scala —
scalikejdbc ConnectionPool over a postgresql:// URL). There is no JVM
and no JDBC here, so the wire layer is implemented directly against the
public PostgreSQL frontend/backend protocol (v3.0): StartupMessage,
trust / cleartext / MD5 password authentication, the simple query
cycle (Query -> RowDescription / DataRow* / CommandComplete /
ReadyForQuery), and typed text-format decoding by column OID.

Scope, stated plainly (docs/storage.md "networked-SQL story"): this
client implements the protocol from its public specification and is
exercised in-tree against a wire-faithful in-process emulator
(tests/pg_emulator.py) — zero egress means no real PostgreSQL server
exists in this environment to integration-test against. SCRAM-SHA-256
and TLS negotiation are not implemented (documented gaps; MD5 and
cleartext cover the classic deployments the reference's examples use).

Queries use the SIMPLE protocol with client-side literal binding (the
extended protocol's Parse/Bind adds round trips the DAO layer never
amortizes); see :func:`quote_literal` for the escaping rules.
"""

from __future__ import annotations

import hashlib
import socket
import struct
import threading


class PGError(Exception):
    """Server ErrorResponse: carries the SQLSTATE in ``code``."""

    def __init__(self, code: str, message: str):
        super().__init__(f"[{code}] {message}")
        self.code = code
        self.message = message


class PGProtocolError(Exception):
    """Malformed or unexpected protocol traffic."""


def quote_literal(value) -> str:
    """SQL literal for client-side binding under the simple protocol.

    Strings use standard_conforming escaping (doubled single quotes;
    backslash is literal). Bytes become a hex bytea cast. NUL bytes are
    rejected — PostgreSQL text values cannot carry them and silently
    truncating would corrupt data."""
    if value is None:
        return "NULL"
    if isinstance(value, bool):
        return "TRUE" if value else "FALSE"
    if isinstance(value, int):
        return str(value)
    if isinstance(value, float):
        if value != value or value in (float("inf"), float("-inf")):
            return f"'{value}'::float8"
        return repr(value)
    if isinstance(value, (bytes, bytearray, memoryview)):
        return "'\\x" + bytes(value).hex() + "'::bytea"
    s = str(value)
    if "\x00" in s:
        raise ValueError("NUL byte in SQL string literal")
    return "'" + s.replace("'", "''") + "'"


def bind_placeholders(sql: str, params: tuple) -> str:
    """Replace ``?`` placeholders with quoted literals, skipping quoted
    regions of the SQL text itself. Placeholder/param count mismatches
    raise (even for zero params — a bare ``?`` must never ship)."""
    out = []
    it = iter(params)
    i, n = 0, len(sql)
    used = 0
    while i < n:
        ch = sql[i]
        if ch == "'":
            j = i + 1
            while j < n:
                if sql[j] == "'":
                    if j + 1 < n and sql[j + 1] == "'":
                        j += 2
                        continue
                    break
                j += 1
            out.append(sql[i:j + 1])
            i = j + 1
        elif ch == "?":
            try:
                out.append(quote_literal(next(it)))
            except StopIteration:
                raise PGProtocolError(
                    f"more placeholders than params in {sql!r}")
            used += 1
            i += 1
        else:
            out.append(ch)
            i += 1
    if used != len(params):
        raise PGProtocolError(
            f"{len(params)} params for {used} placeholders in {sql!r}")
    return "".join(out)


def _decode_value(oid: int, raw: bytes | None):
    """Text-format value decode by type OID (the ones our SQL surface
    produces; unknown OIDs come back as str)."""
    if raw is None:
        return None
    text = raw.decode("utf-8")
    if oid in (20, 21, 23, 26):      # int8/int2/int4/oid
        return int(text)
    if oid in (700, 701, 1700):      # float4/float8/numeric
        return float(text)
    if oid == 16:                    # bool
        return text == "t"
    if oid == 17:                    # bytea (hex form)
        if text.startswith("\\x"):
            return bytes.fromhex(text[2:])
        raise PGProtocolError("bytea escape format not supported; "
                              "set bytea_output=hex")
    return text


class PGConnection:
    """One authenticated protocol-v3 session; thread-safe via a lock
    (one in-flight query cycle at a time — the simple protocol is
    strictly request/response)."""

    def __init__(self, host: str, port: int, user: str, database: str,
                 password: str | None = None, timeout: float = 30.0):
        self.user = user
        self.password = password
        self._lock = threading.Lock()
        self._sock = socket.create_connection((host, port), timeout=timeout)
        self._buf = b""
        self._startup(user, database)

    # -- framing ----------------------------------------------------------

    def _send(self, data: bytes) -> None:
        self._sock.sendall(data)

    def _recv_exact(self, n: int) -> bytes:
        while len(self._buf) < n:
            chunk = self._sock.recv(65536)
            if not chunk:
                raise PGProtocolError("server closed the connection")
            self._buf += chunk
        out, self._buf = self._buf[:n], self._buf[n:]
        return out

    def _read_message(self) -> tuple[bytes, bytes]:
        head = self._recv_exact(5)
        tag = head[:1]
        (length,) = struct.unpack("!I", head[1:5])
        if length < 4:
            raise PGProtocolError(f"bad message length {length}")
        return tag, self._recv_exact(length - 4)

    @staticmethod
    def _message(tag: bytes, payload: bytes) -> bytes:
        return tag + struct.pack("!I", len(payload) + 4) + payload

    # -- session ----------------------------------------------------------

    def _startup(self, user: str, database: str) -> None:
        params = (f"user\x00{user}\x00database\x00{database}\x00\x00"
                  ).encode("utf-8")
        body = struct.pack("!I", 196608) + params     # protocol 3.0
        self._send(struct.pack("!I", len(body) + 4) + body)
        while True:
            tag, payload = self._read_message()
            if tag == b"R":
                (kind,) = struct.unpack("!I", payload[:4])
                if kind == 0:                          # AuthenticationOk
                    continue
                if kind == 3:                          # cleartext
                    self._password_message(self._require_password())
                    continue
                if kind == 5:                          # md5
                    salt = payload[4:8]
                    inner = hashlib.md5(
                        self._require_password().encode()
                        + self.user.encode()).hexdigest()
                    digest = hashlib.md5(
                        inner.encode() + salt).hexdigest()
                    self._password_message("md5" + digest)
                    continue
                raise PGProtocolError(
                    f"unsupported authentication request {kind} "
                    "(SCRAM/GSS not implemented — use md5, cleartext "
                    "or trust)")
            elif tag in (b"S", b"K", b"N"):            # status/key/notice
                continue
            elif tag == b"Z":                          # ReadyForQuery
                return
            elif tag == b"E":
                raise self._error(payload)
            else:
                raise PGProtocolError(
                    f"unexpected startup message {tag!r}")

    def _require_password(self) -> str:
        if self.password is None:
            raise PGError("28P01", "server requested a password but none "
                                   "was configured (set PASSWORD)")
        return self.password

    def _password_message(self, secret: str) -> None:
        self._send(self._message(b"p", secret.encode("utf-8") + b"\x00"))

    @staticmethod
    def _error(payload: bytes) -> PGError:
        code, msg = "XX000", "unknown error"
        for field in payload.split(b"\x00"):
            if not field:
                continue
            k, v = field[:1], field[1:].decode("utf-8", "replace")
            if k == b"C":
                code = v
            elif k == b"M":
                msg = v
        return PGError(code, msg)

    # -- queries ----------------------------------------------------------

    def execute(self, sql: str, params: tuple = ()) -> list[tuple]:
        """One simple-query cycle; returns the LAST statement's rows."""
        return self.execute_raw(bind_placeholders(sql, tuple(params)))

    def execute_raw(self, bound: str) -> list[tuple]:
        """Run SQL whose literals are ALREADY bound — no placeholder
        scan (batch callers bind row-by-row and join)."""
        with self._lock:
            self._send(self._message(b"Q", bound.encode("utf-8") + b"\x00"))
            rows: list[tuple] = []
            oids: list[int] = []
            error: PGError | None = None
            while True:
                tag, payload = self._read_message()
                if tag == b"T":                        # RowDescription
                    (ncols,) = struct.unpack("!H", payload[:2])
                    oids, off = [], 2
                    for _ in range(ncols):
                        end = payload.index(b"\x00", off)
                        # name, table oid(4), attnum(2), TYPE OID(4),
                        # typlen(2), atttypmod(4), format(2)
                        (oid,) = struct.unpack(
                            "!I", payload[end + 7:end + 11])
                        oids.append(oid)
                        off = end + 19
                    rows = []
                elif tag == b"D":                      # DataRow
                    (ncols,) = struct.unpack("!H", payload[:2])
                    vals, off = [], 2
                    for c in range(ncols):
                        (ln,) = struct.unpack(
                            "!i", payload[off:off + 4])
                        off += 4
                        if ln < 0:
                            vals.append(None)
                        else:
                            vals.append(_decode_value(
                                oids[c] if c < len(oids) else 25,
                                payload[off:off + ln]))
                            off += ln
                    rows.append(tuple(vals))
                elif tag in (b"C", b"I", b"N", b"S"):   # complete/empty/…
                    continue
                elif tag == b"E":
                    error = self._error(payload)       # Z still follows
                elif tag == b"Z":                      # ReadyForQuery
                    if error is not None:
                        raise error
                    return rows
                else:
                    raise PGProtocolError(
                        f"unexpected message {tag!r} in query cycle")

    def close(self) -> None:
        try:
            self._send(self._message(b"X", b""))
        except OSError:
            pass
        try:
            self._sock.close()
        except OSError:
            pass
