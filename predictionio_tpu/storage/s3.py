"""S3 model storage backend.

Parity: storage/s3/src/main/scala/.../s3/{StorageClient,S3Models}.scala:36-95
— model blobs as objects ``<BASE_PATH>/<id>`` in a bucket, with optional
custom endpoint and region. The reference used the AWS Java SDK; this
implementation speaks the S3 REST API directly over stdlib HTTP with
AWS Signature V4 request signing (no SDK dependency), which also makes
it work against any S3-compatible store (MinIO, localstack, GCS interop
endpoint) via ``ENDPOINT``.

Config properties:
  ``BUCKET_NAME`` (required), ``BASE_PATH`` (key prefix, default ``""``),
  ``REGION`` (default ``us-east-1``), ``ENDPOINT`` (default
  ``https://s3.<region>.amazonaws.com``; path-style addressing is used so
  custom endpoints work), ``ACCESS_KEY_ID`` / ``SECRET_ACCESS_KEY``
  (fall back to ``AWS_ACCESS_KEY_ID`` / ``AWS_SECRET_ACCESS_KEY`` env),
  plus the ``RETRY_*``/``BREAKER_*`` resilience knobs
  (docs/operations-resilience.md). Every object round trip routes
  through ``resilient()``: transport failures and 5xx retry with
  jittered backoff and feed the circuit breaker; 404 and other 4xx pass
  through unchanged for the callers' not-found handling.
"""

from __future__ import annotations

import datetime
import hashlib
import hmac
import os
import urllib.error
import urllib.parse
import urllib.request

from predictionio_tpu.storage import base
from predictionio_tpu.storage.base import Model, StorageClientConfig
from predictionio_tpu.utils.resilience import (
    Resilience,
    TransientError,
    is_transient_http_status,
    resilient,
)


class S3Error(RuntimeError):
    pass


def _hmac(key: bytes, msg: str) -> bytes:
    return hmac.new(key, msg.encode(), hashlib.sha256).digest()


def _uri_encode(s: str) -> str:
    # S3 canonical URI encoding: everything except unreserved chars and "/"
    return urllib.parse.quote(s, safe="/-_.~")


def sign_v4_headers(
    method: str,
    url: str,
    region: str,
    access_key: str,
    secret_key: str,
    payload: bytes,
    now: datetime.datetime | None = None,
) -> dict[str, str]:
    """AWS Signature V4 headers for one S3 request (service ``s3``).

    Exposed as a function so tests can pin ``now`` and check against
    known-good signatures.
    """
    parts = urllib.parse.urlsplit(url)
    now = now or datetime.datetime.now(datetime.timezone.utc)
    amz_date = now.strftime("%Y%m%dT%H%M%SZ")
    datestamp = now.strftime("%Y%m%d")
    payload_hash = hashlib.sha256(payload).hexdigest()

    canonical_headers = (
        f"host:{parts.netloc}\n"
        f"x-amz-content-sha256:{payload_hash}\n"
        f"x-amz-date:{amz_date}\n"
    )
    signed_headers = "host;x-amz-content-sha256;x-amz-date"
    canonical_request = "\n".join(
        [
            method,
            _uri_encode(parts.path or "/"),
            parts.query,  # model keys produce no query strings
            canonical_headers,
            signed_headers,
            payload_hash,
        ]
    )
    scope = f"{datestamp}/{region}/s3/aws4_request"
    string_to_sign = "\n".join(
        [
            "AWS4-HMAC-SHA256",
            amz_date,
            scope,
            hashlib.sha256(canonical_request.encode()).hexdigest(),
        ]
    )
    k = _hmac(("AWS4" + secret_key).encode(), datestamp)
    k = _hmac(k, region)
    k = _hmac(k, "s3")
    k = _hmac(k, "aws4_request")
    signature = hmac.new(k, string_to_sign.encode(), hashlib.sha256).hexdigest()
    return {
        "x-amz-date": amz_date,
        "x-amz-content-sha256": payload_hash,
        "Authorization": (
            f"AWS4-HMAC-SHA256 Credential={access_key}/{scope}, "
            f"SignedHeaders={signed_headers}, Signature={signature}"
        ),
    }


class S3Models(base.Models):
    def __init__(
        self,
        bucket: str,
        base_path: str = "",
        region: str = "us-east-1",
        endpoint: str | None = None,
        access_key: str | None = None,
        secret_key: str | None = None,
        timeout: float = 30.0,
        resilience: Resilience | None = None,
    ):
        self._bucket = bucket
        self._base_path = base_path.strip("/")
        self._region = region
        self._endpoint = (endpoint or f"https://s3.{region}.amazonaws.com").rstrip("/")
        self._access_key = access_key or os.environ.get("AWS_ACCESS_KEY_ID", "")
        self._secret_key = secret_key or os.environ.get("AWS_SECRET_ACCESS_KEY", "")
        self._timeout = timeout
        self._resilience = resilience or Resilience("s3")

    def _url(self, model_id: str) -> str:
        safe = urllib.parse.quote(model_id, safe="")
        key = f"{self._base_path}/{safe}" if self._base_path else safe
        return f"{self._endpoint}/{self._bucket}/{key}"

    def _request(self, method: str, model_id: str, payload: bytes = b""):
        return resilient(
            self._resilience, self._raw_request, method, model_id, payload)

    def _raw_request(self, method: str, model_id: str, payload: bytes = b""):
        """One signed object round trip. Only reachable through
        ``resilient()``: transport failures and 5xx raise TransientError
        (retried under the policy); 4xx — including the 404s the callers
        map to not-found — pass through untouched."""
        url = self._url(model_id)
        headers = {}
        if self._access_key:
            headers = sign_v4_headers(
                method, url, self._region, self._access_key, self._secret_key, payload
            )
        req = urllib.request.Request(url, data=payload or None, method=method,
                                     headers=headers)
        try:
            return urllib.request.urlopen(req, timeout=self._timeout)
        except urllib.error.HTTPError as exc:
            if is_transient_http_status(exc.code):
                raise TransientError(
                    f"{method} {model_id}: HTTP {exc.code}") from exc
            raise
        except urllib.error.URLError as exc:
            raise TransientError(f"{method} {model_id}: {exc.reason}") from exc

    def insert(self, model: Model) -> None:
        with self._request("PUT", model.id, model.models) as resp:
            if resp.status not in (200, 201):
                raise S3Error(f"PUT {model.id}: HTTP {resp.status}")

    def get(self, model_id: str) -> Model | None:
        try:
            with self._request("GET", model_id) as resp:
                return Model(model_id, resp.read())
        except urllib.error.HTTPError as exc:
            if exc.code == 404:
                return None
            raise S3Error(f"GET {model_id}: HTTP {exc.code}") from exc

    def delete(self, model_id: str) -> None:
        try:
            with self._request("DELETE", model_id):
                pass
        except urllib.error.HTTPError as exc:
            if exc.code != 404:
                raise S3Error(f"DELETE {model_id}: HTTP {exc.code}") from exc


class S3StorageClient(base.BaseStorageClient):
    prefix = "S3"

    def __init__(self, config: StorageClientConfig = StorageClientConfig()):
        super().__init__(config)
        props = config.properties
        bucket = props.get("BUCKET_NAME")
        if not bucket:
            raise S3Error("s3 storage source requires a BUCKET_NAME property")
        source = props.get("SOURCE_NAME", bucket)
        self._models = S3Models(
            bucket=bucket,
            base_path=props.get("BASE_PATH", ""),
            region=props.get("REGION", "us-east-1"),
            endpoint=props.get("ENDPOINT"),
            access_key=props.get("ACCESS_KEY_ID"),
            secret_key=props.get("SECRET_ACCESS_KEY"),
            resilience=Resilience.from_properties(f"s3/{source}", props),
        )

    def models(self) -> S3Models:
        return self._models
