"""fileevents backend — append-only JSONL event store (events only).

Fills the reference's HBase role: a backend that implements ONLY the
event-data repository (SURVEY.md §2.4 — hbase has "no metadata DAOs —
HBase is event-store only"). Layout mirrors HBase's table-per-app/channel
(HBEventsUtil.eventTableName): one log file
``events_<app>[_<ch>].jsonl`` under the configured PATH, each line an
operation record ``{"op": "put"|"del", ...}``. Reads replay the log into
an in-memory index (compacting deletes); writes append + fsync-free
flush, so inserts are O(1) and sequential — the ingestion-friendly write
path that motivated HBase in the reference.

Config: ``PIO_STORAGE_SOURCES_<NAME>_TYPE=fileevents``,
``PIO_STORAGE_SOURCES_<NAME>_PATH=/dir``.
"""

from __future__ import annotations

import json
import os
import threading
import uuid
from typing import Iterator, Sequence

from predictionio_tpu.core.event import Event
from predictionio_tpu.core.json_codec import event_from_json, event_to_json
from predictionio_tpu.storage import base
from predictionio_tpu.storage.base import EventFilter, StorageClientConfig


def _table_name(app_id: int, channel_id: int | None) -> str:
    """Parity: HBEventsUtil.eventTableName — events_<app>[_<ch>]."""
    suffix = f"_{channel_id}" if channel_id is not None else ""
    return f"events_{app_id}{suffix}.jsonl"


class FileEvents(base.Events):
    def __init__(self, path: str):
        self._path = path
        self._lock = threading.RLock()
        #: (app, channel) -> id -> Event; lazily replayed from disk
        self._index: dict[tuple[int, int | None], dict[str, Event]] = {}
        os.makedirs(path, exist_ok=True)

    # -- log helpers --------------------------------------------------------
    def _file(self, app_id: int, channel_id: int | None) -> str:
        return os.path.join(self._path, _table_name(app_id, channel_id))

    def _load(self, app_id: int, channel_id: int | None) -> dict[str, Event]:
        key = (app_id, channel_id)
        if key in self._index:
            return self._index[key]
        table: dict[str, Event] = {}
        path = self._file(app_id, channel_id)
        if os.path.exists(path):
            with open(path) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    rec = json.loads(line)
                    if rec["op"] == "put":
                        event = event_from_json(rec["event"], validate=False)
                        table[event.event_id] = event
                    elif rec["op"] == "del":
                        table.pop(rec["id"], None)
        self._index[key] = table
        return table

    def _append(self, app_id: int, channel_id: int | None, rec: dict) -> None:
        with open(self._file(app_id, channel_id), "a") as f:
            f.write(json.dumps(rec) + "\n")

    # -- Events DAO ---------------------------------------------------------
    def init(self, app_id: int, channel_id: int | None = None) -> bool:
        with self._lock:
            self._load(app_id, channel_id)
            path = self._file(app_id, channel_id)
            if not os.path.exists(path):
                open(path, "a").close()
        return True

    def remove(self, app_id: int, channel_id: int | None = None) -> bool:
        with self._lock:
            self._index.pop((app_id, channel_id), None)
            path = self._file(app_id, channel_id)
            if os.path.exists(path):
                os.remove(path)
                return True
            return False

    def close(self) -> None:
        pass

    def insert(self, event: Event, app_id: int, channel_id: int | None = None) -> str:
        event_id = event.event_id or uuid.uuid4().hex
        event = event.with_event_id(event_id)
        with self._lock:
            table = self._load(app_id, channel_id)
            table[event_id] = event
            self._append(app_id, channel_id,
                         {"op": "put", "event": event_to_json(event)})
        return event_id

    def insert_batch(
        self, events: Sequence[Event], app_id: int, channel_id: int | None = None
    ) -> list[str]:
        ids = []
        with self._lock:
            table = self._load(app_id, channel_id)
            lines = []
            for event in events:
                event_id = event.event_id or uuid.uuid4().hex
                event = event.with_event_id(event_id)
                table[event_id] = event
                lines.append(json.dumps({"op": "put", "event": event_to_json(event)}))
                ids.append(event_id)
            with open(self._file(app_id, channel_id), "a") as f:
                f.write("\n".join(lines) + "\n")
        return ids

    def get(self, event_id: str, app_id: int, channel_id: int | None = None) -> Event | None:
        with self._lock:
            return self._load(app_id, channel_id).get(event_id)

    def delete(self, event_id: str, app_id: int, channel_id: int | None = None) -> bool:
        with self._lock:
            table = self._load(app_id, channel_id)
            if event_id not in table:
                return False
            del table[event_id]
            self._append(app_id, channel_id, {"op": "del", "id": event_id})
            return True

    def find(
        self,
        app_id: int,
        channel_id: int | None = None,
        filter: EventFilter = EventFilter(),
    ) -> Iterator[Event]:
        with self._lock:
            events = [
                e for e in self._load(app_id, channel_id).values()
                if filter.matches(e)
            ]
        events.sort(key=lambda e: (e.event_time, e.event_id or ""),
                    reverse=filter.reversed)
        if filter.limit is not None and filter.limit >= 0:
            events = events[: filter.limit]
        return iter(events)


class FileEventsStorageClient(base.BaseStorageClient):
    """Events-only client; the metadata/model accessors keep the base
    class's NotImplementedError, mirroring how the reference's hbase
    backend simply has no metadata DAO classes."""

    def __init__(self, config: StorageClientConfig = StorageClientConfig()):
        super().__init__(config)
        path = config.properties.get(
            "PATH",
            os.path.join(
                os.environ.get("PIO_FS_BASEDIR",
                               os.path.join(os.path.expanduser("~"), ".pio_store")),
                "fileevents",
            ),
        )
        self._events = FileEvents(path)

    def events(self) -> FileEvents:
        return self._events
