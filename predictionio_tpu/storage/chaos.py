"""Fault-injection ("chaos") storage backend.

Wraps any registered Events/metadata/Models backend and injects seeded,
DETERMINISTIC faults and latency at the DAO boundary, so the whole stack
— ingest, training reads, model persistence, serving — can be
chaos-tested end to end with reproducible runs (beyond reference: the
reference proved fault behavior only against live dockerized stores).

Two invariants make injected faults safe to retry:

- a fault fires BEFORE the inner operation runs, so a faulted call
  never partially applies — retrying cannot duplicate or lose data;
- the fault sequence is drawn from one seeded ``random.Random``, so a
  given (seed, operation sequence) always fails at the same points.

The chaos client carries its own :class:`Resilience` ABOVE the injector,
exactly like a remote backend wraps its network boundary: callers see
either the inner backend's normal result (after invisible retries) or a
:class:`StorageUnavailableError` — never a raw injected fault.

Registered in the storage registry as type ``chaos``. Config
(``PIO_STORAGE_SOURCES_<NAME>_*``):

- ``TARGET`` (required) — the wrapped backend's registered type; every
  ``TARGET_<KEY>`` property is forwarded to it as ``<KEY>``. (Named
  ``TARGET`` rather than ``TARGET_TYPE`` because the registry's env
  parser would read a ``…_TYPE`` suffix as its own source declaration;
  ``TARGET_TYPE`` is still accepted in programmatic configs.)
- ``FAULT_RATE`` (default ``0.3``) — probability a call faults.
- ``SEED`` (default ``0``) — the deterministic fault stream.
- ``ERROR`` (default ``chaos``) — injected class: ``chaos``
  (:class:`ChaosError`), ``connection`` (ConnectionError) or
  ``timeout`` (TimeoutError).
- ``LATENCY_MS`` (default ``0``; ``DELAY_MS`` is an alias) — mean
  injected latency; ``LATENCY_JITTER_MS`` adds a uniform spread.
- ``DELAY_PROB`` (default ``1.0``) — probability a call is delayed at
  all, drawn from the same seeded stream as the faults: slow-backend
  behavior (some calls slow, most fast — the long-tail shape that
  defeats a fixed timeout) becomes testable deterministically.
- the standard ``RETRY_*``/``BREAKER_*`` knobs (defaults here are
  retry-heavy: 12 attempts at 1ms base, breaker off) so a 30% fault
  rate is absorbed invisibly unless the operator tightens the policy.

Python API: ``ChaosStorageClient.wrap(inner_client, fault_rate=…,
seed=…)`` wraps an already-built client (how the chaos conformance
tests run sqlite/memory under fault injection).
"""

from __future__ import annotations

import functools
import random
import threading
from typing import Callable

from predictionio_tpu.storage import base
from predictionio_tpu.storage.base import BaseStorageClient, StorageClientConfig
from predictionio_tpu.utils.resilience import (
    SYSTEM_CLOCK,
    Clock,
    Resilience,
    RetryPolicy,
    TransientError,
    resilient,
)


class ChaosError(TransientError):
    """An injected transient fault."""


_ERROR_CLASSES: dict[str, Callable[[str], BaseException]] = {
    "chaos": lambda op: ChaosError(f"injected fault in {op}"),
    "connection": lambda op: ConnectionError(f"injected connection loss in {op}"),
    "timeout": lambda op: TimeoutError(f"injected timeout in {op}"),
}


class ChaosInjector:
    """Seeded fault/latency source shared by all DAOs of one source."""

    def __init__(
        self,
        fault_rate: float = 0.3,
        seed: int = 0,
        error: str = "chaos",
        latency_ms: float = 0.0,
        latency_jitter_ms: float = 0.0,
        delay_prob: float = 1.0,
        clock: Clock = SYSTEM_CLOCK,
    ):
        if error not in _ERROR_CLASSES:
            raise ValueError(
                f"unknown chaos ERROR {error!r} "
                f"(choose from {sorted(_ERROR_CLASSES)})")
        self.fault_rate = fault_rate
        self.seed = seed
        self._error = _ERROR_CLASSES[error]
        self._latency = latency_ms / 1e3
        self._jitter = latency_jitter_ms / 1e3
        #: probability a call is delayed at all (1.0 = every call, the
        #: pre-PR 6 behavior); < 1.0 models a long-tail slow backend
        self._delay_prob = delay_prob
        self._clock = clock
        self._rng = random.Random(seed)
        self._lock = threading.Lock()
        self.faults_injected = 0
        self.delays_injected = 0
        self.calls = 0

    def set_fault_rate(self, fault_rate: float) -> None:
        """Thread-safe runtime fault-rate flip — the chaos suites'
        outage window (``set_fault_rate(1.0)`` = hard outage,
        ``set_fault_rate(0.0)`` = recovery) without racing the seeded
        draw in :meth:`before` on another thread."""
        with self._lock:
            self.fault_rate = fault_rate

    def before(self, op: str) -> None:
        """Maybe sleep, maybe raise — always BEFORE the inner op runs."""
        with self._lock:
            self.calls += 1
            roll = self._rng.random()
            latency = 0.0
            if self._latency or self._jitter:
                # the delay roll is drawn only when delay_prob < 1.0,
                # keeping the (seed, op-sequence) fault stream of
                # always-delay and no-latency configs unchanged
                delayed = (self._delay_prob >= 1.0
                           or self._rng.random() < self._delay_prob)
                if delayed:
                    latency = (self._latency
                               + self._rng.uniform(0, self._jitter))
                    self.delays_injected += 1
            fault = roll < self.fault_rate
            if fault:
                self.faults_injected += 1
        if latency > 0:
            self._clock.sleep(latency)
        if fault:
            raise self._error(op)


class _ChaosDAO:
    """Generic proxy: every public DAO method gets fault injection plus
    the resilient() wrapper; private attrs and ``close`` pass through
    (cleanup must never flake)."""

    _PASSTHROUGH = frozenset({"close"})

    def __init__(self, inner, injector: ChaosInjector, resilience: Resilience):
        self._inner = inner
        self._injector = injector
        self._resilience = resilience

    def __getattr__(self, name: str):
        attr = getattr(self._inner, name)
        if (name.startswith("_") or not callable(attr)
                or name in self._PASSTHROUGH):
            return attr

        @functools.wraps(attr)
        def guarded(*args, **kwargs):
            def attempt():
                self._injector.before(name)
                return attr(*args, **kwargs)
            return resilient(self._resilience, attempt)

        self.__dict__[name] = guarded  # cache per proxy instance
        return guarded


class ChaosStorageClient(BaseStorageClient):
    """Registered as type ``chaos``; see the module docstring."""

    prefix = "CHAOS"

    def __init__(self, config: StorageClientConfig = StorageClientConfig()):
        super().__init__(config)
        props = config.properties
        target_type = props.get("TARGET") or props.get("TARGET_TYPE")
        if not target_type:
            raise ValueError(
                "chaos storage source requires a TARGET property "
                "naming the wrapped backend type")
        source = props.get("SOURCE_NAME", f"{target_type}")
        inner_props = {
            k[len("TARGET_"):]: v for k, v in props.items()
            if k.startswith("TARGET_") and k != "TARGET_TYPE"
        }
        inner_props.setdefault("SOURCE_NAME", f"{source}/target")
        from predictionio_tpu.storage import registry  # avoid import cycle

        registry._builtin_backends()
        if target_type not in registry._BACKENDS:
            raise registry.StorageError(
                f"chaos TARGET_TYPE {target_type!r} is not a registered "
                f"backend type (available: {sorted(registry._BACKENDS)})")
        inner = registry._BACKENDS[target_type](
            StorageClientConfig(
                parallel=config.parallel, test=config.test,
                properties=inner_props))
        self._init_wrapping(
            inner,
            injector=ChaosInjector(
                fault_rate=float(props.get("FAULT_RATE", "0.3")),
                seed=int(props.get("SEED", "0")),
                error=props.get("ERROR", "chaos"),
                latency_ms=float(props.get(
                    "LATENCY_MS", props.get("DELAY_MS", "0"))),
                latency_jitter_ms=float(props.get("LATENCY_JITTER_MS", "0")),
                delay_prob=float(props.get("DELAY_PROB", "1.0")),
            ),
            resilience=Resilience.from_properties(
                f"chaos/{source}", props,
                max_attempts=12, base_delay=0.001, max_delay=0.02,
                failure_threshold=0),
        )

    def _init_wrapping(self, inner: BaseStorageClient,
                       injector: ChaosInjector,
                       resilience: Resilience) -> None:
        self.inner = inner
        self.injector = injector
        self.resilience = resilience
        self._daos: dict[str, _ChaosDAO] = {}
        self._lock = threading.Lock()

    @classmethod
    def wrap(
        cls,
        inner: BaseStorageClient,
        fault_rate: float = 0.3,
        seed: int = 0,
        error: str = "chaos",
        latency_ms: float = 0.0,
        latency_jitter_ms: float = 0.0,
        delay_prob: float = 1.0,
        resilience: Resilience | None = None,
        name: str = "chaos",
        clock: Clock = SYSTEM_CLOCK,
    ) -> "ChaosStorageClient":
        """Wrap an already-constructed client (test/notebook API)."""
        self = cls.__new__(cls)
        BaseStorageClient.__init__(self, inner.config)
        self._init_wrapping(
            inner,
            injector=ChaosInjector(
                fault_rate=fault_rate, seed=seed, error=error,
                latency_ms=latency_ms, latency_jitter_ms=latency_jitter_ms,
                delay_prob=delay_prob, clock=clock),
            resilience=resilience or Resilience(
                name,
                policy=RetryPolicy(max_attempts=12, base_delay=0.001,
                                   max_delay=0.02),
                clock=clock,
            ),
        )
        return self

    def _wrapped(self, kind: str, factory) -> _ChaosDAO:
        with self._lock:
            if kind not in self._daos:
                self._daos[kind] = _ChaosDAO(
                    factory(), self.injector, self.resilience)
            return self._daos[kind]

    def events(self) -> base.Events:
        return self._wrapped("events", self.inner.events)

    def apps(self) -> base.Apps:
        return self._wrapped("apps", self.inner.apps)

    def access_keys(self) -> base.AccessKeys:
        return self._wrapped("access_keys", self.inner.access_keys)

    def channels(self) -> base.Channels:
        return self._wrapped("channels", self.inner.channels)

    def engine_instances(self) -> base.EngineInstances:
        return self._wrapped("engine_instances", self.inner.engine_instances)

    def evaluation_instances(self) -> base.EvaluationInstances:
        return self._wrapped("evaluation_instances",
                             self.inner.evaluation_instances)

    def models(self) -> base.Models:
        return self._wrapped("models", self.inner.models)

    def close(self) -> None:
        self.inner.close()
