"""PostgreSQL storage backend: the networked-SQL client.

Role parity: storage/jdbc/src/main/scala/.../jdbc/StorageClient.scala —
the reference's production SQL deployment is PostgreSQL-over-JDBC; this
backend is PostgreSQL over the in-tree wire client
(:mod:`predictionio_tpu.storage.pgwire`).

Design: the embedded sqlite backend's DAO classes are the single
source of truth for the SQL data model (tables, indexes, WHERE
assembly — themselves mirroring JDBCLEvents/JDBCApps/…); this module
reuses them UNCHANGED over a connection adapter that (a) rewrites the
three sqlite-isms into PostgreSQL (AUTOINCREMENT -> SERIAL,
BLOB -> BYTEA, INSERT OR REPLACE -> INSERT … ON CONFLICT DO UPDATE on
the first/primary-key column), (b) binds ``?`` placeholders as quoted
literals for the simple query protocol, and (c) maps server SQLSTATEs
onto the sqlite exception surface the DAO layer's control flow already
handles (42P01 "relation does not exist" -> OperationalError carrying
"no such table" for the auto-init path; 23xxx -> IntegrityError).

Config (PIO_STORAGE_SOURCES_<NAME>_*): HOST (localhost), PORT (5432),
USERNAME (pio), PASSWORD, DATABASE (pio), plus RETRY_*/BREAKER_*
resilience knobs (docs/operations-resilience.md) — connection
establishment retries with jittered backoff and feeds a circuit
breaker; query cycles are never auto-retried (no idempotency guarantee
under the simple protocol). Conformance-tested over the
real wire protocol against the in-process emulator
(tests/pg_emulator.py) — see docs/storage.md for what that does and
does not prove in a zero-egress environment.
"""

from __future__ import annotations

import queue
import re
import sqlite3
import threading

from predictionio_tpu.storage import base, sqlite as sq
from predictionio_tpu.storage.base import StorageClientConfig
from predictionio_tpu.storage.pgwire import PGConnection, PGError
from predictionio_tpu.utils.resilience import Resilience, resilient

_AUTOINC = re.compile(r"INTEGER PRIMARY KEY AUTOINCREMENT", re.IGNORECASE)
_BLOB = re.compile(r"\bBLOB\b", re.IGNORECASE)
_OR_REPLACE = re.compile(
    r"^\s*INSERT\s+OR\s+REPLACE\s+INTO\s+(\S+)\s*\(([^)]*)\)\s*(.*)$",
    re.IGNORECASE | re.DOTALL,
)
# explicit-id inserts into the SERIAL tables desync the sequence on
# real PostgreSQL (a later auto-id insert then collides — ADVICE r4);
# detect them so execute() can re-sync with setval on the same session
_EXPLICIT_SERIAL_ID = re.compile(
    r"^\s*INSERT\s+INTO\s+(pio_meta_apps|pio_meta_channels)\s*\(\s*id\b",
    re.IGNORECASE,
)


def translate_sql(sql: str) -> str:
    """sqlite dialect -> PostgreSQL for the closed DAO statement set."""
    sql = _AUTOINC.sub("SERIAL PRIMARY KEY", sql)
    sql = _BLOB.sub("BYTEA", sql)
    m = _OR_REPLACE.match(sql)
    if m:
        table, cols_raw, rest = m.groups()
        cols = [c.strip() for c in cols_raw.split(",")]
        pk, others = cols[0], cols[1:]
        if others:
            sets = ", ".join(f"{c} = EXCLUDED.{c}" for c in others)
            conflict = f" ON CONFLICT ({pk}) DO UPDATE SET {sets}"
        else:
            conflict = f" ON CONFLICT ({pk}) DO NOTHING"
        sql = (f"INSERT INTO {table} ({cols_raw}) {rest.rstrip()}"
               f"{conflict}")
    return sql


def _map_error(err: PGError) -> Exception:
    if err.code == "42P01":
        # phrase chosen so sqlite._is_no_table recognizes it and the
        # DAO layer's auto-init-on-first-insert path engages
        return sqlite3.OperationalError(f"no such table: {err.message}")
    if err.code.startswith("23"):
        return sqlite3.IntegrityError(err.message)
    return sqlite3.OperationalError(f"[{err.code}] {err.message}")


class _PGPool:
    """Bounded PGConnection pool presenting the sqlite ``_Connection``
    interface (execute/executemany/close) the DAO classes consume."""

    POOL_SIZE = 4
    BORROW_TIMEOUT = 60.0

    def __init__(self, host: str, port: int, user: str,
                 password: str | None, database: str,
                 resilience: Resilience | None = None):
        self._args = (host, port, user, database, password)
        self._pool: "queue.Queue[PGConnection]" = queue.Queue()
        self._created = 0
        self._lock = threading.Lock()
        self._closed = False
        # connection ESTABLISHMENT is the resilient boundary: a down
        # server manifests here, and a fresh connect is always safe to
        # retry. Query cycles are NOT retried — the simple protocol
        # gives no idempotency guarantee for a re-sent INSERT — so
        # retryable covers OSError (refused/reset/timeout), while
        # PGError (bad auth, SQL errors) passes through untouched.
        self._resilience = resilience or Resilience(
            "postgres", retryable=(OSError,))

    def _connect(self) -> PGConnection:
        return resilient(self._resilience, self._open_connection)

    def _open_connection(self) -> PGConnection:
        host, port, user, database, password = self._args
        return PGConnection(host, port, user=user, database=database,
                            password=password)

    def _borrow(self) -> PGConnection:
        if self._closed:
            raise sqlite3.ProgrammingError("storage connection is closed")
        try:
            return self._pool.get_nowait()
        except queue.Empty:
            pass
        with self._lock:
            below = self._created < self.POOL_SIZE
            if below:
                self._created += 1
        if below:
            try:
                return self._connect()
            except Exception:
                with self._lock:
                    self._created -= 1
                raise
        try:
            return self._pool.get(timeout=self.BORROW_TIMEOUT)
        except queue.Empty:
            # surface exhaustion through the backend's documented
            # exception contract, not a bare queue.Empty
            raise sqlite3.OperationalError(
                f"connection pool exhausted ({self.POOL_SIZE} connections "
                f"busy for {self.BORROW_TIMEOUT}s)") from None

    def _drop(self, conn) -> None:
        with self._lock:
            self._created -= 1
        try:
            conn.close()
        except Exception:
            pass

    def _give_back(self, conn) -> None:
        # a close() racing an in-flight query must not re-enqueue an
        # orphaned socket (nothing would ever borrow or close it)
        if self._closed:
            self._drop(conn)
        else:
            self._pool.put(conn)

    def _run(self, fn):
        conn = self._borrow()
        try:
            out = fn(conn)
        except PGError as err:
            # server-side error: the session completed its query cycle
            # (ReadyForQuery followed) and is reusable
            self._give_back(conn)
            raise _map_error(err) from err
        except BaseException:
            # protocol-level failure OR an interrupt mid-cycle: the
            # session state is unknown — drop the connection and free
            # its slot (BaseException so KeyboardInterrupt cannot leak
            # the slot and wedge the pool)
            self._drop(conn)
            raise
        self._give_back(conn)
        return out

    def execute(self, sql: str, params: tuple = ()) -> list[tuple]:
        sql_t = translate_sql(sql)
        m = _EXPLICIT_SERIAL_ID.match(sql_t)

        def run(c):
            out = c.execute(sql_t, tuple(params))
            if m:
                # re-sync the sequence past the explicitly inserted id
                # so the next auto-id insert cannot collide (skipped on
                # failure: an exception above bypasses this). GREATEST
                # against nextval keeps the re-sync MONOTONIC: a plain
                # setval(MAX(id)) could move the sequence backward past
                # ids a concurrent uncommitted auto-insert already drew
                # (its row is not visible to MAX), recreating the
                # collision; nextval always reads >= the current value
                # (one burned id, harmless)
                t = m.group(1)
                c.execute(
                    f"SELECT setval(pg_get_serial_sequence('{t}', 'id'), "
                    f"GREATEST((SELECT COALESCE(MAX(id), 1) FROM {t}), "
                    f"nextval(pg_get_serial_sequence('{t}', 'id'))))")
            return out

        return self._run(run)

    def executemany(self, sql: str, seq) -> None:
        sql_t = translate_sql(sql)

        def run(c):
            # one implicit transaction per Query message: bind every
            # row client-side and ship the batch as a single
            # multi-statement simple query (matches sqlite
            # executemany's all-or-nothing commit); execute_raw skips
            # a second placeholder scan over the joined batch string
            from predictionio_tpu.storage.pgwire import bind_placeholders

            stmts = [bind_placeholders(sql_t, tuple(p)) for p in seq]
            if stmts:
                c.execute_raw("; ".join(stmts))
        self._run(run)

    def close(self) -> None:
        self._closed = True
        while True:
            try:
                self._pool.get_nowait().close()
            except queue.Empty:
                break


class PGStorageClient(base.BaseStorageClient):
    """All repositories over the PostgreSQL wire client, DAO logic
    shared with the embedded backend (single SQL data model)."""

    prefix = "PG"

    def __init__(self, config: StorageClientConfig = StorageClientConfig()):
        super().__init__(config)
        p = config.properties
        host = p.get("HOST", "localhost")
        port = int(p.get("PORT", "5432"))
        source = p.get("SOURCE_NAME", f"{host}:{port}")
        self._conn = _PGPool(
            host=host,
            port=port,
            user=p.get("USERNAME", "pio"),
            password=p.get("PASSWORD"),
            database=p.get("DATABASE", "pio"),
            resilience=Resilience.from_properties(
                f"postgres/{source}", p, retryable=(OSError,)),
        )
        self._lock = threading.RLock()
        self._cache: dict[str, object] = {}

    def _cached(self, key: str, factory):
        with self._lock:
            if key not in self._cache:
                self._cache[key] = factory(self._conn)
            return self._cache[key]

    def events(self):
        return self._cached("events", sq.SQLiteEvents)

    def apps(self):
        return self._cached("apps", sq.SQLiteApps)

    def access_keys(self):
        return self._cached("access_keys", sq.SQLiteAccessKeys)

    def channels(self):
        return self._cached("channels", sq.SQLiteChannels)

    def engine_instances(self):
        return self._cached("engine_instances", sq.SQLiteEngineInstances)

    def evaluation_instances(self):
        return self._cached("evaluation_instances",
                            sq.SQLiteEvaluationInstances)

    def models(self):
        return self._cached("models", sq.SQLiteModels)

    def close(self) -> None:
        self._conn.close()
