"""Env-var driven storage registry and repository wiring.

Parity with the reference Storage object
(reference: data/src/main/scala/.../data/storage/Storage.scala:120-423):

- Sources are declared as ``PIO_STORAGE_SOURCES_<NAME>_TYPE`` plus
  arbitrary ``PIO_STORAGE_SOURCES_<NAME>_<KEY>`` properties.
- Repositories bind to sources via
  ``PIO_STORAGE_REPOSITORIES_{METADATA,EVENTDATA,MODELDATA}_{NAME,SOURCE}``.
- Clients are created lazily and cached per source.

Where the reference discovers DAO classes by reflected class name
(Storage.scala:218-233, 279-328), this registry keeps an explicit
``BACKENDS`` mapping of type name -> StorageClient factory — the
idiomatic-Python equivalent (no classpath scanning), extensible via
``register_backend``.

When no env config is present at all, a self-contained default is used
(sqlite metadata+events+models under $PIO_FS_BASEDIR or ~/.pio_store) so
the framework works out of the box — the reference instead hard-fails
(Storage.scala:166-177); the default serves its conf/pio-env.sh.template
role.
"""

from __future__ import annotations

import logging
import os
import threading
from typing import Callable, Mapping

from predictionio_tpu.storage.base import (
    AccessKeys,
    Apps,
    BaseStorageClient,
    Channels,
    EngineInstances,
    EvaluationInstances,
    Events,
    Models,
    StorageClientConfig,
)

logger = logging.getLogger(__name__)

EVENT_DATA = "EVENTDATA"
META_DATA = "METADATA"
MODEL_DATA = "MODELDATA"

_SOURCES_PREFIX = "PIO_STORAGE_SOURCES"
_REPOSITORIES_PREFIX = "PIO_STORAGE_REPOSITORIES"

BackendFactory = Callable[[StorageClientConfig], BaseStorageClient]
_BACKENDS: dict[str, BackendFactory] = {}
_builtins_loaded = False


class StorageError(RuntimeError):
    """Misconfigured or unsupported storage (Storage.scala StorageException)."""


def register_backend(type_name: str, factory: BackendFactory) -> None:
    """Register a backend type (the plugin-registry replacement for the
    reference's class-name reflection, Storage.scala:218-233)."""
    _BACKENDS[type_name] = factory


def _builtin_backends() -> None:
    global _builtins_loaded
    if _builtins_loaded:
        return
    _builtins_loaded = True
    from predictionio_tpu.storage.binevents import BinEventsStorageClient
    from predictionio_tpu.storage.elasticsearch import ESStorageClient
    from predictionio_tpu.storage.fileevents import FileEventsStorageClient
    from predictionio_tpu.storage.hdfs import HDFSStorageClient
    from predictionio_tpu.storage.localfs import LocalFSStorageClient
    from predictionio_tpu.storage.memory import MemoryStorageClient
    from predictionio_tpu.storage.postgres import PGStorageClient
    from predictionio_tpu.storage.s3 import S3StorageClient
    from predictionio_tpu.storage.sqlite import SQLiteStorageClient

    _BACKENDS.setdefault("memory", MemoryStorageClient)
    _BACKENDS.setdefault("sqlite", SQLiteStorageClient)
    # "jdbc" maps to the embedded SQL backend so reference pio-env.sh files
    # whose sources say TYPE=jdbc keep working.
    _BACKENDS.setdefault("jdbc", SQLiteStorageClient)
    # networked SQL over the in-tree PostgreSQL wire client
    # (storage/pgwire + storage/postgres — the reference's production
    # JDBC deployment role, StorageClient.scala)
    _BACKENDS.setdefault("postgres", PGStorageClient)
    _BACKENDS.setdefault("pg", PGStorageClient)
    _BACKENDS.setdefault("localfs", LocalFSStorageClient)
    # append-only JSONL event store — the reference's hbase role
    # (event-data only)
    _BACKENDS.setdefault("fileevents", FileEventsStorageClient)
    # binary event log with the native (C++) scan path; "hbase" aliases to
    # it for pio-env.sh compatibility — it is the high-throughput
    # event-store role the reference filled with HBase. Note binevents
    # (.bin under PATH or ~/.pio_store/binevents) and fileevents (.jsonl)
    # use different on-disk formats/directories; pick one per deployment.
    _BACKENDS.setdefault("binevents", BinEventsStorageClient)
    _BACKENDS.setdefault("hbase", BinEventsStorageClient)
    # network-filesystem and object-store model repositories
    # (reference storage/hdfs, storage/s3)
    _BACKENDS.setdefault("hdfs", HDFSStorageClient)
    _BACKENDS.setdefault("s3", S3StorageClient)
    # REST metadata/event store (reference storage/elasticsearch, 5.x REST);
    # "elasticsearch1" aliases to it so pio-env.sh files written for the
    # reference's 1.x transport backend keep working (storage/elasticsearch1
    # was metadata-only — this one is a superset).
    _BACKENDS.setdefault("elasticsearch", ESStorageClient)
    _BACKENDS.setdefault("elasticsearch1", ESStorageClient)
    # fault-injection wrapper around any registered backend (TARGET_TYPE
    # + forwarded TARGET_* props) — chaos-test the whole stack end to end
    from predictionio_tpu.storage.chaos import ChaosStorageClient

    _BACKENDS.setdefault("chaos", ChaosStorageClient)


class Storage:
    """Lazily-constructed registry of storage clients + repository DAOs.

    A ``Storage`` instance is the analogue of the reference's global
    ``Storage`` object; instance-scoped here so tests can build isolated
    registries. ``Storage.default()`` gives the process-wide one.
    """

    _default: "Storage | None" = None
    _default_lock = threading.Lock()

    def __init__(self, env: Mapping[str, str] | None = None):
        self._env = dict(env if env is not None else os.environ)
        self._clients: dict[str, BaseStorageClient] = {}
        self._lock = threading.RLock()
        self._sources = self._parse_sources()
        self._repositories = self._parse_repositories()

    # -- global default -----------------------------------------------------
    @classmethod
    def default(cls) -> "Storage":
        with cls._default_lock:
            if cls._default is None:
                cls._default = Storage()
            return cls._default

    @classmethod
    def reset_default(cls) -> None:
        with cls._default_lock:
            if cls._default is not None:
                cls._default.close()
            cls._default = None

    # -- env parsing (Storage.scala:120-199) --------------------------------
    def _parse_sources(self) -> dict[str, tuple[str, StorageClientConfig]]:
        sources: dict[str, tuple[str, StorageClientConfig]] = {}
        # A source's name is everything between the prefix and the _TYPE
        # suffix, so names may themselves contain underscores (PIO_SQLITE).
        names = {
            k[len(_SOURCES_PREFIX) + 1 : -len("_TYPE")]
            for k in self._env
            if k.startswith(_SOURCES_PREFIX + "_") and k.endswith("_TYPE")
            and len(k) > len(_SOURCES_PREFIX) + 1 + len("_TYPE")
        }
        # a key like PIO_STORAGE_SOURCES_X_FOO_TYPE is ambiguous: source
        # "X_FOO"'s type, or property "FOO_TYPE" of source "X". When a
        # shorter source X exists, resolve by the TYPE value: registered
        # backend types declare a source, anything else stays X's property
        # (warned, so a typo'd backend name is visible). Names with no
        # shorter source are kept even when unregistered — external
        # backends may register after Storage() but before first use.
        _builtin_backends()
        for name in sorted(names):
            shorter = [o for o in names if o != name and name.startswith(o + "_")]
            if not shorter:
                continue
            type_val = self._env[f"{_SOURCES_PREFIX}_{name}_TYPE"]
            if type_val not in _BACKENDS:
                logger.warning(
                    "PIO_STORAGE_SOURCES_%s_TYPE=%r is not a registered "
                    "backend type; treating it as property %s_TYPE of "
                    "source %s (registered types: %s)",
                    name, type_val, name[len(shorter[0]) + 1:], shorter[0],
                    ", ".join(sorted(_BACKENDS)),
                )
                names.discard(name)
        for name in names:
            type_key = f"{_SOURCES_PREFIX}_{name}_TYPE"
            prefix = f"{_SOURCES_PREFIX}_{name}_"
            # keys that belong to a LONGER source name sharing this prefix
            # (e.g. source PIO vs PIO_SQLITE) are not this source's props
            longer = [f"{_SOURCES_PREFIX}_{other}_" for other in names
                      if other != name and other.startswith(name + "_")]
            props = {
                k[len(prefix):]: v
                for k, v in self._env.items()
                if k.startswith(prefix) and k != type_key
                and not any(k.startswith(lp) for lp in longer)
            }
            # backends label their resilience metrics/breakers by source
            props.setdefault("SOURCE_NAME", name)
            sources[name] = (
                self._env[type_key],
                StorageClientConfig(
                    parallel=props.pop("PARALLEL", "false").lower() == "true",
                    test=props.pop("TEST", "false").lower() == "true",
                    properties=props,
                ),
            )
        if sources:
            # surfaced so misparsed names (a property key ending in _TYPE
            # reads as its own source) are visible to operators
            logger.info(
                "storage sources: %s",
                {n: t for n, (t, _) in sorted(sources.items())},
            )
        return sources

    def _parse_repositories(self) -> dict[str, str]:
        repos: dict[str, str] = {}
        for repo in (META_DATA, EVENT_DATA, MODEL_DATA):
            source = self._env.get(f"{_REPOSITORIES_PREFIX}_{repo}_SOURCE")
            if source:
                repos[repo] = source
        if not repos:
            repos = self._default_repositories()
        missing = [r for r in (META_DATA, EVENT_DATA, MODEL_DATA) if r not in repos]
        if missing:
            raise StorageError(
                f"Repositories {missing} have no configured source. Set "
                f"{_REPOSITORIES_PREFIX}_<REPO>_SOURCE and matching "
                f"{_SOURCES_PREFIX}_<NAME>_TYPE environment variables."
            )
        return repos

    def _default_repositories(self) -> dict[str, str]:
        base = self._env.get(
            "PIO_FS_BASEDIR", os.path.join(os.path.expanduser("~"), ".pio_store")
        )
        self._sources.setdefault(
            "DEFAULT_SQLITE",
            (
                "sqlite",
                StorageClientConfig(
                    properties={"PATH": os.path.join(base, "pio.sqlite")}
                ),
            ),
        )
        self._sources.setdefault(
            "DEFAULT_LOCALFS",
            (
                "localfs",
                StorageClientConfig(properties={"PATH": os.path.join(base, "models")}),
            ),
        )
        return {
            META_DATA: "DEFAULT_SQLITE",
            EVENT_DATA: "DEFAULT_SQLITE",
            MODEL_DATA: "DEFAULT_LOCALFS",
        }

    # -- client construction (Storage.scala:201-276) ------------------------
    def client_for_source(self, source_name: str) -> BaseStorageClient:
        with self._lock:
            if source_name in self._clients:
                return self._clients[source_name]
            if source_name not in self._sources:
                raise StorageError(f"Undefined storage source: {source_name}")
            type_name, config = self._sources[source_name]
            _builtin_backends()
            if type_name not in _BACKENDS:
                raise StorageError(
                    f"Storage type {type_name!r} is not registered "
                    f"(available: {sorted(_BACKENDS)})"
                )
            client = _BACKENDS[type_name](config)
            self._clients[source_name] = client
            return client

    def _repo_client(self, repo: str) -> BaseStorageClient:
        return self.client_for_source(self._repositories[repo])

    # -- repository accessors (Storage.scala:370-423) -----------------------
    def get_events(self) -> Events:
        return self._repo_client(EVENT_DATA).events()

    def get_meta_data_apps(self) -> Apps:
        return self._repo_client(META_DATA).apps()

    def get_meta_data_access_keys(self) -> AccessKeys:
        return self._repo_client(META_DATA).access_keys()

    def get_meta_data_channels(self) -> Channels:
        return self._repo_client(META_DATA).channels()

    def get_meta_data_engine_instances(self) -> EngineInstances:
        return self._repo_client(META_DATA).engine_instances()

    def get_meta_data_evaluation_instances(self) -> EvaluationInstances:
        return self._repo_client(META_DATA).evaluation_instances()

    def get_model_data_models(self) -> Models:
        return self._repo_client(MODEL_DATA).models()

    # -- verification (Storage.scala:341-363) -------------------------------
    def verify_all_data_objects(self) -> None:
        """Touch every repository DAO; used by `pio status`."""
        self.get_meta_data_apps()
        self.get_meta_data_access_keys()
        self.get_meta_data_channels()
        self.get_meta_data_engine_instances()
        self.get_meta_data_evaluation_instances()
        self.get_model_data_models()
        self.get_events()

    def close(self) -> None:
        with self._lock:
            for client in self._clients.values():
                client.close()
            self._clients.clear()
