"""Similar-product engine template: ALS item factors + cosine similarity.

Parity: examples/scala-parallel-similarproduct/ — DataSource reads users,
items and "view" events (DataSource.scala), ALSAlgorithm trains implicit
ALS and answers {items, num, categories?, whiteList?, blackList?} queries
with the items most cosine-similar to the query set
(ALSAlgorithm.scala `similar` / productFeatures cosine ranking).

TPU design: similarity ranking is one jitted normalized matmul + top_k
over the device-resident item-factor table (ops/topk.similar_topk) —
no pairwise RDD cartesian.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from predictionio_tpu.controller import (
    Algorithm,
    DataSource,
    Engine,
    FirstServing,
    Params,
    SanityCheck,
    ShardedAlgorithm,
)
from predictionio_tpu.controller.base import PersistentModelManifest
from predictionio_tpu.models.als import ALSModel, build_allow_vector
from predictionio_tpu.ops.als import (
    RatingsCOO,
    als_train,
    resolve_shard_factors,
)
from predictionio_tpu.templates.recommendation import ALSPreparator, TrainingData
from predictionio_tpu.utils.bimap import EntityIdIxMap


@dataclasses.dataclass(frozen=True)
class Query:
    """Parity: similarproduct Query.scala: items, num, categories,
    whiteList, blackList."""

    items: tuple = ()
    num: int = 10
    categories: tuple | None = None
    white_list: tuple | None = None
    black_list: tuple | None = None


@dataclasses.dataclass(frozen=True)
class ItemScore:
    item: str
    score: float


@dataclasses.dataclass(frozen=True)
class PredictedResult:
    item_scores: tuple[ItemScore, ...] = ()


@dataclasses.dataclass(frozen=True)
class SimilarTrainingData(SanityCheck):
    """View triples + per-item category sets."""

    users: np.ndarray
    items: np.ndarray
    ratings: np.ndarray
    categories: dict  # item id -> tuple of category strings

    def sanity_check(self) -> None:
        if len(self.users) == 0:
            raise ValueError("no view events; ingest user-view-item events first")


@dataclasses.dataclass(frozen=True)
class DataSourceParams(Params):
    app_name: str = ""
    event_names: tuple = ("view",)
    entity_type: str = "user"
    target_entity_type: str = "item"
    item_entity_type: str = "item"


class SimilarProductDataSource(DataSource):
    """Reads view events + item $set properties (categories).

    Parity: similarproduct DataSource.scala (viewEvents + items with
    "categories" property).
    """

    params_class = DataSourceParams

    def read_training(self, ctx) -> SimilarTrainingData:
        p = self.params
        users, items, ratings = [], [], []
        for ev in ctx.event_store().find(
            p.app_name,
            entity_type=p.entity_type,
            event_names=list(p.event_names),
            target_entity_type=p.target_entity_type,
        ):
            if ev.target_entity_id is None:
                continue
            users.append(ev.entity_id)
            items.append(ev.target_entity_id)
            ratings.append(1.0)
        categories: dict[str, tuple] = {}
        props = ctx.event_store().aggregate_properties(
            p.app_name, p.item_entity_type
        )
        for item_id, pm in props.items():
            cats = pm.get_opt("categories")
            if cats:
                categories[item_id] = tuple(cats)
        return SimilarTrainingData(
            users=np.asarray(users, dtype=object),
            items=np.asarray(items, dtype=object),
            ratings=np.asarray(ratings, dtype=np.float32),
            categories=categories,
        )


@dataclasses.dataclass(frozen=True)
class SimilarPreparedData:
    coo: RatingsCOO
    user_ids: EntityIdIxMap
    item_ids: EntityIdIxMap
    seen_by_user: dict
    categories: dict


class SimilarProductPreparator(ALSPreparator):
    """ALSPreparator + category carry-through."""

    def prepare(self, ctx, td: SimilarTrainingData) -> SimilarPreparedData:
        base = super().prepare(
            ctx,
            TrainingData(users=td.users, items=td.items, ratings=td.ratings),
        )
        return SimilarPreparedData(
            coo=base.coo,
            user_ids=base.user_ids,
            item_ids=base.item_ids,
            seen_by_user=base.seen_by_user,
            categories=td.categories,
        )


@dataclasses.dataclass(frozen=True)
class ALSAlgorithmParams(Params):
    rank: int = 10
    num_iterations: int = 20
    lambda_: float = 0.01
    alpha: float = 1.0
    seed: int = 3
    use_mesh: bool = True
    #: DP×MP tensor parallelism (engine.json "shardFactors";
    #: env PIO_TRAIN_SHARD_FACTORS=1/0 overrides fleet-wide); see
    #: docs/parallelism.md
    shard_factors: bool = False


@dataclasses.dataclass
class SimilarModel:
    """ALSModel + item categories for query-time filtering."""

    als: ALSModel
    categories: dict  # item id -> tuple of categories


class SimilarALSAlgorithm(ShardedAlgorithm):
    """Implicit ALS; cosine top-k at query time.

    Parity: similarproduct ALSAlgorithm.scala (ALS.trainImplicit ->
    productFeatures cosine similarity with whiteList/blackList/categories
    filters).
    """

    params_class = ALSAlgorithmParams
    query_class = Query
    #: Hu-Koren confidence weighting by default; the add-rateevent
    #: variant flips this to train explicit ALS-WR on rating values
    #: (reference ALSAlgorithm.scala:128 ALS.train vs trainImplicit)
    implicit_prefs = True

    def train(self, ctx, pd: SimilarPreparedData) -> SimilarModel:
        p = self.params
        mesh = ctx.mesh_if_parallel if p.use_mesh else None
        factors = als_train(
            pd.coo,
            rank=p.rank,
            iterations=p.num_iterations,
            lam=p.lambda_,
            implicit=self.implicit_prefs,
            alpha=p.alpha,
            seed=p.seed,
            mesh=mesh,
            shard_factors=resolve_shard_factors(p.shard_factors),
        )
        als = ALSModel(
            rank=p.rank,
            user_factors=factors.user,
            item_factors=factors.item,
            user_ids=pd.user_ids,
            item_ids=pd.item_ids,
            seen_by_user=pd.seen_by_user,
        )
        return SimilarModel(als=als, categories=pd.categories)

    def _allow_vector(self, model: SimilarModel, query: Query) -> np.ndarray | None:
        """Business-rule eligibility as a dense 0/1 vector (fused into the
        scoring kernel, ops/topk)."""
        return build_allow_vector(
            model.als.item_ids,
            categories=query.categories,
            category_map=model.categories,
            white_list=query.white_list,
            black_list=query.black_list,
        )

    def batch_predict(self, model: SimilarModel, queries):
        """Queries carry heterogeneous item lists and per-query business
        rules, so each takes the single-query kernel (already one jitted
        dispatch per query): the base map-over-predict is the right
        implementation, re-exposed past ShardedAlgorithm's must-override
        guard."""
        return Algorithm.batch_predict(self, model, queries)

    def predict(self, model: SimilarModel, query: Query) -> PredictedResult:
        allow = self._allow_vector(model, query)
        sims = model.als.similar(list(query.items), query.num, allow=allow)
        return PredictedResult(
            item_scores=tuple(ItemScore(item=i, score=s) for i, s in sims)
        )

    def make_persistent_model(self, ctx, model: SimilarModel):
        import json
        import os

        from predictionio_tpu.controller.persistent_model import checkpoint_location

        location = checkpoint_location(ctx, "simals")
        model.als.save(location)
        with open(os.path.join(location, "categories.json"), "w") as f:
            json.dump({k: list(v) for k, v in model.categories.items()}, f)
        return PersistentModelManifest(
            class_name=f"{type(self).__module__}.{type(self).__name__}",
            location=location,
        )

    def load_model(self, ctx, manifest: PersistentModelManifest) -> SimilarModel:
        import json
        import os

        als = ALSModel.load(manifest.location)
        with open(os.path.join(manifest.location, "categories.json")) as f:
            categories = {k: tuple(v) for k, v in json.load(f).items()}
        return SimilarModel(als=als, categories=categories)


def engine_factory() -> Engine:
    return Engine(
        data_source_class_map=SimilarProductDataSource,
        preparator_class_map=SimilarProductPreparator,
        algorithm_class_map={"als": SimilarALSAlgorithm, "": SimilarALSAlgorithm},
        serving_class_map=FirstServing,
    )
