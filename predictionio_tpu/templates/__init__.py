"""Official engine templates — the four template families of the reference
(SURVEY.md §2.8): classification (NaiveBayes), recommendation (ALS),
similarproduct (ALS item similarity), ecommercerecommendation (ALS with
business rules)."""
