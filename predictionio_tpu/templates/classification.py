"""Classification engine template: NaiveBayes over entity properties.

Parity: examples/scala-parallel-classification/ — DataSource reads each
user's ``$set`` properties (numeric attr fields + a categorical label,
reference DataSource.scala reads "attr0/1/2" + "plan"), the algorithm is
NaiveBayes (NaiveBayesAlgorithm.scala:33-43 calling MLlib; here
models/naive_bayes on the mesh), and queries carry the attr vector,
answered with the predicted label.

Usage (engine.json):
    {"engineFactory":
       "predictionio_tpu.templates.classification.engine_factory",
     "datasource": {"params": {"app_name": "MyApp",
                               "attrs": ["attr0", "attr1", "attr2"],
                               "label": "plan"}},
     "algorithms": [{"name": "naive", "params": {"smoothing": 1.0}}]}
"""

from __future__ import annotations

import dataclasses
from typing import Sequence

import numpy as np

from predictionio_tpu.controller import (
    AverageMetric,
    DataSource,
    Engine,
    EngineParams,
    EngineParamsGenerator,
    Evaluation,
    FirstServing,
    HostModelAlgorithm,
    IdentityPreparator,
    MetricEvaluator,
    Params,
    SanityCheck,
)
from predictionio_tpu.models import logreg, naive_bayes
from predictionio_tpu.utils.bimap import BiMap


@dataclasses.dataclass(frozen=True)
class DataSourceParams(Params):
    app_name: str = ""
    attrs: tuple = ("attr0", "attr1", "attr2")
    label: str = "plan"
    entity_type: str = "user"
    eval_k: int = 0  # >0 enables k-fold read_eval


@dataclasses.dataclass(frozen=True)
class TrainingData(SanityCheck):
    """Dense features [N, F] + integer labels [N] + label vocabulary."""

    features: np.ndarray
    labels: np.ndarray
    label_map: BiMap

    def sanity_check(self) -> None:
        if len(self.features) == 0:
            raise ValueError(
                "training data is empty; ingest $set events with attr/label "
                "properties first"
            )
        if len(self.features) != len(self.labels):
            raise ValueError("features/labels length mismatch")


@dataclasses.dataclass(frozen=True)
class Query:
    attrs: Sequence[float]


@dataclasses.dataclass(frozen=True)
class PredictedResult:
    label: str
    scores: dict


class ClassificationDataSource(DataSource):
    """Reads aggregated entity properties into dense arrays.

    Parity: examples/scala-parallel-classification/.../DataSource.scala
    (aggregateProperties over users -> LabeledPoint).
    """

    params_class = DataSourceParams

    def _read(self, ctx) -> TrainingData:
        p = self.params
        props = ctx.event_store().aggregate_properties(
            p.app_name, p.entity_type, required=list(p.attrs) + [p.label]
        )
        rows, labels = [], []
        for entity_id, pm in sorted(props.items()):
            rows.append([pm.get(a, float) for a in p.attrs])
            labels.append(str(pm.get(p.label)))
        label_map = BiMap.string_int(sorted(set(labels)))
        return TrainingData(
            features=np.asarray(rows, dtype=np.float32).reshape(len(rows), len(p.attrs)),
            labels=np.asarray([label_map[l] for l in labels], dtype=np.int32),
            label_map=label_map,
        )

    def read_training(self, ctx) -> TrainingData:
        return self._read(ctx)

    def read_eval(self, ctx):
        """k-fold split by row index (e2 CrossValidation parity,
        e2/.../evaluation/CrossValidation.scala:24-76)."""
        p = self.params
        full = self._read(ctx)
        folds = []
        n = len(full.labels)
        idx = np.arange(n)
        for k in range(p.eval_k):
            test_mask = (idx % p.eval_k) == k
            td = TrainingData(
                features=full.features[~test_mask],
                labels=full.labels[~test_mask],
                label_map=full.label_map,
            )
            inv = full.label_map.inverse
            qa = [
                (Query(attrs=tuple(map(float, full.features[i]))), inv[int(full.labels[i])])
                for i in np.where(test_mask)[0]
            ]
            folds.append((td, {"fold": k}, qa))
        return folds


@dataclasses.dataclass(frozen=True)
class AlgorithmParams(Params):
    smoothing: float = 1.0
    use_mesh: bool = True


@dataclasses.dataclass
class NBModel:
    nb: naive_bayes.MultinomialNBModel
    label_map: BiMap


def _results_from_log_probs(queries, log_probs, label_map: BiMap):
    """Shared (index, PredictedResult) assembly from a [N, C] score
    matrix of per-label log probabilities."""
    rows = np.asarray(log_probs)
    best = rows.argmax(axis=1)
    inv = label_map.inverse
    return [
        (i, PredictedResult(
            label=inv[int(b)],
            scores={inv[int(c)]: float(s) for c, s in enumerate(row)},
        ))
        for (i, _), b, row in zip(queries, best, rows)
    ]


def _query_features(queries):
    import jax.numpy as jnp

    return jnp.asarray([list(q.attrs) for _, q in queries], dtype=jnp.float32)


class NaiveBayesAlgorithm(HostModelAlgorithm):
    """Parity: NaiveBayesAlgorithm.scala:33-43 (MLlib NaiveBayes.train ->
    models/naive_bayes.train_multinomial on the mesh)."""

    params_class = AlgorithmParams
    query_class = Query

    def train(self, ctx, pd: TrainingData) -> NBModel:
        mesh = ctx.mesh_if_parallel if self.params.use_mesh else None
        nb = naive_bayes.train_multinomial(
            pd.features,
            pd.labels,
            num_classes=len(pd.label_map),
            smoothing=self.params.smoothing,
            mesh=mesh,
        )
        return NBModel(nb=nb, label_map=pd.label_map)

    def predict(self, model: NBModel, query: Query) -> PredictedResult:
        return self.batch_predict(model, [(0, query)])[0][1]

    def batch_predict(self, model: NBModel, queries):
        import jax.nn

        if not queries:
            return []
        scores = naive_bayes.predict_multinomial_scores(
            model.nb.log_prior, model.nb.log_theta, _query_features(queries)
        )
        # normalize the log-joint to per-label log posteriors so scores
        # are comparable across algorithms (BlendedServing averages them
        # with logreg's log_softmax outputs; argmax is unchanged)
        return _results_from_log_probs(
            queries, jax.nn.log_softmax(scores, axis=1), model.label_map
        )


# ---------------------------------------------------------------------------
# Second algorithm: logistic regression (the add-algorithm variant).
# Role parity: examples/scala-parallel-classification/add-algorithm adds
# MLlib RandomForest beside NaiveBayes to demonstrate heterogeneous
# multi-algorithm engines; the TPU-native second learner is softmax
# regression (models/logreg — random forests are scalar-branchy and map
# poorly to the MXU).
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class LogRegAlgorithmParams(Params):
    iterations: int = 300
    lr: float = 0.1
    l2: float = 1e-4
    use_mesh: bool = True


@dataclasses.dataclass
class LRModel:
    lr: logreg.LogRegModel
    label_map: BiMap


class LogisticRegressionAlgorithm(HostModelAlgorithm):
    """Parity role: RandomForestAlgorithm.scala (the second learner in the
    add-algorithm variant); same Query/PredictedResult contract as
    NaiveBayesAlgorithm so both can serve in one engine."""

    params_class = LogRegAlgorithmParams
    query_class = Query

    def train(self, ctx, pd: TrainingData) -> LRModel:
        p = self.params
        mesh = ctx.mesh_if_parallel if p.use_mesh else None
        model = logreg.train_logreg(
            pd.features,
            pd.labels,
            num_classes=len(pd.label_map),
            l2=p.l2,
            iterations=p.iterations,
            lr=p.lr,
            mesh=mesh,
        )
        return LRModel(lr=model, label_map=pd.label_map)

    def predict(self, model: LRModel, query: Query) -> PredictedResult:
        return self.batch_predict(model, [(0, query)])[0][1]

    def batch_predict(self, model: LRModel, queries):
        if not queries:
            return []
        scores = logreg.predict_logreg_scores(
            model.lr.weights, _query_features(queries)
        )
        return _results_from_log_probs(queries, scores, model.label_map)


class BlendedServing(FirstServing):
    """Average per-label scores across algorithms and re-argmax — a
    blended multi-algorithm result (the reference's add-algorithm Serving
    keeps `predictedResults.head`; blending is the natural upgrade once
    both learners emit per-label log scores)."""

    def serve(self, query: Query, predictions) -> PredictedResult:
        if len(predictions) == 1:
            return predictions[0]
        blended: dict[str, float] = {}
        for pred in predictions:
            for label, score in pred.scores.items():
                blended[label] = blended.get(label, 0.0) + score / len(predictions)
        if not blended:
            return predictions[0]
        best = max(blended, key=blended.get)
        return PredictedResult(label=best, scores=blended)


def engine_factory() -> Engine:
    return Engine(
        data_source_class_map=ClassificationDataSource,
        preparator_class_map=IdentityPreparator,
        algorithm_class_map={
            "naive": NaiveBayesAlgorithm,
            "logreg": LogisticRegressionAlgorithm,
            "": NaiveBayesAlgorithm,
        },
        serving_class_map={
            "": FirstServing,
            "first": FirstServing,
            "blended": BlendedServing,
        },
    )


# ---------------------------------------------------------------------------
# Evaluation: Accuracy over k-fold splits (role of the reference
# classification template's AccuracyEvaluation in
# examples/scala-parallel-classification/.../Evaluation.scala)
# ---------------------------------------------------------------------------


class Accuracy(AverageMetric):
    """1.0 when the predicted label equals the held-out label."""

    def calculate_qpa(self, q, p, a) -> float:
        return 1.0 if p.label == a else 0.0


class ClassificationEvaluation(Evaluation):
    """`pio eval predictionio_tpu.templates.classification.ClassificationEvaluation
    predictionio_tpu.templates.classification.DefaultParamsList`"""

    def __init__(self, output_path: str | None = "best.json"):
        super().__init__()
        self.engine_evaluator = (
            engine_factory(),
            MetricEvaluator(Accuracy(), output_path=output_path),
        )


class DefaultParamsList(EngineParamsGenerator):
    """Smoothing grid like the reference's EngineParamsList."""

    def __init__(self, app_name: str = "ClassApp", eval_k: int = 3,
                 attrs: tuple = ("attr0", "attr1", "attr2"),
                 label: str = "plan"):
        super().__init__([
            EngineParams.of(
                data_source=DataSourceParams(app_name=app_name, attrs=attrs,
                                             label=label, eval_k=eval_k),
                algorithms=[("naive", AlgorithmParams(smoothing=s))],
            )
            for s in (0.5, 1.0, 2.0)
        ])
