"""Session-based sequential recommendation engine template.

Next-item prediction over each user's time-ordered event history with a
causal transformer (models/seqrec, SASRec-family) — the neural
counterpart of the reference's MarkovChain transition model
(e2/.../engine/MarkovChain.scala:26-84) and its experimental
complementary-purchase template family (examples/experimental). Query
{"user": ..., "num": N} (or {"items": [recent ids], "num": N}) answers
with the N most likely next items.

Long sessions are first-class: with engine.json mesh axes
{"data": D, "seq": S} the attention runs as ring attention over the
"seq" mesh axis (ops/attention.py), so context length scales across
devices over ICI.

Usage (engine.json):
    {"engineFactory":
       "predictionio_tpu.templates.sessionrec.engine_factory",
     "datasource": {"params": {"app_name": "MyApp",
                               "event_names": ["view", "buy"]}},
     "algorithms": [{"name": "seqrec",
                     "params": {"d_model": 64, "n_layers": 2,
                                "max_len": 64, "epochs": 20}}]}
"""

from __future__ import annotations

import dataclasses
from typing import Any, Mapping, Sequence

import numpy as np

from predictionio_tpu.controller import (
    DataSource,
    Engine,
    EngineParams,
    EngineParamsGenerator,
    Evaluation,
    FirstServing,
    HostModelAlgorithm,
    IdentityPreparator,
    MetricEvaluator,
    OptionAverageMetric,
    Params,
    SanityCheck,
)
from predictionio_tpu.models import seqrec
from predictionio_tpu.utils.bimap import BiMap

_NEG = np.float32(-1e30)


@dataclasses.dataclass(frozen=True)
class Query:
    user: str = ""
    items: tuple = ()        # explicit recent-item history (overrides user)
    num: int = 10
    black_list: tuple = ()


@dataclasses.dataclass(frozen=True)
class ItemScore:
    item: str
    score: float


@dataclasses.dataclass(frozen=True)
class PredictedResult:
    item_scores: tuple = ()


@dataclasses.dataclass(frozen=True)
class DataSourceParams(Params):
    app_name: str = ""
    event_names: tuple = ("view", "buy")
    entity_type: str = "user"
    target_entity_type: str = "item"
    min_sequence_len: int = 2
    eval_k: int = 0


@dataclasses.dataclass
class TrainingData(SanityCheck):
    sequences: dict  # user id -> [item ids, time-ordered]

    def sanity_check(self) -> None:
        assert self.sequences, "no user event sequences found"


class SessionDataSource(DataSource):
    """Reads per-user time-ordered item interaction sequences.

    The event scan mirrors the reference recommendation DataSource
    (tests/pio_tests/engines/recommendation-engine/src/main/scala/
    DataSource.scala:38-105) but keeps event order instead of folding
    to ratings."""

    params_class = DataSourceParams

    def _read(self, ctx) -> TrainingData:
        p = self.params
        events = ctx.event_store().find(
            p.app_name,
            entity_type=p.entity_type,
            event_names=list(p.event_names),
            target_entity_type=p.target_entity_type,
        )
        per_user: dict[str, list] = {}
        for ev in events:
            if not ev.target_entity_id:
                continue
            per_user.setdefault(ev.entity_id, []).append(
                (ev.event_time, ev.target_entity_id)
            )
        sequences = {
            user: [item for _, item in sorted(pairs, key=lambda t: t[0])]
            for user, pairs in per_user.items()
        }
        sequences = {
            u: seq for u, seq in sequences.items()
            if len(seq) >= self.params.min_sequence_len
        }
        return TrainingData(sequences=sequences)

    def read_training(self, ctx) -> TrainingData:
        return self._read(ctx)

    def read_eval(self, ctx):
        """Leave-one-out per fold: hold out each user's final item
        (the standard sequential-recommendation protocol)."""
        p = self.params
        full = self._read(ctx)
        folds = []
        users = sorted(full.sequences)
        k = max(p.eval_k, 1)
        for fold in range(k):
            train_seqs, qa = {}, []
            for i, u in enumerate(users):
                seq = full.sequences[u]
                if i % k == fold and len(seq) > p.min_sequence_len:
                    train_seqs[u] = seq[:-1]
                    qa.append((Query(user=u), seq[-1]))
                else:
                    train_seqs[u] = seq
            folds.append((TrainingData(sequences=train_seqs), {"fold": fold}, qa))
        return folds


@dataclasses.dataclass(frozen=True)
class AlgorithmParams(Params):
    d_model: int = 64
    n_heads: int = 2
    n_layers: int = 2
    max_len: int = 64
    epochs: int = 20
    batch_size: int = 64
    lr: float = 1e-3
    seed: int = 0
    use_mesh: bool = True
    remat: bool = False  # jax.checkpoint each block (long-context memory)
    # mid-training checkpoint/resume (models/seqrec): state written every
    # N epochs to checkpoint_dir; a re-run resumes from the last one
    checkpoint_dir: str = ""
    checkpoint_every: int = 0


@dataclasses.dataclass
class SeqRecEngineModel:
    params: dict            # transformer weights (host numpy pytree)
    cfg: seqrec.SeqRecConfig
    item_index: BiMap       # item id string -> dense index (1-based)
    histories: dict         # user -> [dense item indices] (serving state)
    # device-resident weight cache, populated on first predict; never
    # serialized (recreated after checkpoint load / reload)
    device_tree: Any = dataclasses.field(default=None, repr=False,
                                         compare=False)

    def __getstate__(self):
        state = self.__dict__.copy()
        state["device_tree"] = None
        return state


class SeqRecAlgorithm(HostModelAlgorithm):
    """Trains the causal transformer on the mesh; serves jitted top-k."""

    params_class = AlgorithmParams
    query_class = Query

    def train(self, ctx, pd: TrainingData) -> SeqRecEngineModel:
        p = self.params
        items = sorted({i for seq in pd.sequences.values() for i in seq})
        # dense ids start at 1: index 0 is the PAD token
        item_index = BiMap({item: i + 1 for i, item in enumerate(items)})
        dense = {
            u: [item_index[i] for i in seq] for u, seq in pd.sequences.items()
        }
        cfg = seqrec.SeqRecConfig(
            vocab=len(items) + 1,
            max_len=p.max_len,
            d_model=p.d_model,
            n_heads=p.n_heads,
            n_layers=p.n_layers,
            remat=p.remat,
        )
        mesh = ctx.mesh_if_parallel if p.use_mesh else None
        if mesh is not None and "seq" in mesh.shape and \
                p.max_len % int(mesh.shape["seq"]):
            raise ValueError(
                f"max_len {p.max_len} must be a multiple of the seq mesh "
                f"axis size ({int(mesh.shape['seq'])})"
            )
        weights = seqrec.train(
            list(dense.values()), cfg,
            epochs=p.epochs, batch_size=p.batch_size, lr=p.lr,
            seed=p.seed, mesh=mesh,
            checkpoint_dir=p.checkpoint_dir or None,
            checkpoint_every=p.checkpoint_every,
        )
        import jax

        return SeqRecEngineModel(
            params=jax.tree.map(np.asarray, weights),
            cfg=cfg,
            item_index=item_index,
            histories=dense,
        )

    # -- serving ------------------------------------------------------------

    def _history_for(self, model: SeqRecEngineModel, query: Query):
        if query.items:
            return [
                model.item_index.get(i)
                for i in query.items
                if model.item_index.get(i) is not None
            ]
        return model.histories.get(query.user, [])

    def predict(self, model: SeqRecEngineModel, query: Query) -> PredictedResult:
        # single-query serving is the B=1 case of the batched path —
        # one mask/history implementation keeps the two in lockstep
        return self.batch_predict(model, [(0, query)])[0][1]

    def batch_predict(self, model: SeqRecEngineModel, queries):
        """Batched eval path: power-of-two batch buckets through one
        jitted forward (seqrec.predict_topk_batch with per-query masks)
        instead of |queries| B=1 calls — the Engine.eval hot path."""
        import jax.numpy as jnp

        S = model.cfg.max_len
        base_mask = np.zeros((model.cfg.vocab,), np.float32)
        base_mask[seqrec.PAD] = _NEG
        prepared, out = [], []
        for i, q in queries:
            history = self._history_for(model, q)
            if not history:
                out.append((i, PredictedResult()))
                continue
            tail = history[-S:]
            hist = np.zeros((S,), np.int32)
            hist[: len(tail)] = tail
            mask = base_mask.copy()
            for dense_id in tail:               # don't repeat the session
                mask[dense_id] = _NEG
            for item in q.black_list:
                di = model.item_index.get(item)
                if di is not None:
                    mask[di] = _NEG
            prepared.append((i, q, hist, mask))
        if not prepared:
            return out

        # menu-ized STATIC top-k width (ops/topk.serving_k: client-
        # controlled num must not retrace predict_topk_batch; results
        # trim per query below)
        from predictionio_tpu.ops.topk import serving_k

        k = serving_k(max(q.num for _, q, _, _ in prepared),
                      model.cfg.vocab - 1)
        inv = model.item_index.inverse
        pos = 0
        while pos < len(prepared):
            remaining = len(prepared) - pos
            bucket = 1
            while bucket * 2 <= min(remaining, 256):
                bucket *= 2
            chunk = prepared[pos : pos + bucket]
            pos += bucket
            scores, ids = seqrec.predict_topk_batch(
                _as_device_tree(model),
                jnp.asarray(np.stack([h for _, _, h, _ in chunk])),
                k, model.cfg,
                jnp.asarray(np.stack([m for _, _, _, m in chunk])),
            )
            for (i, q, _, _), svals, sids in zip(
                    chunk, np.asarray(scores), np.asarray(ids)):
                items = []
                for v, ix in zip(svals[: q.num], sids[: q.num]):
                    if v <= _NEG / 2:
                        continue
                    item = inv.get(int(ix))
                    if item is not None:
                        items.append(ItemScore(item=item, score=float(v)))
                out.append((i, PredictedResult(item_scores=tuple(items))))
        return out


def _as_device_tree(model: SeqRecEngineModel):
    """Device-put the weight pytree once per model instance (serving keeps
    models HBM-resident between requests — SURVEY.md §7 stage 7). Cached
    on the model object itself, so a hot-swap (/reload) naturally drops
    the old device weights with the old model."""
    if model.device_tree is None:
        import jax

        model.device_tree = jax.tree.map(jax.device_put, dict(model.params))
    return model.device_tree


def engine_factory() -> Engine:
    return Engine(
        data_source_class_map=SessionDataSource,
        preparator_class_map=IdentityPreparator,
        algorithm_class_map={"seqrec": SeqRecAlgorithm},
        serving_class_map=FirstServing,
    )


# ---------------------------------------------------------------------------
# Evaluation: HitRate@K over leave-one-out folds (the standard
# sequential-recommendation protocol; read_eval holds out each user's
# final item). Role of the per-template Evaluation.scala in the
# reference template families.
# ---------------------------------------------------------------------------


class HitRateAtK(OptionAverageMetric):
    """1.0 when the held-out next item appears in the top-k, else 0."""

    def __init__(self, k: int = 10):
        self.k = k

    @property
    def header(self) -> str:
        return f"HitRate@{self.k}"

    def calculate_qpa(self, q, p, a) -> float | None:
        # the held-out item always exists, so an empty prediction is a
        # miss (0.0), never a skip — None would inflate the average
        top = [s.item for s in p.item_scores[: self.k]]
        return 1.0 if a in top else 0.0


class SessionRecEvaluation(Evaluation):
    """`pio eval predictionio_tpu.templates.sessionrec.SessionRecEvaluation
    predictionio_tpu.templates.sessionrec.DefaultParamsList`"""

    def __init__(self, k: int = 10, output_path: str | None = "best.json"):
        super().__init__()
        self.engine_evaluator = (
            engine_factory(),
            MetricEvaluator(HitRateAtK(k=k), output_path=output_path),
        )


class DefaultParamsList(EngineParamsGenerator):
    def __init__(self, app_name: str = "SessApp", eval_k: int = 2):
        super().__init__([
            EngineParams.of(
                data_source=DataSourceParams(app_name=app_name, eval_k=eval_k),
                algorithms=[(
                    "seqrec",
                    AlgorithmParams(d_model=d, n_layers=layers, max_len=32,
                                    epochs=15, batch_size=32, lr=3e-3),
                )],
            )
            for d in (32, 64)
            for layers in (1, 2)
        ])

