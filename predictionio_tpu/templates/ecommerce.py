"""E-commerce recommendation template: implicit ALS + business rules.

Parity: examples/scala-parallel-ecommercerecommendation/ — DataSource
reads view/buy events plus item $set properties; ECommAlgorithm trains
implicit ALS; queries {user, num, categories?, whiteList?, blackList?}
are answered with business-rule filtering: seen items, query black/white
lists, category membership, and "unavailableItems" read live from a
constraint entity at query time (ECommAlgorithm.scala's
`predictKnownUser` / filter chain). Unknown users fall back to ranking
by items similar to their recent views (`predictSimilar` path).

TPU design: every filter becomes a 0/1 eligibility vector multiplied
into the jitted score+top_k kernel — the rule chain costs one fused
elementwise op instead of per-item RDD filters.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from predictionio_tpu.controller import (
    Algorithm,
    DataSource,
    Engine,
    FirstServing,
    Params,
    SanityCheck,
    ShardedAlgorithm,
)
from predictionio_tpu.controller.base import PersistentModelManifest
from predictionio_tpu.models.als import ALSModel, build_allow_vector
from predictionio_tpu.ops.als import (
    RatingsCOO,
    als_train,
    resolve_shard_factors,
)
from predictionio_tpu.templates.recommendation import ALSPreparator, TrainingData
from predictionio_tpu.utils.bimap import EntityIdIxMap


@dataclasses.dataclass(frozen=True)
class Query:
    user: str = ""
    num: int = 10
    categories: tuple | None = None
    white_list: tuple | None = None
    black_list: tuple | None = None


@dataclasses.dataclass(frozen=True)
class ItemScore:
    item: str
    score: float


@dataclasses.dataclass(frozen=True)
class PredictedResult:
    item_scores: tuple[ItemScore, ...] = ()


@dataclasses.dataclass(frozen=True)
class ECommTrainingData(SanityCheck):
    users: np.ndarray
    items: np.ndarray
    weights: np.ndarray
    categories: dict  # item id -> tuple of categories

    def sanity_check(self) -> None:
        if len(self.users) == 0:
            raise ValueError("no view/buy events; ingest events first")


@dataclasses.dataclass(frozen=True)
class DataSourceParams(Params):
    app_name: str = ""
    view_events: tuple = ("view",)
    buy_events: tuple = ("buy",)
    buy_weight: float = 4.0  # buys count more than views in the confidence
    entity_type: str = "user"
    target_entity_type: str = "item"
    item_entity_type: str = "item"


class ECommDataSource(DataSource):
    """Parity: ecommercerecommendation DataSource.scala (viewEvents,
    buyEvents, items with categories)."""

    params_class = DataSourceParams

    def read_training(self, ctx) -> ECommTrainingData:
        p = self.params
        users, items, weights = [], [], []
        store = ctx.event_store()
        for names, weight in ((p.view_events, 1.0), (p.buy_events, p.buy_weight)):
            for ev in store.find(
                p.app_name,
                entity_type=p.entity_type,
                event_names=list(names),
                target_entity_type=p.target_entity_type,
            ):
                if ev.target_entity_id is None:
                    continue
                users.append(ev.entity_id)
                items.append(ev.target_entity_id)
                weights.append(weight)
        categories: dict[str, tuple] = {}
        for item_id, pm in store.aggregate_properties(
            p.app_name, p.item_entity_type
        ).items():
            cats = pm.get_opt("categories")
            if cats:
                categories[item_id] = tuple(cats)
        return ECommTrainingData(
            users=np.asarray(users, dtype=object),
            items=np.asarray(items, dtype=object),
            weights=np.asarray(weights, dtype=np.float32),
            categories=categories,
        )


@dataclasses.dataclass(frozen=True)
class ECommPreparedData:
    coo: RatingsCOO
    user_ids: EntityIdIxMap
    item_ids: EntityIdIxMap
    seen_by_user: dict
    categories: dict


class ECommPreparator(ALSPreparator):
    def prepare(self, ctx, td: ECommTrainingData) -> ECommPreparedData:
        base = super().prepare(
            ctx,
            TrainingData(users=td.users, items=td.items, ratings=td.weights),
        )
        return ECommPreparedData(
            coo=base.coo,
            user_ids=base.user_ids,
            item_ids=base.item_ids,
            seen_by_user=base.seen_by_user,
            categories=td.categories,
        )


@dataclasses.dataclass(frozen=True)
class ECommAlgorithmParams(Params):
    """Parity: ECommAlgorithmParams (appName/unseenOnly/seenEvents/
    similarEvents/rank/numIterations/lambda/alpha/seed)."""

    app_name: str = ""
    unseen_only: bool = True
    similar_events: tuple = ("view",)
    unavailable_constraint_entity: str = "constraint"
    unavailable_constraint_id: str = "unavailableItems"
    recent_events_num: int = 10
    rank: int = 10
    num_iterations: int = 20
    lambda_: float = 0.01
    alpha: float = 1.0
    seed: int = 3
    use_mesh: bool = True
    #: DP×MP tensor parallelism (engine.json "shardFactors";
    #: env PIO_TRAIN_SHARD_FACTORS=1/0 overrides fleet-wide); see
    #: docs/parallelism.md
    shard_factors: bool = False


@dataclasses.dataclass
class ECommModel:
    als: ALSModel
    categories: dict


class ECommAlgorithm(ShardedAlgorithm):
    """Implicit ALS + live business-rule filtering.

    Parity: ECommAlgorithm.scala — train:ALS.trainImplicit;
    predict: known user -> filtered personal top-k, unknown user ->
    similar-to-recent-views top-k; unavailable items re-read per query.
    """

    params_class = ECommAlgorithmParams
    query_class = Query

    def __init__(self, params=None):
        super().__init__(params)
        self._ctx = None

    def train(self, ctx, pd: ECommPreparedData) -> ECommModel:
        p = self.params
        self._ctx = ctx
        mesh = ctx.mesh_if_parallel if p.use_mesh else None
        factors = als_train(
            pd.coo,
            rank=p.rank,
            iterations=p.num_iterations,
            lam=p.lambda_,
            implicit=True,
            alpha=p.alpha,
            seed=p.seed,
            mesh=mesh,
            shard_factors=resolve_shard_factors(p.shard_factors),
        )
        als = ALSModel(
            rank=p.rank,
            user_factors=factors.user,
            item_factors=factors.item,
            user_ids=pd.user_ids,
            item_ids=pd.item_ids,
            seen_by_user=pd.seen_by_user,
        )
        return ECommModel(als=als, categories=pd.categories)

    # -- query-time helpers -------------------------------------------------
    def _unavailable_items(self) -> set[str]:
        """Live read of the unavailableItems constraint ($set on a
        constraint entity — ECommAlgorithm.scala's
        LEventStore.findByEntity("constraint", "unavailableItems"))."""
        p = self.params
        if self._ctx is None or not p.app_name:
            return set()
        try:
            events = list(
                self._ctx.event_store().find_by_entity(
                    p.app_name,
                    p.unavailable_constraint_entity,
                    p.unavailable_constraint_id,
                    event_names=["$set"],
                    limit=1,
                    latest=True,
                )
            )
        except Exception:
            return set()
        if not events:
            return set()
        items = events[0].properties.get_opt("items")
        return set(items) if items else set()

    def _recent_items(self, user: str) -> list[str]:
        """The user's recent viewed items (for the unknown-user fallback).
        Parity: ECommAlgorithm's recentEvents query."""
        p = self.params
        if self._ctx is None or not p.app_name:
            return []
        try:
            events = self._ctx.event_store().find_by_entity(
                p.app_name,
                "user",
                user,
                event_names=list(p.similar_events),
                limit=p.recent_events_num,
                latest=True,
            )
            return [e.target_entity_id for e in events if e.target_entity_id]
        except Exception:
            return []

    def _allow_vector(self, model: ECommModel,
                      query: Query) -> np.ndarray | None:
        item_ids = model.als.item_ids
        n = len(item_ids)
        allow = build_allow_vector(
            item_ids,
            categories=query.categories,
            category_map=model.categories,
            white_list=query.white_list,
            black_list=query.black_list,
        )
        unavailable = self._unavailable_items()
        if allow is None:
            if not unavailable:
                # genuinely unrestricted: None (not an all-ones array)
                # keeps the fast default-allow path AND lets the online
                # overlay's cold-start items merge — an allow vector is
                # catalog-indexed and would force catalog-only serving
                # (models/als._recommend_online; docs/freshness.md)
                return None
            allow = np.ones(n, dtype=np.float32)
        for item_id in unavailable:
            ix = item_ids.get(item_id)
            if ix is not None:
                allow[ix] = 0.0
        return allow

    def batch_predict(self, model: ECommModel, queries):
        """Per-query business rules (categories/lists/availability) need a
        per-query allow vector, so each query takes the single-query
        path: the base map-over-predict is the right implementation,
        re-exposed past ShardedAlgorithm's must-override guard."""
        return Algorithm.batch_predict(self, model, queries)

    def predict(self, model: ECommModel, query: Query) -> PredictedResult:
        allow = self._allow_vector(model, query)
        # an online-folded user has a served vector even when absent
        # from training (cold-start-to-served; docs/freshness.md)
        if (query.user in model.als.user_ids
                or model.als.online_delta(query.user) is not None):
            recs = model.als.recommend(
                query.user, query.num, allow=allow,
                exclude_seen=self.params.unseen_only,
            )
        else:
            recent = self._recent_items(query.user)
            recs = model.als.similar(recent, query.num, allow=allow) if recent else []
        return PredictedResult(
            item_scores=tuple(ItemScore(item=i, score=s) for i, s in recs)
        )

    def make_persistent_model(self, ctx, model: ECommModel):
        import json
        import os

        from predictionio_tpu.controller.persistent_model import checkpoint_location

        location = checkpoint_location(ctx, "ecomm")
        model.als.save(location)
        with open(os.path.join(location, "categories.json"), "w") as f:
            json.dump({k: list(v) for k, v in model.categories.items()}, f)
        return PersistentModelManifest(
            class_name=f"{type(self).__module__}.{type(self).__name__}",
            location=location,
        )

    def load_model(self, ctx, manifest: PersistentModelManifest) -> ECommModel:
        import json
        import os

        self._ctx = ctx
        als = ALSModel.load(manifest.location)
        with open(os.path.join(manifest.location, "categories.json")) as f:
            categories = {k: tuple(v) for k, v in json.load(f).items()}
        return ECommModel(als=als, categories=categories)


def engine_factory() -> Engine:
    return Engine(
        data_source_class_map=ECommDataSource,
        preparator_class_map=ECommPreparator,
        algorithm_class_map={"ecomm": ECommAlgorithm, "": ECommAlgorithm},
        serving_class_map=FirstServing,
    )
