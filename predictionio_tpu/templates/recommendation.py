"""Recommendation engine template: ALS over rate/buy events.

Parity: examples/scala-parallel-recommendation/ and the canonical copy at
tests/pio_tests/engines/recommendation-engine/ — DataSource reads "rate"
and "buy" events (DataSource.scala:38-105; buy counts as rating 4.0),
ALSAlgorithm trains MLlib ALS over BiMap-indexed ratings
(ALSAlgorithm.scala:40-120), queries are {user, num} answered with
ranked item scores, and readEval provides k-fold splits for Precision@K
evaluation (Evaluation.scala).

TPU design: the Preparator is the ragged→static boundary (builds dense
indices + padded rating buckets); the algorithm is a ShardedAlgorithm
whose factor tables are computed by ops/als on the mesh and stay
device-resident for serving; top-k ranking is one jitted matmul+top_k
(ops/topk) instead of per-user RDD sorts.
"""

from __future__ import annotations

import dataclasses
from typing import Sequence

import numpy as np

from predictionio_tpu.controller import (
    EngineParams,
    EngineParamsGenerator,
    Evaluation,
    MetricEvaluator,
    OptionAverageMetric,
    DataSource,
    Engine,
    FirstServing,
    Params,
    Preparator,
    SanityCheck,
    ShardedAlgorithm,
)
from predictionio_tpu.controller.base import PersistentModelManifest
from predictionio_tpu.models.als import ALSModel, build_allow_vector
from predictionio_tpu.ops import topk as topk_ops
from predictionio_tpu.ops.als import (
    RatingsCOO,
    als_train,
    resolve_shard_factors,
)
from predictionio_tpu.utils.bimap import EntityIdIxMap


# ---------------------------------------------------------------------------
# Data types (Query/PredictedResult parity with the reference template JSON)
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class Query:
    """{user, num} plus the custom-query variant's optional id filters
    (reference: examples/scala-parallel-recommendation/custom-query —
    whiteList/blackList narrowing; category-based filtering is the
    ecommerce template's role)."""

    user: str
    num: int = 10
    white_list: tuple | None = None  # None = no restriction; [] = none eligible
    black_list: tuple | None = None  # always excluded


@dataclasses.dataclass(frozen=True)
class ItemScore:
    item: str
    score: float


@dataclasses.dataclass(frozen=True)
class PredictedResult:
    item_scores: tuple[ItemScore, ...] = ()


@dataclasses.dataclass(frozen=True)
class TrainingData(SanityCheck):
    """Raw (user, item, rating) triples as host object arrays."""

    users: np.ndarray
    items: np.ndarray
    ratings: np.ndarray

    def sanity_check(self) -> None:
        if len(self.users) == 0:
            raise ValueError(
                "ratings are empty; ingest rate/buy events first "
                "(reference DataSource.scala sanity: train with events)"
            )


@dataclasses.dataclass(frozen=True)
class PreparedData:
    """Dense-index ratings + id maps + per-user seen items: everything the
    mesh kernels need, all static-shaped."""

    coo: RatingsCOO
    user_ids: EntityIdIxMap
    item_ids: EntityIdIxMap
    seen_by_user: dict[int, np.ndarray]


# ---------------------------------------------------------------------------
# DataSource
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class DataSourceParams(Params):
    app_name: str = ""
    event_names: tuple = ("rate", "buy")
    buy_rating: float = 4.0  # reference: buy event treated as rating 4
    entity_type: str = "user"
    target_entity_type: str = "item"
    eval_k: int = 0
    eval_query_num: int = 10
    seed: int = 3


def ratings_from_columns(cols, buy_rating: float):
    """One EventColumns batch -> (users, items, ratings) arrays, or
    None when nothing survives. The columnar rating rule, vectorized:
    rows need a target entity (code compare against the batch's None
    code), ``rate`` events take their properties' ``rating`` (rows
    whose rating is missing/malformed are dropped — the row-path rule),
    everything else is an implicit signal worth ``buy_rating``. Shared
    by the DataSource and bench_ingest.py so the benchmark measures
    exactly the code the train path runs."""
    n = len(cols)
    if n == 0:
        return None
    none_code = cols.target_entity_id.code_of(None)
    keep = np.ones(n, dtype=bool)
    if none_code is not None:
        keep &= cols.target_entity_id.codes != none_code
    ratings = np.full(n, buy_rating, dtype=np.float32)
    rate_code = cols.event.code_of("rate")
    if rate_code is not None:
        for i in np.nonzero(keep & (cols.event.codes == rate_code))[0]:
            try:
                ratings[i] = float(cols.properties_raw(int(i)).get("rating"))
            except (KeyError, TypeError, ValueError):
                keep[i] = False
    idx = np.nonzero(keep)[0]
    if len(idx) == 0:
        return None
    return (cols.entity_id.decode()[idx],
            cols.target_entity_id.decode()[idx],
            ratings[idx])


class RecommendationDataSource(DataSource):
    """Reads rate/buy events into rating triples.

    Parity: recommendation-engine DataSource.scala:38-105 (getRatings:
    rate -> rating value, buy -> fixed 4.0; latest event wins per pair is
    NOT applied — the reference keeps all, MLlib averages duplicates;
    here duplicates are kept and the ALS solve sees each occurrence).
    """

    params_class = DataSourceParams

    def _ratings(self, ctx) -> TrainingData:
        """Columnar train read: EventStore.scan hands struct-of-arrays
        batches (core/columns.py), and per batch the entity/target
        columns land in the output arrays by vectorized code selection
        — no per-event Python loop over Event objects. The only row
        work left is the properties parse for ``rate`` events (the
        rating value lives in the lazy JSON column), touched solely for
        the rows that survive the mask."""
        p = self.params
        user_parts: list[np.ndarray] = []
        item_parts: list[np.ndarray] = []
        rating_parts: list[np.ndarray] = []
        for cols in ctx.event_store().scan(
            p.app_name,
            entity_type=p.entity_type,
            event_names=list(p.event_names),
            target_entity_type=p.target_entity_type,
        ):
            part = ratings_from_columns(cols, p.buy_rating)
            if part is None:
                continue
            user_parts.append(part[0])
            item_parts.append(part[1])
            rating_parts.append(part[2])
        if not user_parts:
            empty = np.asarray([], dtype=object)
            return TrainingData(
                users=empty, items=empty.copy(),
                ratings=np.asarray([], dtype=np.float32))
        return TrainingData(
            users=np.concatenate(user_parts),
            items=np.concatenate(item_parts),
            ratings=np.concatenate(rating_parts),
        )

    def read_training(self, ctx) -> TrainingData:
        return self._ratings(ctx)

    def read_eval(self, ctx):
        """k-fold split of ratings; per-fold queries are the test-fold
        users, actuals their test-fold items. Parity: DataSource.readEval
        (DataSource.scala:82-105, zipWithUniqueId % kFold)."""
        p = self.params
        full = self._ratings(ctx)
        n = len(full.users)
        rng = np.random.default_rng(p.seed)
        fold_of = rng.integers(0, p.eval_k, size=n)
        folds = []
        for k in range(p.eval_k):
            test = fold_of == k
            td = TrainingData(
                users=full.users[~test],
                items=full.items[~test],
                ratings=full.ratings[~test],
            )
            by_user: dict[str, list[str]] = {}
            for u, i in zip(full.users[test], full.items[test]):
                by_user.setdefault(u, []).append(i)
            qa = [
                (Query(user=u, num=p.eval_query_num), tuple(items))
                for u, items in sorted(by_user.items())
            ]
            folds.append((td, {"fold": k}, qa))
        return folds


# ---------------------------------------------------------------------------
# Preparator
# ---------------------------------------------------------------------------


class ALSPreparator(Preparator):
    """String ids -> dense indices + COO ratings (the BiMap step the
    reference did inside ALSAlgorithm.train, ALSAlgorithm.scala:46-63,
    moved to the Preparator where the ragged→static conversion belongs)."""

    def prepare(self, ctx, td: TrainingData) -> PreparedData:
        user_ids = EntityIdIxMap.from_ids(td.users)
        item_ids = EntityIdIxMap.from_ids(td.items)
        rows = user_ids.to_index(td.users)
        cols = item_ids.to_index(td.items)
        seen: dict[int, set[int]] = {}
        for r, c in zip(rows, cols):
            seen.setdefault(int(r), set()).add(int(c))
        return PreparedData(
            coo=RatingsCOO(
                rows=rows,
                cols=cols,
                vals=np.asarray(td.ratings, dtype=np.float32),
                num_rows=len(user_ids),
                num_cols=len(item_ids),
            ),
            user_ids=user_ids,
            item_ids=item_ids,
            seen_by_user={
                u: np.asarray(sorted(s), dtype=np.int32) for u, s in seen.items()
            },
        )


# ---------------------------------------------------------------------------
# Algorithm
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class ALSAlgorithmParams(Params):
    """Parity: ALSAlgorithmParams (ALSAlgorithm.scala:30-38): rank,
    numIterations, lambda, seed."""

    rank: int = 10
    num_iterations: int = 10
    lambda_: float = 0.01
    seed: int = 3
    implicit_prefs: bool = False
    alpha: float = 1.0
    use_mesh: bool = True
    exclude_seen: bool = True
    #: row-shard the factor tables over the mesh's "model" axis (DP×MP
    #: tensor parallelism, engine.json "shardFactors";
    #: env PIO_TRAIN_SHARD_FACTORS=1/0 overrides fleet-wide) — for catalogs
    #: whose tables exceed one device's HBM; see docs/parallelism.md
    shard_factors: bool = False


class ALSAlgorithm(ShardedAlgorithm):
    """ALS matrix factorization on the device mesh.

    Parity: ALSAlgorithm (ALSAlgorithm.scala:40-120) — MLlib `ALS.train`
    becomes ops/als.als_train; `model.recommendProducts` becomes the
    jitted masked top-k.
    """

    params_class = ALSAlgorithmParams
    query_class = Query

    def train(self, ctx, pd: PreparedData) -> ALSModel:
        p = self.params
        mesh = ctx.mesh_if_parallel if p.use_mesh else None
        factors = als_train(
            pd.coo,
            rank=p.rank,
            iterations=p.num_iterations,
            lam=p.lambda_,
            implicit=p.implicit_prefs,
            alpha=p.alpha,
            seed=p.seed,
            mesh=mesh,
            shard_factors=resolve_shard_factors(p.shard_factors),
        )
        return ALSModel(
            rank=p.rank,
            user_factors=factors.user,
            item_factors=factors.item,
            user_ids=pd.user_ids,
            item_ids=pd.item_ids,
            seen_by_user=pd.seen_by_user,
        )

    def predict(self, model: ALSModel, query: Query) -> PredictedResult:
        recs = model.recommend(
            query.user, query.num,
            allow=build_allow_vector(model.item_ids,
                                     white_list=query.white_list,
                                     black_list=query.black_list),
            exclude_seen=self.params.exclude_seen,
        )
        return PredictedResult(
            item_scores=tuple(ItemScore(item=i, score=s) for i, s in recs)
        )

    def batch_predict(self, model: ALSModel, queries):
        """All queries scored in one matmul + top_k — the RDD-join
        analogue (ALSAlgorithm batchPredict path). Queries carrying
        white/black-list filters need a per-query eligibility vector, so
        they take the single-query path; the unfiltered rest batch."""
        if not queries:
            return []

        def single_path(q: Query) -> bool:
            # per-query eligibility vectors AND online-overlay users
            # (folded vector / cold-start items — the batched kernel
            # scores only the base tables; models/als.needs_online_path)
            return (q.white_list is not None or bool(q.black_list)
                    or model.needs_online_path(q.user))

        out = [(qi, self.predict(model, q)) for qi, q in queries
               if single_path(q)]
        queries = [(qi, q) for qi, q in queries if not single_path(q)]
        known = [
            (qi, model.user_ids[q.user], q.num)
            for qi, q in queries
            if q.user in model.user_ids
        ]
        out += [(qi, PredictedResult()) for qi, q in queries
                if q.user not in model.user_ids]
        if not known:
            return out
        uixs = np.asarray([u for _, u, _ in known], dtype=np.int32)
        max_num = max(n for _, _, n in known)
        # right-size the seen arrays to the smallest menu width covering
        # the real counts (smaller uploads, bounded compile-shape menu);
        # a batch whose heaviest user exceeds the menu gets the next
        # power of two instead — exclude_seen is a correctness contract,
        # so the seen list must NEVER silently truncate (a >512-item
        # history would otherwise re-recommend already-seen items)
        pad = topk_ops._SEEN_WIDTHS[0]
        if self.params.exclude_seen:
            widest = max(
                (len(model.seen_by_user.get(int(u), ())) for _, u, _ in known),
                default=0,
            )
            for cap in topk_ops._SEEN_WIDTHS:
                pad = cap
                if widest <= cap:
                    break
            while pad < widest:
                pad *= 2
        B = len(known)
        # pad the BATCH dimension to the shared power-of-two menu
        # (ops/topk.BATCH_WIDTHS): every distinct B is a fresh jit
        # signature, and on a remote-compile backend each costs tens
        # of seconds — the serving micro-batcher produces arbitrary
        # batch sizes, so without this a varying-concurrency workload
        # compiles forever instead of dispatching (padding rows repeat
        # row 0 and are sliced off the result). Eval-scale batches
        # pass through unpadded (serving_batch docstring). The
        # recompile sentinel (obs/compile.py) watches this contract in
        # production: a post-warmup width that misses the compiled
        # menu counts on pio_serving_recompile_total with a WARN, and
        # tests/test_compile_obs.py pins on-menu == zero /
        # off-menu == one through this exact path
        padB = topk_ops.serving_batch(B)
        if padB != B:
            uixs = np.concatenate(
                [uixs, np.full(padB - B, uixs[0], dtype=np.int32)])
        cols = np.zeros((padB, pad), dtype=np.int32)
        mask = np.zeros((padB, pad), dtype=np.float32)
        if self.params.exclude_seen:
            for j, (_, u, _) in enumerate(known):
                s = model.seen_by_user.get(int(u), np.empty(0, dtype=np.int32))[:pad]
                cols[j, : len(s)] = s
                mask[j, : len(s)] = 1.0
        n_items = model.item_factors.shape[0]
        # menu-ized STATIC top_k width (ops/topk.serving_k: client-
        # controlled num must not retrace; results trim per query below)
        k = topk_ops.serving_k(min(max_num, n_items), n_items)
        # the model dispatches by its configured retrieval: brute picks
        # flat vs chunked-scan (ops/topk), ann probes the IVF index and
        # exact-rescores the shortlist (ops/ann); seen arrays stay
        # NumPy so the brute dispatcher's host-side _trim_seen can
        # right-size them
        vals, idxs = model.batch_topk(uixs, cols, mask, None, k)
        vals = np.asarray(vals)[:B]
        idxs = np.asarray(idxs)[:B]
        inv = model.item_ids.inverse
        for j, (qi, _, num) in enumerate(known):
            scores = []
            for v, i in zip(vals[j][:num], idxs[j][:num]):
                if not np.isfinite(v):
                    break
                scores.append(ItemScore(item=inv[int(i)], score=float(v)))
            out.append((qi, PredictedResult(item_scores=tuple(scores))))
        return out

    # -- persistence: orbax-style directory checkpoint + manifest ----------
    def make_persistent_model(self, ctx, model: ALSModel):
        """Unlike the reference's PAlgorithm (forced retrain-on-deploy for
        RDD models, PAlgorithm.scala:89-101), sharded factors persist via
        a directory checkpoint + manifest (SURVEY.md §7 hard-parts)."""
        from predictionio_tpu.controller.persistent_model import checkpoint_location

        location = checkpoint_location(ctx, "als")
        model.save(location)
        return PersistentModelManifest(
            class_name=f"{type(self).__module__}.{type(self).__name__}",
            location=location,
        )

    def load_model(self, ctx, manifest: PersistentModelManifest) -> ALSModel:
        return ALSModel.load(manifest.location)


def engine_factory() -> Engine:
    return Engine(
        data_source_class_map=RecommendationDataSource,
        preparator_class_map=ALSPreparator,
        algorithm_class_map={"als": ALSAlgorithm, "": ALSAlgorithm},
        serving_class_map=FirstServing,
    )


# ---------------------------------------------------------------------------
# Evaluation: Precision@K + params grid (reference: the recommendation
# template's Evaluation.scala — PrecisionAtK OptionAverageMetric and the
# rank x numIterations EngineParamsList; tests/pio_tests/engines/
# recommendation-engine/src/main/scala/Evaluation.scala)
# ---------------------------------------------------------------------------


class PrecisionAtK(OptionAverageMetric):
    """Fraction of the top-k recommendations that are in the user's
    held-out item set (read_eval's answer is the tuple of test-fold
    items). Returns None (excluded from the average) for users with no
    held-out items — the reference's OptionAverageMetric contract."""

    def __init__(self, k: int = 10):
        self.k = k

    @property
    def header(self) -> str:
        return f"Precision@{self.k}"

    def calculate_qpa(self, q, p, a) -> float | None:
        relevant = set(a)
        if not relevant:
            return None
        top = [s.item for s in p.item_scores[: self.k]]
        if not top:
            return 0.0
        hits = sum(1 for item in top if item in relevant)
        # reference parity: tpCount / min(k, |relevant|) (Evaluation.scala)
        return hits / min(self.k, len(relevant))


class MAPAtK(OptionAverageMetric):
    """Mean Average Precision at k — the BASELINE.md north-star quality
    gate ("matching MAP@10"). Average of precision@i over the ranks i of
    relevant items inside the top-k, divided by min(k, |relevant|);
    None (skip) for users with no held-out items."""

    def __init__(self, k: int = 10):
        self.k = k

    @property
    def header(self) -> str:
        return f"MAP@{self.k}"

    def calculate_qpa(self, q, p, a) -> float | None:
        relevant = set(a)
        if not relevant:
            return None
        top = [s.item for s in p.item_scores[: self.k]]
        hits, precision_sum = 0, 0.0
        for rank, item in enumerate(top, start=1):
            if item in relevant:
                hits += 1
                precision_sum += hits / rank
        return precision_sum / min(self.k, len(relevant))


class RecommendationEvaluation(Evaluation):
    """`pio eval predictionio_tpu.templates.recommendation.RecommendationEvaluation
    predictionio_tpu.templates.recommendation.DefaultParamsList`"""

    def __init__(self, k: int = 10, output_path: str | None = "best.json"):
        super().__init__()
        self.engine_evaluator = (
            engine_factory(),
            MetricEvaluator(PrecisionAtK(k=k),
                            other_metrics=[MAPAtK(k=k)],
                            output_path=output_path),
        )


class DefaultParamsList(EngineParamsGenerator):
    """rank x iterations grid like the reference's EngineParamsList."""

    def __init__(self, app_name: str = "RecApp", eval_k: int = 2):
        super().__init__([
            EngineParams.of(
                data_source=DataSourceParams(app_name=app_name, eval_k=eval_k),
                algorithms=[(
                    "als",
                    ALSAlgorithmParams(rank=rank, num_iterations=it,
                                       lambda_=0.05, seed=3),
                )],
            )
            for rank in (8, 16)
            for it in (5, 10)
        ])
