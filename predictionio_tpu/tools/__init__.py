"""Ops tooling: dashboard, admin API, event export/import.

Parity: the reference's `tools` module servers and Spark drivers
(tools/src/main/scala/.../tools/{dashboard/,admin/,export/,imprt/}).
"""
