"""Evaluation dashboard on :9000.

Parity: tools/src/main/scala/.../tools/dashboard/Dashboard.scala:40-160 —
lists completed EvaluationInstances newest-first and serves each
instance's evaluator results as text, HTML, or JSON:

- ``GET /``                                        HTML index of completed
                                                   evaluation instances
- ``GET /engine_instances/{id}/evaluator_results.txt``
- ``GET /engine_instances/{id}/evaluator_results.html``
- ``GET /engine_instances/{id}/evaluator_results.json``

(the reference's path segment is "engine_instances" even though the data
is EvaluationInstances — kept for URL parity, Dashboard.scala:101-141).

CORS: every response carries ``Access-Control-Allow-Origin: *`` and an
``OPTIONS`` preflight for a routed resource answers with the allowed
methods, header whitelist, and a 20-day max-age — parity with the
``CORSSupport`` trait the reference mixes into the dashboard
(tools/.../dashboard/CorsSupport.scala:31-77, wired at
Dashboard.scala:89).
"""

from __future__ import annotations

import html
import json
import logging
import re
import time
from http.server import BaseHTTPRequestHandler

from predictionio_tpu.api.http_base import (
    REQUEST_ID_HEADER,
    RestServer,
    access_log_enabled,
    emit_access_log,
    ensure_access_log_handler,
    resolve_request_id,
)
from predictionio_tpu.obs.exporter import CONTENT_TYPE as PROMETHEUS_CONTENT_TYPE
from predictionio_tpu.obs.exporter import render_prometheus
from predictionio_tpu.obs.registry import (
    HistogramFamily,
    MetricRegistry,
    resilience_collector,
    server_info_collector,
)
from predictionio_tpu.storage.registry import Storage

logger = logging.getLogger(__name__)

_RESULTS_RE = re.compile(
    r"^/engine_instances/([^/]+)/evaluator_results\.(txt|html|json)$"
)

# CorsSupport.scala:33-45 — the origin header goes on every response;
# the remaining two only on OPTIONS preflights.
_CORS_ORIGIN = ("Access-Control-Allow-Origin", "*")
_CORS_PREFLIGHT = (
    ("Access-Control-Allow-Headers",
     "Origin, X-Requested-With, Content-Type, Accept, Accept-Encoding, "
     "Accept-Language, Host, Referer, User-Agent"),
    ("Access-Control-Max-Age", "1728000"),
)


class DashboardService:
    def __init__(self, storage: Storage | None = None,
                 access_log: bool | None = None):
        self.storage = storage or Storage.default()
        # observability plane (docs/observability.md): the dashboard
        # exposes its own scrape point — request latency + the
        # process-global resilience counters — and the shared
        # structured-access-log/request-id contract
        self.access_log = access_log_enabled(access_log)
        if self.access_log:
            ensure_access_log_handler()
        self.request_latency = HistogramFamily(
            "pio_http_request_seconds",
            "HTTP request walltime by route (handler-measured)",
            "route", ("index", "results", "metrics"))
        self.registry = MetricRegistry()
        self.registry.register(self.request_latency.collect)
        self.registry.register(resilience_collector())
        self.registry.register(server_info_collector("dashboard"))

    def route_label(self, path: str) -> str:
        if path == "/":
            return "index"
        if path == "/metrics":
            return "metrics"
        if _RESULTS_RE.match(path):
            return "results"
        return "other"

    def handle(self, method: str, path: str) -> tuple[int, str, str]:
        """Returns (status, content_type, body)."""
        if method != "GET":
            return (405, "application/json", json.dumps({"message": "GET only"}))
        if path == "/":
            return (200, "text/html; charset=UTF-8", self.index_html())
        if path == "/metrics":
            return (200, PROMETHEUS_CONTENT_TYPE,
                    render_prometheus(self.registry))
        m = _RESULTS_RE.match(path)
        if m:
            instance_id, fmt = m.groups()
            instance = self.storage.get_meta_data_evaluation_instances().get(instance_id)
            if instance is None or instance.status != "EVALCOMPLETED":
                return (404, "application/json",
                        json.dumps({"message": f"instance {instance_id} not found"}))
            if fmt == "txt":
                return (200, "text/plain; charset=UTF-8", instance.evaluator_results)
            if fmt == "html":
                return (200, "text/html; charset=UTF-8", instance.evaluator_results_html)
            return (200, "application/json", instance.evaluator_results_json or "{}")
        return (404, "application/json", json.dumps({"message": f"no route for {path}"}))

    def index_html(self) -> str:
        """The dashboard index (Dashboard.scala:93-100 + twirl template)."""
        rows = []
        for inst in self.storage.get_meta_data_evaluation_instances().get_completed():
            rows.append(
                "<tr><td>{id}</td><td>{start}</td><td>{cls}</td><td>{oneliner}</td>"
                "<td><a href='/engine_instances/{id}/evaluator_results.txt'>txt</a> "
                "<a href='/engine_instances/{id}/evaluator_results.html'>HTML</a> "
                "<a href='/engine_instances/{id}/evaluator_results.json'>JSON</a>"
                "</td></tr>".format(
                    id=html.escape(inst.id),
                    start=html.escape(inst.start_time.isoformat()),
                    cls=html.escape(inst.evaluation_class),
                    oneliner=html.escape(inst.evaluator_results[:200]),
                )
            )
        return (
            "<html><head><title>predictionio_tpu dashboard</title></head><body>"
            "<h1>Completed Evaluations</h1>"
            "<table border=1><tr><th>ID</th><th>Started</th><th>Evaluation</th>"
            "<th>Result</th><th>Details</th></tr>"
            + "".join(rows)
            + "</table></body></html>"
        )


class _Handler(BaseHTTPRequestHandler):
    service: DashboardService

    def do_GET(self) -> None:  # noqa: N802
        t_start = time.perf_counter()
        path = self.path.split("?")[0]
        request_id = resolve_request_id(self.headers)
        status, ctype, body = self.service.handle("GET", path)
        data = body.encode()
        self.send_response(status)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(data)))
        self.send_header(REQUEST_ID_HEADER, request_id)
        self.send_header(*_CORS_ORIGIN)
        self.end_headers()
        self.wfile.write(data)
        dt = time.perf_counter() - t_start
        self.service.request_latency.observe(
            self.service.route_label(path), dt)
        if self.service.access_log:
            emit_access_log("dashboard", "GET", path, status, dt,
                            request_id, client=self.address_string())

    def do_OPTIONS(self) -> None:  # noqa: N802
        """CORS preflight (CorsSupport.scala:48-63): a routed path answers
        with the methods it supports; unknown paths still 404."""
        path = self.path.split("?")[0]
        known = (path == "/" or path == "/metrics"
                 or _RESULTS_RE.match(path) is not None)
        self.send_response(200 if known else 404)
        self.send_header("Access-Control-Allow-Methods", "OPTIONS, GET")
        self.send_header(*_CORS_ORIGIN)
        for header in _CORS_PREFLIGHT:
            self.send_header(*header)
        self.send_header("Content-Length", "0")
        self.end_headers()

    def log_message(self, format: str, *args) -> None:
        logger.debug("%s - %s", self.address_string(), format % args)


class Dashboard(RestServer):
    """Parity: Dashboard.createDashboard (Dashboard.scala:60-91)."""

    log_label = "Dashboard"
    thread_name = "pio-dashboard"

    def __init__(self, storage: Storage | None = None, ip: str = "0.0.0.0",
                 port: int = 9000, access_log: bool | None = None):
        super().__init__(_Handler, DashboardService(storage, access_log),
                         ip, port)
