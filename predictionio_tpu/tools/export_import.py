"""Event export/import: event store ↔ JSON-lines or Parquet files.

Parity: tools/src/main/scala/.../tools/{export/EventsToFile.scala:43-108,
imprt/FileToEvents.scala:43-106} — the reference ran these as Spark
drivers writing/reading RDDs with a json-or-parquet format option
(EventsToFile.scala:97-105); here they stream through the host in
batches (storage I/O is the bound, not compute). The json format is one
API JSON event per line, identical to the reference's json output mode.
The parquet format is one row per event with the API JSON field names as
columns; divergence from the reference (documented): `properties` is a
JSON-encoded string column rather than a Spark-inferred struct — the
event schema is open, so a string column is the faithful self-describing
encoding (and round-trips schemalessly), while Spark's struct inference
could silently widen/conflict across exports.
"""

from __future__ import annotations

import json
import logging
from typing import TextIO

from predictionio_tpu.core.json_codec import event_from_json, event_to_json
from predictionio_tpu.storage.base import EventFilter
from predictionio_tpu.storage.registry import Storage

logger = logging.getLogger(__name__)

_BATCH = 500


def export_events(
    storage: Storage,
    app_id: int,
    output: TextIO,
    channel_id: int | None = None,
) -> int:
    """Write every event of (app, channel) as JSON lines; returns count
    (EventsToFile.scala:84-96)."""
    n = 0
    for event in storage.get_events().find(app_id, channel_id, EventFilter()):
        output.write(json.dumps(event_to_json(event)) + "\n")
        n += 1
    logger.info("exported %d events (app %s)", n, app_id)
    return n


class ImportFormatError(ValueError):
    """A line failed to parse/validate. Carries how many events were
    already committed so the operator knows the partial state."""

    def __init__(self, line_no: int, reason: str, imported: int):
        super().__init__(
            f"line {line_no}: {reason} ({imported} event(s) already imported)"
        )
        self.line_no = line_no
        self.imported = imported


def import_events(
    storage: Storage,
    app_id: int,
    input: TextIO,
    channel_id: int | None = None,
) -> int:
    """Read JSON-lines events and batch-insert; returns count
    (FileToEvents.scala:85-101). Raises ImportFormatError on a bad line,
    reporting how much of the file was committed before it."""
    events_dao = storage.get_events()
    batch = []
    n = 0
    for line_no, line in enumerate(input, start=1):
        line = line.strip()
        if not line:
            continue
        try:
            batch.append(event_from_json(json.loads(line)))
        except Exception as e:
            raise ImportFormatError(line_no, str(e), n)
        if len(batch) >= _BATCH:
            events_dao.insert_batch(batch, app_id, channel_id)
            n += len(batch)
            batch = []
    if batch:
        events_dao.insert_batch(batch, app_id, channel_id)
        n += len(batch)
    logger.info("imported %d events (app %s)", n, app_id)
    return n


# ---------------------------------------------------------------------------
# Parquet format (EventsToFile.scala:97-105 `--format parquet`)
# ---------------------------------------------------------------------------

# API JSON field name -> column; all strings except tags (list<string>).
_PARQUET_FIELDS = (
    "eventId", "event", "entityType", "entityId", "targetEntityType",
    "targetEntityId", "properties", "eventTime", "tags", "prId",
    "creationTime",
)


def _parquet_schema():
    import pyarrow as pa

    return pa.schema(
        [
            (name, pa.list_(pa.string()) if name == "tags" else pa.string())
            for name in _PARQUET_FIELDS
        ]
    )


def export_events_parquet(
    storage: Storage,
    app_id: int,
    path: str,
    channel_id: int | None = None,
) -> int:
    """Write every event of (app, channel) to one Parquet file; returns
    count. Batches rows so memory stays flat on large apps."""
    import pyarrow as pa
    import pyarrow.parquet as pq

    schema = _parquet_schema()
    n = 0
    with pq.ParquetWriter(path, schema) as writer:
        rows: list[dict] = []

        def flush():
            nonlocal n
            if rows:
                writer.write_table(pa.Table.from_pylist(rows, schema=schema))
                n += len(rows)
                rows.clear()

        for event in storage.get_events().find(app_id, channel_id, EventFilter()):
            obj = event_to_json(event)
            obj["properties"] = json.dumps(obj.get("properties", {}))
            rows.append({f: obj.get(f) for f in _PARQUET_FIELDS})
            if len(rows) >= _BATCH:
                flush()
        flush()
    logger.info("exported %d events to parquet (app %s)", n, app_id)
    return n


def import_events_parquet(
    storage: Storage,
    app_id: int,
    path: str,
    channel_id: int | None = None,
) -> int:
    """Read a Parquet event file (as written by export_events_parquet)
    and batch-insert; returns count."""
    import pyarrow.parquet as pq

    events_dao = storage.get_events()
    try:
        pf = pq.ParquetFile(path)
    except Exception as e:  # ArrowInvalid on non-parquet input
        raise ImportFormatError(0, f"not a parquet file: {e}", 0)
    n = 0
    for rb in pf.iter_batches(batch_size=_BATCH):
        batch = []
        for row in rb.to_pylist():
            obj = {k: v for k, v in row.items() if v is not None}
            try:
                if "properties" in obj:
                    obj["properties"] = json.loads(obj["properties"])
                batch.append(event_from_json(obj))
            except Exception as e:
                raise ImportFormatError(n + len(batch) + 1, str(e), n)
        if batch:
            events_dao.insert_batch(batch, app_id, channel_id)
            n += len(batch)
    logger.info("imported %d events from parquet (app %s)", n, app_id)
    return n
