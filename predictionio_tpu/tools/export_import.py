"""Event export/import: event store ↔ JSON-lines files.

Parity: tools/src/main/scala/.../tools/{export/EventsToFile.scala:43-108,
imprt/FileToEvents.scala:43-106} — the reference ran these as Spark
drivers writing/reading RDDs; here they stream through the host in
batches (storage I/O is the bound, not compute). File format: one API
JSON event per line, identical to the reference's json output mode.
"""

from __future__ import annotations

import json
import logging
from typing import TextIO

from predictionio_tpu.core.json_codec import event_from_json, event_to_json
from predictionio_tpu.storage.base import EventFilter
from predictionio_tpu.storage.registry import Storage

logger = logging.getLogger(__name__)

_BATCH = 500


def export_events(
    storage: Storage,
    app_id: int,
    output: TextIO,
    channel_id: int | None = None,
) -> int:
    """Write every event of (app, channel) as JSON lines; returns count
    (EventsToFile.scala:84-96)."""
    n = 0
    for event in storage.get_events().find(app_id, channel_id, EventFilter()):
        output.write(json.dumps(event_to_json(event)) + "\n")
        n += 1
    logger.info("exported %d events (app %s)", n, app_id)
    return n


class ImportFormatError(ValueError):
    """A line failed to parse/validate. Carries how many events were
    already committed so the operator knows the partial state."""

    def __init__(self, line_no: int, reason: str, imported: int):
        super().__init__(
            f"line {line_no}: {reason} ({imported} event(s) already imported)"
        )
        self.line_no = line_no
        self.imported = imported


def import_events(
    storage: Storage,
    app_id: int,
    input: TextIO,
    channel_id: int | None = None,
) -> int:
    """Read JSON-lines events and batch-insert; returns count
    (FileToEvents.scala:85-101). Raises ImportFormatError on a bad line,
    reporting how much of the file was committed before it."""
    events_dao = storage.get_events()
    batch = []
    n = 0
    for line_no, line in enumerate(input, start=1):
        line = line.strip()
        if not line:
            continue
        try:
            batch.append(event_from_json(json.loads(line)))
        except Exception as e:
            raise ImportFormatError(line_no, str(e), n)
        if len(batch) >= _BATCH:
            events_dao.insert_batch(batch, app_id, channel_id)
            n += len(batch)
            batch = []
    if batch:
        events_dao.insert_batch(batch, app_id, channel_id)
        n += len(batch)
    logger.info("imported %d events (app %s)", n, app_id)
    return n
