"""Admin REST API on :7071.

Parity: tools/src/main/scala/.../tools/admin/{AdminAPI.scala:39-161,
CommandClient.scala} — experimental app administration over REST:

- ``GET  /``                     health check ``{"status": "alive"}``
- ``GET  /cmd/app``              list apps
- ``POST /cmd/app``              create app (body: {"name", "id"?, "description"?})
- ``DELETE /cmd/app/{name}``     delete app (keys, channels, events, row)
- ``DELETE /cmd/app/{name}/data`` wipe the app's event data
"""

from __future__ import annotations

import json
import logging
import re
from http.server import BaseHTTPRequestHandler
from typing import Any

from predictionio_tpu.api.http_base import RestServer
from predictionio_tpu.storage.base import AccessKey, App
from predictionio_tpu.storage.registry import Storage

logger = logging.getLogger(__name__)

_APP_RE = re.compile(r"^/cmd/app/([^/]+)$")
_APP_DATA_RE = re.compile(r"^/cmd/app/([^/]+)/data$")


class CommandClient:
    """DAO-backed admin commands. Parity: CommandClient.scala
    (futureAppNew/futureAppList/futureAppDelete/futureAppDataDelete)."""

    def __init__(self, storage: Storage):
        self.storage = storage
        self.apps = storage.get_meta_data_apps()
        self.keys = storage.get_meta_data_access_keys()
        self.channels = storage.get_meta_data_channels()
        self.events = storage.get_events()

    def app_list(self) -> list[dict[str, Any]]:
        out = []
        for app in self.apps.get_all():
            app_keys = self.keys.get_by_app_id(app.id)
            out.append({
                "name": app.name,
                "id": app.id,
                "accessKeys": [k.key for k in app_keys],
            })
        return out

    def app_new(self, name: str, app_id: int = 0, description: str | None = None) -> dict:
        if self.apps.get_by_name(name) is not None:
            raise ValueError(f"App {name} already exists.")
        new_id = self.apps.insert(App(app_id, name, description))
        if new_id is None:
            raise ValueError(f"App {name} could not be created.")
        self.events.init(new_id)
        key = self.keys.insert(AccessKey("", new_id, ()))
        return {"name": name, "id": new_id, "accessKey": key}

    def app_delete(self, name: str) -> None:
        app = self.apps.get_by_name(name)
        if app is None:
            raise KeyError(f"App {name} does not exist.")
        for c in self.channels.get_by_app_id(app.id):
            self.events.remove(app.id, c.id)
            self.channels.delete(c.id)
        self.events.remove(app.id)
        for k in self.keys.get_by_app_id(app.id):
            self.keys.delete(k.key)
        self.apps.delete(app.id)

    def app_data_delete(self, name: str) -> None:
        app = self.apps.get_by_name(name)
        if app is None:
            raise KeyError(f"App {name} does not exist.")
        self.events.remove(app.id)
        self.events.init(app.id)


class AdminService:
    def __init__(self, storage: Storage | None = None):
        self.client = CommandClient(storage or Storage.default())

    def handle(self, method: str, path: str, body: Any) -> tuple[int, Any]:
        try:
            if method == "GET" and path == "/":
                return (200, {"status": "alive"})
            if method == "GET" and path == "/cmd/app":
                return (200, {"apps": self.client.app_list()})
            if method == "POST" and path == "/cmd/app":
                if not isinstance(body, dict) or not body.get("name"):
                    return (400, {"message": "body must be JSON with a 'name'"})
                created = self.client.app_new(
                    body["name"], int(body.get("id") or 0), body.get("description")
                )
                return (201, created)
            m = _APP_DATA_RE.match(path)
            if m and method == "DELETE":
                self.client.app_data_delete(m.group(1))
                return (200, {"message": f"Data of app {m.group(1)} deleted."})
            m = _APP_RE.match(path)
            if m and method == "DELETE":
                self.client.app_delete(m.group(1))
                return (200, {"message": f"App {m.group(1)} deleted."})
            return (404, {"message": f"no route for {method} {path}"})
        except ValueError as e:
            return (409, {"message": str(e)})
        except KeyError as e:
            return (404, {"message": str(e).strip("'\"")})


class _Handler(BaseHTTPRequestHandler):
    service: AdminService

    def _dispatch(self, method: str) -> None:
        body = None
        if method == "POST":
            length = int(self.headers.get("Content-Length") or 0)
            raw = self.rfile.read(length) if length else b""
            if raw:
                try:
                    body = json.loads(raw)
                except json.JSONDecodeError:
                    self._respond(400, {"message": "invalid JSON body"})
                    return
        status, payload = self.service.handle(method, self.path.split("?")[0], body)
        self._respond(status, payload)

    def _respond(self, status: int, payload: Any) -> None:
        data = json.dumps(payload).encode()
        self.send_response(status)
        self.send_header("Content-Type", "application/json; charset=UTF-8")
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def do_GET(self) -> None:  # noqa: N802
        self._dispatch("GET")

    def do_POST(self) -> None:  # noqa: N802
        self._dispatch("POST")

    def do_DELETE(self) -> None:  # noqa: N802
        self._dispatch("DELETE")

    def log_message(self, format: str, *args) -> None:
        logger.debug("%s - %s", self.address_string(), format % args)


class AdminServer(RestServer):
    """Parity: AdminServer.createAdminServer (AdminAPI.scala:137-154)."""

    log_label = "Admin API"
    thread_name = "pio-adminserver"

    def __init__(self, storage: Storage | None = None, ip: str = "0.0.0.0",
                 port: int = 7071):
        super().__init__(_Handler, AdminService(storage), ip, port)
