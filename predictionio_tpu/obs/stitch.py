"""Cross-process trace stitching: join the per-process segments of one
fleet request into a single span tree (docs/observability.md).

A request that crosses the router hop leaves one trace SEGMENT per
process — the router's (admission, attempt, retry, hedge spans) and one
per replica that saw an attempt — all sharing a ``traceId``. The router
forwards ``X-PIO-Trace-Id`` plus ``X-PIO-Parent-Span`` (the span id of
its attempt span), so each replica segment records which remote span it
nests under (``parentSpanId`` on the segment document).

:func:`stitch` joins the documents:

- each segment becomes a synthetic root span (the segment's name and
  duration) parented on its ``parentSpanId`` — or on nothing for the
  root segment (no ``parentSpanId``; ties broken by earliest wall
  start);
- the segment's own spans keep their ids (process-prefixed, so no
  cross-segment collisions) and hang off the synthetic root when they
  had no in-segment parent;
- span start offsets are re-expressed relative to the ROOT segment's
  wall start using each segment's wall-clock ``startTime``. Same-host
  fleets make that exact to NTP noise; the renderer never relies on a
  child sitting strictly inside its parent's interval.

Orphan segments (their ``parentSpanId`` names a span no collected
segment contains — e.g. the parent fell off a bounded trace ring) are
kept, parented at the root, and flagged ``"orphan": true`` rather than
dropped: a stitched view must degrade to "everything we know" instead
of silently narrowing.

Pure functions over JSON-able dicts — no I/O, no clock reads (the obs
plane never pushes; the router's merge endpoint and ``pio trace`` do
the fetching).
"""

from __future__ import annotations

from typing import Any, Iterable

#: synthetic span id prefix for segment roots — cannot collide with
#: real span ids (those start with "s")
_SEG = "seg"


def stitch(segments: Iterable[dict]) -> dict | None:
    """One stitched trace document from the segments of one trace, or
    None when ``segments`` is empty. Input docs are ``Trace.to_dict``
    output (optionally annotated with ``source`` by the collector)."""
    docs = sorted(segments, key=lambda d: d.get("startTime") or 0.0)
    if not docs:
        return None
    root_idx = next(
        (i for i, d in enumerate(docs) if not d.get("parentSpanId")), 0)
    root = docs[root_idx]
    base_start = root.get("startTime") or 0.0
    known_spans: set[str] = set()
    for doc in docs:
        for span in doc.get("spans", ()):
            known_spans.add(span["spanId"])

    spans: list[dict] = []
    seg_docs: list[dict] = []
    for i, doc in enumerate(docs):
        seg_id = f"{_SEG}{i}"
        offset_ms = ((doc.get("startTime") or base_start) - base_start) * 1e3
        parent = doc.get("parentSpanId") or ""
        orphan = False
        if doc is not root and parent and parent not in known_spans:
            # the remote parent span was never collected (ring bound,
            # dead worker): keep the segment, attach it at the root
            parent, orphan = "", True
        seg_span = {
            "name": doc.get("name", "trace"),
            "spanId": seg_id,
            "startMs": round(offset_ms, 3),
            "durationMs": doc.get("durationMs"),
            "segment": True,
        }
        if doc is not root and not parent:
            parent = f"{_SEG}{root_idx}"
        if parent:
            seg_span["parentId"] = parent
        if orphan:
            seg_span["orphan"] = True
        for key in ("service", "source", "requestId", "tags"):
            if doc.get(key) is not None:
                seg_span[key] = doc[key]
        spans.append(seg_span)
        for span in doc.get("spans", ()):
            out = dict(span)
            out["startMs"] = round(span["startMs"] + offset_ms, 3)
            if not out.get("parentId"):
                out["parentId"] = seg_id
            spans.append(out)

        seg_docs.append({
            "segment": seg_id,
            "name": doc.get("name"),
            "service": doc.get("service"),
            "source": doc.get("source"),
            "startTime": doc.get("startTime"),
            "durationMs": doc.get("durationMs"),
            "spanCount": len(doc.get("spans", ())),
        })

    return {
        "traceId": root.get("traceId"),
        "name": root.get("name"),
        "startTime": base_start,
        "durationMs": root.get("durationMs"),
        **({"requestId": root["requestId"]}
           if root.get("requestId") else {}),
        "segments": seg_docs,
        "spans": spans,
    }


def _children(spans: list[dict]) -> dict[str, list[dict]]:
    by_parent: dict[str, list[dict]] = {}
    for span in spans:
        by_parent.setdefault(span.get("parentId", ""), []).append(span)
    for kids in by_parent.values():
        kids.sort(key=lambda s: (s.get("startMs") or 0.0, s["spanId"]))
    return by_parent


def render_tree(doc: dict) -> str:
    """Operator-facing text tree of a stitched trace (``pio trace``)."""
    lines = [
        f"trace {doc.get('traceId')}  {doc.get('name')}"
        + (f"  request_id={doc['requestId']}" if doc.get("requestId") else "")
        + (f"  {doc['durationMs']:.3f}ms"
           if doc.get("durationMs") is not None else "")
    ]
    by_parent = _children(doc.get("spans", []))
    seen: set[str] = set()

    def walk(parent: str, indent: str) -> None:
        kids = [s for s in by_parent.get(parent, [])
                if s["spanId"] not in seen]
        # a malformed segment set (duplicate span ids, a parent loop)
        # must render partially, never recurse forever
        seen.update(s["spanId"] for s in kids)
        for i, span in enumerate(kids):
            last = i == len(kids) - 1
            branch, cont = ("└─ ", "   ") if last else ("├─ ", "│  ")
            where = ""
            if span.get("segment"):
                service = span.get("service") or "?"
                source = span.get("source")
                where = f"  [{service}{' ' + source if source else ''}]"
                if span.get("orphan"):
                    where += " (orphan)"
            dur = (f"  {span['durationMs']:.3f}ms"
                   if span.get("durationMs") is not None else "")
            start = (f"  @{span['startMs']:.3f}ms"
                     if span.get("startMs") is not None else "")
            lines.append(f"{indent}{branch}{span['name']}{dur}{start}{where}")
            walk(span["spanId"], indent + cont)

    walk("", "")
    return "\n".join(lines)


def to_chrome_trace(doc: dict) -> dict:
    """Chrome trace-viewer JSON (``chrome://tracing`` / Perfetto) for a
    stitched trace — complete ("X") events in microseconds, one pid per
    segment, named via metadata events."""
    events: list[dict[str, Any]] = []
    seg_pid: dict[str, int] = {}
    for i, seg in enumerate(doc.get("segments", ())):
        seg_pid[seg["segment"]] = i
        label = seg.get("service") or seg.get("name") or seg["segment"]
        if seg.get("source"):
            label = f"{label} {seg['source']}"
        events.append({
            "ph": "M", "name": "process_name", "pid": i, "tid": 0,
            "args": {"name": label},
        })
    # spans belong to the segment they were recorded in: segment roots
    # map by their own id, ordinary spans inherit from their segment
    # root via the parent chain
    by_id = {s["spanId"]: s for s in doc.get("spans", ())}

    def pid_of(span: dict) -> int:
        cursor = span
        hops: set[str] = set()
        while cursor is not None and cursor["spanId"] not in hops:
            hops.add(cursor["spanId"])
            if cursor["spanId"] in seg_pid:
                return seg_pid[cursor["spanId"]]
            cursor = by_id.get(cursor.get("parentId", ""))
        return 0

    for span in doc.get("spans", ()):
        events.append({
            "ph": "X",
            "name": span["name"],
            "pid": pid_of(span),
            "tid": 0,
            "ts": round((span.get("startMs") or 0.0) * 1e3, 1),
            "dur": round((span.get("durationMs") or 0.0) * 1e3, 1),
            "args": {"spanId": span["spanId"],
                     **({"orphan": True} if span.get("orphan") else {})},
        })
    return {"displayTimeUnit": "ms", "traceEvents": events}
