"""Device memory and FLOPs accounting — the half of the device/compiler
observability layer below :mod:`~predictionio_tpu.obs.compile`
(docs/observability.md "Device and compiler observability").

Three pieces, all degrade-gracefully on backends that expose nothing
(the CPU tier-1 environment must scrape clean, just sparser):

- **HBM gauges** — ``jax.local_devices()`` ``memory_stats()`` rendered
  as ``pio_device_bytes_in_use`` / ``pio_device_peak_bytes_in_use`` /
  ``pio_device_bytes_limit`` per device. CPU devices return no stats
  and contribute no samples (absent, not zero — a dashboard must not
  read "0 bytes of HBM" on a host backend).
- **Peak-FLOPs table** — dense per-chip peaks keyed by device kind
  (bf16/matmul peaks, the number MFU is conventionally quoted
  against), overridable with ``PIO_DEVICE_PEAK_FLOPS`` for kinds the
  table has not met (including CPU, where the override is the ONLY way
  to get a non-null MFU).
- **TrainProfiler** — drives ``pio train --profile``: binds to the
  training trace, samples per-stage memory high-water via the span
  observer hook, bins the recompile sentinel's compile events into the
  DASE stages, prices executed FLOPs from the captured
  ``Compiled.cost_analysis()`` data, and emits the ``TRAIN_REPORT``
  document plus the ``pio_train_mfu`` / ``pio_train_stage_hbm_peak_bytes``
  gauges (exported by :func:`train_report_collector`, which any server
  in the same process picks up through its MetricRegistry).

MFU here is measured honestly or not at all: a null ``mfu`` with a
``mfuReason`` beats a fabricated number (reading guidance in
docs/observability.md).
"""

from __future__ import annotations

import logging
import os
import sys
import time
from typing import Any, Callable, Iterable, Mapping

from predictionio_tpu.obs.compile import CompileRecorder, recorder
from predictionio_tpu.obs.registry import Metric

logger = logging.getLogger(__name__)

#: the TRAIN_REPORT.json schema tag — bump on breaking field changes
TRAIN_REPORT_SCHEMA = "pio.train_report.v1"

#: dense matmul peak FLOPs per CHIP by device-kind substring
#: (lowercased, first match wins — more specific entries first). The
#: bf16 systolic-array peaks every public MFU figure is quoted
#: against; chips whose kind string this table has not met report a
#: null MFU with a reason instead of a guess.
PEAK_FLOPS_TABLE: tuple[tuple[str, float], ...] = (
    ("v6e", 918e12),      # Trillium
    ("v5p", 459e12),
    ("v5e", 197e12),
    ("v5 lite", 197e12),
    ("v5litepod", 197e12),
    ("v4", 275e12),
    ("v3", 123e12),
    ("v2", 45e12),
)

_PEAK_FLOPS_ENV = "PIO_DEVICE_PEAK_FLOPS"


def peak_flops_for_kind(device_kind: str) -> float | None:
    kind = device_kind.lower()
    for needle, peak in PEAK_FLOPS_TABLE:
        if needle in kind:
            return peak
    return None


def resolve_peak_flops(device_kind: str) -> tuple[float | None, str]:
    """(peak FLOPs per chip, source) for ``device_kind``. The
    ``PIO_DEVICE_PEAK_FLOPS`` override wins over the table (operators
    measuring a new chip, or assigning CPU an honest local peak);
    ``source`` is ``"env"``/``"table"`` or the reason there is none."""
    raw = os.environ.get(_PEAK_FLOPS_ENV, "").strip()
    if raw:
        try:
            value = float(raw)
            if value > 0:
                return value, "env"
            logger.warning("%s=%r is not positive; ignoring",
                           _PEAK_FLOPS_ENV, raw)
        except ValueError:
            logger.warning("%s=%r is not a number; ignoring",
                           _PEAK_FLOPS_ENV, raw)
    peak = peak_flops_for_kind(device_kind)
    if peak is not None:
        return peak, "table"
    return None, (f"no peak-FLOPs table entry for device kind "
                  f"{device_kind!r} (set {_PEAK_FLOPS_ENV})")


# ---------------------------------------------------------------------------
# device memory
# ---------------------------------------------------------------------------

#: memory_stats() keys -> exported gauge suffixes (only these three:
#: allocator-internal counters vary per backend and churn per release)
_MEM_FIELDS = (
    ("bytes_in_use", "pio_device_bytes_in_use",
     "Device memory currently allocated (memory_stats bytes_in_use)"),
    ("peak_bytes_in_use", "pio_device_peak_bytes_in_use",
     "Device memory high-water since process start"),
    ("bytes_limit", "pio_device_bytes_limit",
     "Device memory capacity visible to the allocator"),
)


def device_memory_snapshot() -> dict[str, dict[str, float]]:
    """``{device_label: {field: value}}`` for every local device that
    exposes ``memory_stats()`` — empty on host-only backends, empty on
    any jax runtime error (an obs read must never take the server
    down), and empty in processes that never imported jax: a /metrics
    scrape must not be the thing that initializes a device backend in
    a deliberately jax-free worker (the prefork echo/test engines)."""
    if "jax" not in sys.modules:
        return {}
    try:
        import jax

        devices = jax.local_devices()
    except Exception:
        return {}
    out: dict[str, dict[str, float]] = {}
    for dev in devices:
        try:
            stats = dev.memory_stats()
        except Exception:
            stats = None
        if not stats:
            continue
        label = f"{dev.platform}:{dev.id}"
        fields = {}
        for field, _, _ in _MEM_FIELDS:
            value = stats.get(field)
            if value is not None:
                fields[field] = float(value)
        if fields:
            fields["device_kind"] = getattr(dev, "device_kind", dev.platform)
            out[label] = fields
    return out


def device_memory_collector() -> Callable[[], Iterable[Metric]]:
    """Scrape-time HBM gauges; contributes nothing on backends without
    ``memory_stats`` (the graceful-absence contract)."""

    def collect() -> list[Metric]:
        snapshot = device_memory_snapshot()
        if not snapshot:
            return []
        out = []
        for field, name, help_text in _MEM_FIELDS:
            samples = [
                ({"device": label, "kind": str(stats.get("device_kind", ""))},
                 stats[field])
                for label, stats in sorted(snapshot.items())
                if field in stats
            ]
            if samples:
                out.append(Metric(name=name, kind="gauge", help=help_text,
                                  samples=samples))
        return out

    return collect


def _primary_device_kind() -> str:
    try:
        import jax

        dev = jax.local_devices()[0]
        return getattr(dev, "device_kind", dev.platform)
    except Exception:
        return "unknown"


def _device_count() -> int:
    try:
        import jax

        return max(1, jax.local_device_count())
    except Exception:
        return 1


# ---------------------------------------------------------------------------
# the train profiler (`pio train --profile`)
# ---------------------------------------------------------------------------

#: the last profiled train run's report, exported by
#: :func:`train_report_collector` (per process, like the recorder)
_LAST_REPORT: dict | None = None


class TrainProfiler:
    """Per-stage wall/compile/execute split, MFU, and HBM high-water
    for one training run.

    Usage (what ``run_train(profiler=...)`` does)::

        profiler = TrainProfiler(profile_dir=args.profile_dir)
        profiler.begin(trace)          # before engine.train
        ...                            # the traced run
        report = profiler.finish(trace, outcome)

    ``begin`` flips the recompile sentinel into cost-capture mode (per
    new signature it additionally prices the program via the AOT
    ``Compiled.cost_analysis()`` — documented profile-time overhead)
    and installs a span observer on the trace that samples device
    memory as each DASE stage closes. ``finish`` is idempotent and
    always runs (the driver calls it in a ``finally``), so an aborted
    run still stops the ``jax.profiler`` trace."""

    def __init__(self, recorder_: CompileRecorder | None = None,
                 profile_dir: str | None = None,
                 clock: Callable[[], float] = time.perf_counter):
        self.recorder = recorder_ if recorder_ is not None else recorder()
        self.profile_dir = profile_dir
        self._clock = clock
        self._stage_mem: dict[str, dict[str, float]] = {}
        self._baseline_events = 0
        self._t0: float | None = None
        self._jax_trace_on = False
        self._finished = False

    # -- lifecycle -----------------------------------------------------------
    def begin(self, trace: Any) -> None:
        self.recorder.capture_cost = True
        self._baseline_events = len(self.recorder.events())
        if trace is not None:
            trace.observer = self._on_span
        if self.profile_dir:
            try:
                import jax.profiler

                os.makedirs(self.profile_dir, exist_ok=True)
                jax.profiler.start_trace(self.profile_dir)
                self._jax_trace_on = True
            except Exception as e:
                logger.warning("--profile-dir: jax.profiler trace "
                               "unavailable (%s); continuing without", e)
        # the wall clock starts AFTER the capture machinery is up:
        # jax.profiler.start_trace costs seconds on a cold process, and
        # charging it to the run would deflate MFU and report an
        # execute split dominated by the profiler itself
        self._t0 = self._clock()

    def _on_span(self, name: str, start_off: float, dur: float) -> None:
        # called from Trace.add_span as each stage span closes; keep
        # the per-stage MAX so repeated spans (one per algorithm in the
        # train stage) keep the high-water
        snapshot = device_memory_snapshot()
        if not snapshot:
            return
        peak = max((s.get("peak_bytes_in_use", 0.0)
                    for s in snapshot.values()), default=0.0)
        in_use = sum(s.get("bytes_in_use", 0.0) for s in snapshot.values())
        have = self._stage_mem.get(name)
        if have is None or peak >= have.get("peak_bytes_in_use", 0.0):
            self._stage_mem[name] = {"peak_bytes_in_use": peak,
                                     "bytes_in_use": in_use}

    def finish(self, trace: Any, instance_id: str = "",
               status: str = "") -> dict:
        """Stop captures and build the TRAIN_REPORT document. Also
        publishes it for :func:`train_report_collector`."""
        global _LAST_REPORT
        if self._jax_trace_on:
            try:
                import jax.profiler

                jax.profiler.stop_trace()
            except Exception as e:  # pragma: no cover - backend drift
                logger.warning("jax.profiler stop_trace failed: %s", e)
            self._jax_trace_on = False
        if self._finished:
            return _LAST_REPORT or {}
        self._finished = True
        self.recorder.capture_cost = False
        wall = (self._clock() - self._t0) if self._t0 is not None else 0.0

        events = self.recorder.events()[self._baseline_events:]
        compile_total = sum(e[4] for e in events)
        stages = self._stage_split(trace)

        device_kind = _primary_device_kind()
        peak_flops, peak_source = resolve_peak_flops(device_kind)
        flops_total = self.recorder.executed_flops()
        mfu, mfu_reason = self._mfu(flops_total, peak_flops, peak_source,
                                    wall, device_kind)

        mem = device_memory_snapshot()
        hbm_peak = max((s.get("peak_bytes_in_use")
                        for s in mem.values()
                        if s.get("peak_bytes_in_use") is not None),
                       default=None)
        report = {
            "schema": TRAIN_REPORT_SCHEMA,
            "instanceId": instance_id,
            "status": status,
            "deviceKind": device_kind,
            "deviceCount": _device_count(),
            "wallSeconds": round(wall, 6),
            "stages": stages,
            "compile": {
                "totalSeconds": round(compile_total, 6),
                "totalCompiles": len(events),
                "table": self.recorder.recompile_table(),
            },
            "flops": {
                "executed": flops_total,
                "peakPerChip": peak_flops,
                "peakSource": peak_source if peak_flops is not None else None,
            },
            "mfu": mfu,
            "mfuReason": mfu_reason,
            "hbm": {
                "peakBytes": hbm_peak,
                "perStage": {name: dict(vals)
                             for name, vals in self._stage_mem.items()}
                            or None,
            },
            "profileDir": self.profile_dir,
        }
        _LAST_REPORT = report
        return report

    # -- pieces --------------------------------------------------------------
    def _stage_split(self, trace: Any) -> dict[str, dict]:
        """Per-stage wall/compile/execute: wall from the trace's span
        records, compile via the recorder's ONE midpoint-binning rule
        (:meth:`CompileRecorder.compile_seconds_between` — events from
        runs before this trace started cannot land in its intervals,
        the clock is monotonic), execute as the remainder (device
        execution and host work are indistinguishable without a
        profiler trace — --profile-dir is the deep-dive)."""
        stages: dict[str, dict] = {}
        if trace is None:
            return stages
        t0 = trace.start_perf
        intervals: dict[str, list[tuple[float, float]]] = {}
        for name, _parent, _sid, start_off, dur in trace.spans():
            intervals.setdefault(name, []).append(
                (t0 + start_off, t0 + start_off + dur))
        for name, spans in intervals.items():
            wall = sum(e - s for s, e in spans)
            compile_s = sum(
                self.recorder.compile_seconds_between(s, e)
                for s, e in spans)
            stages[name] = {
                "wallSeconds": round(wall, 6),
                "compileSeconds": round(compile_s, 6),
                "executeSeconds": round(max(0.0, wall - compile_s), 6),
            }
        return stages

    @staticmethod
    def _mfu(flops_total: float | None, peak_flops: float | None,
             peak_source: str, wall: float,
             device_kind: str) -> tuple[float | None, str]:
        if flops_total is None:
            return None, ("backend exposed no cost analysis for the "
                          "executed programs")
        if peak_flops is None:
            return None, peak_source  # carries the no-table-entry reason
        if wall <= 0:
            return None, "zero measured wall time"
        per_chip = flops_total / wall / _device_count()
        return per_chip / peak_flops, "ok"


def summarize_train_report(report: Mapping[str, Any]) -> str:
    """The one-line human summary `pio train --profile` prints."""
    compile_doc = report.get("compile", {})
    mfu = report.get("mfu")
    mfu_text = (f"{mfu * 100:.2f}%" if isinstance(mfu, (int, float))
                else f"n/a ({report.get('mfuReason', 'unknown')})")
    hbm = (report.get("hbm") or {}).get("peakBytes")
    hbm_text = (f"{hbm / (1 << 30):.2f} GiB" if hbm is not None else "n/a")
    wall = report.get("wallSeconds", 0.0)
    total_c = compile_doc.get("totalSeconds", 0.0)
    return (f"wall {wall:.2f}s | compile {total_c:.2f}s "
            f"({compile_doc.get('totalCompiles', 0)} compiles) | "
            f"execute {max(0.0, wall - total_c):.2f}s | "
            f"MFU {mfu_text} | HBM peak {hbm_text} | "
            f"device {report.get('deviceKind', '?')}"
            f" x{report.get('deviceCount', 1)}")


def train_report_collector() -> Callable[[], Iterable[Metric]]:
    """Gauges from the LAST profiled train run in this process —
    nothing until one ran (`pio train --profile`; the acceptance gauge
    ROADMAP item 1 measures against)."""

    def collect() -> list[Metric]:
        report = _LAST_REPORT
        if report is None:
            return []
        out = []
        mfu = report.get("mfu")
        if isinstance(mfu, (int, float)):
            out.append(Metric(
                name="pio_train_mfu", kind="gauge",
                help="Model FLOPs utilization of the last profiled "
                     "train run (executed FLOPs / wall / peak per chip)",
                samples=[({}, float(mfu))]))
        out.append(Metric(
            name="pio_train_compile_seconds", kind="gauge",
            help="XLA compile seconds inside the last profiled train",
            samples=[({},
                      float(report.get("compile", {})
                            .get("totalSeconds", 0.0)))]))
        per_stage = (report.get("hbm") or {}).get("perStage") or {}
        samples = [({"stage": stage},
                    float(vals.get("peak_bytes_in_use", 0.0)))
                   for stage, vals in sorted(per_stage.items())]
        if samples:
            out.append(Metric(
                name="pio_train_stage_hbm_peak_bytes", kind="gauge",
                help="Device memory high-water sampled as each DASE "
                     "stage of the last profiled train closed "
                     "(monotone across stages: allocator high-water)",
                samples=samples))
        return out

    return collect
