"""Scrape-time aggregation across processes: parse Prometheus text
back into :class:`~predictionio_tpu.obs.registry.Metric` families and
merge families from several sources into one truthful exposition
(docs/observability.md, docs/fleet.md).

Two fan-out consumers (both in the fleet tier — this module stays pure,
no I/O, so the obs plane keeps its "scrapers pull, the plane never
pushes" lint invariant):

- ``pio router --workers N``: N SO_REUSEPORT processes each hold a
  private registry, and a scrape lands on ONE of them. The scraped
  worker pulls its peers' expositions (fleet/workers.py) and merges, so
  ``/metrics`` reports fleet-of-workers truth instead of a 1/N sample.
- ``GET /fleet/metrics``: the router scrapes each replica's
  ``/metrics`` and re-exports with a ``replica`` label.

Merge rules by family kind:

- **counter** — samples with identical label sets are SUMMED (totals
  across workers are the number an operator wants);
- **histogram** — merged bucket-wise on the union of the bound
  ladders: each source's cumulative snapshot is converted to per-bucket
  deltas, deltas land on their own bound in the union ladder, and the
  result is re-accumulated — exact when ladders agree (the common
  case: same code, same DEFAULT_BOUNDS) and lossless w.r.t. the
  coarser source otherwise. Sums and counts add.
- **gauge** — NOT summed (the sum of two workers' breaker states is
  meaningless): each sample gains a source label (``worker="1234"``)
  and all are kept, so per-worker truth stays visible.
"""

from __future__ import annotations

import re
from typing import Iterable, Mapping, Sequence

from predictionio_tpu.obs.histogram import HistogramSnapshot
from predictionio_tpu.obs.registry import Metric

_SAMPLE_RE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?:\{(?P<labels>[^}]*)\})?"
    r"\s+(?P<value>\S+)\s*$")
_LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')
_UNESCAPE_RE = re.compile(r"\\(.)")
_UNESCAPES = {"n": "\n", '"': '"', "\\": "\\"}


def unescape_label_value(value: str) -> str:
    """Single-pass inverse of exporter.escape_label_value. Sequential
    ``str.replace`` passes are WRONG here: they re-scan bytes produced
    by earlier passes, so ``a\\nb`` (backslash, 'n') unescaped
    newline-first turns into a real newline. One regex pass cannot
    re-read its own output."""
    return _UNESCAPE_RE.sub(
        lambda m: _UNESCAPES.get(m.group(1), m.group(1)), value)


class ExpositionParseError(ValueError):
    """The text is not parseable Prometheus 0.0.4 exposition."""


def _parse_value(raw: str) -> float:
    if raw == "NaN":
        return float("nan")
    if raw == "+Inf":
        return float("inf")
    if raw == "-Inf":
        return float("-inf")
    return float(raw)


def parse_exposition(text: str) -> list[Metric]:
    """Parse one ``/metrics`` body back into Metric families —
    histograms are reconstructed into :class:`HistogramSnapshot` form
    (bounds from ``le=``, cumulative buckets, sum, count) so a merged
    family re-renders through the same exporter. Raises
    :class:`ExpositionParseError` on malformed input; fan-out callers
    catch it per source and degrade instead of failing the scrape."""
    try:
        return _parse_exposition(text)
    except ExpositionParseError:
        raise
    except (ValueError, KeyError) as exc:
        # a garbled value token (float('1.2e')), a bucket line without
        # le=, a NaN bucket count — all mean "this body is not valid
        # exposition", and the contract above is that callers only
        # need to catch ExpositionParseError to degrade per source
        raise ExpositionParseError(f"malformed exposition: {exc}") from exc


def _parse_exposition(text: str) -> list[Metric]:
    families: dict[str, Metric] = {}
    # histogram assembly: family -> {frozen base labels: parts}
    hist_parts: dict[str, dict[tuple, dict]] = {}
    for line in text.splitlines():
        if not line.strip():
            continue
        if line.startswith("# HELP ") or line.startswith("# TYPE "):
            kind_line = line.startswith("# TYPE ")
            _, _, rest = line.partition(
                "# TYPE " if kind_line else "# HELP ")
            name, _, payload = rest.partition(" ")
            fam = families.get(name)
            if fam is None:
                fam = families[name] = Metric(name=name, kind="untyped",
                                              help="")
            if kind_line:
                if payload not in ("counter", "gauge", "histogram",
                                   "untyped"):
                    raise ExpositionParseError(
                        f"unsupported TYPE {payload!r} for {name}")
                fam.kind = payload
            else:
                fam.help = payload
            continue
        if line.startswith("#"):
            continue    # comments are legal exposition
        m = _SAMPLE_RE.match(line)
        if m is None:
            raise ExpositionParseError(f"unparseable line: {line!r}")
        sample_name = m.group("name")
        labels = {
            k: unescape_label_value(v)
            for k, v in _LABEL_RE.findall(m.group("labels") or "")
        }
        value = _parse_value(m.group("value"))
        family = sample_name
        suffix = ""
        for cand in ("_bucket", "_sum", "_count"):
            base = sample_name[:-len(cand)] if sample_name.endswith(cand) \
                else None
            if base is not None and families.get(base) is not None \
                    and families[base].kind == "histogram":
                family, suffix = base, cand
                break
        fam = families.get(family)
        if fam is None:
            raise ExpositionParseError(
                f"sample before HELP/TYPE: {line!r}")
        if fam.kind == "histogram":
            base_labels = {k: v for k, v in labels.items() if k != "le"}
            key = tuple(sorted(base_labels.items()))
            part = hist_parts.setdefault(family, {}).setdefault(
                key, {"labels": base_labels, "buckets": {},
                      "sum": 0.0, "count": 0})
            if suffix == "_bucket":
                part["buckets"][_parse_value(labels["le"])] = int(value)
            elif suffix == "_sum":
                part["sum"] = value
            elif suffix == "_count":
                part["count"] = int(value)
            else:
                raise ExpositionParseError(
                    f"bare sample on histogram family: {line!r}")
        else:
            fam.samples.append((labels, value))

    inf = float("inf")
    for family, by_labels in hist_parts.items():
        fam = families[family]
        for part in by_labels.values():
            buckets = part["buckets"]
            if inf not in buckets:
                raise ExpositionParseError(
                    f"histogram {family} lacks a +Inf bucket")
            bounds = tuple(sorted(b for b in buckets if b != inf))
            cumulative = tuple(buckets[b] for b in bounds) + (buckets[inf],)
            fam.histograms.append((part["labels"], HistogramSnapshot(
                bounds=bounds or (inf,),
                cumulative=cumulative if bounds else (buckets[inf],
                                                      buckets[inf]),
                sum=part["sum"],
                count=part["count"],
            )))
    return list(families.values())


def merge_snapshots(snaps: Sequence[HistogramSnapshot]) -> HistogramSnapshot:
    """Bucket-wise merge on the union bound ladder (module docstring)."""
    inf = float("inf")
    # a parsed +Inf-only histogram carries bounds=(inf,): keep inf out
    # of the union ladder (its mass is the overflow below) or the
    # merged snapshot renders two conflicting le="+Inf" bucket lines
    union = sorted({b for s in snaps for b in s.bounds if b != inf})
    totals = [0] * (len(union) + 1)
    total_sum = 0.0
    total_count = 0
    index = {b: i for i, b in enumerate(union)}
    for snap in snaps:
        prev = 0
        for bound, cum in zip(snap.bounds, snap.cumulative):
            if bound == inf:
                break   # bounds ascend: only the overflow remains
            totals[index[bound]] += cum - prev
            prev = cum
        totals[-1] += snap.cumulative[-1] - prev     # the +Inf overflow
        total_sum += snap.sum
        total_count += snap.count
    cumulative: list[int] = []
    running = 0
    for delta in totals:
        running += delta
        cumulative.append(running)
    return HistogramSnapshot(
        bounds=tuple(union) or (float("inf"),),
        cumulative=tuple(cumulative) if union else (running, running),
        sum=total_sum,
        count=total_count,
    )


def source_count_metric(name: str, help: str, count: int) -> Metric:
    """The "how many processes fed this scrape" gauge every merged
    exposition appends AFTER :func:`merge_sources` (so it never gains a
    per-source label itself): ``pio_router_workers`` on the router,
    ``pio_serving_workers`` on the engine server. A reading below the
    launched worker count means a sibling is dead or wedged —
    docs/fleet.md and docs/serving-performance.md runbooks key off it."""
    return Metric(name=name, kind="gauge", help=help,
                  samples=[({}, float(count))])


def relabel(metrics: Iterable[Metric], extra: Mapping[str, str]) -> list[Metric]:
    """Copies with ``extra`` merged into every sample's label set (the
    ``replica=...``/``group=...`` annotation on ``/fleet/metrics``,
    plus ``engine=...`` behind a multi-engine gateway). Existing keys
    are not overwritten — a replica that already labels per worker (or
    already exports its own ``engine`` label) keeps its labels, so the
    gateway's annotation can never collide with a source's. Label
    VALUES pass through untouched: escaping happens at render time and
    unescaping at parse time, so a hostile engine name (quotes,
    backslashes, newlines) round-trips exactly (pinned in
    tests/test_fleet_obs.py)."""
    out = []
    for m in metrics:
        out.append(Metric(
            name=m.name, kind=m.kind, help=m.help,
            samples=[({**extra, **labels}, value)
                     for labels, value in m.samples],
            histograms=[({**extra, **labels}, snap)
                        for labels, snap in m.histograms],
        ))
    return out


def merge_sources(sources: Sequence[tuple[str, list[Metric]]],
                  source_label: str = "worker") -> list[Metric]:
    """Merge several processes' family lists into one namespace
    (module docstring's rules). ``sources`` is ``(source_id,
    families)`` pairs; gauges gain ``{source_label: source_id}``.
    A family whose kind disagrees across sources is dropped from the
    merge rather than failing the whole scrape (the disagreement is a
    version skew between workers, not a reason to blind the operator)."""
    kinds: dict[str, str] = {}
    skip: set[str] = set()
    for _, families in sources:
        for fam in families:
            have = kinds.setdefault(fam.name, fam.kind)
            if have != fam.kind:
                skip.add(fam.name)
    merged: dict[str, Metric] = {}
    # counter samples sum by label set; histograms merge per label set
    counter_acc: dict[str, dict[tuple, float]] = {}
    hist_acc: dict[str, dict[tuple, list[HistogramSnapshot]]] = {}
    for source_id, families in sources:
        for fam in families:
            if fam.name in skip:
                continue
            out = merged.get(fam.name)
            if out is None:
                out = merged[fam.name] = Metric(
                    name=fam.name, kind=fam.kind, help=fam.help)
            if fam.kind == "histogram":
                acc = hist_acc.setdefault(fam.name, {})
                for labels, snap in fam.histograms:
                    acc.setdefault(
                        tuple(sorted(labels.items())), []).append(snap)
            elif fam.kind == "counter":
                acc_c = counter_acc.setdefault(fam.name, {})
                for labels, value in fam.samples:
                    key = tuple(sorted(labels.items()))
                    acc_c[key] = acc_c.get(key, 0.0) + value
            else:   # gauge / untyped: keep all, labeled per source
                for labels, value in fam.samples:
                    out.samples.append(
                        ({source_label: source_id, **labels}, value))
    for name, acc_c in counter_acc.items():
        merged[name].samples = [
            (dict(key), value) for key, value in sorted(acc_c.items())]
    for name, acc in hist_acc.items():
        merged[name].histograms = [
            (dict(key), merge_snapshots(snaps))
            for key, snaps in sorted(acc.items())]
    return list(merged.values())
