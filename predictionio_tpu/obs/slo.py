"""SLO engine: declarative service-level objectives evaluated into
multi-window burn-rate gauges, plus the fleet-pressure signal
(docs/fleet.md "Autoscaling signals", docs/observability.md).

An :class:`SLOObjective` names what "good" means — availability (non-
5xx) or latency (answered within ``threshold_ms``) — and a ``target``
fraction of good requests. The engine folds every request outcome into
a per-second ring (one lock, one list write — hot-path cheap, clock
injectable for deterministic tests) and, at scrape time only, evaluates

    burn_rate(window) = bad_fraction(window) / (1 - target)

the standard multi-window burn-rate construction (Google SRE workbook):
``burn == 1`` means the error budget is being spent exactly at the
sustainable rate; an alerting controller pages when the FAST window
burns hot (the incident is happening now) AND the slow window confirms
it is not a blip. The fast gauge reacting while the slow one lags is
exactly the property the chaos test pins.

``pio_fleet_pressure`` is the Clipper-style scaling signal derived from
the queue-wait/device-dispatch split the batcher already measures:

    pressure = p95(queue_wait) / (p95(queue_wait) + p95(device_dispatch))

0 means requests never wait (scale down candidate), → 1 means latency
is queueing, not model time — adding replicas helps (scale up); model-
bound saturation (device time growing) keeps pressure LOW, telling the
controller that more replicas of the same hardware are the wrong move.
Exported by the engine server from its own histograms and by the router
(``/fleet/metrics``) from the bucket-merged fleet-wide histograms.
"""

from __future__ import annotations

import dataclasses
import os
import threading
from typing import Sequence

from predictionio_tpu.obs.histogram import HistogramSnapshot
from predictionio_tpu.obs.registry import Collector, Metric
from predictionio_tpu.utils.resilience import SYSTEM_CLOCK, Clock

AVAILABILITY = "availability"
LATENCY = "latency"


@dataclasses.dataclass(frozen=True)
class SLOObjective:
    """One objective: ``target`` fraction of requests must be good."""

    name: str
    target: float                       # e.g. 0.999
    kind: str = AVAILABILITY            # AVAILABILITY | LATENCY
    #: latency objectives: good iff answered (non-5xx) within this
    threshold_ms: float = 0.0

    def __post_init__(self):
        if not 0.0 < self.target < 1.0:
            raise ValueError(
                f"SLO target must be in (0, 1), got {self.target}")
        if self.kind not in (AVAILABILITY, LATENCY):
            raise ValueError(f"unknown SLO kind {self.kind!r}")
        if self.kind == LATENCY and self.threshold_ms <= 0:
            raise ValueError("latency SLO needs threshold_ms > 0")

    @property
    def budget(self) -> float:
        return 1.0 - self.target

    def is_bad(self, ok: bool, latency_s: float) -> bool:
        if not ok:
            return True             # a failed request violates every SLO
        if self.kind == LATENCY:
            return latency_s * 1e3 > self.threshold_ms
        return False


#: multi-window convention: the fast window catches the incident, the
#: slow window keeps one bad minute from paging at 3am
DEFAULT_WINDOWS: tuple[tuple[str, float], ...] = (
    ("fast", 300.0), ("slow", 3600.0))


def _env_float(key: str, default: float) -> float:
    raw = os.environ.get(key)
    if raw is None:
        return default
    try:
        return float(raw)
    except ValueError:
        return default


def default_slos() -> tuple[SLOObjective, ...]:
    """The stock objectives every server ships with, env-tunable at
    server construction (the ServerConfig discipline — read at call
    time): ``PIO_SLO_AVAILABILITY_TARGET`` (default 99.9%),
    ``PIO_SLO_LATENCY_MS`` + ``PIO_SLO_LATENCY_TARGET`` (default 99%
    under 500ms; ``PIO_SLO_LATENCY_MS=0`` drops the latency SLO)."""
    objectives = [SLOObjective(
        name="availability",
        target=_env_float("PIO_SLO_AVAILABILITY_TARGET", 0.999))]
    threshold = _env_float("PIO_SLO_LATENCY_MS", 500.0)
    if threshold > 0:
        objectives.append(SLOObjective(
            name=f"latency_{threshold:g}ms", kind=LATENCY,
            threshold_ms=threshold,
            target=_env_float("PIO_SLO_LATENCY_TARGET", 0.99)))
    return tuple(objectives)


def default_windows() -> tuple[tuple[str, float], ...]:
    """``PIO_SLO_FAST_WINDOW_S`` / ``PIO_SLO_SLOW_WINDOW_S`` overrides
    of :data:`DEFAULT_WINDOWS`."""
    return (
        ("fast", max(1.0, _env_float("PIO_SLO_FAST_WINDOW_S", 300.0))),
        ("slow", max(1.0, _env_float("PIO_SLO_SLOW_WINDOW_S", 3600.0))),
    )


class SLOEngine:
    """Per-second outcome ring + scrape-time burn-rate evaluation.

    One lock guards the ring at the writer (``record``, every request)
    and the reader (``burn_rates``, scrape time) — the ServingStats
    lock discipline. A ring slot is ``[second, total, bad_0, ...,
    bad_{n-1}]`` (one bad counter per objective); slots recycle by
    ``second % len(ring)`` with the absolute second stored so stale
    laps never leak into a window."""

    def __init__(self, objectives: Sequence[SLOObjective] | None = None,
                 windows: Sequence[tuple[str, float]] | None = None,
                 clock: Clock = SYSTEM_CLOCK):
        self.objectives = tuple(objectives if objectives is not None
                                else default_slos())
        self.windows = tuple(windows if windows is not None
                             else default_windows())
        if not self.windows:
            raise ValueError("SLOEngine needs at least one window")
        self._clock = clock
        self._lock = threading.Lock()
        horizon = int(max(seconds for _, seconds in self.windows)) + 1
        #: slot: [absolute_second, total, bad per objective...]
        self._ring: list[list[int]] = [
            [-1, 0] + [0] * len(self.objectives) for _ in range(horizon)
        ]

    # -- hot path ------------------------------------------------------------
    def record(self, ok: bool, latency_s: float) -> None:
        """Fold one request outcome in (one lock acquisition)."""
        second = int(self._clock.monotonic())
        bad = [obj.is_bad(ok, latency_s) for obj in self.objectives]
        with self._lock:
            slot = self._ring[second % len(self._ring)]
            if slot[0] != second:
                slot[0] = second
                for i in range(1, len(slot)):
                    slot[i] = 0
            slot[1] += 1
            for i, b in enumerate(bad):
                if b:
                    slot[2 + i] += 1

    # -- scrape path ---------------------------------------------------------
    def _window_counts(self, now_s: int,
                       window_s: float) -> list[tuple[int, list[int]]]:
        lo = now_s - int(window_s)
        out = []
        with self._lock:
            for slot in self._ring:
                if lo < slot[0] <= now_s:
                    out.append((slot[1], list(slot[2:])))
        return out

    def burn_rates(self) -> dict[tuple[str, str], float]:
        """``{(slo_name, window_label): burn}`` — 0.0 for an idle
        window (no traffic means no budget spend; an autoscaler must
        not page on silence)."""
        now_s = int(self._clock.monotonic())
        out: dict[tuple[str, str], float] = {}
        for label, seconds in self.windows:
            counts = self._window_counts(now_s, seconds)
            total = sum(t for t, _ in counts)
            for i, obj in enumerate(self.objectives):
                if total == 0:
                    out[(obj.name, label)] = 0.0
                    continue
                bad = sum(b[i] for _, b in counts)
                out[(obj.name, label)] = (bad / total) / obj.budget
        return out

    def max_burns(self) -> dict[str, float]:
        """``{window_label: worst burn across objectives}`` — the
        scale-up signal shape the fleet controller consumes
        (fleet/controller.py ``ScaleSignals``): any objective burning
        hot in a window makes that window hot."""
        out: dict[str, float] = {label: 0.0 for label, _ in self.windows}
        for (_slo, window), rate in self.burn_rates().items():
            if rate > out.get(window, 0.0):
                out[window] = rate
        return out

    # -- registry adapter ----------------------------------------------------
    def collector(self) -> Collector:
        def collect() -> list[Metric]:
            burn = Metric(
                name="pio_slo_burn_rate", kind="gauge",
                help="Error-budget burn rate per SLO and window "
                     "(1 = budget spent exactly at the sustainable "
                     "rate; docs/fleet.md autoscaler contract)")
            for (slo, window), rate in sorted(self.burn_rates().items()):
                burn.samples.append(
                    ({"slo": slo, "window": window}, rate))
            target = Metric(
                name="pio_slo_target", kind="gauge",
                help="Configured good-fraction target per SLO")
            for obj in self.objectives:
                target.samples.append(({"slo": obj.name}, obj.target))
            windows = Metric(
                name="pio_slo_window_seconds", kind="gauge",
                help="Evaluation window lengths by label")
            for label, seconds in self.windows:
                windows.samples.append(({"window": label}, seconds))
            return [burn, target, windows]

        return collect


def labeled_burn_metric(engines: Sequence[tuple[dict, "SLOEngine"]],
                        name: str = "pio_slo_burn_rate",
                        help: str = "Error-budget burn rate per SLO "
                                    "and window") -> Metric:
    """Fold SEVERAL SLO engines into ONE burn-rate family, each
    engine's samples stamped with its label set — the multi-tenant
    gateway's per-engine burn gauges (fleet/gateway.py): N engines
    cannot each register their own collector for the same family name
    (the exporter would render N conflicting HELP/TYPE blocks), so the
    gateway builds the merged family here at scrape time."""
    metric = Metric(name=name, kind="gauge", help=help)
    for labels, engine in engines:
        for (slo, window), rate in sorted(engine.burn_rates().items()):
            metric.samples.append(
                ({**labels, "slo": slo, "window": window}, rate))
    return metric


# ---------------------------------------------------------------------------
# fleet pressure (module docstring)
# ---------------------------------------------------------------------------

def fleet_pressure(queue_wait: HistogramSnapshot,
                   device_dispatch: HistogramSnapshot,
                   q: float = 0.95) -> float:
    """Queue share of tail latency in [0, 1]; 0.0 when idle."""
    wait = queue_wait.quantile(q) or 0.0
    device = device_dispatch.quantile(q) or 0.0
    if wait + device <= 0.0:
        return 0.0
    return wait / (wait + device)


def pressure_metric(queue_wait: HistogramSnapshot,
                    device_dispatch: HistogramSnapshot,
                    labels: dict[str, str] | None = None) -> Metric:
    return Metric(
        name="pio_fleet_pressure", kind="gauge",
        help="Queue-wait share of p95 serving latency (0 idle, ->1 "
             "queue-bound: add replicas; docs/fleet.md)",
        samples=[(dict(labels or {}),
                  fleet_pressure(queue_wait, device_dispatch))])


def serving_pressure_collector(stats) -> Collector:
    """Engine-server adapter: derive the pressure gauge from the
    ServingStats queue-wait / device-dispatch histograms at scrape
    time."""

    def collect() -> list[Metric]:
        return [pressure_metric(stats.queue_wait.snapshot(),
                                stats.device_time.snapshot())]

    return collect
