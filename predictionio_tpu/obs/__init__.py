"""Observability plane: request tracing, latency histograms, metric
registry, and Prometheus text export (docs/observability.md).

Zero-dependency by design — the serving plane must not grow a client
library for the privilege of being measured. Seven layers:

- :mod:`~predictionio_tpu.obs.trace` — Dapper-style spans with ids,
  parent links, and contextvar propagation that survives the
  QueryBatcher's thread handoff and the deadline-dispatch pool;
- :mod:`~predictionio_tpu.obs.histogram` — log-bucketed latency
  histograms with lock-guarded snapshots, shared by serving and ingest;
- :mod:`~predictionio_tpu.obs.registry` — one metric registry per
  server that adopts the existing ServingStats / IngestStats /
  resilience counters through scrape-time collectors;
- :mod:`~predictionio_tpu.obs.exporter` — Prometheus text-format
  rendering for ``GET /metrics``;
- :mod:`~predictionio_tpu.obs.aggregate` — exposition parsing and
  cross-process merge rules (worker peering, ``/fleet/metrics``);
- :mod:`~predictionio_tpu.obs.stitch` — cross-process trace stitching
  plus text/Chrome-trace renderers (``pio trace``);
- :mod:`~predictionio_tpu.obs.slo` — declarative SLOs evaluated into
  multi-window burn-rate gauges and the fleet-pressure signal;
- :mod:`~predictionio_tpu.obs.compile` — the recompile sentinel:
  ``instrumented_jit`` wraps the package's jit entry points and turns
  post-warmup serving compiles into counters, WARNs and trace spans;
- :mod:`~predictionio_tpu.obs.device` — device memory gauges, the
  peak-FLOPs table, and the ``pio train --profile`` profiler
  (TRAIN_REPORT.json + MFU/HBM gauges).

The fan-out I/O that feeds aggregate/stitch lives in the FLEET tier
(fleet/workers.py, api/router_server.py) — obs/ itself stays pure
(scrapers pull; the plane never pushes — the lint invariant).

The disabled path is near-free: one flag check and no allocation per
request (``trace.start_trace`` is only called behind the server's
``tracing`` flag; ambient ``span()`` returns a shared no-op when no
trace is active), so tracing defaults off in benches.
"""

from predictionio_tpu.obs.aggregate import (
    merge_snapshots,
    merge_sources,
    parse_exposition,
    relabel,
    unescape_label_value,
)
from predictionio_tpu.obs.compile import (
    CompileRecorder,
    compile_metrics_collector,
    instrumented_jit,
)
from predictionio_tpu.obs.device import (
    TrainProfiler,
    device_memory_collector,
    device_memory_snapshot,
    resolve_peak_flops,
    summarize_train_report,
    train_report_collector,
)
from predictionio_tpu.obs.exporter import (
    escape_label_value,
    render_metrics,
    render_prometheus,
)
from predictionio_tpu.obs.histogram import LatencyHistogram
from predictionio_tpu.obs.registry import (
    HistogramFamily,
    Metric,
    MetricRegistry,
    ingest_collector,
    resilience_collector,
    server_info_collector,
    serving_collector,
)
from predictionio_tpu.obs.slo import (
    SLOEngine,
    SLOObjective,
    fleet_pressure,
    serving_pressure_collector,
)
from predictionio_tpu.obs.stitch import render_tree, stitch, to_chrome_trace
from predictionio_tpu.obs.trace import (
    PARENT_SPAN_HEADER,
    TRACE_ID_HEADER,
    Trace,
    TraceLog,
    active_trace,
    parse_trace_context,
    span,
    start_trace,
    tracing_default,
    use_trace,
)

__all__ = [
    "CompileRecorder",
    "HistogramFamily",
    "LatencyHistogram",
    "Metric",
    "MetricRegistry",
    "PARENT_SPAN_HEADER",
    "SLOEngine",
    "SLOObjective",
    "TRACE_ID_HEADER",
    "Trace",
    "TraceLog",
    "TrainProfiler",
    "active_trace",
    "compile_metrics_collector",
    "device_memory_collector",
    "device_memory_snapshot",
    "escape_label_value",
    "instrumented_jit",
    "fleet_pressure",
    "ingest_collector",
    "merge_snapshots",
    "merge_sources",
    "parse_exposition",
    "parse_trace_context",
    "relabel",
    "render_metrics",
    "render_prometheus",
    "render_tree",
    "resilience_collector",
    "resolve_peak_flops",
    "server_info_collector",
    "serving_collector",
    "serving_pressure_collector",
    "span",
    "start_trace",
    "stitch",
    "summarize_train_report",
    "to_chrome_trace",
    "train_report_collector",
    "tracing_default",
    "unescape_label_value",
    "use_trace",
]
