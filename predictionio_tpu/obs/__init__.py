"""Observability plane: request tracing, latency histograms, metric
registry, and Prometheus text export (docs/observability.md).

Zero-dependency by design — the serving plane must not grow a client
library for the privilege of being measured. Four layers:

- :mod:`~predictionio_tpu.obs.trace` — Dapper-style spans with ids,
  parent links, and contextvar propagation that survives the
  QueryBatcher's thread handoff and the deadline-dispatch pool;
- :mod:`~predictionio_tpu.obs.histogram` — log-bucketed latency
  histograms with lock-guarded snapshots, shared by serving and ingest;
- :mod:`~predictionio_tpu.obs.registry` — one metric registry per
  server that adopts the existing ServingStats / IngestStats /
  resilience counters through scrape-time collectors;
- :mod:`~predictionio_tpu.obs.exporter` — Prometheus text-format
  rendering for ``GET /metrics``.

The disabled path is near-free: one flag check and no allocation per
request (``trace.start_trace`` is only called behind the server's
``tracing`` flag; ambient ``span()`` returns a shared no-op when no
trace is active), so tracing defaults off in benches.
"""

from predictionio_tpu.obs.exporter import render_prometheus
from predictionio_tpu.obs.histogram import LatencyHistogram
from predictionio_tpu.obs.registry import (
    HistogramFamily,
    Metric,
    MetricRegistry,
    ingest_collector,
    resilience_collector,
    server_info_collector,
    serving_collector,
)
from predictionio_tpu.obs.trace import (
    Trace,
    TraceLog,
    active_trace,
    span,
    start_trace,
    tracing_default,
    use_trace,
)

__all__ = [
    "HistogramFamily",
    "LatencyHistogram",
    "Metric",
    "MetricRegistry",
    "Trace",
    "TraceLog",
    "active_trace",
    "ingest_collector",
    "render_prometheus",
    "resilience_collector",
    "server_info_collector",
    "serving_collector",
    "span",
    "start_trace",
    "tracing_default",
    "use_trace",
]
