"""Log-bucketed latency histograms with lock-guarded snapshots.

One histogram is a fixed ladder of upper bounds (log-spaced powers of
two by default: 100µs, 200µs, ... ~13s) plus a +Inf overflow bucket, a
running sum, and a count. ``observe`` is the hot-path write: one bisect
over a 18-entry tuple and one lock acquisition — cheap enough for every
request on the serving and ingest paths. ``snapshot`` reads everything
under the same lock, so a concurrent scrape never sees a torn histogram
(count always equals the +Inf cumulative bucket; the sum matches the
observations that produced the counts).

The snapshot's bucket counts are CUMULATIVE (each bucket counts all
observations ≤ its bound), which is exactly the Prometheus histogram
exposition shape (``*_bucket{le=...}``) and makes quantile estimation a
single scan.
"""

from __future__ import annotations

import threading
from bisect import bisect_left
from typing import Iterable, NamedTuple, Sequence

#: default bucket ladder: powers of two from 100µs to ~13.1s. Log
#: spacing keeps relative error bounded (~2x) across the whole range a
#: serving path spans — sub-ms cache hits to multi-second cold batches
#: — with a ladder small enough to scan per observe.
DEFAULT_BOUNDS: tuple[float, ...] = tuple(
    0.0001 * (1 << i) for i in range(18)
)


class HistogramSnapshot(NamedTuple):
    """An atomic view of one histogram (see module docstring)."""

    #: upper bounds, ascending; the implicit +Inf bucket follows
    bounds: tuple[float, ...]
    #: cumulative counts per bound, plus the +Inf total as the last entry
    cumulative: tuple[int, ...]
    #: sum of observed values (seconds)
    sum: float
    #: total observations — always equals ``cumulative[-1]``
    count: int

    def quantile(self, q: float) -> float | None:
        """Upper-bound estimate of the q-quantile (0 < q <= 1): the
        bound of the first bucket whose cumulative count reaches
        q*count. None when empty; the top bound is returned for
        overflow observations (the estimate saturates, it never
        invents a value beyond the ladder)."""
        if self.count == 0:
            return None
        need = q * self.count
        for bound, cum in zip(self.bounds, self.cumulative):
            if cum >= need:
                return bound
        return self.bounds[-1]

    def summary_ms(self) -> dict:
        """Operator-facing summary for the JSON status docs."""
        mean = self.sum / self.count if self.count else None
        to_ms = lambda v: round(v * 1e3, 3) if v is not None else None  # noqa: E731
        return {
            "count": self.count,
            "meanMs": to_ms(mean),
            "p50Ms": to_ms(self.quantile(0.50)),
            "p95Ms": to_ms(self.quantile(0.95)),
            "p99Ms": to_ms(self.quantile(0.99)),
        }


class LatencyHistogram:
    """Thread-safe log-bucketed histogram of seconds (module docstring).

    One lock guards counts, sum, and count at writers AND readers —
    the ServingStats/IngestStats discipline, so the lock-discipline
    lint needs no suppressions and a scrape never tears."""

    __slots__ = ("bounds", "_lock", "_counts", "_sum", "_count")

    def __init__(self, bounds: Sequence[float] = DEFAULT_BOUNDS):
        if not bounds or list(bounds) != sorted(bounds):
            raise ValueError("histogram bounds must be ascending and non-empty")
        self.bounds = tuple(float(b) for b in bounds)
        self._lock = threading.Lock()
        # one slot per bound + the +Inf overflow slot
        self._counts = [0] * (len(self.bounds) + 1)
        self._sum = 0.0
        self._count = 0

    def observe(self, seconds: float) -> None:
        idx = bisect_left(self.bounds, seconds)
        with self._lock:
            self._counts[idx] += 1
            self._sum += seconds
            self._count += 1

    def observe_many(self, values: Iterable[float]) -> None:
        """Batched observe: ONE lock acquisition for a whole batch's
        worth of samples (the batcher records every entry's queue wait
        in one call)."""
        indexed = [(bisect_left(self.bounds, v), v) for v in values]
        if not indexed:
            return
        with self._lock:
            for idx, v in indexed:
                self._counts[idx] += 1
                self._sum += v
            self._count += len(indexed)

    def snapshot(self) -> HistogramSnapshot:
        with self._lock:
            counts = list(self._counts)
            total_sum = self._sum
            count = self._count
        cumulative: list[int] = []
        running = 0
        for c in counts:
            running += c
            cumulative.append(running)
        return HistogramSnapshot(
            bounds=self.bounds,
            cumulative=tuple(cumulative),
            sum=total_sum,
            count=count,
        )
