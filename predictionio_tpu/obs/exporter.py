"""Prometheus text exposition (version 0.0.4) over a MetricRegistry.

Pure rendering — no client library, no network. The output contract is
pinned by a round-trip test (tests/test_observability.py parses the
text back and checks it against the registry), so a scraper and this
renderer can't drift apart silently:

- every family gets ``# HELP`` and ``# TYPE`` lines;
- counter sample names end in ``_total``;
- histograms expose cumulative ``_bucket{le=...}`` series ending in
  ``le="+Inf"``, plus ``_sum`` and ``_count``, with
  ``_count == _bucket{le="+Inf"}`` (the torn-snapshot invariant the
  lock-guarded HistogramSnapshot carries through to the wire).
"""

from __future__ import annotations

from typing import Mapping

from predictionio_tpu.obs.registry import MetricRegistry

#: the content type Prometheus scrapers expect for this format
CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


def _escape_help(text: str) -> str:
    return text.replace("\\", "\\\\").replace("\n", "\\n")


def _escape_label(value: str) -> str:
    return (value.replace("\\", "\\\\")
            .replace('"', '\\"')
            .replace("\n", "\\n"))


def _fmt_value(value: float) -> str:
    if value != value:  # NaN
        return "NaN"
    if value == int(value) and abs(value) < 1e15:
        return str(int(value))
    return repr(float(value))


def _fmt_labels(labels: Mapping[str, str]) -> str:
    if not labels:
        return ""
    inner = ",".join(
        f'{k}="{_escape_label(str(v))}"' for k, v in sorted(labels.items())
    )
    return "{" + inner + "}"


def render_prometheus(registry: MetricRegistry) -> str:
    """Render every family in the registry, sorted by name so
    successive scrapes diff cleanly."""
    lines: list[str] = []
    for metric in sorted(registry.collect(), key=lambda m: m.name):
        lines.append(f"# HELP {metric.name} {_escape_help(metric.help)}")
        lines.append(f"# TYPE {metric.name} {metric.kind}")
        if metric.kind == "histogram":
            for labels, snap in metric.histograms:
                base = dict(labels)
                # cumulative[-1] is the +Inf bucket; pairs below cover
                # the finite bounds
                for bound, cum in zip(snap.bounds, snap.cumulative):
                    lines.append(
                        f"{metric.name}_bucket"
                        f"{_fmt_labels({**base, 'le': repr(float(bound))})}"
                        f" {cum}")
                lines.append(
                    f"{metric.name}_bucket"
                    f"{_fmt_labels({**base, 'le': '+Inf'})}"
                    f" {snap.cumulative[-1]}")
                lines.append(
                    f"{metric.name}_sum{_fmt_labels(base)}"
                    f" {_fmt_value(snap.sum)}")
                lines.append(
                    f"{metric.name}_count{_fmt_labels(base)}"
                    f" {snap.count}")
            continue
        for labels, value in metric.samples:
            lines.append(
                f"{metric.name}{_fmt_labels(labels)} {_fmt_value(value)}")
    return "\n".join(lines) + "\n"
