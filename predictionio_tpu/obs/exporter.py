"""Prometheus text exposition (version 0.0.4) over a MetricRegistry.

Pure rendering — no client library, no network. The output contract is
pinned by a round-trip test (tests/test_observability.py parses the
text back and checks it against the registry), so a scraper and this
renderer can't drift apart silently:

- every family gets ``# HELP`` and ``# TYPE`` lines;
- counter sample names end in ``_total``;
- histograms expose cumulative ``_bucket{le=...}`` series ending in
  ``le="+Inf"``, plus ``_sum`` and ``_count``, with
  ``_count == _bucket{le="+Inf"}`` (the torn-snapshot invariant the
  lock-guarded HistogramSnapshot carries through to the wire).
"""

from __future__ import annotations

from typing import Iterable, Mapping

from predictionio_tpu.obs.registry import Metric, MetricRegistry

#: the content type Prometheus scrapers expect for this format
CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


def _escape_help(text: str) -> str:
    return text.replace("\\", "\\\\").replace("\n", "\\n")


def escape_label_value(value: str) -> str:
    """Text-format 0.0.4 label-value escaping: backslash FIRST (or the
    other escapes' backslashes would be doubled), then quote and
    line-feed. The exact inverse lives in obs/aggregate.py
    (``unescape_label_value``) and the pair is pinned by a round-trip
    test with hostile values — replica addresses and SLO names become
    label values on the fleet endpoints."""
    return (value.replace("\\", "\\\\")
            .replace('"', '\\"')
            .replace("\n", "\\n"))


#: backward-compatible internal alias
_escape_label = escape_label_value


def _fmt_value(value: float) -> str:
    if value != value:  # NaN
        return "NaN"
    if value == int(value) and abs(value) < 1e15:
        return str(int(value))
    return repr(float(value))


def _fmt_labels(labels: Mapping[str, str]) -> str:
    if not labels:
        return ""
    inner = ",".join(
        f'{k}="{_escape_label(str(v))}"' for k, v in sorted(labels.items())
    )
    return "{" + inner + "}"


def render_prometheus(registry: MetricRegistry) -> str:
    """Render every family in the registry, sorted by name so
    successive scrapes diff cleanly."""
    return render_metrics(registry.collect())


def render_metrics(metrics: Iterable[Metric]) -> str:
    """Render an explicit family list — the registry-less path the
    fleet aggregation endpoints use (merged worker/replica families
    are plain :class:`Metric` lists, not a live registry)."""
    lines: list[str] = []
    for metric in sorted(metrics, key=lambda m: m.name):
        lines.append(f"# HELP {metric.name} {_escape_help(metric.help)}")
        lines.append(f"# TYPE {metric.name} {metric.kind}")
        if metric.kind == "histogram":
            for labels, snap in metric.histograms:
                base = dict(labels)
                # cumulative[-1] is the +Inf bucket; pairs below cover
                # the finite bounds. A parsed +Inf-only snapshot
                # (aggregate.parse_exposition) carries bounds=(inf,) —
                # skip it or this renders a second, conflicting
                # le="+Inf" line
                for bound, cum in zip(snap.bounds, snap.cumulative):
                    if bound == float("inf"):
                        continue
                    lines.append(
                        f"{metric.name}_bucket"
                        f"{_fmt_labels({**base, 'le': repr(float(bound))})}"
                        f" {cum}")
                lines.append(
                    f"{metric.name}_bucket"
                    f"{_fmt_labels({**base, 'le': '+Inf'})}"
                    f" {snap.cumulative[-1]}")
                lines.append(
                    f"{metric.name}_sum{_fmt_labels(base)}"
                    f" {_fmt_value(snap.sum)}")
                lines.append(
                    f"{metric.name}_count{_fmt_labels(base)}"
                    f" {snap.count}")
            continue
        for labels, value in metric.samples:
            lines.append(
                f"{metric.name}{_fmt_labels(labels)} {_fmt_value(value)}")
    return "\n".join(lines) + "\n"
