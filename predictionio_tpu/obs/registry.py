"""The metric registry: one per server, composed of scrape-time
collectors that ADOPT the counters the repo already keeps — ServingStats
(api/stats.py), IngestStats, the resilience registry
(utils/resilience.py) — instead of duplicating bookkeeping on the hot
path. A collector is any callable returning :class:`Metric` families;
it runs only when ``GET /metrics`` is scraped, so the steady-state cost
of the registry is zero.

Per-server (not process-global) on purpose: ServingStats/IngestStats
are per-service objects and two servers in one process (every e2e test,
the feedback loop's engine+event pair) must not collide in one
namespace. The resilience counters ARE process-global and appear on
every server's registry — by design, since backend health is relevant
wherever it is scraped.
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Any, Callable, Iterable, Mapping, Sequence

from predictionio_tpu.obs.histogram import HistogramSnapshot, LatencyHistogram

#: label sets are plain dicts; values are escaped at render time
Labels = Mapping[str, str]


@dataclasses.dataclass
class Metric:
    """One metric family: name, type, help, and its samples. Counter
    and gauge families carry ``samples``; histogram families carry
    ``histograms`` (label set -> snapshot)."""

    name: str
    kind: str  # "counter" | "gauge" | "histogram"
    help: str
    samples: list[tuple[dict[str, str], float]] = dataclasses.field(
        default_factory=list)
    histograms: list[tuple[dict[str, str], HistogramSnapshot]] = \
        dataclasses.field(default_factory=list)


Collector = Callable[[], Iterable[Metric]]


class MetricRegistry:
    """Scrape-time composition of collectors (module docstring)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._collectors: list[Collector] = []

    def register(self, collector: Collector) -> None:
        with self._lock:
            self._collectors.append(collector)

    def collect(self) -> list[Metric]:
        """All families from all collectors, same-name families merged
        (collectors on one registry share a namespace; a kind mismatch
        on the same name is a programming error worth failing loud on
        the scrape path, where tests live)."""
        with self._lock:
            collectors = list(self._collectors)
        out: list[Metric] = []
        for collector in collectors:
            out.extend(collector())
        return merge_families(out)


def merge_families(metrics: Sequence[Metric]) -> list[Metric]:
    """Merge same-name families into one (duplicate HELP/TYPE blocks
    are invalid exposition), failing loud on a kind mismatch. Input
    families are never mutated — the first occurrence is copied.
    Factored out of :meth:`MetricRegistry.collect` so composite
    collectors (the multi-engine gateway, the per-tenant scale set)
    can merge before registering."""
    by_name: dict[str, Metric] = {}
    for metric in metrics:
        have = by_name.get(metric.name)
        if have is None:
            by_name[metric.name] = dataclasses.replace(
                metric,
                samples=list(metric.samples),
                histograms=list(metric.histograms),
            )
            continue
        if have.kind != metric.kind:
            raise ValueError(
                f"metric {metric.name!r} registered as both "
                f"{have.kind!r} and {metric.kind!r}")
        have.samples.extend(metric.samples)
        have.histograms.extend(metric.histograms)
    return list(by_name.values())


class HistogramFamily:
    """A labeled family of LatencyHistograms with a FIXED label-value
    set built up front — the hot path never allocates a histogram, and
    an unexpected label value falls into ``other`` instead of growing
    the family unboundedly (a scrape-cardinality guard)."""

    FALLBACK = "other"

    def __init__(self, name: str, help: str, label: str,
                 values: Sequence[str], bounds=None):
        self.name = name
        self.help = help
        self.label = label
        values = [*values] + ([self.FALLBACK]
                              if self.FALLBACK not in values else [])
        self._hists: dict[str, LatencyHistogram] = {
            v: (LatencyHistogram(bounds) if bounds is not None
                else LatencyHistogram())
            for v in values
        }

    def observe(self, value: str, seconds: float) -> None:
        hist = self._hists.get(value)
        if hist is None:
            hist = self._hists[self.FALLBACK]
        hist.observe(seconds)

    def get(self, value: str) -> LatencyHistogram:
        return self._hists.get(value) or self._hists[self.FALLBACK]

    def collect(self) -> list[Metric]:
        return [Metric(
            name=self.name, kind="histogram", help=self.help,
            histograms=[
                ({self.label: value}, hist.snapshot())
                for value, hist in self._hists.items()
            ],
        )]


def counts_to_snapshot(counts: Mapping[int, int]) -> HistogramSnapshot:
    """A Prometheus-histogram view of an exact-value count table (the
    batch-size histograms ServingStats/IngestStats keep): bounds are
    the observed sizes, the sum is the total of size×count."""
    sizes = sorted(counts)
    cumulative: list[int] = []
    running = 0
    total = 0.0
    for size in sizes:
        running += counts[size]
        cumulative.append(running)
        total += size * counts[size]
    return HistogramSnapshot(
        bounds=tuple(float(s) for s in sizes) or (1.0,),
        cumulative=tuple(cumulative + [running]) if sizes else (0, 0),
        sum=total,
        count=running,
    )


# ---------------------------------------------------------------------------
# adapters over the existing stats objects (duck-typed: no api/ import,
# keeping obs/ dependency-free below the serving layer)
# ---------------------------------------------------------------------------

def serving_collector(stats: Any) -> Collector:
    """Adopt a :class:`~predictionio_tpu.api.stats.ServingStats`:
    hot-path counters, the dispatched batch-size histogram, and the
    queue-wait / device-dispatch latency histograms the batcher feeds
    (the Clipper-style queue-vs-model attribution)."""

    def collect() -> list[Metric]:
        counts = stats.raw_counts()
        out = [
            Metric(
                name=f"pio_serving_{field}_total", kind="counter",
                help=f"ServingStats counter {field!r} (api/stats.py)",
                samples=[({}, float(value))],
            )
            for field, value in counts.items()
        ]
        out.append(Metric(
            name="pio_serving_batch_size", kind="histogram",
            help="Dispatched (post-dedup) batch sizes",
            histograms=[({}, counts_to_snapshot(stats.batch_histogram()))],
        ))
        ann_hist = stats.ann_histogram()
        if ann_hist:
            # present only once ANN retrieval has served a query — a
            # brute-force deployment's exposition stays unchanged
            out.append(Metric(
                name="pio_serving_ann_shortlist_size", kind="histogram",
                help="ANN shortlist widths exact-rescored per query "
                     "(candidate columns incl. pad; ops/ann)",
                histograms=[({}, counts_to_snapshot(ann_hist))],
            ))
        out.append(Metric(
            name="pio_serving_queue_wait_seconds", kind="histogram",
            help="Per-query wait from enqueue to device dispatch "
                 "(the batcher's queue component of serving latency)",
            histograms=[({}, stats.queue_wait.snapshot())],
        ))
        out.append(Metric(
            name="pio_serving_device_dispatch_seconds", kind="histogram",
            help="Per-batch device dispatch time (query_batch walltime)",
            histograms=[({}, stats.device_time.snapshot())],
        ))
        return out

    return collect


def ingest_collector(stats: Any) -> Collector:
    """Adopt an :class:`~predictionio_tpu.api.stats.IngestStats`:
    batch/event totals, the inserted batch-size histogram, storage
    insert latency, and both rate estimates (windowed + EWMA)."""

    def collect() -> list[Metric]:
        batches, events = stats.totals()
        ewma, windowed, window_s = stats.rates()
        out = [
            Metric(
                name="pio_ingest_batches_total", kind="counter",
                help="Successful storage insert calls (1 event or many)",
                samples=[({}, float(batches))],
            ),
            Metric(
                name="pio_ingest_events_total", kind="counter",
                help="Events successfully inserted",
                samples=[({}, float(events))],
            ),
            Metric(
                name="pio_ingest_batch_size", kind="histogram",
                help="Inserted batch sizes (1 = single-event posts)",
                histograms=[({}, counts_to_snapshot(stats.batch_histogram()))],
            ),
            Metric(
                name="pio_ingest_insert_seconds", kind="histogram",
                help="Storage insert/insert_batch walltime per call",
                histograms=[({}, stats.insert_latency.snapshot())],
            ),
        ]
        if windowed is not None:
            # HELP must be stable scrape-to-scrape metadata — the
            # current window length is itself a sample, not help text
            out.append(Metric(
                name="pio_ingest_events_per_sec_windowed", kind="gauge",
                help="True windowed ingest rate over the trailing "
                     "complete seconds (see pio_ingest_window_seconds)",
                samples=[({}, windowed)],
            ))
            out.append(Metric(
                name="pio_ingest_window_seconds", kind="gauge",
                help="Complete seconds covered by the windowed rate",
                samples=[({}, float(window_s))],
            ))
        if ewma is not None:
            out.append(Metric(
                name="pio_ingest_events_per_sec_ewma", kind="gauge",
                help="EWMA of instantaneous batch rate (observability "
                     "signal; closed-loop caveat in api/stats.py)",
                samples=[({}, ewma)],
            ))
        return out

    return collect


def wal_collector(wal: Any, drainer: Any) -> Collector:
    """Adopt a :class:`~predictionio_tpu.data.wal.WriteAheadLog` and
    its drainer (duck-typed like the other adapters): journal depth and
    disk footprint, the ride-through mode gauge, and the lifetime
    journal/replay/dead-letter counters — the operator's view of the
    ingest durability ladder (docs/operations-resilience.md)."""

    def collect() -> list[Metric]:
        c = wal.counters()
        gauges = (
            ("pio_ingest_wal_depth",
             "Journaled events awaiting replay into storage",
             float(c["depth"])),
            ("pio_ingest_wal_bytes",
             "Pending journal bytes on disk (budget: wal_max_bytes)",
             float(c["bytes"])),
            ("pio_ingest_wal_mode",
             "Durable-ingest mode: 0 idle (direct inserts), 1 draining "
             "(ride-through backlog replaying), 2 backpressure "
             "(journal at disk budget; ingest shedding 503s)",
             float(drainer.mode())),
        )
        counters = (
            ("pio_ingest_wal_journaled_total",
             "Events appended to the write-ahead journal",
             float(c["journaledTotal"])),
            ("pio_ingest_wal_replayed_total",
             "Journaled events successfully replayed into storage",
             float(c["replayedTotal"])),
            ("pio_ingest_wal_dead_letter_total",
             "Records quarantined to the dead-letter series",
             float(c["deadLetterTotal"])),
            ("pio_ingest_wal_corrupt_total",
             "CRC-corrupt journal records skipped at recovery",
             float(c["corruptRecords"])),
        )
        return [
            *(Metric(name=n, kind="gauge", help=h, samples=[({}, v)])
              for n, h, v in gauges),
            *(Metric(name=n, kind="counter", help=h, samples=[({}, v)])
              for n, h, v in counters),
        ]

    return collect


def online_collector(svc: Any) -> Collector:
    """Adopt an :class:`~predictionio_tpu.online.service.OnlineFoldIn`
    (duck-typed like the other adapters): the freshness plane's
    operator view — event→serving lag, fold throughput counters, and
    overlay occupancy (docs/freshness.md has the runbook keyed on
    these families)."""

    def collect() -> list[Metric]:
        m = svc.metrics()
        out = [
            Metric(
                name="pio_online_folded_events_total", kind="counter",
                help="Events folded into the deployed model between "
                     "retrains (online/service.py)",
                samples=[({}, float(m["foldedEventsTotal"]))],
            ),
            Metric(
                name="pio_online_fold_cycles_total", kind="counter",
                help="Completed fold-in cycles (tail→solve→publish)",
                samples=[({}, float(m["foldCycles"]))],
            ),
            Metric(
                name="pio_online_fenced_total", kind="counter",
                help="Deltas discarded by the model-generation fence "
                     "(computed pre-/reload, never applied)",
                samples=[({}, float(m["fenced"]))],
            ),
            Metric(
                name="pio_online_overlay_evictions_total", kind="counter",
                help="Overlay LRU evictions (user falls back to the "
                     "base vector; grow PIO_ONLINE_OVERLAY_MAX if "
                     "this churns)",
                samples=[({}, float(m["evictions"]))],
            ),
            Metric(
                name="pio_online_overlay_size", kind="gauge",
                help="Live overlay entries (folded users + delta items)",
                samples=[({}, float(m["overlaySize"]))],
            ),
            Metric(
                name="pio_online_enabled", kind="gauge",
                help="1 when the fold-in loop is running (0: --online "
                     "requested but the deployment cannot fold in)",
                samples=[({}, 1.0 if m["enabled"] else 0.0)],
            ),
        ]
        if m["lagSeconds"] is not None:
            # absent until the first fold: a gauge of "no data" must
            # not masquerade as zero lag
            out.append(Metric(
                name="pio_online_freshness_lag_seconds", kind="gauge",
                help="Event time → applied-to-serving time of the "
                     "latest fold-in cycle (worst event in the batch)",
                samples=[({}, float(m["lagSeconds"]))],
            ))
        return out

    return collect


#: breaker state encoding for the gauge (strings are not a sample value)
_BREAKER_STATES = {"closed": 0.0, "half-open": 1.0, "half_open": 1.0,
                   "open": 2.0}


def resilience_collector() -> Collector:
    """Adopt the process-global resilience registry
    (utils/resilience.registry_snapshot): per-policy counters, breaker
    state (0 closed / 1 half-open / 2 open) and open transitions."""

    def collect() -> list[Metric]:
        # deferred import: obs/ stays importable below the utils layer
        from predictionio_tpu.utils.resilience import registry_snapshot

        counters: dict[str, Metric] = {}
        state = Metric(
            name="pio_resilience_breaker_state", kind="gauge",
            help="Circuit breaker state: 0 closed, 1 half-open, 2 open")
        opens = Metric(
            name="pio_resilience_breaker_opens_total", kind="counter",
            help="Circuit breaker open transitions")
        for policy, snap in registry_snapshot().items():
            labels = {"policy": policy}
            for field, value in snap.items():
                if field == "breaker":
                    code = _BREAKER_STATES.get(str(value.get("state")))
                    if code is not None:
                        state.samples.append((labels, code))
                    opens.samples.append(
                        (labels, float(value.get("opens", 0))))
                    continue
                if not isinstance(value, (int, float)):
                    continue
                name = f"pio_resilience_{field}_total"
                fam = counters.setdefault(name, Metric(
                    name=name, kind="counter",
                    help=f"Resilience counter {field!r} per policy "
                         "(utils/resilience.py)"))
                fam.samples.append((labels, float(value)))
        out = list(counters.values())
        if state.samples:
            out.append(state)
        if opens.samples:
            out.append(opens)
        return out

    return collect


def server_info_collector(server: str) -> Collector:
    """A constant ``pio_server_info`` gauge carrying the server role
    and framework version — the join key dashboards group scrapes by."""

    def collect() -> list[Metric]:
        from predictionio_tpu import __version__

        return [Metric(
            name="pio_server_info", kind="gauge",
            help="Constant 1; labels carry server role and version",
            samples=[({"server": server, "version": __version__}, 1.0)],
        )]

    return collect
