"""Dapper-style request tracing for the serving, ingest, and training
paths (Sigelman et al., 2010; docs/observability.md).

A :class:`Trace` is one request's (or one train run's) span tree:
flat records of ``(name, parent, start offset, duration)`` appended
under a lock, so spans measured on OTHER threads — the QueryBatcher's
dispatcher recording queue-wait and device time, the deadline pool
running a non-batched predict — land on the same trace safely.

Propagation has two legs:

- **ambient** — a contextvar carries the active trace on the current
  thread; ``span(name)`` opens a child span against it and is a shared
  no-op when no trace is active (one contextvar read, no allocation —
  the near-free disabled path). ``contextvars.copy_context`` captures
  it, so the engine server's deadline-dispatch pool threads
  (``EngineService._query_with_deadline``) inherit the trace for free.
- **explicit** — queue handoffs (QueryBatcher.submit) carry the trace
  object on the queue entry; the dispatcher thread calls
  ``Trace.add_span`` with externally measured intervals.

Traces are sampled into a bounded :class:`TraceLog` ring per server,
served as JSON on ``GET /traces.json``.
"""

from __future__ import annotations

import contextlib
import itertools
import os
import re
import threading
import time
import uuid
from collections import deque
from contextvars import ContextVar
from typing import Any, Iterator, Mapping

#: process-unique trace-id scheme: one random prefix per process plus a
#: sequence — same uniqueness story as uuid4 for correlation purposes,
#: without an os.urandom read (a syscall) on every traced request.
#: itertools.count threads safely under the GIL (a single C call).
_TRACE_ID_PREFIX = uuid.uuid4().hex[:16]
_TRACE_ID_SEQ = itertools.count(1)

#: span ids carry a per-SEGMENT prefix (fleet PR): a trace that crosses
#: the router hop collects spans from several trace segments, and bare
#: per-trace sequences ("s0", "s1") would collide between the router's
#: segment and each replica's when the stitcher joins them — cycling
#: the stitched parent links. The prefix is a per-process random part
#: (unique across the fleet's processes w.h.p., no syscall per span)
#: plus a per-process segment counter (unique across the many servers
#: an e2e test runs in ONE process).
_SPAN_ID_PREFIX = uuid.uuid4().hex[:6]
_SPAN_SEG_SEQ = itertools.count(1)

#: cross-process trace context headers (docs/observability.md): the
#: router forwards the trace id plus the span id of ITS attempt span,
#: so the replica's trace segment nests under the right attempt when
#: the trees are stitched back together.
TRACE_ID_HEADER = "X-PIO-Trace-Id"
PARENT_SPAN_HEADER = "X-PIO-Parent-Span"

#: inbound trace context is adopted only when it looks like ids this
#: framework (or a well-behaved peer) mints — anything else (spaces,
#: quotes, control bytes, unbounded length) is DROPPED and a fresh
#: local trace is started instead: a hostile header must never inject
#: into trace documents nor 500 the request.
_TRACE_CTX_RE = re.compile(r"^[A-Za-z0-9._:-]{1,128}$")


def parse_trace_context(
        headers: Mapping[str, str]) -> tuple[str | None, str | None]:
    """``(trace_id, parent_span_id)`` from inbound headers, each None
    when absent OR malformed/oversized (never raises — the caller
    falls back to fresh local ids). ``headers`` may be an
    ``email.Message`` (case-insensitive get) or a lowercased dict."""

    def clean(name: str) -> str | None:
        raw = headers.get(name) or headers.get(name.lower())
        if raw and _TRACE_CTX_RE.match(raw):
            return raw
        return None

    return clean(TRACE_ID_HEADER), clean(PARENT_SPAN_HEADER)


def tracing_default() -> bool:
    """The process-wide default for servers whose config leaves
    ``tracing`` unset: the ``PIO_TRACE`` env var. Read at CALL time
    (server construction), never frozen at import."""
    return os.environ.get("PIO_TRACE", "").strip().lower() in (
        "1", "true", "yes", "on")


_current: ContextVar["Trace | None"] = ContextVar("pio_trace", default=None)

#: span record slots: (name, parent_span_id, span_id, start_s, dur_s)
_ROOT_PARENT = ""


class Trace:
    """One request's spans. Cheap to create (an id, a list); creation
    is gated behind the server's tracing flag so the disabled path
    never allocates.

    Concurrency contract (why there is NO lock): span records are
    appended with ``list.append`` — atomic under the GIL — and every
    read (``to_dict``/``stage_seconds``) first takes an atomic
    ``list(...)`` copy, so a reader can never see a half-written
    record (tuples are immutable and fully built before the append).
    In the serving wiring the writers never actually overlap anyway:
    the handler thread is blocked on its future while the batcher's
    dispatcher records queue-wait/device spans. A lock here would add
    two GIL handoff points per span on a 24-thread serving path for a
    race that cannot corrupt anything — measured as a real qps cost
    in the tracing-overhead bench phase."""

    __slots__ = ("trace_id", "name", "request_id", "parent_span_id",
                 "service", "tags", "_t0", "_wall_start", "_spans",
                 "_span_seq", "_span_prefix", "_duration", "observer")

    def __init__(self, name: str, request_id: str | None = None,
                 trace_id: str | None = None,
                 parent_span_id: str | None = None,
                 service: str | None = None):
        self.trace_id = (trace_id
                         or f"{_TRACE_ID_PREFIX}{next(_TRACE_ID_SEQ):012x}")
        self.name = name
        self.request_id = request_id
        #: the REMOTE span this whole segment nests under (the router's
        #: attempt span id, forwarded via X-PIO-Parent-Span); None for
        #: a root segment
        self.parent_span_id = parent_span_id
        #: which server recorded this segment ("router"/"engine"/...)
        self.service = service
        self.tags: dict[str, Any] = {}
        self._t0 = time.perf_counter()
        self._wall_start = time.time()
        #: flat records: (name, parent_id, span_id, start_off_s, dur_s)
        self._spans: list[tuple[str, str, str, float, float]] = []
        #: per-trace span-id sequence — ids must survive pre-allocation
        #: (reserve_span_id) and concurrent hedge-thread appends, so a
        #: counter, not len(self._spans) (GIL-atomic single C call)
        self._span_seq = itertools.count()
        self._span_prefix = f"{_SPAN_ID_PREFIX}{next(_SPAN_SEG_SEQ):x}"
        self._duration: float | None = None
        #: optional span-completion callback ``(name, start_off_s,
        #: dur_s)`` — the train profiler samples device memory as each
        #: DASE stage closes (obs/device.TrainProfiler). Exceptions are
        #: swallowed: an observer must never fail the traced work.
        self.observer = None

    # -- span recording ------------------------------------------------------
    def span(self, name: str, parent_id: str = _ROOT_PARENT) -> "_ActiveSpan":
        """Context manager timing one in-thread stage."""
        return _ActiveSpan(self, name, parent_id)

    def reserve_span_id(self) -> str:
        """A span id usable BEFORE its span is recorded — the router
        must put its attempt span's id on the forward headers before
        the attempt runs, then record the span with the reserved id
        once the exchange finishes (``add_span(span_id=...)``)."""
        return f"s{self._span_prefix}.{next(self._span_seq):x}"

    def add_span(self, name: str, start_perf: float, end_perf: float,
                 parent_id: str = _ROOT_PARENT,
                 span_id: str | None = None) -> str:
        """Record an interval measured elsewhere (e.g. the batcher's
        dispatcher thread timing queue-wait with its own clock reads).
        ``start_perf``/``end_perf`` are ``time.perf_counter`` values.
        Returns the new span id (usable as a parent link).

        Span ids are a process prefix + per-trace sequence, not uuids:
        the sequence keeps them unique within the trace, the prefix
        across the processes a stitched fleet trace spans, and the hot
        path never pays an os.urandom read per span."""
        if span_id is None:
            span_id = f"s{self._span_prefix}.{next(self._span_seq):x}"
        self._spans.append(
            (name, parent_id, span_id,
             start_perf - self._t0, max(0.0, end_perf - start_perf)))
        observer = self.observer
        if observer is not None:
            try:
                observer(name, start_perf - self._t0,
                         max(0.0, end_perf - start_perf))
            except Exception:
                pass
        return span_id

    def finish(self, **tags: Any) -> None:
        self._duration = time.perf_counter() - self._t0
        if tags:
            self.tags.update(tags)

    # -- views ---------------------------------------------------------------
    @property
    def start_perf(self) -> float:
        """The ``time.perf_counter`` origin span offsets are relative
        to — lets external clock readings (the recompile sentinel's
        compile events) be binned into this trace's spans."""
        return self._t0

    def spans(self) -> list[tuple[str, str, str, float, float]]:
        """Atomic copy of the raw span records ``(name, parent_id,
        span_id, start_off_s, dur_s)`` (the Trace read contract)."""
        return list(self._spans)

    def stage_seconds(self) -> dict[str, float]:
        """Total seconds per span name, insertion-ordered — the
        ``pio train`` stage breakdown."""
        out: dict[str, float] = {}
        for name, _, _, _, dur in list(self._spans):
            out[name] = out.get(name, 0.0) + dur
        return out

    def to_dict(self) -> dict:
        spans = list(self._spans)
        duration = self._duration
        tags = dict(self.tags)
        doc: dict[str, Any] = {
            "traceId": self.trace_id,
            "name": self.name,
            "startTime": self._wall_start,
            "durationMs": (round(duration * 1e3, 3)
                           if duration is not None else None),
            "spans": [
                {
                    "name": name,
                    "spanId": span_id,
                    **({"parentId": parent} if parent else {}),
                    "startMs": round(start * 1e3, 3),
                    "durationMs": round(dur * 1e3, 3),
                }
                for name, parent, span_id, start, dur in sorted(
                    spans, key=lambda s: s[3])
            ],
        }
        if self.request_id:
            doc["requestId"] = self.request_id
        if self.parent_span_id:
            doc["parentSpanId"] = self.parent_span_id
        if self.service:
            doc["service"] = self.service
        if tags:
            doc["tags"] = tags
        return doc


class _ActiveSpan:
    """The in-thread span context manager (``Trace.span``)."""

    __slots__ = ("_trace", "_name", "_parent", "_start", "span_id")

    def __init__(self, trace: Trace, name: str, parent_id: str):
        self._trace = trace
        self._name = name
        self._parent = parent_id
        self._start = 0.0
        self.span_id = ""

    def __enter__(self) -> "_ActiveSpan":
        self._start = time.perf_counter()
        return self

    def __exit__(self, *exc) -> None:
        self.span_id = self._trace.add_span(
            self._name, self._start, time.perf_counter(), self._parent)


class _NullSpan:
    """Shared no-op for the disabled path: ``span()`` with no active
    trace returns THIS singleton — no allocation, two no-op calls."""

    __slots__ = ()
    span_id = ""

    def __enter__(self) -> "_NullSpan":
        return self

    def __exit__(self, *exc) -> None:
        return None


_NULL_SPAN = _NullSpan()


def start_trace(name: str, request_id: str | None = None,
                trace_id: str | None = None,
                parent_span_id: str | None = None,
                service: str | None = None) -> Trace:
    """A new root trace (or, with ``trace_id``/``parent_span_id`` from
    :func:`parse_trace_context`, a CHILD SEGMENT of a cross-process
    trace). Call sites gate this behind their tracing flag — the flag
    check is the whole cost of the disabled path."""
    return Trace(name, request_id=request_id, trace_id=trace_id,
                 parent_span_id=parent_span_id, service=service)


def active_trace() -> Trace | None:
    return _current.get()


@contextlib.contextmanager
def use_trace(trace: Trace | None) -> Iterator[Trace | None]:
    """Bind ``trace`` as the ambient trace for the current context.
    ``contextvars.copy_context()`` carries the binding onto pool
    threads (the deadline-dispatch path)."""
    token = _current.set(trace)
    try:
        yield trace
    finally:
        _current.reset(token)


def span(name: str):
    """Ambient child span: records against the current trace, or is a
    shared no-op when none is active (one contextvar read, zero
    allocation)."""
    trace = _current.get()
    if trace is None:
        return _NULL_SPAN
    return trace.span(name)


class TraceLog:
    """Bounded ring of recently finished traces (newest first on
    read). Recording is one deque append under the ring's lock —
    serialization to JSON-able dicts happens at READ time, relying on
    the lock-free :class:`Trace` read contract (``to_dict`` copies the
    span list atomically under the GIL; see the Trace docstring for
    why the trace itself carries no lock), so the request hot path
    never pays for a trace nobody is looking at. The ring's one lock
    guards the deque at writers and readers."""

    def __init__(self, maxlen: int = 64):
        self._lock = threading.Lock()
        self._ring: deque[Trace] = deque(maxlen=maxlen)
        self._recorded = 0

    def record(self, trace: Trace) -> None:
        with self._lock:
            self._ring.append(trace)
            self._recorded += 1

    def snapshot(self) -> list[dict]:
        with self._lock:
            traces = list(reversed(self._ring))
        return [t.to_dict() for t in traces]

    def find(self, trace_id: str) -> list[dict]:
        """Every recorded segment of one trace (a hedged request can
        leave several segments with the same id in ONE ring)."""
        with self._lock:
            traces = [t for t in self._ring if t.trace_id == trace_id]
        return [t.to_dict() for t in traces]

    @property
    def recorded(self) -> int:
        with self._lock:
            return self._recorded
