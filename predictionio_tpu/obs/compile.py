"""The recompile sentinel: XLA compilation observability for the
package's jit entry points (docs/observability.md "Device and compiler
observability").

``jax.jit`` retraces and recompiles on every new abstract input
signature. On the training path that is expected cold-start cost; on
the SERVING path a compile firing under a live request is a
multi-second latency cliff hiding inside one response — the exact
failure mode the ``ops/topk.BATCH_WIDTHS`` menu exists to prevent, and
until this module, an invisible one. :func:`instrumented_jit` wraps
``jax.jit`` so every entry point in ``ops/`` reports:

- ``pio_jit_compiles_total{fn}`` — compiles per function;
- ``pio_jit_compile_seconds_total`` — cumulative seconds spent inside
  XLA compilation (trace + lower + backend compile, attributed via
  ``jax.monitoring`` duration events, falling back to call walltime
  when the monitoring hook is unavailable);
- ``pio_serving_recompile_total`` — compiles that fired AFTER the
  serving warmup mark, each with a WARN log and an ``xla_compile``
  span on the ambient trace (a live request paying a compile is an
  incident, not a detail).

Compile DETECTION rides the jitted callable's own cache
(``_cache_size()`` before/after the call — the exact cache ``jax.jit``
consults, so the sentinel can never disagree with the compiler about
what was a miss); where that private hook is absent the wrapper falls
back to its own abstract-signature set. Calls made with tracer
arguments (jit-of-jit inlining) never bump the inner cache and are
never counted.

The recorder itself (:class:`CompileRecorder`) is plain Python with an
injectable clock — unit-testable without jax, and jax is only imported
once :func:`instrumented_jit` actually wraps something, keeping
``obs/`` importable below the compute layer.
"""

from __future__ import annotations

import functools
import logging
import threading
import time
import zlib
from contextvars import ContextVar
from typing import Any, Callable, Iterable

from predictionio_tpu.obs.registry import Metric

logger = logging.getLogger(__name__)

#: signatures are bounded strings: a pathological arg tree (hundreds of
#: bucket slabs) must not turn the recompile table into a memory leak
_SIG_MAX_CHARS = 200

#: bounded compile-event history — enough for any real train run's
#: per-stage binning (a run with thousands of compiles has bigger
#: problems), never an unbounded list on a long-lived server
_MAX_EVENTS = 1024


def _crc(text: str) -> str:
    return f"{zlib.crc32(text.encode('utf-8', 'replace')):08x}"


def describe_abstract_signature(args: tuple, kwargs: dict) -> str:
    """A human-readable abstract signature: arrays as ``dtype[shape]``,
    static scalars by value — the key the recompile table groups by.
    Bounded length (tail replaced by a crc32 so distinct giant
    signatures stay distinct)."""

    def leaf(x: Any) -> str:
        shape = getattr(x, "shape", None)
        dtype = getattr(x, "dtype", None)
        if shape is not None and dtype is not None:
            dims = ",".join(str(d) for d in shape)
            return f"{getattr(dtype, 'name', dtype)}[{dims}]"
        if isinstance(x, (tuple, list)):
            return "(" + ",".join(leaf(e) for e in x) + ")"
        if isinstance(x, (bool, int, float, str, bytes, type(None))):
            return repr(x)
        return type(x).__name__

    parts = [leaf(a) for a in args]
    parts += [f"{k}={leaf(v)}" for k, v in sorted(kwargs.items())]
    sig = "(" + ", ".join(parts) + ")"
    if len(sig) > _SIG_MAX_CHARS:
        sig = sig[: _SIG_MAX_CHARS - 12] + "...#" + _crc(sig)
    return sig


class CompileRecorder:
    """Thread-safe ledger of jit compiles: per-function counts, the
    per-(function, signature) recompile table, cumulative compile
    seconds, and the post-warmup serving-recompile counter.

    ``clock`` is injectable (``time.perf_counter`` in production,
    a ManualClock in tests) and only stamps event times — the compile
    DURATIONS are measured by the caller and passed in."""

    def __init__(self, clock: Any = time.perf_counter):
        self._lock = threading.Lock()
        # either a bare callable (time.perf_counter) or the repo's
        # Clock protocol (utils/resilience: .monotonic()/.sleep())
        self._clock = (clock.monotonic
                       if hasattr(clock, "monotonic") and not callable(clock)
                       else clock)
        self._compiles: dict[str, int] = {}
        self._seconds: dict[str, float] = {}
        #: (fn, signature) -> compile count — the recompile table
        self._signatures: dict[tuple[str, str], int] = {}
        #: (fn, signature) -> calls (tracked only while capture_cost,
        #: for the profiler's executed-FLOPs accounting)
        self._calls: dict[tuple[str, str], int] = {}
        #: (fn, signature) -> per-call FLOPs from cost analysis
        #: (present only when the backend priced the program)
        self._flops: dict[tuple[str, str], float] = {}
        #: signatures whose pricing was ATTEMPTED (capture mode) — a
        #: backend answering "no data" must not be re-asked per call
        self._priced: set[tuple[str, str]] = set()
        #: recent compile events: (fn, sig, start, end, seconds) —
        #: ``start``/``end`` are clock values, used by the train
        #: profiler to bin compile time into DASE stages
        self._events: list[tuple[str, str, float, float, float]] = []
        self._serving_recompiles = 0
        self._warmup_done = False
        #: profile mode: track per-signature calls + capture cost
        #: analysis on compile (the instrumented_jit wrapper reads it)
        self.capture_cost = False

    # -- recording -----------------------------------------------------------
    def record_compile(self, fn: str, signature: str, seconds: float,
                       start: float | None = None,
                       end: float | None = None) -> bool:
        """Count one compile. Returns True when it fired post-warmup
        (a serving recompile) — the caller owns the WARN/span side
        effects so this stays side-effect-free for unit tests except
        for the log line, which lives in :func:`note_serving_recompile`.
        """
        if end is None:
            end = self._clock()
        if start is None:
            start = end - seconds
        with self._lock:
            self._compiles[fn] = self._compiles.get(fn, 0) + 1
            self._seconds[fn] = self._seconds.get(fn, 0.0) + seconds
            key = (fn, signature)
            self._signatures[key] = self._signatures.get(key, 0) + 1
            if len(self._events) < _MAX_EVENTS:
                self._events.append((fn, signature, start, end, seconds))
            post_warmup = self._warmup_done
            if post_warmup:
                self._serving_recompiles += 1
        return post_warmup

    def note_serving_recompile(self, fn: str, signature: str,
                               seconds: float) -> None:
        """The operator-facing side of a post-warmup compile: the WARN
        that turns a silent latency cliff into a searchable incident
        (runbook: docs/observability.md 'The recompile runbook')."""
        logger.warning(
            "serving recompile: %s compiled for new signature %s "
            "(%.3fs) AFTER warmup — a live request paid this compile. "
            "Off-menu batch or top-k width? Check ops/topk "
            "BATCH_WIDTHS/serving_batch and _K_WIDTHS/serving_k "
            "snapping (runbook: docs/observability.md).",
            fn, signature, seconds)

    def record_call(self, fn: str, signature: str) -> None:
        """Per-signature call counting — only while ``capture_cost``
        (the profiler's executed-FLOPs accounting needs calls × FLOPs
        per signature; steady-state serving skips the bookkeeping)."""
        with self._lock:
            key = (fn, signature)
            self._calls[key] = self._calls.get(key, 0) + 1

    def ensure_priced(self, fn: str, signature: str,
                      price: Callable[[], float | None]) -> None:
        """Price one signature's program at most once (capture mode):
        ``price`` runs OUTSIDE the lock (it may lower+compile) and a
        None answer ("backend has no cost data") is remembered so the
        backend is not re-asked on every call — programs compiled
        BEFORE profiling began get priced on their first profiled
        call, so a warm process still reports executed FLOPs."""
        key = (fn, signature)
        with self._lock:
            if key in self._priced:
                return
            self._priced.add(key)
        value = price()
        if value is not None:
            with self._lock:
                self._flops[key] = value

    # -- warmup --------------------------------------------------------------
    def mark_warmup_complete(self) -> None:
        with self._lock:
            self._warmup_done = True

    @property
    def warmup_complete(self) -> bool:
        with self._lock:
            return self._warmup_done

    def reset(self) -> None:
        """Back to the just-constructed state (tests; a fresh bench
        phase). The process-global recorder outlives servers, so e2e
        tests reset instead of re-importing."""
        with self._lock:
            self._compiles.clear()
            self._seconds.clear()
            self._signatures.clear()
            self._calls.clear()
            self._flops.clear()
            self._priced.clear()
            self._events.clear()
            self._serving_recompiles = 0
            self._warmup_done = False
            self.capture_cost = False

    # -- views ---------------------------------------------------------------
    def totals(self) -> tuple[int, float, int]:
        """(compiles, compile_seconds, serving_recompiles)."""
        with self._lock:
            return (sum(self._compiles.values()),
                    sum(self._seconds.values()),
                    self._serving_recompiles)

    def compiles_by_fn(self) -> dict[str, int]:
        with self._lock:
            return dict(self._compiles)

    def seconds_by_fn(self) -> dict[str, float]:
        with self._lock:
            return dict(self._seconds)

    def recompile_table(self) -> list[dict]:
        """One row per (function, signature): the TRAIN_REPORT /
        /stats.json table a menu-drift investigation starts from."""
        with self._lock:
            sig_counts = dict(self._signatures)
            flops = dict(self._flops)
            calls = dict(self._calls)
        return [
            {"fn": fn, "signature": sig, "compiles": n,
             **({"flopsPerCall": flops[(fn, sig)]}
                if (fn, sig) in flops else {}),
             **({"calls": calls[(fn, sig)]} if (fn, sig) in calls else {})}
            for (fn, sig), n in sorted(sig_counts.items())
        ]

    def events(self) -> list[tuple[str, str, float, float, float]]:
        with self._lock:
            return list(self._events)

    def compile_seconds_between(self, start: float, end: float) -> float:
        """Compile seconds whose event MIDPOINT falls in [start, end) —
        the profiler's per-stage binning (clock values from the same
        clock the recorder stamps with)."""
        total = 0.0
        for _, _, s, e, secs in self.events():
            mid = (s + e) / 2.0
            if start <= mid < end:
                total += secs
        return total

    def executed_flops(self) -> float | None:
        """Σ flops(signature) × calls(signature) over every signature
        with cost data — None when NO signature carried any (the
        backend exposed no cost analysis)."""
        with self._lock:
            flops = dict(self._flops)
            calls = dict(self._calls)
        total, have = 0.0, False
        for key, per_call in flops.items():
            if per_call is None:
                continue
            n = calls.get(key, 0)
            if n:
                have = True
                total += per_call * n
        return total if have else None

    def stats_doc(self) -> dict:
        """The /stats.json 'compile' section."""
        compiles, seconds, recompiles = self.totals()
        return {
            "compiles": compiles,
            "compileSeconds": round(seconds, 6),
            "servingRecompiles": recompiles,
            "warmupComplete": self.warmup_complete,
            "byFunction": self.compiles_by_fn(),
        }


#: the process-global recorder every instrumented entry point reports
#: to by default (per-process, like the jit caches it observes)
_GLOBAL_RECORDER = CompileRecorder()


def recorder() -> CompileRecorder:
    return _GLOBAL_RECORDER


# ---------------------------------------------------------------------------
# compile-duration attribution: jax.monitoring fires per-phase duration
# events (/jax/core/compile/...) synchronously on the compiling thread;
# a contextvar scope attributes them to the instrumented call in flight
# ---------------------------------------------------------------------------


class _CompileScope:
    __slots__ = ("seconds", "parent")

    def __init__(self, parent: "_CompileScope | None"):
        self.seconds = 0.0
        self.parent = parent


_SCOPE: ContextVar[_CompileScope | None] = ContextVar(
    "pio_compile_scope", default=None)

_LISTENER_LOCK = threading.Lock()
_LISTENER_STATE = {"registered": False, "available": False}


def _on_duration_event(name: str, seconds: float, **kwargs) -> None:
    # every phase of a compile (jaxpr trace, MLIR lowering, backend
    # compile) counts toward the call in flight; unrelated events
    # (none currently share the prefix) are ignored
    if not name.startswith("/jax/core/compile/") \
            and not name.startswith("/jax/backend_compile"):
        return
    scope = _SCOPE.get()
    if scope is not None:
        scope.seconds += seconds


def _ensure_listener() -> bool:
    """Register the jax.monitoring listener once per process. Returns
    whether duration attribution is available (False -> the wrapper
    falls back to call walltime for compile seconds)."""
    with _LISTENER_LOCK:
        if _LISTENER_STATE["registered"]:
            return _LISTENER_STATE["available"]
        _LISTENER_STATE["registered"] = True
        try:
            import jax.monitoring

            jax.monitoring.register_event_duration_secs_listener(
                _on_duration_event)
            _LISTENER_STATE["available"] = True
        except Exception:  # pragma: no cover - jax drift guard
            _LISTENER_STATE["available"] = False
        return _LISTENER_STATE["available"]


def _cache_size(jitted: Any) -> int | None:
    try:
        return int(jitted._cache_size())
    except Exception:
        return None


def _cost_analysis_flops(jitted: Any, args: tuple,
                         kwargs: dict) -> float | None:
    """Per-call FLOPs from ``Compiled.cost_analysis()`` via the AOT
    path — only under ``capture_cost`` (profiling): the AOT lowering
    re-traces, which is real work we must not add to steady-state
    serving."""
    try:
        compiled = jitted.lower(*args, **kwargs).compile()
        cost = compiled.cost_analysis()
        if isinstance(cost, (list, tuple)):
            cost = cost[0] if cost else {}
        flops = cost.get("flops") if hasattr(cost, "get") else None
        # XLA reports -1 for programs it cannot price — that is "no
        # data", not negative work
        return float(flops) if flops is not None and flops >= 0 else None
    except Exception:
        return None


def instrumented_jit(fn: Callable | None = None, *,
                     jit_name: str | None = None,
                     recorder: CompileRecorder | None = None,
                     **jit_kwargs) -> Callable:
    """``jax.jit`` with the recompile sentinel attached.

    Drop-in at every decoration site::

        @partial(instrumented_jit, static_argnames=("k",))
        def topk_scores(scores, k): ...

    The wrapped callable behaves like the plain jitted function (same
    cache, same donation/static semantics — everything in
    ``jit_kwargs`` passes straight through) and additionally reports
    compiles to ``recorder`` (the process-global one by default). The
    underlying jitted callable is exposed as ``__wrapped_jit__`` and
    its AOT ``lower`` is re-exported, so existing AOT callers keep
    working."""
    if fn is None:
        return functools.partial(instrumented_jit, jit_name=jit_name,
                                 recorder=recorder, **jit_kwargs)

    import jax  # deferred: obs/ stays importable without a device stack

    jitted = jax.jit(fn, **jit_kwargs)
    label = jit_name or getattr(fn, "__name__", repr(fn))
    listener_ok = _ensure_listener()
    bound_recorder = recorder
    #: signatures this wrapper has counted a compile for. With the
    #: cache hook present it guards ATTRIBUTION under concurrency: two
    #: threads in the same function can both observe a cache-size bump
    #: from ONE compile (the on-menu caller would then be blamed for
    #: the off-menu caller's compile, and the recompile counter would
    #: double) — a compile is only recorded by the caller whose OWN
    #: signature is new, checked-and-added under the lock. Without the
    #: hook (jax drift) it is the whole detection mechanism.
    seen_signatures: set[str] = set()
    seen_lock = threading.Lock()

    def _claim(sig: str) -> bool:
        with seen_lock:
            if sig in seen_signatures:
                return False
            seen_signatures.add(sig)
            return True

    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        rec = bound_recorder if bound_recorder is not None \
            else _GLOBAL_RECORDER
        before = _cache_size(jitted)
        scope = _CompileScope(_SCOPE.get())
        token = _SCOPE.set(scope)
        t0 = time.perf_counter()
        try:
            out = jitted(*args, **kwargs)
        finally:
            t1 = time.perf_counter()
            _SCOPE.reset(token)
        after = _cache_size(jitted)
        if before is not None and after is not None:
            sig = None
            compiled = after > before
            if compiled or rec.capture_cost:
                sig = describe_abstract_signature(args, kwargs)
            if compiled:
                # only the caller whose own signature is new records
                # the compile (see seen_signatures note above)
                compiled = _claim(sig)
        else:
            # cache hook unavailable (jax drift): first-seen abstract
            # signature approximates the jit cache key
            sig = describe_abstract_signature(args, kwargs)
            compiled = _claim(sig)
        if compiled:
            # real compile seconds when the monitoring hook attributed
            # them; the call's walltime (compile-dominated on a miss)
            # otherwise
            seconds = scope.seconds if (listener_ok and scope.seconds > 0) \
                else (t1 - t0)
            post_warmup = rec.record_compile(label, sig, seconds,
                                             start=t0, end=t1)
            if post_warmup:
                rec.note_serving_recompile(label, sig, seconds)
                from predictionio_tpu.obs.trace import active_trace

                trace = active_trace()
                if trace is not None:
                    trace.add_span("xla_compile", t0, t1)
        else:
            # a nested scope that did not itself compile folds its
            # attributed seconds into the enclosing call's scope (they
            # belong to the outer compile in flight)
            if scope.parent is not None and scope.seconds > 0:
                scope.parent.seconds += scope.seconds
        if rec.capture_cost and sig is not None:
            # pricing is lazy and once-per-signature: programs compiled
            # BEFORE the profile window still contribute executed FLOPs
            rec.ensure_priced(
                label, sig,
                lambda: _cost_analysis_flops(jitted, args, kwargs))
            rec.record_call(label, sig)
        return out

    wrapper.__wrapped_jit__ = jitted
    wrapper.lower = jitted.lower
    return wrapper


# ---------------------------------------------------------------------------
# metrics
# ---------------------------------------------------------------------------


def compile_metrics_collector(
        rec: CompileRecorder | None = None) -> Callable[[], Iterable[Metric]]:
    """Scrape-time collector for the sentinel's families. The
    aggregate counters are ALWAYS present (zero-valued on an idle
    server) so dashboards and the worker-merge plane see the families
    before the first compile; the per-function family appears with its
    first sample."""

    def collect() -> list[Metric]:
        r = rec if rec is not None else _GLOBAL_RECORDER
        compiles, seconds, recompiles = r.totals()
        out = [
            Metric(
                name="pio_jit_compile_seconds_total", kind="counter",
                help="Cumulative seconds spent in XLA compilation "
                     "across instrumented jit entry points",
                samples=[({}, seconds)],
            ),
            Metric(
                name="pio_serving_recompile_total", kind="counter",
                help="Jit compiles that fired AFTER serving warmup — "
                     "each one was a live request paying a compile "
                     "(runbook: docs/observability.md)",
                samples=[({}, float(recompiles))],
            ),
        ]
        by_fn = r.compiles_by_fn()
        if by_fn:
            out.append(Metric(
                name="pio_jit_compiles_total", kind="counter",
                help="XLA compiles per instrumented jit entry point",
                samples=[({"fn": fn}, float(n))
                         for fn, n in sorted(by_fn.items())],
            ))
        return out

    return collect
