"""The evaluation workflow driver.

Parity: core/src/main/scala/.../workflow/{CreateWorkflow.scala:143-160 +
253-274 (eval branch), CoreWorkflow.scala:103-163 (runEvaluation),
EvaluationWorkflow.scala:32-43, Workflow.scala:82-138}: resolve the
Evaluation + EngineParamsGenerator, record an INIT EvaluationInstance,
run ``engine.batch_eval`` over the grid, score with the evaluator, and
persist the result renders (one-liner / HTML / JSON) on the instance.
"""

from __future__ import annotations

import dataclasses
import logging
from datetime import datetime, timezone
from typing import Any

from predictionio_tpu.controller.evaluation import (
    BaseEvaluatorResult,
    EngineParamsGenerator,
    Evaluation,
)
from predictionio_tpu.storage.base import EvaluationInstance
from predictionio_tpu.storage.registry import Storage
from predictionio_tpu.utils.reflection import resolve_attr
from predictionio_tpu.workflow.context import EngineContext, WorkflowParams

logger = logging.getLogger(__name__)


def _now() -> datetime:
    return datetime.now(timezone.utc)


def resolve_object(spec: str) -> Any:
    """Resolve "pkg.module.Obj" / "pkg.module:Obj" to an instance.
    Classes are instantiated with no args. Parity:
    WorkflowUtils.getEvaluation/getEngineParamsGenerator
    (WorkflowUtils.scala:72-103)."""
    obj = resolve_attr(spec)
    if isinstance(obj, type):
        obj = obj()
    return obj


@dataclasses.dataclass
class EvalOutcome:
    instance_id: str
    status: str
    result: BaseEvaluatorResult


def run_evaluation(
    evaluation: Evaluation | str,
    engine_params_generator: EngineParamsGenerator | str,
    workflow_params: WorkflowParams = WorkflowParams(),
    storage: Storage | None = None,
    ctx: EngineContext | None = None,
) -> EvalOutcome:
    """Evaluate an engine over a params grid and persist the results.

    ``evaluation`` / ``engine_params_generator`` may be instances
    (programmatic use) or spec strings (CLI path).
    """
    if isinstance(evaluation, str):
        evaluation = resolve_object(evaluation)
    if isinstance(engine_params_generator, str):
        engine_params_generator = resolve_object(engine_params_generator)
    if not isinstance(evaluation, Evaluation):
        raise TypeError(f"{evaluation!r} is not an Evaluation")

    storage = storage or Storage.default()
    ctx = ctx or EngineContext(workflow_params=workflow_params, storage=storage)
    instances = storage.get_meta_data_evaluation_instances()
    instance = EvaluationInstance(
        id="",
        status="INIT",
        start_time=_now(),
        completion_time=_now(),
        evaluation_class=f"{type(evaluation).__module__}.{type(evaluation).__qualname__}",
        engine_params_generator_class=(
            f"{type(engine_params_generator).__module__}."
            f"{type(engine_params_generator).__qualname__}"
        ),
        batch=workflow_params.batch,
        env={},
        mesh_conf=dict(workflow_params.mesh_conf),
    )
    instance_id = instances.insert(instance)
    logger.info("evaluation instance %s: INIT", instance_id)

    engine = evaluation.engine
    evaluator = evaluation.evaluator
    params_list = engine_params_generator.engine_params_list

    # EvaluationWorkflow.runEvaluation (EvaluationWorkflow.scala:34-42)
    engine_eval_data_set = engine.batch_eval(ctx, params_list)
    result = evaluator.evaluate(ctx, evaluation, engine_eval_data_set)

    # CoreWorkflow.runEvaluation persistence (CoreWorkflow.scala:137-155);
    # noSave results leave the instance row at INIT, like the reference.
    if result.no_save:
        logger.info("evaluation instance %s: results not saved (noSave)", instance_id)
        return EvalOutcome(instance_id, "NOSAVE", result)
    completed = dataclasses.replace(
        instances.get(instance_id),
        status="EVALCOMPLETED",
        completion_time=_now(),
        evaluator_results=result.to_one_liner(),
        evaluator_results_html=result.to_html(),
        evaluator_results_json=result.to_json(),
    )
    instances.update(completed)
    logger.info("evaluation instance %s: EVALCOMPLETED — %s",
                instance_id, result.to_one_liner())
    return EvalOutcome(instance_id, "EVALCOMPLETED", result)
