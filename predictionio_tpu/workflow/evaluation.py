"""The evaluation workflow driver.

Parity: core/src/main/scala/.../workflow/{CreateWorkflow.scala:143-160 +
253-274 (eval branch), CoreWorkflow.scala:103-163 (runEvaluation),
EvaluationWorkflow.scala:32-43, Workflow.scala:82-138}: resolve the
Evaluation + EngineParamsGenerator, record an INIT EvaluationInstance,
run ``engine.batch_eval`` over the grid, score with the evaluator, and
persist the result renders (one-liner / HTML / JSON) on the instance.

Beyond parity:

- a raising ``batch_eval``/evaluator persists a **FAILED** instance
  (the reference — and the seed — stranded the row at INIT forever,
  so ``pio status`` could not tell a crash from a run in flight);
- ``parallel > 1`` (``pio eval --parallel N`` / ``PIO_EVAL_PARALLEL``)
  fans grid points over short-lived eval worker processes
  (experiment/grid.py) with per-point fault isolation, streaming each
  point into the instance row as it lands — the instance is readable
  MID-RUN (status ``EVALUATING``, partial grid in
  ``evaluator_results_json``).
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
from datetime import datetime, timezone
from typing import Any

from predictionio_tpu.controller.evaluation import (
    BaseEvaluatorResult,
    EngineParamsGenerator,
    Evaluation,
    MetricEvaluator,
)
from predictionio_tpu.storage.base import EvaluationInstance
from predictionio_tpu.storage.registry import Storage
from predictionio_tpu.utils.reflection import resolve_attr
from predictionio_tpu.workflow.context import EngineContext, WorkflowParams

logger = logging.getLogger(__name__)


def _now() -> datetime:
    return datetime.now(timezone.utc)


def resolve_object(spec: str) -> Any:
    """Resolve "pkg.module.Obj" / "pkg.module:Obj" to an instance.
    Classes are instantiated with no args. Parity:
    WorkflowUtils.getEvaluation/getEngineParamsGenerator
    (WorkflowUtils.scala:72-103)."""
    obj = resolve_attr(spec)
    if isinstance(obj, type):
        obj = obj()
    return obj


def resolve_parallel(parallel: int | None) -> int:
    """``--parallel`` beats ``PIO_EVAL_PARALLEL`` beats 1 (the flag
    pattern every serving knob follows)."""
    if parallel is not None:
        return max(1, int(parallel))
    try:
        return max(1, int(os.environ.get("PIO_EVAL_PARALLEL", "1")))
    except ValueError:
        return 1


@dataclasses.dataclass
class EvalOutcome:
    instance_id: str
    status: str
    result: BaseEvaluatorResult


def run_evaluation(
    evaluation: Evaluation | str,
    engine_params_generator: EngineParamsGenerator | str,
    workflow_params: WorkflowParams = WorkflowParams(),
    storage: Storage | None = None,
    ctx: EngineContext | None = None,
    parallel: int | None = None,
) -> EvalOutcome:
    """Evaluate an engine over a params grid and persist the results.

    ``evaluation`` / ``engine_params_generator`` may be instances
    (programmatic use) or spec strings (CLI path). ``parallel`` > 1
    fans grid points over that many eval worker processes (None reads
    ``PIO_EVAL_PARALLEL``; the default stays sequential, which also
    preserves FastEvalEngine pipeline-prefix sharing across points).
    """
    if isinstance(evaluation, str):
        evaluation = resolve_object(evaluation)
    if isinstance(engine_params_generator, str):
        engine_params_generator = resolve_object(engine_params_generator)
    if not isinstance(evaluation, Evaluation):
        raise TypeError(f"{evaluation!r} is not an Evaluation")

    storage = storage or Storage.default()
    ctx = ctx or EngineContext(workflow_params=workflow_params, storage=storage)
    instances = storage.get_meta_data_evaluation_instances()
    instance = EvaluationInstance(
        id="",
        status="INIT",
        start_time=_now(),
        completion_time=_now(),
        evaluation_class=f"{type(evaluation).__module__}.{type(evaluation).__qualname__}",
        engine_params_generator_class=(
            f"{type(engine_params_generator).__module__}."
            f"{type(engine_params_generator).__qualname__}"
        ),
        batch=workflow_params.batch,
        env={},
        mesh_conf=dict(workflow_params.mesh_conf),
    )
    instance_id = instances.insert(instance)
    logger.info("evaluation instance %s: INIT", instance_id)

    engine = evaluation.engine
    evaluator = evaluation.evaluator
    params_list = engine_params_generator.engine_params_list
    parallel = resolve_parallel(parallel)

    try:
        if parallel > 1 and isinstance(evaluator, MetricEvaluator):
            result = _run_parallel(evaluation, evaluator, params_list,
                                   ctx, parallel, instances, instance_id)
        else:
            if parallel > 1:
                logger.warning(
                    "--parallel %d ignored: %s is not a MetricEvaluator "
                    "(children ship plain scores, not EvalDataSets) — "
                    "falling back to the sequential path",
                    parallel, type(evaluator).__name__)
            # EvaluationWorkflow.runEvaluation
            # (EvaluationWorkflow.scala:34-42)
            engine_eval_data_set = engine.batch_eval(ctx, params_list)
            result = evaluator.evaluate(ctx, evaluation, engine_eval_data_set)
            from predictionio_tpu.experiment.grid import (
                count_sequential_points,
            )
            count_sequential_points(len(params_list))
    except Exception as exc:
        # the seed stranded a crashed run at INIT forever; persist the
        # failure so `pio status` (and `pio experiment`) can tell a
        # crash from a run in flight — then fail the caller honestly
        _persist_failed(instances, instance_id, exc)
        raise

    # CoreWorkflow.runEvaluation persistence (CoreWorkflow.scala:137-155);
    # noSave results leave the instance row at INIT, like the reference.
    if result.no_save:
        logger.info("evaluation instance %s: results not saved (noSave)", instance_id)
        return EvalOutcome(instance_id, "NOSAVE", result)
    completed = dataclasses.replace(
        instances.get(instance_id),
        status="EVALCOMPLETED",
        completion_time=_now(),
        evaluator_results=result.to_one_liner(),
        evaluator_results_html=result.to_html(),
        evaluator_results_json=result.to_json(),
    )
    instances.update(completed)
    logger.info("evaluation instance %s: EVALCOMPLETED — %s",
                instance_id, result.to_one_liner())
    return EvalOutcome(instance_id, "EVALCOMPLETED", result)


def _run_parallel(evaluation, evaluator, params_list, ctx, parallel,
                  instances, instance_id):
    """The parallel grid: stream each finished point into the instance
    row (status EVALUATING — partial grid visible mid-run), then
    reassemble the full MetricEvaluatorResult. Imported lazily so the
    sequential path never pays for multiprocessing plumbing."""
    from predictionio_tpu.experiment.grid import (
        partial_grid_doc,
        result_from_points,
        run_parallel_grid,
    )

    total = len(params_list)
    seen = []

    def _stream(point, done, _total):
        seen.append(point)
        row = dataclasses.replace(
            instances.get(instance_id),
            status="EVALUATING",
            evaluator_results_json=partial_grid_doc(seen, total))
        instances.update(row)

    logger.info("evaluation instance %s: EVALUATING "
                "(%d grid points over %d eval workers)",
                instance_id, total, parallel)
    points = run_parallel_grid(evaluation, evaluator, params_list, ctx,
                               parallel, on_point=_stream)
    result = result_from_points(evaluator, params_list, points,
                                evaluation=evaluation)
    # the final JSON keeps the MetricEvaluatorResult contract
    # (metricHeader/bestIdx/engineParamsScores — what `pio experiment`
    # consumes) and adds the per-point status ledger
    doc = json.loads(result.to_json())
    doc["points"] = [p.to_doc() for p in points]
    result.to_json = lambda: json.dumps(doc, indent=2)  # type: ignore[method-assign]
    return result


def _persist_failed(instances, instance_id: str, exc: Exception) -> None:
    try:
        failed = dataclasses.replace(
            instances.get(instance_id),
            status="FAILED",
            completion_time=_now(),
            evaluator_results=f"{type(exc).__name__}: {exc}",
        )
        instances.update(failed)
        logger.error("evaluation instance %s: FAILED — %s",
                     instance_id, exc)
    except Exception:  # pragma: no cover - metadata store itself down
        logger.exception("could not persist FAILED status for "
                         "evaluation instance %s", instance_id)
