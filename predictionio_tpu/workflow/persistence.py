"""Model persistence: serializing per-algorithm models into the MODELDATA
repository.

Parity: CoreWorkflow.runTrain's Kryo-serialize-and-insert
(reference: core/.../workflow/CoreWorkflow.scala:58-65) and the three
persistence modes of BaseAlgorithm.makePersistentModel
(core/.../core/BaseAlgorithm.scala:111-126; SURVEY.md §5 checkpoint/resume):

1. automatic  — picklable host model -> pickled blob (Kryo equivalent);
2. manifest   — PersistentModelManifest stored, algorithm owns the real
   artifact (e.g. orbax sharded checkpoint);
3. none       — None stored -> retrain on deploy.

numpy/jax arrays inside models are converted to numpy before pickling so
blobs are backend-portable.

Integrity (beyond reference; the fleet tier's "trustworthy generations"
contract, docs/fleet.md): every blob carries a magic header and a
SHA-256 digest of its payload. :func:`deserialize_models` verifies the
digest before unpickling — a bit-flipped or truncated blob raises
:class:`ModelIntegrityError` at load instead of deploying garbage (or
feeding corrupted bytes to pickle), and the engine server's ``/reload``
keeps serving the last-known-good model. Pre-checksum blobs (no magic)
still load, so existing stored instances keep working.
"""

from __future__ import annotations

import dataclasses
import hashlib
import io
import pickle
from typing import Any, Sequence

from predictionio_tpu.controller.base import PersistentModelManifest
from predictionio_tpu.storage.base import Model
from predictionio_tpu.storage.registry import Storage

_FORMAT_VERSION = 1

#: blob header: magic + format byte, then a 32-byte SHA-256 of the
#: pickled payload, then the payload
_MAGIC = b"PIOM\x01"
_DIGEST_LEN = hashlib.sha256().digest_size


class ModelIntegrityError(ValueError):
    """The persisted model blob fails its checksum (bit flip, torn or
    truncated write). The deploy path must fail loudly — never
    unpickle, never serve — and a /reload keeps last-known-good."""


@dataclasses.dataclass(frozen=True)
class _Envelope:
    """What actually lands in the Models repo: per-algo entries tagged by
    persistence mode."""

    version: int
    entries: tuple[tuple[str, Any], ...]  # (mode, payload); mode: auto|manifest|none


def _to_host(obj: Any) -> Any:
    """Pull any jax arrays to numpy for portable pickling.

    Walks generic containers AND plain dataclasses — dataclass models are
    the framework convention but are pytree *leaves* to jax, so
    tree_map/device_get alone would skip the arrays inside them.
    """
    try:
        import jax
        import numpy as _np
    except ImportError:  # pure-host install
        return obj

    def walk(x: Any) -> Any:
        if isinstance(x, jax.Array):
            return _np.asarray(jax.device_get(x))
        if dataclasses.is_dataclass(x) and not isinstance(x, type):
            changes = {
                f.name: walk(getattr(x, f.name)) for f in dataclasses.fields(x)
            }
            return dataclasses.replace(x, **changes)
        if isinstance(x, dict):
            return {k: walk(v) for k, v in x.items()}
        if isinstance(x, tuple):
            out = [walk(v) for v in x]
            # preserve NamedTuple subclasses (common jax model pattern)
            if hasattr(x, "_fields"):
                return type(x)(*out)
            return tuple(out)
        if isinstance(x, list):
            return [walk(v) for v in x]
        return x

    return walk(obj)


def serialize_models(persisted: Sequence[Any]) -> bytes:
    entries: list[tuple[str, Any]] = []
    for p in persisted:
        if p is None:
            entries.append(("none", None))
        elif isinstance(p, PersistentModelManifest):
            entries.append(("manifest", p))
        else:
            entries.append(("auto", _to_host(p)))
    buf = io.BytesIO()
    pickle.dump(_Envelope(_FORMAT_VERSION, tuple(entries)), buf, protocol=pickle.HIGHEST_PROTOCOL)
    payload = buf.getvalue()
    return _MAGIC + hashlib.sha256(payload).digest() + payload


def deserialize_models(blob: bytes) -> list[Any]:
    """Returns the per-algo persisted list (model | manifest | None) for
    Engine.prepare_deploy. Verifies the blob's content digest FIRST
    (module docstring) — corruption raises :class:`ModelIntegrityError`
    before any byte reaches pickle."""
    if blob.startswith(_MAGIC):
        header_len = len(_MAGIC) + _DIGEST_LEN
        if len(blob) < header_len:
            raise ModelIntegrityError(
                "model blob is truncated inside its integrity header")
        digest = blob[len(_MAGIC):header_len]
        payload = blob[header_len:]
        if hashlib.sha256(payload).digest() != digest:
            raise ModelIntegrityError(
                "model blob fails its SHA-256 checksum — bit flip or torn "
                "write; refusing to deserialize a corrupted model")
    else:
        payload = blob  # pre-checksum blob (legacy stored instance)
    env: _Envelope = pickle.loads(payload)
    if env.version != _FORMAT_VERSION:
        raise ValueError(f"unsupported model blob version {env.version}")
    return [payload for _, payload in env.entries]


def save_models(storage: Storage, instance_id: str, persisted: Sequence[Any]) -> None:
    storage.get_model_data_models().insert(
        Model(id=instance_id, models=serialize_models(persisted))
    )


def load_models(storage: Storage, instance_id: str) -> list[Any]:
    model = storage.get_model_data_models().get(instance_id)
    if model is None:
        raise KeyError(f"no persisted models for engine instance {instance_id}")
    return deserialize_models(model.models)
