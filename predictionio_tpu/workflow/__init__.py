"""Workflow layer: train/eval drivers, engine.json parsing, model
persistence, deployment server.

Reference: core/src/main/scala/.../workflow/.
"""

from predictionio_tpu.workflow.context import EngineContext, WorkflowParams

__all__ = ["EngineContext", "WorkflowParams"]
