"""EngineContext — the compute-substrate handle threaded through DASE.

The reference threads a SparkContext through every DASE hook
(reference: core/.../workflow/WorkflowContext.scala:28-46 creates it; every
Base* signature carries ``sc``). The TPU-native replacement carries:

- the `jax.sharding.Mesh` over the chip topology (ICI collectives replace
  Spark shuffle — SURVEY.md §2.6 TPU-equivalent note),
- a PRNG key chain,
- the storage registry (PEventStore role),
- workflow params (batch label, sanity-check/stop-after flags —
  WorkflowParams.scala:30-45).

Mesh axes convention: ``("data", "model")`` — data parallelism over the
first axis, model/embedding sharding over the second; algorithms reshape
as needed via ``with_axes``. Multi-host: `jax.distributed.initialize` is
invoked by the CLI launcher when PIO_NUM_HOSTS>1; in-process code only
ever sees the global mesh.
"""

from __future__ import annotations

import dataclasses
import logging
import math
from typing import Any, Mapping, Sequence

logger = logging.getLogger(__name__)


@dataclasses.dataclass(frozen=True)
class WorkflowParams:
    """Parity: WorkflowParams (WorkflowParams.scala:30-45); sparkEnv is
    replaced by mesh_conf (axis spec)."""

    batch: str = ""
    verbose: int = 2
    save_model: bool = True
    skip_sanity_check: bool = False
    stop_after_read: bool = False
    stop_after_prepare: bool = False
    mesh_conf: Mapping[str, Any] = dataclasses.field(default_factory=dict)
    #: set by run_train before the pipeline runs, so persistence hooks can
    #: key custom checkpoints by training run (the reference passed
    #: engineInstanceId into makeSerializableModels/PersistentModel.save)
    engine_instance_id: str = ""
    #: which algorithm-list slot is being persisted — set by Engine.train
    #: around make_persistent_model so multi-algorithm engines don't
    #: collide on checkpoint locations
    algorithm_slot: int = 0


def _factor_mesh(n: int) -> tuple[int, int]:
    """Default 2D factorization of n devices: (data, model) with the model
    axis the largest power-of-two <= sqrt(n) dividing n."""
    best = 1
    for m in range(1, int(math.isqrt(n)) + 1):
        if n % m == 0:
            best = m
    return (n // best, best)


class EngineContext:
    """One per workflow run; cheap to construct lazily in tests."""

    def __init__(
        self,
        workflow_params: WorkflowParams = WorkflowParams(),
        storage: Any = None,
        mesh: Any = None,
        seed: int = 0,
        devices: Sequence[Any] | None = None,
    ):
        self.workflow_params = workflow_params
        self._storage = storage
        self._mesh = mesh
        self._seed = seed
        self._devices = devices
        self._rng_count = 0

    # -- storage ------------------------------------------------------------
    @property
    def storage(self):
        if self._storage is None:
            from predictionio_tpu.storage.registry import Storage

            self._storage = Storage.default()
        return self._storage

    # -- mesh ---------------------------------------------------------------
    @property
    def mesh(self):
        """The device mesh, built on first use from mesh_conf:
        {"axes": {"data": 4, "model": 2}} or auto-factored from the
        available devices."""
        if self._mesh is None:
            import jax
            import numpy as np
            from jax.sharding import Mesh

            devices = list(self._devices) if self._devices else jax.devices()
            axes_conf = self.workflow_params.mesh_conf.get("axes")
            if axes_conf:
                names = tuple(axes_conf.keys())
                sizes = tuple(int(v) for v in axes_conf.values())
            else:
                names = ("data", "model")
                sizes = _factor_mesh(len(devices))
            total = math.prod(sizes)
            if total > len(devices):
                raise ValueError(
                    f"mesh axes {dict(zip(names, sizes))} need {total} devices, "
                    f"have {len(devices)}"
                )
            mesh_devices = np.asarray(devices[:total]).reshape(sizes)
            self._mesh = Mesh(mesh_devices, names)
            logger.info("created mesh %s over %d %s device(s)",
                        dict(zip(names, sizes)), total, devices[0].platform)
        return self._mesh

    @property
    def mesh_if_parallel(self):
        """The mesh when it spans >1 device, else None — single-chip runs
        should take the plain jit path (same math, no partitioner
        overhead; algorithms pass this to their kernels)."""
        import jax

        devices = list(self._devices) if self._devices else jax.devices()
        if len(devices) <= 1:
            return None
        mesh = self.mesh
        if math.prod(mesh.devices.shape) <= 1:  # explicit 1-device axis spec
            return None
        return mesh

    def with_axes(self, **axes: int) -> "EngineContext":
        """A context whose mesh uses an explicit axis spec."""
        wp = dataclasses.replace(
            self.workflow_params, mesh_conf={**self.workflow_params.mesh_conf, "axes": axes}
        )
        return EngineContext(wp, self._storage, None, self._seed, self._devices)

    def with_workflow_params(self, **changes: Any) -> "EngineContext":
        """A context sharing this one's storage/mesh/rng config but with
        updated WorkflowParams fields (the sanctioned way to derive a
        context — keeps internals private to this class)."""
        wp = dataclasses.replace(self.workflow_params, **changes)
        return EngineContext(wp, self._storage, self._mesh, self._seed, self._devices)

    @property
    def num_devices(self) -> int:
        return math.prod(self.mesh.devices.shape)

    # -- rng ----------------------------------------------------------------
    def next_rng_key(self):
        """A fresh PRNG key per call (fold_in chain from the seed)."""
        import jax

        self._rng_count += 1
        return jax.random.fold_in(jax.random.PRNGKey(self._seed), self._rng_count)

    # -- event store facade (PEventStore role, data/.../store) --------------
    def event_store(self) -> "EventStore":
        from predictionio_tpu.data.store import EventStore

        return EventStore(self.storage)
