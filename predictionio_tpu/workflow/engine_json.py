"""engine.json variant loading.

Parity: CreateWorkflow's variant JSON reading (CreateWorkflow.scala:180-196)
and WorkflowUtils.extractSparkConf (:317-336) — the ``sparkConf`` subtree
becomes ``meshConf`` ({"axes": {"data": N, "model": M}} etc.).
"""

from __future__ import annotations

import json
import os
from typing import Any


def load_variant(path: str = "engine.json") -> dict[str, Any]:
    if not os.path.exists(path):
        raise FileNotFoundError(
            f"{path} not found. An engine project needs an engine.json "
            "(engineFactory + component params)."
        )
    with open(path) as f:
        variant = json.load(f)
    if "engineFactory" not in variant:
        raise ValueError(f"{path} is missing required key 'engineFactory'")
    return variant


def mesh_conf_from_variant(variant: dict[str, Any]) -> dict[str, Any]:
    """Accept the native "meshConf" key; a legacy "sparkConf" subtree from
    a ported reference engine.json is ignored with a logged note."""
    import logging

    if "sparkConf" in variant and "meshConf" not in variant:
        logging.getLogger(__name__).warning(
            "engine.json has a 'sparkConf' subtree, which this framework does "
            "not use; configure the device mesh via 'meshConf' "
            "(e.g. {\"axes\": {\"data\": 4, \"model\": 2}})"
        )
    return dict(variant.get("meshConf", {}))
