"""engine.json variant loading.

Parity: CreateWorkflow's variant JSON reading (CreateWorkflow.scala:180-196)
and WorkflowUtils.extractSparkConf (:317-336) — the ``sparkConf`` subtree
becomes ``meshConf`` ({"axes": {"data": N, "model": M}} etc.).
"""

from __future__ import annotations

import json
import os
from typing import Any


def load_variant(path: str = "engine.json") -> dict[str, Any]:
    if not os.path.exists(path):
        raise FileNotFoundError(
            f"{path} not found. An engine project needs an engine.json "
            "(engineFactory + component params)."
        )
    with open(path) as f:
        variant = json.load(f)
    if "engineFactory" not in variant:
        raise ValueError(f"{path} is missing required key 'engineFactory'")
    return variant


def mesh_conf_from_variant(variant: dict[str, Any]) -> dict[str, Any]:
    """Accept either the native "meshConf" key or a legacy "sparkConf"
    subtree (ignored with a note) for drop-in engine.json compatibility."""
    return dict(variant.get("meshConf", {}))
