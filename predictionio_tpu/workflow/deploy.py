"""Deployment: load a trained engine instance and answer queries.

Parity: core/src/main/scala/.../workflow/CreateServer.scala —
``createServerActorWithEngine`` (:186-244): look up the EngineInstance
(latest completed if unspecified, commands/Engine.scala:224-228),
deserialize the persisted models, run ``Engine.prepare_deploy`` (retrain
Unit models / reload manifests, Engine.scala:199-257), instantiate the
algorithms and serving from the stored params, and expose the steady-state
query path (supplement → per-algo predict → serve, CreateServer.scala:
470-500).

TPU-first: models stay resident (host or HBM) between requests, and the
query path re-uses each algorithm's jitted predict functions — there is
no per-query compilation or device handoff beyond the query tensors.
The micro-batching machinery lives in :mod:`predictionio_tpu.serving`
(batcher + adaptive policy + result cache); ``QueryBatcher`` and
``QueryDeadlineExceeded`` are re-exported here for compatibility.
"""

from __future__ import annotations

import dataclasses
import logging
import os
import threading
import time
from typing import Any, Callable, Sequence

from predictionio_tpu.controller.engine import Engine, resolve_engine_factory
from predictionio_tpu.serving.batcher import (  # noqa: F401  (re-export)
    QueryBatcher,
    QueryDeadlineExceeded,
)
from predictionio_tpu.storage.base import EngineInstance
from predictionio_tpu.storage.registry import Storage
from predictionio_tpu.workflow.context import EngineContext, WorkflowParams
from predictionio_tpu.workflow.persistence import load_models

logger = logging.getLogger(__name__)


def _env_field(key: str, default: Any, cast: Callable[[str], Any]):
    """A frozen-dataclass default overridable via ``PIO_SERVING_<KEY>``
    — the serving-plane analogue of the ``PIO_RESILIENCE_*`` fallbacks
    (utils/resilience._prop), so a deployment tunes the batcher/cache
    without a code change. A malformed value falls back to the coded
    default rather than killing the server at config time (shared
    implementation in utils/envcfg.py)."""
    from predictionio_tpu.utils.envcfg import env_field

    return env_field("PIO_SERVING_", key, default, cast)


def _cast_bool(raw: str) -> bool:
    return raw.strip().lower() in ("1", "true", "yes", "on")


def _online_field(key: str, default: Any, cast: Callable[[str], Any]):
    """``PIO_ONLINE_<KEY>``-overridable defaults for the freshness
    plane's knobs (docs/freshness.md), same degrade-don't-die contract
    as the serving fields."""
    from predictionio_tpu.utils.envcfg import env_field

    return env_field("PIO_ONLINE_", key, default, cast)


def _cast_policy(raw: str) -> str:
    # validated HERE so a typo'd env value degrades to the default with
    # a warning (the _env_field contract) instead of killing the server
    # when make_batch_policy() rejects it at EngineService construction
    value = raw.strip().lower()
    if value not in ("adaptive", "fixed"):
        raise ValueError(value)
    return value


def _cast_retrieval(raw: str) -> str:
    # same degrade-don't-die contract as _cast_policy: a typo'd
    # PIO_SERVING_RETRIEVAL serves brute force with a warning
    value = raw.strip().lower()
    if value not in ("brute", "ann"):
        raise ValueError(value)
    return value


@dataclasses.dataclass(frozen=True)
class ServerConfig:
    """Parity: ServerConfig (CreateServer.scala:74-103)."""

    ip: str = "0.0.0.0"
    port: int = 8000
    engine_instance_id: str | None = None
    #: defaults match run_train's engine.json fallbacks (train.py:93-95)
    engine_id: str | None = None
    engine_version: str | None = None
    engine_variant: str | None = None
    #: feedback loop: POST prediction events back to the event server
    feedback: bool = False
    event_server_ip: str = "0.0.0.0"
    event_server_port: int = 7070
    access_key: str = ""
    #: socket timeout for the fire-and-forget feedback POST — bounds how
    #: long a stalled event server can pin a pio-feedback thread (the
    #: untimed-blocking-io lint invariant; threads are daemonic but each
    #: stuck one leaks a socket until the peer answers)
    feedback_timeout_s: float = 10.0
    #: when set, /stop and /reload require ?accessKey=<server_key>
    #: (common KeyAuthentication, KeyAuthentication.scala:33-60)
    server_key: str | None = None
    #: TPU-first micro-batching (beyond reference): coalesce concurrent
    #: queries into ONE device dispatch through the algorithms'
    #: batch_predict hook. On a remote-attached device a dispatch costs
    #: a full RTT (~100ms on the axon tunnel), so N concurrent clients
    #: served individually serialize at ~1/RTT qps while the same model
    #: scores thousands of queries per dispatch batched. Opt-in; with
    #: the adaptive policy a lone query pays (near) zero added latency.
    batching: bool = _env_field("BATCHING", False, _cast_bool)
    #: "adaptive" (EWMA-driven wait, serving/batch_policy.py) or
    #: "fixed" (the legacy constant window)
    batch_policy: str = _env_field("BATCH_POLICY", "adaptive", _cast_policy)
    batch_max: int = _env_field("BATCH_MAX", 64, int)
    #: for "adaptive": the CAP on the coalescing wait; for "fixed": the
    #: constant window
    batch_wait_ms: float = _env_field("BATCH_WAIT_MS", 5.0, float)
    #: result cache (serving/result_cache.py): LRU+TTL over canonical
    #: query JSON, invalidated on /reload. Off by default — only enable
    #: for engines whose predictions depend on nothing but the query
    #: and the deployed model (a custom Serving reading live state per
    #: request would serve stale results from a cache)
    cache_enabled: bool = _env_field("CACHE_ENABLED", False, _cast_bool)
    cache_max_entries: int = _env_field("CACHE_MAX_ENTRIES", 4096, int)
    cache_ttl_s: float = _env_field("CACHE_TTL_S", 30.0, float)
    #: shared-memory result cache (`pio deploy --shm-cache`;
    #: serving/shm_cache, docs/serving-performance.md "Shared-memory
    #: serving plane"): back the result cache with ONE
    #: multiprocessing.shared_memory segment all pool workers attach —
    #: a key warmed by any worker is hot for every sibling, and a
    #: /reload re-warms once instead of N times. Requires
    #: ``cache_enabled``; platforms without POSIX shm warn and fall
    #: back to the private LRU (degrade-don't-die)
    shm_cache: bool = _env_field("SHM", False, _cast_bool)
    #: slot count of the direct-mapped table (also the entry cap the
    #: snapshot reports); colliding keys overwrite — it's a cache
    shm_slots: int = _env_field("SHM_SLOTS", 4096, int)
    #: bytes per slot: header + canonical key + pickled prediction;
    #: oversized entries simply stay uncached
    shm_slot_bytes: int = _env_field("SHM_SLOT_BYTES", 4096, int)
    #: segment name shared by the pool (the deploy CLI generates and
    #: owns one per pool); empty = a private per-process segment
    shm_segment: str = _env_field("SHM_SEGMENT", "", str)
    #: graceful degradation (beyond reference): per-request time budget
    #: for /queries.json. Propagated as the ambient resilience deadline
    #: (utils/resilience.deadline_scope — storage retries stop sleeping
    #: when the budget can't cover them) and into QueryBatcher.submit.
    #: Clients may lower it per request with an X-PIO-Deadline-Ms
    #: header; exhaustion maps to 503 + Retry-After, not a hung socket.
    #: 0 disables (legacy behavior: 300s batcher wait, no deadline).
    request_deadline_ms: float = _env_field("REQUEST_DEADLINE_MS", 0.0, float)
    #: sublinear retrieval (ops/ann; docs/serving-performance.md):
    #: "brute" scores the full item table per query, "ann" probes the
    #: IVF-flat MIPS index persisted beside the model (built at deploy
    #: when missing) and exact-rescores the shortlist — O(sqrt(catalog))
    #: instead of O(catalog) per query, recall measured by the quality
    #: harness. Applies to every model exposing ``configure_retrieval``
    #: (the ALS family behind the recommendation / similarproduct /
    #: ecommerce templates); other models ignore it.
    retrieval: str = _env_field("RETRIEVAL", "brute", _cast_retrieval)
    #: IVF cell count for a deploy-time index build (0 = auto
    #: ~4*sqrt(n)); persisted indexes keep their build-time geometry
    ann_nlist: int = _env_field("ANN_NLIST", 0, int)
    #: cells probed per query (0 = auto nlist/64, floored at 16);
    #: higher = better recall, more rescore work
    ann_nprobe: int = _env_field("ANN_NPROBE", 0, int)
    #: cap on shortlist candidates exact-rescored per query (0 = all
    #: probed candidates)
    ann_rescore: int = _env_field("ANN_RESCORE", 0, int)
    #: observability plane (docs/observability.md). ``tracing`` turns
    #: on per-request span collection for /queries.json (served back on
    #: GET /traces.json); None defers to the PIO_TRACE env var at
    #: server construction. Off by default — the disabled path is one
    #: flag check per request, which is what the serving bench runs.
    tracing: bool | None = None
    #: structured JSON access logs on the ``pio.access`` logger; None
    #: defers to the PIO_ACCESS_LOG env var (api/http_base.py)
    access_log: bool | None = None
    #: prefork worker pool (docs/serving-performance.md "Multi-process
    #: serving"): ``pio deploy --workers N`` runs N engine-server
    #: processes sharing ONE SO_REUSEPORT listen port — one CPython
    #: process tops out on its GIL long before a multi-core host does.
    #: Each worker holds its own model replica (mmap-share it via
    #: PIO_CHECKPOINT_MMAP=r; utils/checkpoint), batcher, cache, and
    #: registry; cross-worker truth/coherence ride worker_spool_dir.
    workers: int = _env_field("WORKERS", 1, int)
    #: spool directory for worker peering + shared admin state
    #: (fleet/workers.WorkerHub, serving/workers.WorkerCoherence); the
    #: CLI mkdtemps it and passes it to every worker. None = no pool.
    worker_spool_dir: str | None = None
    #: this worker's ordinal in the pool (0 = the parent process; the
    #: CLI stamps 1..N-1 onto each sibling spawn) — drives best-effort
    #: CPU-affinity placement (serving/placement): contiguous stripes
    #: of the available cores, degrade-don't-die on hosts with fewer
    #: cores than workers
    worker_index: int = 0
    #: the pool-wide allowed-CPU set, captured by the deploy CLI
    #: BEFORE the parent pins itself to stripe 0 and threaded to every
    #: worker spawn: a supervisor respawn inherits the parent's
    #: already-narrowed affinity mask, so the child must carve its
    #: stripe from this snapshot, not from sched_getaffinity. None =
    #: carve from the process's own inherited mask.
    cpu_allowlist: tuple[int, ...] | None = None
    #: bind with SO_REUSEPORT so the N worker processes share the port
    #: (set by the CLI when workers > 1)
    reuse_port: bool = False
    #: socket bound per sibling fetch on the scrape fan-out paths
    #: (/metrics, /stats.json, /traces.json merging) — a wedged worker
    #: costs the scrape its timeout, never a hang (the untimed-
    #: blocking-io contract)
    worker_peer_timeout_s: float = _env_field("WORKER_PEER_TIMEOUT_S",
                                              2.0, float)
    #: cadence of the shared-admin-state sync loop: a /reload, /drain,
    #: or retrieval reconfig landing on ANY worker reaches every
    #: sibling within about this many seconds
    admin_sync_interval_s: float = _env_field("ADMIN_SYNC_INTERVAL_S",
                                              0.5, float)
    #: real-time freshness plane (`pio deploy --online`; online/,
    #: docs/freshness.md): tail the event store between retrains and
    #: fold touched users' ALS vectors into the deployed model with the
    #: closed-form rank x rank solve — event→recommendation freshness
    #: in seconds instead of a retrain cadence. ALS-family engines
    #: only; others log a warning and serve batch-only.
    online: bool = _online_field("ENABLED", False, _cast_bool)
    #: tail polling interval: the upper bound the speed layer adds on
    #: top of ingest latency (freshness lag ≈ interval + solve time)
    online_interval_s: float = _online_field("INTERVAL_S", 1.0, float)
    #: bounded overlay: at most this many folded USERS held between
    #: retrains (items cap at a quarter of it); LRU-evicted users fall
    #: back to their base vector — the pre-online behavior
    online_overlay_max: int = _online_field("OVERLAY_MAX", 4096, int)
    #: directory for the durable tail cursor (exactly-once resume
    #: across restarts); empty = in-memory cursor, re-tailed from
    #: deploy time after a restart (correct — fold-in is idempotent —
    #: just fresh-start)
    online_state_dir: str = _online_field("STATE_DIR", "", str)


class DeployedEngine:
    """A loaded engine instance ready to serve queries — the ServerActor
    state (CreateServer.scala:384-401)."""

    def __init__(
        self,
        engine: Engine,
        instance: EngineInstance,
        algorithms: Sequence[Any],
        serving: Any,
        models: Sequence[Any],
    ):
        self.engine = engine
        self.instance = instance
        self.algorithms = list(algorithms)
        self.serving = serving
        self.models = list(models)
        self.start_time = time.time()
        # request bookkeeping (CreateServer.scala:399-401, 583-590);
        # ThreadingHTTPServer serves queries concurrently — the reference
        # serialized these updates through an actor, here a lock
        self._stats_lock = threading.Lock()
        self.request_count = 0
        self.avg_serving_sec = 0.0
        self.last_serving_sec = 0.0

    @property
    def query_class(self) -> type | None:
        for component in [*self.algorithms, self.serving]:
            qc = getattr(component, "query_class", None)
            if qc is not None:
                return qc
        return None

    def query(self, query: Any) -> Any:
        """The steady-state predict path (CreateServer.scala:479-500)."""
        t0 = time.perf_counter()
        supplemented = self.serving.supplement(query)
        predictions = [
            algo.predict(model, supplemented)
            for algo, model in zip(self.algorithms, self.models)
        ]
        served = self.serving.serve(query, predictions)
        self.record_served(time.perf_counter() - t0)
        return served

    def query_batch(self, queries: Sequence[Any]) -> list[Any]:
        """N queries, ONE device dispatch per algorithm: the serving
        analogue of the eval batch path — supplement each, route the
        whole batch through ``batch_predict`` (vectorized matmul+top_k
        for the ALS algorithms; the base default maps ``predict``, so
        every engine is batchable), then serve each query with its own
        predictions. Used by the opt-in micro-batcher
        (ServerConfig.batching)."""
        t0 = time.perf_counter()
        supplemented = [self.serving.supplement(q) for q in queries]
        indexed = list(enumerate(supplemented))
        per_algo: list[dict[int, Any]] = []
        for algo, model in zip(self.algorithms, self.models):
            per_algo.append(dict(algo.batch_predict(model, indexed)))
        served = [
            self.serving.serve(q, [preds[i] for preds in per_algo])
            for i, q in enumerate(queries)
        ]
        dt = time.perf_counter() - t0
        for _ in queries:           # bookkeeping counts every query
            self.record_served(dt)
        return served

    def record_served(self, dt: float) -> None:
        """Count one answered query in the request bookkeeping. The
        predict paths call it internally; the serving layer calls it
        for queries answered WITHOUT their own dispatch (cache hits,
        deduped batch waiters) so a hot cache never reads as an idle
        server. Public API — stand-ins for DeployedEngine must carry
        it."""
        with self._stats_lock:
            self.request_count += 1
            self.avg_serving_sec += (dt - self.avg_serving_sec) / self.request_count
            self.last_serving_sec = dt


def retrieval_targets(models: Sequence[Any]):
    """The models a deployment's retrieval knobs apply to: anything
    exposing ``configure_retrieval`` directly (ALSModel) or through an
    ``als`` attribute (the similarproduct/ecommerce wrappers). One
    resolver so the deploy wiring and the serving stats agree on the
    target set."""
    for model in models:
        if hasattr(model, "configure_retrieval"):
            yield model
        elif hasattr(getattr(model, "als", None), "configure_retrieval"):
            yield model.als


def apply_retrieval_config(models: Sequence[Any],
                           config: "ServerConfig") -> None:
    """Push the ServerConfig retrieval knobs onto every capable model
    (no-op for engines without an ANN-capable model)."""
    for target in retrieval_targets(models):
        target.configure_retrieval(
            config.retrieval, nprobe=config.ann_nprobe,
            rescore=config.ann_rescore, nlist=config.ann_nlist)


def resolve_engine_instance(
    storage: Storage,
    config: ServerConfig,
) -> EngineInstance:
    """By id when given, else the latest completed matching
    (engine_id, engine_version, variant) — commands/Engine.scala:224-228."""
    instances = storage.get_meta_data_engine_instances()
    if config.engine_instance_id:
        instance = instances.get(config.engine_instance_id)
        if instance is None:
            raise LookupError(f"engine instance {config.engine_instance_id!r} not found")
        return instance
    if config.engine_id is not None:
        instance = instances.get_latest_completed(
            config.engine_id,
            config.engine_version or "1",
            config.engine_variant or config.engine_id,
        )
    else:
        # no identity given: latest COMPLETED instance overall
        completed = [i for i in instances.get_all() if i.status == "COMPLETED"]
        instance = max(completed, key=lambda i: i.start_time, default=None)
    if instance is None:
        raise LookupError(
            "no completed engine instance found; run `pio train` first "
            f"(engine_id={config.engine_id}, variant={config.engine_variant!r})"
        )
    return instance


def load_deployed_engine(
    storage: Storage | None = None,
    config: ServerConfig | None = None,
    ctx: EngineContext | None = None,
    engine: Engine | None = None,
) -> DeployedEngine:
    """createServerActorWithEngine (CreateServer.scala:186-244)."""
    # built at CALL time: a module-level default instance would freeze
    # the PIO_SERVING_* env reads at import
    config = config if config is not None else ServerConfig()
    storage = storage or Storage.default()
    ctx = ctx or EngineContext(workflow_params=WorkflowParams(), storage=storage)
    instance = resolve_engine_instance(storage, config)
    if engine is None:
        engine = resolve_engine_factory(instance.engine_factory)()
    engine_params = engine.params_from_instance_json(
        instance.data_source_params,
        instance.preparator_params,
        instance.algorithms_params,
        instance.serving_params,
    )
    persisted = load_models(storage, instance.id)
    # one set of algorithm instances for BOTH load_model and serving:
    # load hooks stash serve-time state (e.g. the context for live
    # constraint reads) on the instance
    _, _, algorithms, serving = engine.make_components(engine_params)
    models = engine.prepare_deploy(ctx, engine_params, persisted,
                                   algorithms=algorithms)
    # retrieval mode is deployment config, not model data: applied on
    # every load (including the /reload path, which swaps the whole
    # DeployedEngine — the new model arrives with the same knobs)
    apply_retrieval_config(models, config)
    logger.info(
        "deployed engine instance %s (%s; %d algorithm(s))",
        instance.id, instance.engine_factory, len(algorithms),
    )
    return DeployedEngine(engine, instance, algorithms, serving, models)
