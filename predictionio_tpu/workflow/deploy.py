"""Deployment: load a trained engine instance and answer queries.

Parity: core/src/main/scala/.../workflow/CreateServer.scala —
``createServerActorWithEngine`` (:186-244): look up the EngineInstance
(latest completed if unspecified, commands/Engine.scala:224-228),
deserialize the persisted models, run ``Engine.prepare_deploy`` (retrain
Unit models / reload manifests, Engine.scala:199-257), instantiate the
algorithms and serving from the stored params, and expose the steady-state
query path (supplement → per-algo predict → serve, CreateServer.scala:
470-500).

TPU-first: models stay resident (host or HBM) between requests, and the
query path re-uses each algorithm's jitted predict functions — there is
no per-query compilation or device handoff beyond the query tensors.
"""

from __future__ import annotations

import contextlib
import dataclasses
import logging
import threading
import time
from concurrent.futures import Future
from concurrent.futures import TimeoutError as FuturesTimeoutError
from typing import Any, Sequence

from predictionio_tpu.controller.engine import Engine, resolve_engine_factory
from predictionio_tpu.storage.base import EngineInstance
from predictionio_tpu.storage.registry import Storage
from predictionio_tpu.utils.resilience import (
    deadline_scope,
    record_fallback,
    remaining_deadline,
)
from predictionio_tpu.workflow.context import EngineContext, WorkflowParams
from predictionio_tpu.workflow.persistence import load_models

logger = logging.getLogger(__name__)


@dataclasses.dataclass(frozen=True)
class ServerConfig:
    """Parity: ServerConfig (CreateServer.scala:74-103)."""

    ip: str = "0.0.0.0"
    port: int = 8000
    engine_instance_id: str | None = None
    #: defaults match run_train's engine.json fallbacks (train.py:93-95)
    engine_id: str | None = None
    engine_version: str | None = None
    engine_variant: str | None = None
    #: feedback loop: POST prediction events back to the event server
    feedback: bool = False
    event_server_ip: str = "0.0.0.0"
    event_server_port: int = 7070
    access_key: str = ""
    #: socket timeout for the fire-and-forget feedback POST — bounds how
    #: long a stalled event server can pin a pio-feedback thread (the
    #: untimed-blocking-io lint invariant; threads are daemonic but each
    #: stuck one leaks a socket until the peer answers)
    feedback_timeout_s: float = 10.0
    #: when set, /stop and /reload require ?accessKey=<server_key>
    #: (common KeyAuthentication, KeyAuthentication.scala:33-60)
    server_key: str | None = None
    #: TPU-first micro-batching (beyond reference): coalesce concurrent
    #: queries into ONE device dispatch through the algorithms'
    #: batch_predict hook. On a remote-attached device a dispatch costs
    #: a full RTT (~100ms on the axon tunnel), so N concurrent clients
    #: served individually serialize at ~1/RTT qps while the same model
    #: scores thousands of queries per dispatch batched. Opt-in: adds
    #: up to batch_wait_ms latency to a lone query.
    batching: bool = False
    batch_max: int = 64
    batch_wait_ms: float = 5.0
    #: graceful degradation (beyond reference): per-request time budget
    #: for /queries.json. Propagated as the ambient resilience deadline
    #: (utils/resilience.deadline_scope — storage retries stop sleeping
    #: when the budget can't cover them) and into QueryBatcher.submit.
    #: Clients may lower it per request with an X-PIO-Deadline-Ms
    #: header; exhaustion maps to 503 + Retry-After, not a hung socket.
    #: 0 disables (legacy behavior: 300s batcher wait, no deadline).
    request_deadline_ms: float = 0.0


class QueryDeadlineExceeded(RuntimeError):
    """A query's time budget expired while WAITING for its result — as
    distinct from the work itself raising TimeoutError (which, on
    Python 3.11+, is the same class as concurrent.futures.TimeoutError
    and must not be misreported as a blown deadline)."""

    def __init__(self, budget: float):
        super().__init__(f"query deadline exceeded ({budget:.3f}s budget)")
        self.budget = budget


class DeployedEngine:
    """A loaded engine instance ready to serve queries — the ServerActor
    state (CreateServer.scala:384-401)."""

    def __init__(
        self,
        engine: Engine,
        instance: EngineInstance,
        algorithms: Sequence[Any],
        serving: Any,
        models: Sequence[Any],
    ):
        self.engine = engine
        self.instance = instance
        self.algorithms = list(algorithms)
        self.serving = serving
        self.models = list(models)
        self.start_time = time.time()
        # request bookkeeping (CreateServer.scala:399-401, 583-590);
        # ThreadingHTTPServer serves queries concurrently — the reference
        # serialized these updates through an actor, here a lock
        self._stats_lock = threading.Lock()
        self.request_count = 0
        self.avg_serving_sec = 0.0
        self.last_serving_sec = 0.0

    @property
    def query_class(self) -> type | None:
        for component in [*self.algorithms, self.serving]:
            qc = getattr(component, "query_class", None)
            if qc is not None:
                return qc
        return None

    def query(self, query: Any) -> Any:
        """The steady-state predict path (CreateServer.scala:479-500)."""
        t0 = time.perf_counter()
        supplemented = self.serving.supplement(query)
        predictions = [
            algo.predict(model, supplemented)
            for algo, model in zip(self.algorithms, self.models)
        ]
        served = self.serving.serve(query, predictions)
        self._record(time.perf_counter() - t0)
        return served

    def query_batch(self, queries: Sequence[Any]) -> list[Any]:
        """N queries, ONE device dispatch per algorithm: the serving
        analogue of the eval batch path — supplement each, route the
        whole batch through ``batch_predict`` (vectorized matmul+top_k
        for the ALS algorithms; the base default maps ``predict``, so
        every engine is batchable), then serve each query with its own
        predictions. Used by the opt-in micro-batcher
        (ServerConfig.batching)."""
        t0 = time.perf_counter()
        supplemented = [self.serving.supplement(q) for q in queries]
        indexed = list(enumerate(supplemented))
        per_algo: list[dict[int, Any]] = []
        for algo, model in zip(self.algorithms, self.models):
            per_algo.append(dict(algo.batch_predict(model, indexed)))
        served = [
            self.serving.serve(q, [preds[i] for preds in per_algo])
            for i, q in enumerate(queries)
        ]
        dt = time.perf_counter() - t0
        for _ in queries:           # bookkeeping counts every query
            self._record(dt)
        return served

    def _record(self, dt: float) -> None:
        with self._stats_lock:
            self.request_count += 1
            self.avg_serving_sec += (dt - self.avg_serving_sec) / self.request_count
            self.last_serving_sec = dt


def resolve_engine_instance(
    storage: Storage,
    config: ServerConfig,
) -> EngineInstance:
    """By id when given, else the latest completed matching
    (engine_id, engine_version, variant) — commands/Engine.scala:224-228."""
    instances = storage.get_meta_data_engine_instances()
    if config.engine_instance_id:
        instance = instances.get(config.engine_instance_id)
        if instance is None:
            raise LookupError(f"engine instance {config.engine_instance_id!r} not found")
        return instance
    if config.engine_id is not None:
        instance = instances.get_latest_completed(
            config.engine_id,
            config.engine_version or "1",
            config.engine_variant or config.engine_id,
        )
    else:
        # no identity given: latest COMPLETED instance overall
        completed = [i for i in instances.get_all() if i.status == "COMPLETED"]
        instance = max(completed, key=lambda i: i.start_time, default=None)
    if instance is None:
        raise LookupError(
            "no completed engine instance found; run `pio train` first "
            f"(engine_id={config.engine_id}, variant={config.engine_variant!r})"
        )
    return instance


def load_deployed_engine(
    storage: Storage | None = None,
    config: ServerConfig = ServerConfig(),
    ctx: EngineContext | None = None,
    engine: Engine | None = None,
) -> DeployedEngine:
    """createServerActorWithEngine (CreateServer.scala:186-244)."""
    storage = storage or Storage.default()
    ctx = ctx or EngineContext(workflow_params=WorkflowParams(), storage=storage)
    instance = resolve_engine_instance(storage, config)
    if engine is None:
        engine = resolve_engine_factory(instance.engine_factory)()
    engine_params = engine.params_from_instance_json(
        instance.data_source_params,
        instance.preparator_params,
        instance.algorithms_params,
        instance.serving_params,
    )
    persisted = load_models(storage, instance.id)
    # one set of algorithm instances for BOTH load_model and serving:
    # load hooks stash serve-time state (e.g. the context for live
    # constraint reads) on the instance
    _, _, algorithms, serving = engine.make_components(engine_params)
    models = engine.prepare_deploy(ctx, engine_params, persisted,
                                   algorithms=algorithms)
    logger.info(
        "deployed engine instance %s (%s; %d algorithm(s))",
        instance.id, instance.engine_factory, len(algorithms),
    )
    return DeployedEngine(engine, instance, algorithms, serving, models)


class QueryBatcher:
    """Coalesces concurrent queries into one device dispatch — the
    TPU-first serving feature a per-query dispatch model can't offer
    (beyond reference; the reference's spray actor served queries
    strictly one predict per request, CreateServer.scala:495-497).

    Handler threads ``submit()`` and block on a future; one dispatcher
    thread drains the queue — after the first query arrives it waits at
    most ``batch_wait_ms`` (or until ``batch_max``) for companions,
    then runs the whole batch through ``DeployedEngine.query_batch``.
    A failing batch is retried query-by-query so one poisoned query
    500s alone instead of taking its batch down. ``get_deployed`` is
    read fresh per batch, so /reload hot-swaps apply from the next
    batch on."""

    def __init__(self, get_deployed, batch_max: int = 64,
                 batch_wait_ms: float = 5.0):
        import queue as _queue

        self._get_deployed = get_deployed
        # clamped to 256: the ALS batch_predict pads batch dims to a
        # power-of-two menu only up to 256 (above, every distinct size
        # would be a fresh jit signature — the retrace stall the menu
        # exists to prevent); 256 queries per dispatch is plenty
        self._batch_max = max(1, min(int(batch_max), 256))
        self._wait_s = max(0.0, batch_wait_ms) / 1e3
        self._queue: "_queue.Queue" = _queue.Queue()
        self._stopped = False
        self.batches = 0
        self.batched_queries = 0
        self._thread = threading.Thread(
            target=self._run, name="pio-query-batcher", daemon=True)
        self._thread.start()

    def submit(self, query: Any, timeout: float = 300.0) -> Any:
        """Enqueue and wait; raises whatever the predict path raised.

        The caller's ambient resilience deadline (deadline_scope) rides
        along into the dispatcher thread — contextvars do not cross
        threads, so the remaining budget is captured here and re-entered
        around the batch dispatch and any per-query fallbacks."""
        if self._stopped:
            raise RuntimeError("query batcher is stopped")
        rem = remaining_deadline()
        deadline = time.monotonic() + rem if rem is not None else None
        fut: Future = Future()
        self._queue.put((query, fut, deadline))
        if self._stopped and not fut.done():
            # close() raced the enqueue: the dispatcher (or close's
            # drain) may never see this entry — fail fast instead of
            # letting the handler hang out the timeout (done() guards
            # the benign double-completion race)
            try:
                fut.set_exception(RuntimeError("query batcher is stopped"))
            except Exception:
                pass
        try:
            return fut.result(timeout=timeout)
        except FuturesTimeoutError:
            if not fut.done():
                # the WAIT expired (a blown budget) — not an exception
                # from the predict path, which fut.done() distinguishes
                # even on 3.11 where the two classes are aliased
                raise QueryDeadlineExceeded(timeout) from None
            raise

    def close(self) -> None:
        self._stopped = True
        self._queue.put(None)
        self._thread.join(timeout=5)
        self._fail_pending()

    def _fail_pending(self) -> None:
        """Fail anything still queued after the dispatcher exited —
        a blocked submit must get its 500 now, not at timeout."""
        import queue as _queue

        while True:
            try:
                item = self._queue.get_nowait()
            except _queue.Empty:
                return
            if item is None:
                continue
            _, fut, _ = item
            if not fut.done():
                try:
                    fut.set_exception(
                        RuntimeError("query batcher is stopped"))
                except Exception:
                    pass

    # -- dispatcher ---------------------------------------------------------
    def _run(self) -> None:
        import queue as _queue

        while True:
            item = self._queue.get()
            if item is None:
                return
            batch = [item]
            deadline = time.perf_counter() + self._wait_s
            while len(batch) < self._batch_max:
                remaining = deadline - time.perf_counter()
                if remaining <= 0:
                    break
                try:
                    nxt = self._queue.get(timeout=remaining)
                except _queue.Empty:
                    break
                if nxt is None:
                    self._finish(batch)
                    return
                batch.append(nxt)
            self._finish(batch)

    @staticmethod
    def _scope(deadline_abs: float | None):
        """Re-enter a caller's deadline (absolute monotonic) on the
        dispatcher thread; nested scopes only ever shrink."""
        if deadline_abs is None:
            return contextlib.nullcontext()
        return deadline_scope(max(0.0, deadline_abs - time.monotonic()))

    def _finish(self, batch) -> None:
        deployed = self._get_deployed()
        deadlines = [d for _, _, d in batch if d is not None]
        try:
            # the batch shares one dispatch: honor its tightest deadline
            with self._scope(min(deadlines) if deadlines else None):
                results = deployed.query_batch([q for q, _, _ in batch])
            for (_, fut, _), served in zip(batch, results):
                fut.set_result(served)
            self.batches += 1  # pio: lint-ignore[lock-discipline]: dispatcher is the ONLY writer; stats reads may run one batch stale
            self.batched_queries += len(batch)  # pio: lint-ignore[lock-discipline]: single-writer stats counter, same as above
        except Exception:
            logger.exception(
                "batched predict failed; retrying %d queries individually",
                len(batch))
            record_fallback("serving/query-batcher")
            for q, fut, deadline in batch:
                if fut.done():
                    continue
                try:
                    # re-resolve per query: a /reload mid-batch must not
                    # pin the whole fallback pass to the dead instance
                    # the batch dispatch captured
                    with self._scope(deadline):
                        fut.set_result(self._get_deployed().query(q))
                except Exception as e:          # noqa: BLE001
                    fut.set_exception(e)
