"""Deployment: load a trained engine instance and answer queries.

Parity: core/src/main/scala/.../workflow/CreateServer.scala —
``createServerActorWithEngine`` (:186-244): look up the EngineInstance
(latest completed if unspecified, commands/Engine.scala:224-228),
deserialize the persisted models, run ``Engine.prepare_deploy`` (retrain
Unit models / reload manifests, Engine.scala:199-257), instantiate the
algorithms and serving from the stored params, and expose the steady-state
query path (supplement → per-algo predict → serve, CreateServer.scala:
470-500).

TPU-first: models stay resident (host or HBM) between requests, and the
query path re-uses each algorithm's jitted predict functions — there is
no per-query compilation or device handoff beyond the query tensors.
"""

from __future__ import annotations

import dataclasses
import logging
import threading
import time
from typing import Any, Sequence

from predictionio_tpu.controller.engine import Engine, resolve_engine_factory
from predictionio_tpu.storage.base import EngineInstance
from predictionio_tpu.storage.registry import Storage
from predictionio_tpu.workflow.context import EngineContext, WorkflowParams
from predictionio_tpu.workflow.persistence import load_models

logger = logging.getLogger(__name__)


@dataclasses.dataclass(frozen=True)
class ServerConfig:
    """Parity: ServerConfig (CreateServer.scala:74-103)."""

    ip: str = "0.0.0.0"
    port: int = 8000
    engine_instance_id: str | None = None
    #: defaults match run_train's engine.json fallbacks (train.py:93-95)
    engine_id: str | None = None
    engine_version: str | None = None
    engine_variant: str | None = None
    #: feedback loop: POST prediction events back to the event server
    feedback: bool = False
    event_server_ip: str = "0.0.0.0"
    event_server_port: int = 7070
    access_key: str = ""
    #: when set, /stop and /reload require ?accessKey=<server_key>
    #: (common KeyAuthentication, KeyAuthentication.scala:33-60)
    server_key: str | None = None


class DeployedEngine:
    """A loaded engine instance ready to serve queries — the ServerActor
    state (CreateServer.scala:384-401)."""

    def __init__(
        self,
        engine: Engine,
        instance: EngineInstance,
        algorithms: Sequence[Any],
        serving: Any,
        models: Sequence[Any],
    ):
        self.engine = engine
        self.instance = instance
        self.algorithms = list(algorithms)
        self.serving = serving
        self.models = list(models)
        self.start_time = time.time()
        # request bookkeeping (CreateServer.scala:399-401, 583-590);
        # ThreadingHTTPServer serves queries concurrently — the reference
        # serialized these updates through an actor, here a lock
        self._stats_lock = threading.Lock()
        self.request_count = 0
        self.avg_serving_sec = 0.0
        self.last_serving_sec = 0.0

    @property
    def query_class(self) -> type | None:
        for component in [*self.algorithms, self.serving]:
            qc = getattr(component, "query_class", None)
            if qc is not None:
                return qc
        return None

    def query(self, query: Any) -> Any:
        """The steady-state predict path (CreateServer.scala:479-500)."""
        t0 = time.perf_counter()
        supplemented = self.serving.supplement(query)
        predictions = [
            algo.predict(model, supplemented)
            for algo, model in zip(self.algorithms, self.models)
        ]
        served = self.serving.serve(query, predictions)
        dt = time.perf_counter() - t0
        with self._stats_lock:
            self.request_count += 1
            self.avg_serving_sec += (dt - self.avg_serving_sec) / self.request_count
            self.last_serving_sec = dt
        return served


def resolve_engine_instance(
    storage: Storage,
    config: ServerConfig,
) -> EngineInstance:
    """By id when given, else the latest completed matching
    (engine_id, engine_version, variant) — commands/Engine.scala:224-228."""
    instances = storage.get_meta_data_engine_instances()
    if config.engine_instance_id:
        instance = instances.get(config.engine_instance_id)
        if instance is None:
            raise LookupError(f"engine instance {config.engine_instance_id!r} not found")
        return instance
    if config.engine_id is not None:
        instance = instances.get_latest_completed(
            config.engine_id,
            config.engine_version or "1",
            config.engine_variant or config.engine_id,
        )
    else:
        # no identity given: latest COMPLETED instance overall
        completed = [i for i in instances.get_all() if i.status == "COMPLETED"]
        instance = max(completed, key=lambda i: i.start_time, default=None)
    if instance is None:
        raise LookupError(
            "no completed engine instance found; run `pio train` first "
            f"(engine_id={config.engine_id}, variant={config.engine_variant!r})"
        )
    return instance


def load_deployed_engine(
    storage: Storage | None = None,
    config: ServerConfig = ServerConfig(),
    ctx: EngineContext | None = None,
    engine: Engine | None = None,
) -> DeployedEngine:
    """createServerActorWithEngine (CreateServer.scala:186-244)."""
    storage = storage or Storage.default()
    ctx = ctx or EngineContext(workflow_params=WorkflowParams(), storage=storage)
    instance = resolve_engine_instance(storage, config)
    if engine is None:
        engine = resolve_engine_factory(instance.engine_factory)()
    engine_params = engine.params_from_instance_json(
        instance.data_source_params,
        instance.preparator_params,
        instance.algorithms_params,
        instance.serving_params,
    )
    persisted = load_models(storage, instance.id)
    # one set of algorithm instances for BOTH load_model and serving:
    # load hooks stash serve-time state (e.g. the context for live
    # constraint reads) on the instance
    _, _, algorithms, serving = engine.make_components(engine_params)
    models = engine.prepare_deploy(ctx, engine_params, persisted,
                                   algorithms=algorithms)
    logger.info(
        "deployed engine instance %s (%s; %d algorithm(s))",
        instance.id, instance.engine_factory, len(algorithms),
    )
    return DeployedEngine(engine, instance, algorithms, serving, models)
